"""Log query API: structured log search DSL over log tables.

Reference: src/log-query (660 LoC) + src/servers/src/http/logs.rs — a JSON
DSL (table, time_filter, column filters, limit) compiled to a plan. Here
the DSL evaluates host-side over the region scan: log search is
string-matching territory, which stays off the device by design.

Request shape (subset of the reference's LogQuery):
{
  "table": {"schema": "public", "table": "loki_logs"},
  "time_filter": {"start": "2026-01-01T00:00:00Z", "end": "..."},
  "filters": [{"column": "line", "filters": [
      {"contains": "error"} | {"prefix": "GET"} | {"regex": "..."} |
      {"exists": true} | {"eq": "value"}
  ]}],
  "columns": ["ts", "line", "app"],   # optional projection
  "limit": {"fetch": 100, "skip": 0}
}
"""

from __future__ import annotations

import re

import numpy as np

from greptimedb_tpu.errors import InvalidArguments
from greptimedb_tpu.query.engine import QueryResult
from greptimedb_tpu.query.parser import parse_timestamp_str


def _parse_time(v) -> int | None:
    if v is None:
        return None
    if isinstance(v, (int, float)):
        return int(v)
    return parse_timestamp_str(str(v))


def _term_pred(cond: dict):
    """cond → single-term predicate for index pruning; None when the cond
    cannot prune (its semantics aren't term-local, e.g. exists:true)."""
    if "contains" in cond:
        needle = str(cond["contains"])
        return lambda t: needle in t
    if "prefix" in cond:
        p = str(cond["prefix"])
        return lambda t: t.startswith(p)
    if "regex" in cond:
        try:
            rx = re.compile(str(cond["regex"]))
        except re.error:
            return None  # row-level _match raises the proper error
        return lambda t: rx.search(t) is not None
    if "eq" in cond:
        v = str(cond["eq"])
        return lambda t: t == v
    return None


def _cond_pred(cond: dict):
    """cond → (kind, text, predicate-over-coerced-strings) for the
    fingerprint-prefilterable filter kinds, None for the rest
    (exists: not value-local).  The predicate is THE definition of the
    filter's truth — the host row loop and the fingerprint-verified map
    both evaluate exactly it, so the two routes cannot diverge."""
    if "contains" in cond:
        needle = str(cond["contains"])
        return ("contains", needle, lambda s, t=needle: t in s)
    if "prefix" in cond:
        p = str(cond["prefix"])
        return ("prefix", p, lambda s, p=p: s.startswith(p))
    if "regex" in cond:
        try:
            rx = re.compile(str(cond["regex"]))
        except re.error as e:
            raise InvalidArguments(f"bad regex {cond['regex']!r}: {e}") from None
        return ("regex", str(cond["regex"]),
                lambda s, rx=rx: rx.search(s) is not None)
    if "match" in cond or "matches" in cond:
        # full-text match (shared semantics with SQL matches(); empty-token
        # queries match nothing); "matches" is the documented spelling,
        # "match" the original one — same filter
        from greptimedb_tpu.storage.index import ft_predicate

        q = str(cond.get("matches", cond.get("match")))
        return ("matches", q, ft_predicate("matches", q))
    if "eq" in cond:
        v = str(cond["eq"])
        return ("eq", v, lambda s, v=v: s == v)
    return None


def _match(cond: dict, values: np.ndarray, vmap: dict | None = None
           ) -> np.ndarray:
    strs = np.asarray([("" if v is None else str(v)) for v in values],
                      dtype=object)
    n = len(strs)
    got = _cond_pred(cond)
    if got is not None:
        _kind, _text, pred = got
        if vmap is not None:
            # fingerprint route: per-DISTINCT-value truth precomputed
            # (fulltext/resident.py verified_bools over the resident
            # dictionary); rows reduce to a dict probe.  Values the
            # resident vocabulary has not seen yet (hot appends) fall
            # back to the same predicate — bit-exact either way.
            return np.array(
                [vmap[s] if s in vmap else pred(s) for s in strs],
                dtype=bool)
        return np.array([pred(s) for s in strs], dtype=bool)
    if "exists" in cond:
        has = np.array([s != "" for s in strs], dtype=bool)
        return has if cond["exists"] else ~has
    raise InvalidArguments(f"unknown log filter {cond!r}")


def _fingerprint_maps(db, table_name: str, view, query: dict) -> dict:
    """Per-(filter, cond) value→bool maps from the resident fingerprint
    index, for the DSL filter kinds it can serve (contains/prefix/regex/
    eq/matches).  Only consults state that is ALREADY resident
    (RegionCacheManager.peek_table — a cold table stays fully on the
    host path); with `GREPTIME_FULLTEXT=off` or on any miss the caller's
    per-row predicate loop runs unchanged, and rows whose value the
    resident vocabulary has not seen fall back per value — the host path
    is the fallback twin at every granularity."""
    from greptimedb_tpu.fulltext import enabled

    if not enabled():
        return {}
    cache_mgr = getattr(db, "cache", None)
    ex = getattr(getattr(db, "engine", None), "executor", None)
    ft = getattr(ex, "fulltext_cache", None)
    if cache_mgr is None or ft is None:
        return {}
    dt = cache_mgr.peek_table(view)
    if dt is None or getattr(dt, "dicts_root", 0) == 0:
        return {}
    out: dict = {}
    for fi, f in enumerate(query.get("filters") or []):
        col = f.get("column")
        vocab = dt.dicts.get(col)
        if not vocab:
            continue
        for ci, cond in enumerate(f.get("filters") or []):
            got = _cond_pred(cond)
            if got is None:
                continue
            kind, text, pred = got
            # the verified memo sees raw vocabulary items; truth is
            # defined over the DSL's coerced strings — one wrapper, and
            # variant="dsl" namespaces the memo so the SQL path (whose
            # subject for NULL is str(None)) can never serve this
            # coercion's truth or vice versa
            coerced = lambda v, p=pred: p("" if v is None else str(v))
            vmap = ft.verified_map(table_name, dt, col, vocab, coerced,
                                   kind, text, variant="dsl")
            if vmap is not None:
                out[(fi, ci)] = vmap
    return out


def execute_log_query(db, query: dict) -> QueryResult:
    if not isinstance(query, dict):
        raise InvalidArguments("log query body must be a JSON object")
    tbl = query.get("table") or {}
    name = tbl.get("table")
    if not name:
        raise InvalidArguments("log query needs table.table")
    schema_name = tbl.get("schema", "public")
    full = f"{schema_name}.{name}" if schema_name != db.current_db else name

    view = db._table_view(full)
    ts_name = view.schema.time_index.name
    tf = query.get("time_filter") or {}
    lo = _parse_time(tf.get("start"))
    hi = _parse_time(tf.get("end"))
    # scan only what the filters + projection touch
    needed: set[str] = set()
    for f in query.get("filters") or []:
        if f.get("column"):
            needed.add(str(f["column"]))
    if query.get("columns"):
        needed.update(str(c) for c in query["columns"])
    # without an explicit projection the response returns every column, so
    # only restrict the scan when the caller named its columns
    want = sorted(needed | {ts_name}) if query.get("columns") else None
    # tag-column filters become file-level pruning predicates evaluated
    # against each SST's exact term dictionary (inverted-index sidecar);
    # the row-level filter below still applies in full
    tag_cols = {c.name for c in view.schema.tag_columns}
    per_col: dict[str, list] = {}
    for f in query.get("filters") or []:
        col = f.get("column")
        if col in tag_cols:
            per_col.setdefault(col, []).extend(
                p for p in (_term_pred(c) for c in f.get("filters") or [])
                if p is not None
            )
    tag_preds = {
        c: (lambda t, ps=tuple(ps): all(p(t) for p in ps))
        for c, ps in per_col.items() if ps
    }
    # full-text "match" filters on string FIELD columns prune SST files
    # via the sidecar token sets
    from greptimedb_tpu.storage.index import tokenize

    from greptimedb_tpu.datatypes.types import ConcreteDataType as _CDT

    ft_tokens: dict[str, list] = {}
    field_cols = {c.name for c in view.schema.field_columns
                  if c.dtype in (_CDT.STRING, _CDT.JSON)}
    for f in query.get("filters") or []:
        col = f.get("column")
        if col in field_cols:
            for cond in f.get("filters") or []:
                if "match" in cond:
                    ft_tokens.setdefault(col, []).extend(
                        tokenize(str(cond["match"]))
                    )
    host = view.scan_host((lo, hi), columns=want,
                          tag_preds=tag_preds or None,
                          ft_tokens=ft_tokens or None)
    n = len(host[ts_name])
    vmaps = _fingerprint_maps(db, full, view, query)
    keep = np.ones(n, dtype=bool)
    for fi, f in enumerate(query.get("filters") or []):
        col = f.get("column")
        if col not in host:
            raise InvalidArguments(f"unknown filter column {col!r}")
        for ci, cond in enumerate(f.get("filters") or []):
            keep &= _match(cond, host[col], vmaps.get((fi, ci)))
    idx = np.nonzero(keep)[0]
    # newest first, like the reference's default ordering for log search
    order = np.argsort(host[ts_name][idx].astype(np.int64))[::-1]
    idx = idx[order]
    lim = query.get("limit") or {}
    skip = int(lim.get("skip", 0))
    fetch = lim.get("fetch")
    idx = idx[skip: skip + int(fetch)] if fetch is not None else idx[skip:]

    columns = query.get("columns")
    if columns:
        bad = [c for c in columns if c not in host]
        if bad:
            raise InvalidArguments(f"unknown columns {bad}")
        names = list(columns)
    else:
        names = [c.name for c in view.schema]
    rows = []
    for i in idx.tolist():
        row = []
        for c in names:
            v = host[c][i]
            row.append(int(v) if isinstance(v, np.integer) else
                       float(v) if isinstance(v, np.floating) else v)
        rows.append(row)
    return QueryResult(names, rows)
