"""PostgreSQL wire protocol server (reference: pgwire 0.40, port 4003).

Protocol v3, both flavors:
- simple query (Q): RowDescription/DataRow/CommandComplete cycle — psql.
- extended query (P/B/D/E/C/S/H): named prepared statements + portals
  with text AND binary parameter/result formats — what JDBC, psycopg3
  and asyncpg actually send.  Portals execute lazily on first
  Describe/Execute and cache their result, so Describe(portal) reports
  the real result schema; errors suppress further extended messages
  until Sync, per the protocol's error-recovery rule.
"""

from __future__ import annotations

import asyncio
import re
import struct
import threading

from greptimedb_tpu.errors import GreptimeError
from greptimedb_tpu.servers.placeholders import scan_placeholders, sql_literal
from greptimedb_tpu.servers.tcp import ThreadedTcpServer

# trailing LIMIT n [OFFSET m] clause (rewritten to LIMIT 0 by the
# Describe-statement schema probe)
_TAIL_LIMIT = re.compile(r"(?is)\blimit\s+\d+(\s+offset\s+\d+)?\s*$")

_OID = {
    "Boolean": 16, "Int8": 21, "Int16": 21, "Int32": 23, "Int64": 20,
    "UInt8": 21, "UInt16": 23, "UInt32": 20, "UInt64": 20,
    "Float32": 700, "Float64": 701,
    "TimestampSecond": 20, "TimestampMillisecond": 20,
    "TimestampMicrosecond": 20, "TimestampNanosecond": 20,
    "String": 25,
}


class _Prepared:
    __slots__ = ("sql", "positions", "n_params", "param_oids")

    def __init__(self, sql: str, param_oids: list[int]):
        self.sql = sql
        self.positions = scan_placeholders(sql, "dollar")
        if any(p[2] < 1 for p in self.positions):
            raise ValueError("there is no parameter $0")
        self.n_params = max((p[2] for p in self.positions), default=0)
        # pad/truncate the declared oids to the placeholder count
        # (0 = unspecified, inferred as text)
        self.param_oids = (param_oids + [0] * self.n_params)[:self.n_params]


class _Portal:
    __slots__ = ("stmt", "bound_sql", "result_formats", "result", "offset")

    def __init__(self, stmt: _Prepared, bound_sql: str,
                 result_formats: list[int]):
        self.stmt = stmt
        self.bound_sql = bound_sql
        self.result_formats = result_formats
        self.result = None  # QueryResult once executed
        self.offset = 0  # rows already streamed (max_rows suspension)


class _PgConn:
    def __init__(self, server: "PostgresServer", reader, writer):
        self.server = server
        self.reader = reader
        self.writer = writer
        self.session_db = "public"  # per-connection database
        self.session_tz = "UTC"
        self.user = ""  # startup-packet user = scheduler tenant identity
        self.stmts: dict[str, _Prepared] = {}
        self.portals: dict[str, _Portal] = {}
        self._skip_until_sync = False

    def _msg(self, tag: bytes, payload: bytes) -> None:
        self.writer.write(tag + struct.pack(">I", len(payload) + 4) + payload)

    def _ready(self) -> None:
        self._msg(b"Z", b"I")

    def _error(self, msg: str, code: str = "XX000") -> None:
        fields = (b"SERROR\x00" + b"C" + code.encode() + b"\x00"
                  + b"M" + msg.encode("utf-8") + b"\x00" + b"\x00")
        self._msg(b"E", fields)

    def _notice(self, msg: str) -> None:
        """NoticeResponse — used to echo the trace id back when a
        statement carries a traceparent comment (the PostgreSQL analog
        of the HTTP x-greptime-trace-id response header; notices are
        wire-legal at any point and harmless to drivers)."""
        fields = (b"SNOTICE\x00" + b"C00000\x00"
                  + b"M" + msg.encode("utf-8") + b"\x00" + b"\x00")
        self._msg(b"N", fields)

    async def _scram_auth(self, provider, user: str) -> bool:
        """SCRAM-SHA-256 SASL exchange (reference pgwire's SCRAM path;
        algorithm in utils/auth.ScramSha256Server)."""
        from greptimedb_tpu.utils.auth import ScramSha256Server

        async def read_p() -> bytes | None:
            tag = await self.reader.readexactly(1)
            ln = struct.unpack(">I", await self.reader.readexactly(4))[0]
            body = await self.reader.readexactly(ln - 4)
            return body if tag == b"p" else None

        def fail():
            self._error("password authentication failed for "
                        f'user "{user}"', "28P01")

        # AuthenticationSASL with the mechanism list
        self._msg(b"R", struct.pack(">I", 10) + b"SCRAM-SHA-256\x00\x00")
        await self.writer.drain()
        body = await read_p()
        if body is None:
            fail()
            await self.writer.drain()
            return False
        # SASLInitialResponse: mechanism cstr + int32 len + payload
        nul = body.find(b"\x00")
        mech = body[:nul].decode("utf-8", "replace")
        rest = body[nul + 1:]
        (plen,) = struct.unpack(">i", rest[:4])
        client_first = rest[4:4 + plen].decode("utf-8", "replace") if (
            plen >= 0) else ""
        if mech != "SCRAM-SHA-256":
            fail()
            await self.writer.drain()
            return False
        scram = ScramSha256Server(provider, user)
        try:
            server_first = scram.first(client_first)
        except ValueError:
            fail()
            await self.writer.drain()
            return False
        self._msg(b"R", struct.pack(">I", 11) + server_first.encode())
        await self.writer.drain()
        body = await read_p()
        if body is None:
            fail()
            await self.writer.drain()
            return False
        ok, server_final = scram.final(body.decode("utf-8", "replace"))
        if not ok:
            fail()
            await self.writer.drain()
            return False
        self._msg(b"R", struct.pack(">I", 12) + server_final.encode())
        return True

    async def startup(self) -> bool:
        while True:
            hdr = await self.reader.readexactly(4)
            ln = struct.unpack(">I", hdr)[0]
            body = await self.reader.readexactly(ln - 4)
            code = struct.unpack(">I", body[:4])[0]
            if code == 80877103:  # SSLRequest
                ctx = self.server.ssl_context
                if ctx is None:
                    self.writer.write(b"N")
                    await self.writer.drain()
                    continue
                self.writer.write(b"S")
                await self.writer.drain()
                from greptimedb_tpu.utils.tls import upgrade_server_tls

                self.reader, self.writer = await upgrade_server_tls(
                    self.reader, self.writer, ctx)
                self._tls_active = True
                continue
            if code == 196608:  # protocol 3.0
                if self.server.tls_require and not getattr(
                        self, "_tls_active", False):
                    self._error("server requires TLS (sslmode=require)",
                                "28000")
                    await self.writer.drain()
                    return False
                params = {}
                parts = body[4:].split(b"\x00")
                for i in range(0, len(parts) - 1, 2):
                    if parts[i]:
                        params[parts[i].decode()] = parts[i + 1].decode()
                db = params.get("database")
                if db:
                    self.session_db = db
                self.user = params.get("user", "")
                provider = getattr(self.server.db, "user_provider", None)
                if provider is not None and provider.enabled and (
                        self.server.auth_mode == "scram"):
                    if not await self._scram_auth(
                            provider, params.get("user", "")):
                        return False
                elif provider is not None and provider.enabled:
                    # AuthenticationCleartextPassword
                    self._msg(b"R", struct.pack(">I", 3))
                    await self.writer.drain()
                    tag = await self.reader.readexactly(1)
                    ln = struct.unpack(
                        ">I", await self.reader.readexactly(4))[0]
                    pw_body = await self.reader.readexactly(ln - 4)
                    password = pw_body.rstrip(b"\x00").decode(
                        "utf-8", "replace")
                    user = params.get("user", "")
                    if tag != b"p" or not provider.check_plain(user, password):
                        self._error("password authentication failed for "
                                    f'user "{user}"', "28P01")
                        await self.writer.drain()
                        return False
                self._msg(b"R", struct.pack(">I", 0))  # AuthenticationOk
                for k, v in (("server_version", "16.3 (greptimedb-tpu)"),
                             ("server_encoding", "UTF8"),
                             ("client_encoding", "UTF8"),
                             ("DateStyle", "ISO"),
                             ("integer_datetimes", "on")):
                    self._msg(b"S", k.encode() + b"\x00" + v.encode() + b"\x00")
                self._msg(b"K", struct.pack(">II", 1, 0))
                self._ready()
                await self.writer.drain()
                return True
            self._error(f"unsupported protocol {code}", "0A000")
            await self.writer.drain()
            return False

    def _row_description(self, names, types, formats=None) -> None:
        out = struct.pack(">H", len(names))
        for i, (n, t) in enumerate(zip(names, types)):
            oid = _OID.get(t, 25)
            fmt = formats[i] if formats else 0
            out += (n.encode("utf-8") + b"\x00"
                    + struct.pack(">IhIhih", 0, 0, oid, -1, -1, fmt))
        self._msg(b"T", out)

    @staticmethod
    def _text_cell(v) -> bytes:
        if isinstance(v, bool):
            return b"t" if v else b"f"
        if isinstance(v, float):
            return repr(v).encode()
        return str(v).encode("utf-8")

    @staticmethod
    def _binary_cell(v, oid: int) -> bytes:
        if oid == 16:
            return b"\x01" if v else b"\x00"
        if oid == 21:
            return struct.pack(">h", int(v))
        if oid == 23:
            return struct.pack(">i", int(v))
        if oid == 20:
            return struct.pack(">q", int(v))
        if oid == 700:
            return struct.pack(">f", float(v))
        if oid == 701:
            return struct.pack(">d", float(v))
        return _PgConn._text_cell(v)

    def _data_row(self, row, oids=None, formats=None) -> None:
        out = struct.pack(">H", len(row))
        for i, v in enumerate(row):
            if v is None:
                out += struct.pack(">i", -1)
            else:
                if formats and formats[i] == 1:
                    s = self._binary_cell(v, oids[i] if oids else 25)
                else:
                    s = self._text_cell(v)
                out += struct.pack(">i", len(s)) + s
        self._msg(b"D", out)

    # ---- extended query protocol --------------------------------------
    def _ext_error(self, msg: str, code: str = "42000") -> None:
        """Error in extended mode: report it and ignore every message
        until the client's Sync (protocol error-recovery rule)."""
        self._error(msg, code)
        self._skip_until_sync = True

    def _on_parse(self, body: bytes) -> None:
        z1 = body.index(b"\x00")
        name = body[:z1].decode("utf-8", "replace")
        z2 = body.index(b"\x00", z1 + 1)
        sql = body[z1 + 1:z2].decode("utf-8", "replace")
        (n,) = struct.unpack_from(">H", body, z2 + 1)
        oids = list(struct.unpack_from(f">{n}i", body, z2 + 3)) if n else []
        try:
            self.stmts[name] = _Prepared(sql, oids)
        except ValueError as e:
            self._ext_error(str(e), "42P02")
            return
        self._msg(b"1", b"")  # ParseComplete

    @staticmethod
    def _decode_param(raw: bytes | None, oid: int, fmt: int):
        if raw is None:
            return None
        if fmt == 1:  # binary by declared oid
            if oid == 16:
                return raw != b"\x00"
            if oid == 21:
                return struct.unpack(">h", raw)[0]
            if oid == 23:
                return struct.unpack(">i", raw)[0]
            if oid == 20:
                return struct.unpack(">q", raw)[0]
            if oid == 700:
                return struct.unpack(">f", raw)[0]
            if oid == 701:
                return struct.unpack(">d", raw)[0]
            return raw.decode("utf-8", "replace")
        text = raw.decode("utf-8", "replace")
        if oid in (20, 21, 23):
            return int(text)
        if oid in (700, 701):
            return float(text)
        if oid == 16:
            return text.lower() in ("t", "true", "1", "yes", "on")
        if oid == 0:
            # Unspecified OID (lib/pq, psql \bind): postgres infers the
            # type from context; our nearest analog is to pass
            # numeric-looking text through as a numeric literal so
            # comparisons against value/timestamp columns type-check.
            try:
                return int(text)
            except ValueError:
                try:
                    return float(text)
                except ValueError:
                    return text
        return text

    def _bind_sql(self, stmt: _Prepared, vals: list) -> str:
        out, prev = [], 0
        for start, end, pno in stmt.positions:
            out.append(stmt.sql[prev:start])
            out.append(sql_literal(vals[pno - 1]))
            prev = end
        out.append(stmt.sql[prev:])
        return "".join(out)

    def _on_bind(self, body: bytes) -> None:
        z1 = body.index(b"\x00")
        portal = body[:z1].decode("utf-8", "replace")
        z2 = body.index(b"\x00", z1 + 1)
        sname = body[z1 + 1:z2].decode("utf-8", "replace")
        stmt = self.stmts.get(sname)
        if stmt is None:
            self._ext_error(f'prepared statement "{sname}" does not exist',
                            "26000")
            return
        off = z2 + 1
        (nf,) = struct.unpack_from(">H", body, off)
        off += 2
        pformats = list(struct.unpack_from(f">{nf}h", body, off))
        off += 2 * nf
        (np_,) = struct.unpack_from(">H", body, off)
        off += 2
        raws: list[bytes | None] = []
        for _ in range(np_):
            (vlen,) = struct.unpack_from(">i", body, off)
            off += 4
            if vlen < 0:
                raws.append(None)
            else:
                raws.append(body[off:off + vlen])
                off += vlen
        (nrf,) = struct.unpack_from(">H", body, off)
        off += 2
        rformats = list(struct.unpack_from(f">{nrf}h", body, off))
        if np_ != stmt.n_params:
            self._ext_error(
                f"bind supplies {np_} parameters, statement needs "
                f"{stmt.n_params}", "08P01")
            return
        try:
            vals = []
            for i, raw in enumerate(raws):
                fmt = (pformats[i] if len(pformats) > 1
                       else (pformats[0] if pformats else 0))
                vals.append(self._decode_param(raw, stmt.param_oids[i], fmt))
        except Exception as e:  # noqa: BLE001
            self._ext_error(f"invalid parameter value: {e}", "22P02")
            return
        self.portals[portal] = _Portal(stmt, self._bind_sql(stmt, vals),
                                       rformats)
        self._msg(b"2", b"")  # BindComplete

    async def _run_portal(self, portal: _Portal, loop) -> bool:
        """Execute the portal's bound SQL once; cache the result."""
        if portal.result is not None:
            return True
        try:
            fast = self.server.db.try_fast_sql(portal.bound_sql)
            if fast is not None:  # KILL / SHOW PROCESSLIST: no pool queue
                portal.result = fast
                return True
            portal.result, self.session_db, self.session_tz = (
                await loop.run_in_executor(
                    self.server._db_executor, self.server.timed_sql_in_db,
                    portal.bound_sql, self.session_db, self.session_tz,
                    self.user))
            return True
        except GreptimeError as e:
            self._ext_error(e.msg, "42000")
        except Exception as e:  # noqa: BLE001
            self._ext_error(str(e), "XX000")
        return False

    def _portal_formats(self, portal: _Portal, ncols: int):
        rf = portal.result_formats
        if not rf:
            return [0] * ncols
        if len(rf) == 1:
            return rf * ncols
        return (rf + [0] * ncols)[:ncols]

    async def _on_describe(self, body: bytes, loop) -> None:
        kind, name = body[:1], body[1:].split(b"\x00")[0].decode(
            "utf-8", "replace")
        if kind == b"S":
            stmt = self.stmts.get(name)
            if stmt is None:
                self._ext_error(
                    f'prepared statement "{name}" does not exist', "26000")
                return
            self._msg(b"t", struct.pack(">H", stmt.n_params)
                      + b"".join(struct.pack(">i", o or 25)
                                 for o in stmt.param_oids))
            # Row schema without binding: trial-run SELECT-ish statements
            # (NULL-substituted when parameterised); NoData otherwise.
            head = stmt.sql.lstrip().lower()
            if head.startswith(("select", "show", "tql", "explain", "with",
                                "describe", "desc", "values")):
                trial = self._bind_sql(stmt, [None] * stmt.n_params)
                # schema probe only: don't pay for the rows twice
                if head.startswith(("select", "with", "values")):
                    trial = trial.rstrip().rstrip(";").rstrip()
                    trial, n_subs = _TAIL_LIMIT.subn("LIMIT 0", trial)
                    if not n_subs:
                        trial += " LIMIT 0"
                try:
                    r, _, _ = await loop.run_in_executor(
                        self.server._db_executor, self.server.db.sql_in_db,
                        trial, self.session_db, self.session_tz)
                    if r.column_names:
                        types = (r.column_types
                                 or ["String"] * len(r.column_names))
                        self._row_description(r.column_names, types)
                        return
                except Exception:  # noqa: BLE001 — schema probe only
                    pass
            self._msg(b"n", b"")  # NoData
            return
        portal = self.portals.get(name)
        if portal is None:
            self._ext_error(f'portal "{name}" does not exist', "34000")
            return
        if not await self._run_portal(portal, loop):
            return
        r = portal.result
        if r.column_names:
            types = r.column_types or ["String"] * len(r.column_names)
            formats = self._portal_formats(portal, len(r.column_names))
            self._row_description(r.column_names, types, formats)
        else:
            self._msg(b"n", b"")

    async def _on_execute(self, body: bytes, loop) -> None:
        z = body.index(b"\x00")
        name = body[:z].decode("utf-8", "replace")
        (max_rows,) = struct.unpack_from(">i", body, z + 1)
        portal = self.portals.get(name)
        if portal is None:
            self._ext_error(f'portal "{name}" does not exist', "34000")
            return
        if not await self._run_portal(portal, loop):
            return
        r = portal.result
        low = portal.bound_sql.lower().lstrip().rstrip(";")
        if r.column_names:
            types = r.column_types or ["String"] * len(r.column_names)
            oids = [_OID.get(t, 25) for t in types]
            formats = self._portal_formats(portal, len(r.column_names))
            chunk = (r.rows[portal.offset:portal.offset + max_rows]
                     if max_rows > 0 else r.rows[portal.offset:])
            for row in chunk:
                self._data_row(row, oids, formats)
            portal.offset += len(chunk)
            if max_rows > 0 and portal.offset < len(r.rows):
                self._msg(b"s", b"")  # PortalSuspended: more rows remain
            else:
                self._msg(b"C", f"SELECT {len(chunk)}\x00".encode())
        else:
            self._msg(b"C", _complete_tag(low, r) + b"\x00")

    def _on_close(self, body: bytes) -> None:
        kind, name = body[:1], body[1:].split(b"\x00")[0].decode(
            "utf-8", "replace")
        (self.stmts if kind == b"S" else self.portals).pop(name, None)
        self._msg(b"3", b"")  # CloseComplete

    async def run(self) -> None:
        try:
            if not await self.startup():
                self.writer.close()
                return
            loop = asyncio.get_running_loop()
            while True:
                tag = await self.reader.readexactly(1)
                ln = struct.unpack(">I", await self.reader.readexactly(4))[0]
                body = await self.reader.readexactly(ln - 4)
                if tag == b"X":  # Terminate
                    break
                if self._skip_until_sync and tag != b"S":
                    continue
                if tag in (b"P", b"B", b"D", b"E", b"C"):
                    # malformed frames (missing NUL, truncated counts)
                    # must produce an ErrorResponse, not kill the task
                    try:
                        if tag == b"P":
                            self._on_parse(body)
                        elif tag == b"B":
                            self._on_bind(body)
                        elif tag == b"D":
                            await self._on_describe(body, loop)
                        elif tag == b"E":
                            await self._on_execute(body, loop)
                        else:
                            self._on_close(body)
                    except Exception as e:  # noqa: BLE001
                        self._ext_error(
                            f"malformed {tag.decode()} message: {e}", "08P01")
                    await self.writer.drain()
                    continue
                if tag == b"S":  # Sync
                    self._skip_until_sync = False
                    # Drop exhausted portals; keep suspended/unexecuted
                    # ones alive so cursor-style fetch (pgJDBC fetchSize:
                    # Execute/Sync ... Execute/Sync) works across cycles.
                    self.portals = {
                        k: p for k, p in self.portals.items()
                        if p.result is None or (p.result.column_names
                                                and p.offset < len(p.result.rows))
                    }
                    self._ready()
                    await self.writer.drain()
                    continue
                if tag == b"H":  # Flush
                    await self.writer.drain()
                    continue
                if tag != b"Q":
                    self._error(f"unsupported message {tag!r}", "0A000")
                    self._ready()
                    await self.writer.drain()
                    continue
                sql = body.rstrip(b"\x00").decode("utf-8", "replace").strip()
                from greptimedb_tpu.utils.tracing import (
                    extract_sql_trace_context,
                )

                tctx = extract_sql_trace_context(sql)
                if tctx is not None:
                    self._notice(f"x-greptime-trace-id: {tctx[0]}")
                low = sql.lower().rstrip(";")
                if not low or low.startswith(("begin", "commit",
                                              "rollback", "discard")):
                    self._msg(b"C", b"SET\x00")
                    self._ready()
                    await self.writer.drain()
                    continue
                try:
                    fast = self.server.db.try_fast_sql(sql)
                    if fast is not None:  # KILL / SHOW PROCESSLIST
                        result = fast
                    else:
                        result, self.session_db, self.session_tz = (
                            await loop.run_in_executor(
                                self.server._db_executor,
                                self.server.timed_sql_in_db,
                                sql, self.session_db, self.session_tz,
                                self.user,
                            )
                        )
                    if result.column_names:
                        types = (result.column_types
                                 or ["String"] * len(result.column_names))
                        self._row_description(result.column_names, types)
                        for row in result.rows:
                            self._data_row(row)
                        self._msg(b"C", f"SELECT {len(result.rows)}\x00".encode())
                    else:
                        self._msg(b"C", _complete_tag(low, result) + b"\x00")
                except GreptimeError as e:
                    if low.startswith("set"):
                        self._msg(b"C", b"SET\x00")
                        self._ready()
                        await self.writer.drain()
                        continue
                    self._error(e.msg, "42000")
                except Exception as e:  # noqa: BLE001
                    self._error(str(e))
                self._ready()
                await self.writer.drain()
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            self.writer.close()


def _complete_tag(low: str, result) -> bytes:
    """CommandComplete tag by statement kind (drivers parse these)."""
    if low.startswith("insert"):
        return f"INSERT 0 {result.affected_rows}".encode()
    if low.startswith("delete"):
        return f"DELETE {result.affected_rows}".encode()
    if low.startswith("create table"):
        return b"CREATE TABLE"
    if low.startswith("create"):
        return b"CREATE"
    if low.startswith("drop"):
        return b"DROP"
    if low.startswith("alter"):
        return b"ALTER TABLE"
    if low.startswith("truncate"):
        return b"TRUNCATE TABLE"
    if low.startswith("use"):
        return b"USE"
    if low.startswith("set"):
        return b"SET"
    return b"OK"


class PostgresServer(ThreadedTcpServer):
    """TCP server on the PostgreSQL port (reference default 4003)."""

    name = "greptime-pg"
    protocol = "postgres"

    def __init__(self, db, host: str = "127.0.0.1", port: int = 4003, *,
                 ssl_context=None, auth_mode: str = "cleartext",
                 tls_require: bool = False):
        super().__init__(db, host, port)
        # TLS via SSLRequest upgrade; auth_mode "scram" switches password
        # auth to SCRAM-SHA-256 (reference pgwire default with TLS);
        # tls_require rejects clients that skip the upgrade
        self.ssl_context = ssl_context
        self.auth_mode = auth_mode
        self.tls_require = tls_require and ssl_context is not None

    async def _handle(self, reader, writer) -> None:
        await _PgConn(self, reader, writer).run()
