"""PostgreSQL wire protocol server (reference: pgwire 0.40, port 4003).

Protocol v3 simple-query flavor: startup/auth (trust), ParameterStatus,
RowDescription/DataRow/CommandComplete, ErrorResponse with SQLSTATE,
ReadyForQuery cycle, Terminate. Enough for psql and simple drivers'
text-mode queries; the extended (prepared) protocol is a later round.
"""

from __future__ import annotations

import asyncio
import struct
import threading

from greptimedb_tpu.errors import GreptimeError
from greptimedb_tpu.servers.tcp import ThreadedTcpServer

_OID = {
    "Boolean": 16, "Int8": 21, "Int16": 21, "Int32": 23, "Int64": 20,
    "UInt8": 21, "UInt16": 23, "UInt32": 20, "UInt64": 20,
    "Float32": 700, "Float64": 701,
    "TimestampSecond": 20, "TimestampMillisecond": 20,
    "TimestampMicrosecond": 20, "TimestampNanosecond": 20,
    "String": 25,
}


class _PgConn:
    def __init__(self, server: "PostgresServer", reader, writer):
        self.server = server
        self.reader = reader
        self.writer = writer
        self.session_db = "public"  # per-connection database
        self.session_tz = "UTC"

    def _msg(self, tag: bytes, payload: bytes) -> None:
        self.writer.write(tag + struct.pack(">I", len(payload) + 4) + payload)

    def _ready(self) -> None:
        self._msg(b"Z", b"I")

    def _error(self, msg: str, code: str = "XX000") -> None:
        fields = (b"SERROR\x00" + b"C" + code.encode() + b"\x00"
                  + b"M" + msg.encode("utf-8") + b"\x00" + b"\x00")
        self._msg(b"E", fields)

    async def startup(self) -> bool:
        while True:
            hdr = await self.reader.readexactly(4)
            ln = struct.unpack(">I", hdr)[0]
            body = await self.reader.readexactly(ln - 4)
            code = struct.unpack(">I", body[:4])[0]
            if code == 80877103:  # SSLRequest → decline
                self.writer.write(b"N")
                await self.writer.drain()
                continue
            if code == 196608:  # protocol 3.0
                params = {}
                parts = body[4:].split(b"\x00")
                for i in range(0, len(parts) - 1, 2):
                    if parts[i]:
                        params[parts[i].decode()] = parts[i + 1].decode()
                db = params.get("database")
                if db:
                    self.session_db = db
                provider = getattr(self.server.db, "user_provider", None)
                if provider is not None and provider.enabled:
                    # AuthenticationCleartextPassword
                    self._msg(b"R", struct.pack(">I", 3))
                    await self.writer.drain()
                    tag = await self.reader.readexactly(1)
                    ln = struct.unpack(
                        ">I", await self.reader.readexactly(4))[0]
                    pw_body = await self.reader.readexactly(ln - 4)
                    password = pw_body.rstrip(b"\x00").decode(
                        "utf-8", "replace")
                    user = params.get("user", "")
                    if tag != b"p" or not provider.check_plain(user, password):
                        self._error("password authentication failed for "
                                    f'user "{user}"', "28P01")
                        await self.writer.drain()
                        return False
                self._msg(b"R", struct.pack(">I", 0))  # AuthenticationOk
                for k, v in (("server_version", "16.3 (greptimedb-tpu)"),
                             ("server_encoding", "UTF8"),
                             ("client_encoding", "UTF8"),
                             ("DateStyle", "ISO"),
                             ("integer_datetimes", "on")):
                    self._msg(b"S", k.encode() + b"\x00" + v.encode() + b"\x00")
                self._msg(b"K", struct.pack(">II", 1, 0))
                self._ready()
                await self.writer.drain()
                return True
            self._error(f"unsupported protocol {code}", "0A000")
            await self.writer.drain()
            return False

    def _row_description(self, names, types) -> None:
        out = struct.pack(">H", len(names))
        for n, t in zip(names, types):
            oid = _OID.get(t, 25)
            out += (n.encode("utf-8") + b"\x00"
                    + struct.pack(">IhIhih", 0, 0, oid, -1, -1, 0))
        self._msg(b"T", out)

    def _data_row(self, row) -> None:
        out = struct.pack(">H", len(row))
        for v in row:
            if v is None:
                out += struct.pack(">i", -1)
            else:
                if isinstance(v, bool):
                    s = b"t" if v else b"f"
                elif isinstance(v, float):
                    s = repr(v).encode()
                else:
                    s = str(v).encode("utf-8")
                out += struct.pack(">i", len(s)) + s
        self._msg(b"D", out)

    async def run(self) -> None:
        try:
            if not await self.startup():
                self.writer.close()
                return
            loop = asyncio.get_running_loop()
            while True:
                tag = await self.reader.readexactly(1)
                ln = struct.unpack(">I", await self.reader.readexactly(4))[0]
                body = await self.reader.readexactly(ln - 4)
                if tag == b"X":  # Terminate
                    break
                if tag != b"Q":
                    self._error(f"unsupported message {tag!r}", "0A000")
                    self._ready()
                    await self.writer.drain()
                    continue
                sql = body.rstrip(b"\x00").decode("utf-8", "replace").strip()
                low = sql.lower().rstrip(";")
                if not low or low.startswith(("begin", "commit",
                                              "rollback", "discard")):
                    self._msg(b"C", b"SET\x00")
                    self._ready()
                    await self.writer.drain()
                    continue
                try:
                    result, self.session_db, self.session_tz = (
                        await loop.run_in_executor(
                            self.server._db_executor,
                            self.server.db.sql_in_db,
                            sql, self.session_db, self.session_tz,
                        )
                    )
                    if result.column_names:
                        types = (result.column_types
                                 or ["String"] * len(result.column_names))
                        self._row_description(result.column_names, types)
                        for row in result.rows:
                            self._data_row(row)
                        self._msg(b"C", f"SELECT {len(result.rows)}\x00".encode())
                    else:
                        self._msg(b"C", _complete_tag(low, result) + b"\x00")
                except GreptimeError as e:
                    if low.startswith("set"):
                        self._msg(b"C", b"SET\x00")
                        self._ready()
                        await self.writer.drain()
                        continue
                    self._error(e.msg, "42000")
                except Exception as e:  # noqa: BLE001
                    self._error(str(e))
                self._ready()
                await self.writer.drain()
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            self.writer.close()


def _complete_tag(low: str, result) -> bytes:
    """CommandComplete tag by statement kind (drivers parse these)."""
    if low.startswith("insert"):
        return f"INSERT 0 {result.affected_rows}".encode()
    if low.startswith("delete"):
        return f"DELETE {result.affected_rows}".encode()
    if low.startswith("create table"):
        return b"CREATE TABLE"
    if low.startswith("create"):
        return b"CREATE"
    if low.startswith("drop"):
        return b"DROP"
    if low.startswith("alter"):
        return b"ALTER TABLE"
    if low.startswith("truncate"):
        return b"TRUNCATE TABLE"
    if low.startswith("use"):
        return b"USE"
    if low.startswith("set"):
        return b"SET"
    return b"OK"


class PostgresServer(ThreadedTcpServer):
    """TCP server on the PostgreSQL port (reference default 4003)."""

    name = "greptime-pg"

    def __init__(self, db, host: str = "127.0.0.1", port: int = 4003):
        super().__init__(db, host, port)

    async def _handle(self, reader, writer) -> None:
        await _PgConn(self, reader, writer).run()
