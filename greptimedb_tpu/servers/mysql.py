"""MySQL wire protocol server (reference: opensrv-mysql fork, port 4002).

Protocol 4.1 with mysql_native_password auth (accept-all by default, like
the reference without a user provider).  Covers what MySQL clients and
drivers need for SELECT/DDL/DML round trips: handshake, OK/ERR/EOF
packets, column definitions with type mapping, text result rows
(COM_QUERY), and PREPARED STATEMENTS — COM_STMT_PREPARE/EXECUTE/CLOSE/
RESET with binary parameter decoding and binary result rows, which is
what connector libraries and BI tools actually use.
"""

from __future__ import annotations

import asyncio
import struct
import threading

from greptimedb_tpu.errors import GreptimeError
from greptimedb_tpu.servers.placeholders import scan_placeholders, sql_literal
from greptimedb_tpu.servers.tcp import ThreadedTcpServer

# capability flags
CLIENT_LONG_PASSWORD = 0x1
CLIENT_PROTOCOL_41 = 0x200
CLIENT_SECURE_CONNECTION = 0x8000
CLIENT_PLUGIN_AUTH = 0x80000
CLIENT_CONNECT_WITH_DB = 0x8
CLIENT_DEPRECATE_EOF = 0x1000000
CLIENT_SSL = 0x800

SERVER_CAPS = (
    CLIENT_LONG_PASSWORD | CLIENT_PROTOCOL_41 | CLIENT_SECURE_CONNECTION
    | CLIENT_PLUGIN_AUTH | CLIENT_CONNECT_WITH_DB
)

# column types (subset)
MYSQL_TYPE_LONGLONG = 0x08
MYSQL_TYPE_DOUBLE = 0x05
MYSQL_TYPE_VAR_STRING = 0xFD
MYSQL_TYPE_TIMESTAMP = 0x07
MYSQL_TYPE_TINY = 0x01

_TYPE_MAP = {
    "Int8": MYSQL_TYPE_TINY, "Int16": MYSQL_TYPE_LONGLONG,
    "Int32": MYSQL_TYPE_LONGLONG, "Int64": MYSQL_TYPE_LONGLONG,
    "UInt8": MYSQL_TYPE_TINY, "UInt16": MYSQL_TYPE_LONGLONG,
    "UInt32": MYSQL_TYPE_LONGLONG, "UInt64": MYSQL_TYPE_LONGLONG,
    "Float32": MYSQL_TYPE_DOUBLE, "Float64": MYSQL_TYPE_DOUBLE,
    "Boolean": MYSQL_TYPE_TINY,
    # timestamps travel as raw epoch ints in our text rows — declaring them
    # MYSQL_TYPE_TIMESTAMP would make clients parse them as datetimes
    "TimestampSecond": MYSQL_TYPE_LONGLONG,
    "TimestampMillisecond": MYSQL_TYPE_LONGLONG,
    "TimestampMicrosecond": MYSQL_TYPE_LONGLONG,
    "TimestampNanosecond": MYSQL_TYPE_LONGLONG,
}


def _lenenc_int(n: int) -> bytes:
    if n < 251:
        return bytes([n])
    if n < 1 << 16:
        return b"\xfc" + struct.pack("<H", n)
    if n < 1 << 24:
        return b"\xfd" + struct.pack("<I", n)[:3]
    return b"\xfe" + struct.pack("<Q", n)


def _lenenc_str(s: bytes) -> bytes:
    return _lenenc_int(len(s)) + s


class _Conn:
    def __init__(self, server: "MysqlServer", reader, writer):
        self.server = server
        self.reader = reader
        self.writer = writer
        self.seq = 0
        self.caps = 0
        self.session_db = "public"  # per-connection database
        self.session_tz = "UTC"
        self.user = ""  # handshake username = scheduler tenant identity
        # trace id of the last statement that carried a traceparent
        # comment (no headers on this wire — clients read it back via
        # SELECT @@greptime_trace_id, the MySQL analog of the HTTP
        # x-greptime-trace-id response header)
        self.last_trace_id = ""
        # prepared statements: stmt_id -> (sql, param_positions, types)
        self._stmt_map: dict[int, list] = {}
        self._stmt_next = 1

    # ---- packet IO -----------------------------------------------------
    async def read_packet(self) -> bytes | None:
        hdr = await self.reader.readexactly(4)
        ln = hdr[0] | (hdr[1] << 8) | (hdr[2] << 16)
        self.seq = (hdr[3] + 1) & 0xFF
        return await self.reader.readexactly(ln) if ln else b""

    def send(self, payload: bytes) -> None:
        ln = len(payload)
        self.writer.write(
            bytes([ln & 0xFF, (ln >> 8) & 0xFF, (ln >> 16) & 0xFF, self.seq])
            + payload
        )
        self.seq = (self.seq + 1) & 0xFF

    def send_ok(self, affected: int = 0) -> None:
        self.send(b"\x00" + _lenenc_int(affected) + _lenenc_int(0)
                  + struct.pack("<HH", 0x0002, 0))  # autocommit, no warnings

    def send_err(self, msg: str, errno: int = 1064, sqlstate: bytes = b"42000") -> None:
        self.send(b"\xff" + struct.pack("<H", errno) + b"#" + sqlstate
                  + msg.encode("utf-8")[:400])

    def send_eof(self) -> None:
        self.send(b"\xfe" + struct.pack("<HH", 0, 0x0002))

    # ---- handshake ------------------------------------------------------
    async def handshake(self) -> bool:
        import os as _os

        caps = SERVER_CAPS
        if self.server.ssl_context is not None:
            caps |= CLIENT_SSL
        salt = self.salt = _os.urandom(20).replace(b"\x00", b"\x01")
        payload = (
            b"\x0a" + b"8.4.2-greptimedb-tpu\x00"
            + struct.pack("<I", threading.get_ident() & 0xFFFFFFFF)
            + salt[:8] + b"\x00"
            + struct.pack("<H", caps & 0xFFFF)
            + bytes([0x21])  # utf8_general_ci
            + struct.pack("<H", 0x0002)  # status
            + struct.pack("<H", (caps >> 16) & 0xFFFF)
            + bytes([21])  # auth data len
            + b"\x00" * 10
            + salt[8:] + b"\x00"
            + b"mysql_native_password\x00"
        )
        self.seq = 0
        self.send(payload)
        await self.writer.drain()
        try:
            resp = await self.read_packet()
        except (asyncio.IncompleteReadError, ConnectionError):
            return False
        if resp is None:
            return False
        if (self.server.ssl_context is not None and len(resp) >= 4
                and struct.unpack("<I", resp[:4])[0] & CLIENT_SSL):
            # SSLRequest (a short handshake response: caps + max packet +
            # charset + 23 filler, NO username): switch to TLS, then read
            # the real handshake response over the encrypted stream
            from greptimedb_tpu.utils.tls import upgrade_server_tls

            self.reader, self.writer = await upgrade_server_tls(
                self.reader, self.writer, self.server.ssl_context)
            try:
                resp = await self.read_packet()
            except (asyncio.IncompleteReadError, ConnectionError):
                return False
            if resp is None:
                return False
        elif self.server.tls_require:
            self.send_err("server requires TLS connections",
                          errno=3159, sqlstate=b"HY000")
            await self.writer.drain()
            return False
        if len(resp) < 32:
            return False
        self.caps = struct.unpack("<I", resp[:4])[0]
        # username at offset 32 (after max_packet, charset, 23 reserved)
        rest = resp[32:]
        nul = rest.find(b"\x00")
        username = rest[:nul].decode("utf-8", "replace") if nul >= 0 else ""
        self.user = username
        after = rest[nul + 1:]
        auth_response = b""
        if after:
            alen = after[0]
            auth_response = after[1:1 + alen]
            after = after[1 + alen:]
        db = None
        if self.caps & CLIENT_CONNECT_WITH_DB and after:
            dbn = after.find(b"\x00")
            if dbn > 0:
                db = after[:dbn].decode("utf-8", "replace")
            after = after[dbn + 1:] if dbn >= 0 else b""
        client_plugin = ""
        if self.caps & CLIENT_PLUGIN_AUTH and after:
            pn = after.find(b"\x00")
            client_plugin = after[:pn if pn >= 0 else len(after)].decode(
                "utf-8", "replace")
        provider = getattr(self.server.db, "user_provider", None)
        if provider is not None and provider.enabled:
            if client_plugin and client_plugin != "mysql_native_password":
                # MySQL 8 clients default to caching_sha2_password; ask them
                # to switch plugins and resend the native scramble
                self.send(b"\xfe" + b"mysql_native_password\x00"
                          + self.salt + b"\x00")
                await self.writer.drain()
                try:
                    auth_response = await self.read_packet() or b""
                except (asyncio.IncompleteReadError, ConnectionError):
                    return False
            if not provider.check_mysql_native(username, auth_response,
                                               self.salt):
                self.send_err("Access denied for user "
                              f"'{username}'", errno=1045, sqlstate=b"28000")
                await self.writer.drain()
                return False
        if db:
            self.session_db = db
        self.send_ok()
        await self.writer.drain()
        return True

    # ---- result sets ----------------------------------------------------
    def _coldef(self, name: str, type_name: str) -> bytes:
        mtype = _TYPE_MAP.get(type_name, MYSQL_TYPE_VAR_STRING)
        charset = 0x3F if mtype != MYSQL_TYPE_VAR_STRING else 0x21
        flags = 0x20 if type_name.startswith("UInt") else 0  # UNSIGNED
        return (
            _lenenc_str(b"def") + _lenenc_str(b"") + _lenenc_str(b"")
            + _lenenc_str(b"") + _lenenc_str(name.encode("utf-8"))
            + _lenenc_str(b"") + b"\x0c"
            + struct.pack("<H", charset) + struct.pack("<I", 1024)
            + bytes([mtype]) + struct.pack("<H", flags) + bytes([0])
            + b"\x00\x00"
        )

    def send_resultset(self, result) -> None:
        names = result.column_names
        types = result.column_types or ["String"] * len(names)
        self.send(_lenenc_int(len(names)))
        for n, t in zip(names, types):
            self.send(self._coldef(n, t))
        self.send_eof()
        for row in result.rows:
            out = b""
            for v in row:
                if v is None:
                    out += b"\xfb"
                elif isinstance(v, bool):
                    out += _lenenc_str(b"1" if v else b"0")
                elif isinstance(v, float):
                    out += _lenenc_str(repr(v).encode())
                else:
                    out += _lenenc_str(str(v).encode("utf-8"))
            self.send(out)
        self.send_eof()

    # ---- command loop ----------------------------------------------------
    async def run(self) -> None:
        if not await self.handshake():
            self.writer.close()
            return
        while True:
            try:
                pkt = await self.read_packet()
            except (asyncio.IncompleteReadError, ConnectionError):
                break
            if not pkt:
                break
            cmd = pkt[0]
            if cmd == 0x01:  # COM_QUIT
                break
            if cmd == 0x0E:  # COM_PING
                self.send_ok()
            elif cmd == 0x02:  # COM_INIT_DB
                dbname = pkt[1:].decode("utf-8", "replace")
                try:
                    await self._query(f"USE {dbname}")
                except Exception:  # noqa: BLE001 (error already sent)
                    pass
            elif cmd == 0x03:  # COM_QUERY
                sql = pkt[1:].decode("utf-8", "replace")
                try:
                    await self._query(sql)
                except Exception:  # noqa: BLE001 (error already sent)
                    pass
            elif cmd == 0x16:  # COM_STMT_PREPARE
                self._stmt_prepare(pkt[1:].decode("utf-8", "replace"))
            elif cmd == 0x17:  # COM_STMT_EXECUTE
                try:
                    await self._stmt_execute(pkt)
                except Exception:  # noqa: BLE001 (error already sent)
                    pass
            elif cmd == 0x18:  # COM_STMT_SEND_LONG_DATA: NO response ever
                pass  # long-data streaming unsupported; execute will error
            elif cmd == 0x19:  # COM_STMT_CLOSE (no response)
                if len(pkt) >= 5:
                    (sid,) = struct.unpack_from("<I", pkt, 1)
                    self._stmt_map.pop(sid, None)
            elif cmd == 0x1A:  # COM_STMT_RESET
                self.send_ok()
            else:
                self.send_err(f"unsupported command 0x{cmd:02x}", errno=1047,
                              sqlstate=b"08S01")
            await self.writer.drain()
        self.writer.close()

    # ---- prepared statements (binary protocol) -----------------------
    @staticmethod
    def _param_positions(sql: str) -> list[int]:
        """Positions of real ? placeholders (shared literal/comment skip
        rules: servers/placeholders.py)."""
        return [start for start, _end, _no in scan_placeholders(sql, "qmark")]

    def _stmt_prepare(self, sql: str) -> None:
        st = self._stmt_map
        sid = self._stmt_next
        self._stmt_next += 1
        positions = self._param_positions(sql)
        n_params = len(positions)
        st[sid] = [sql, positions, None]  # [sql, positions, cached types]
        # COM_STMT_PREPARE_OK: status, stmt_id, num_columns (0: clients
        # read the real column set from the execute response), num_params
        self.send(
            b"\x00" + struct.pack("<I", sid) + struct.pack("<H", 0)
            + struct.pack("<H", n_params) + b"\x00" + struct.pack("<H", 0)
        )
        if n_params:
            for i in range(n_params):
                self.send(self._coldef(f"?{i}", "String"))
            self.send_eof()

    @staticmethod
    def _decode_binary_params(pkt: bytes, n_params: int,
                              cached_types: list | None):
        """COM_STMT_EXECUTE payload → (python values, types).  Clients
        send type bytes only when new_params_bound_flag=1 (first execute
        after a bind); later executes reuse the cached types."""
        off = 1 + 4 + 1 + 4  # cmd, stmt_id, flags, iteration_count
        nullmap = pkt[off: off + (n_params + 7) // 8]
        off += (n_params + 7) // 8
        new_bound = pkt[off]
        off += 1
        types: list = []
        if new_bound:
            for _ in range(n_params):
                types.append((pkt[off], pkt[off + 1]))
                off += 2
        elif cached_types:
            types = cached_types
        vals: list = []
        for i in range(n_params):
            if nullmap[i // 8] & (1 << (i % 8)):
                vals.append(None)
                continue
            t, unsigned = types[i] if types else (0xFD, 0)
            if t == 0x08:  # LONGLONG
                (v,) = struct.unpack_from(
                    "<Q" if unsigned & 0x80 else "<q", pkt, off)
                off += 8
            elif t == 0x03:  # LONG
                (v,) = struct.unpack_from(
                    "<I" if unsigned & 0x80 else "<i", pkt, off)
                off += 4
            elif t == 0x02:  # SHORT
                (v,) = struct.unpack_from(
                    "<H" if unsigned & 0x80 else "<h", pkt, off)
                off += 2
            elif t == 0x01:  # TINY
                v = pkt[off] if unsigned & 0x80 else struct.unpack_from(
                    "<b", pkt, off)[0]
                off += 1
            elif t == 0x05:  # DOUBLE
                (v,) = struct.unpack_from("<d", pkt, off)
                off += 8
            elif t == 0x04:  # FLOAT
                (v,) = struct.unpack_from("<f", pkt, off)
                off += 4
            elif t == 0x06:  # NULL
                v = None
            else:  # lenenc string-ish (VAR_STRING/STRING/BLOB/DECIMAL...)
                ln = pkt[off]
                off += 1
                if ln == 0xFC:
                    (ln,) = struct.unpack_from("<H", pkt, off)
                    off += 2
                elif ln == 0xFD:
                    ln = int.from_bytes(pkt[off:off + 3], "little")
                    off += 3
                v = pkt[off:off + ln].decode("utf-8", "replace")
                off += ln
            vals.append(v)
        return vals, types

    @staticmethod
    def _substitute(sql: str, positions: list[int], vals: list) -> str:
        out = []
        prev = 0
        for pos, v in zip(positions, vals):
            out.append(sql[prev:pos])
            out.append(sql_literal(v))
            prev = pos + 1
        out.append(sql[prev:])
        return "".join(out)

    async def _stmt_execute(self, pkt: bytes) -> None:
        (sid,) = struct.unpack_from("<I", pkt, 1)
        st = self._stmt_map
        if sid not in st:
            self.send_err(f"unknown statement id {sid}", errno=1243)
            return
        sql, positions, cached_types = st[sid]
        try:
            vals, types = self._decode_binary_params(
                pkt, len(positions), cached_types)
            st[sid][2] = types or cached_types
            bound = self._substitute(sql, positions, vals)
        except Exception as e:  # noqa: BLE001
            self.send_err(f"bad parameter block: {e}", errno=1210)
            return
        await self._query(bound, binary=True)

    def send_binary_resultset(self, result) -> None:
        names = result.column_names
        types = result.column_types or ["String"] * len(names)
        mtypes = [_TYPE_MAP.get(t, MYSQL_TYPE_VAR_STRING) for t in types]
        self.send(_lenenc_int(len(names)))
        for n, t in zip(names, types):
            self.send(self._coldef(n, t))
        self.send_eof()
        nbm = (len(names) + 7 + 2) // 8
        for row in result.rows:
            nullmap = bytearray(nbm)
            body = b""
            for i, (v, mt) in enumerate(zip(row, mtypes)):
                if v is None or (isinstance(v, float) and v != v):
                    nullmap[(i + 2) // 8] |= 1 << ((i + 2) % 8)
                    continue
                unsigned = types[i].startswith("UInt")
                if mt == MYSQL_TYPE_TINY:
                    body += struct.pack("<B" if unsigned else "<b", int(v))
                elif mt == MYSQL_TYPE_LONGLONG:
                    body += struct.pack("<Q" if unsigned else "<q", int(v))
                elif mt == MYSQL_TYPE_DOUBLE:
                    body += struct.pack("<d", float(v))
                else:
                    body += _lenenc_str(str(v).encode("utf-8"))
            self.send(b"\x00" + bytes(nullmap) + body)
        self.send_eof()

    async def _query(self, sql: str, binary: bool = False) -> None:
        loop = asyncio.get_running_loop()
        stripped = sql.strip().rstrip(";").strip()
        # common client housekeeping queries
        low = stripped.lower()
        if low.startswith(("commit", "rollback", "start transaction",
                           "begin")):
            self.send_ok()
            return
        if low in ("select @@version_comment limit 1",):
            from greptimedb_tpu.query.engine import QueryResult

            self.send_resultset(QueryResult(
                ["@@version_comment"], [["greptimedb-tpu"]],
                column_types=["String"]))
            return
        from greptimedb_tpu.utils.tracing import extract_sql_trace_context

        ctx = extract_sql_trace_context(stripped)
        if ctx is not None:
            self.last_trace_id = ctx[0]
        # comment-stripped compare (head only — a multi-MB INSERT must
        # not pay a regex pass): sqlcommenter middleware prefixes EVERY
        # statement, including the readback itself
        if "@@greptime_trace_id" in low[:512]:
            import re as _re

            low_nc = _re.sub(r"\s+", " ", _re.sub(
                r"/\*.*?\*/", " ", low[:512], flags=_re.S)).strip()
            if low_nc == "select @@greptime_trace_id":
                from greptimedb_tpu.query.engine import QueryResult

                self.send_resultset(QueryResult(
                    ["@@greptime_trace_id"], [[self.last_trace_id]],
                    column_types=["String"]))
                return
        try:
            # registry-only statements (KILL, SHOW PROCESSLIST) run inline
            # so they never queue behind the query they target
            fast = self.server.db.try_fast_sql(stripped)
            if fast is not None:
                result = fast
            else:
                result, self.session_db, self.session_tz = (
                    await loop.run_in_executor(
                        self.server._db_executor,
                        self.server.timed_sql_in_db,
                        stripped, self.session_db, self.session_tz,
                        self.user,
                    )
                )
        except GreptimeError as e:
            if low.startswith("set "):
                # exotic client SETs are compat no-ops, not errors
                self.send_ok()
                return
            self.send_err(e.msg, errno=1105, sqlstate=b"HY000")
            raise
        except Exception as e:  # noqa: BLE001
            self.send_err(str(e), errno=1105, sqlstate=b"HY000")
            raise
        if result.column_names:
            if binary:
                self.send_binary_resultset(result)
            else:
                self.send_resultset(result)
        else:
            self.send_ok(result.affected_rows)


class MysqlServer(ThreadedTcpServer):
    """TCP server on the MySQL port (reference default 4002)."""

    name = "greptime-mysql"
    protocol = "mysql"

    def __init__(self, db, host: str = "127.0.0.1", port: int = 4002, *,
                 ssl_context=None, tls_require: bool = False):
        super().__init__(db, host, port)
        self.ssl_context = ssl_context  # STARTTLS after the capability
        # handshake (MySQL protocol's SSLRequest), like opensrv's TLS
        self.tls_require = tls_require and ssl_context is not None

    async def _handle(self, reader, writer) -> None:
        await _Conn(self, reader, writer).run()
