"""HTTP server: SQL API, Prometheus API emulation, ingest protocols, admin.

Route surface mirrors the reference's make_app (src/servers/src/http.rs:775):

    /v1/sql                         SQL (greptime JSON envelope)
    /v1/promql                      native PromQL range query
    /v1/prometheus/api/v1/query          instant query
    /v1/prometheus/api/v1/query_range    range query
    /v1/prometheus/api/v1/labels         label names
    /v1/prometheus/api/v1/label/{n}/values
    /v1/prometheus/api/v1/series         series metadata
    /v1/prometheus/write            remote write (snappy protobuf)
    /v1/influxdb/api/v2/write       line protocol (also /v1/influxdb/write)
    /health /metrics /config /status

Runs the (synchronous) database in a thread-pool executor so the event
loop stays responsive; a dedicated thread hosts the loop so tests and the
standalone binary can start/stop it synchronously.
"""

from __future__ import annotations

import asyncio
import json
import re
import threading
import time

import numpy as np
from aiohttp import web

from greptimedb_tpu.errors import (
    GreptimeError, InvalidArguments, StatusCode, TableNotFound,
)
from greptimedb_tpu.query.engine import QueryResult
from greptimedb_tpu.utils import telemetry
from greptimedb_tpu.utils.snappy import decompress as snappy_decompress
from greptimedb_tpu.utils.tracing import (
    TRACER, parse_trace_id, parse_traceparent,
)

M_REQUESTS = telemetry.REGISTRY.counter(
    "greptime_http_requests_total", "HTTP requests", ("path", "code")
)
M_LATENCY = telemetry.REGISTRY.histogram(
    "greptime_http_request_duration_seconds", "HTTP latency", ("path",)
)
M_INGEST_ROWS = telemetry.REGISTRY.counter(
    "greptime_ingest_rows_total", "Rows ingested", ("protocol",)
)
M_INGEST_BYTES = telemetry.REGISTRY.counter(
    "greptime_ingest_bytes_total", "Wire bytes ingested (pre-decode)",
    ("protocol",)
)
# Per-protocol query latency (reference METRIC_HTTP_SQL_ELAPSED et al):
# one histogram shared by every wire surface — http SQL, the Prometheus
# API emulation, MySQL and PostgreSQL register their own labels on it.
M_PROTOCOL_QUERY = telemetry.REGISTRY.histogram(
    "greptime_protocol_query_duration_seconds",
    "Query latency by wire protocol", ("protocol",)
)


def _request_trace_context(request) -> tuple[str, str] | None:
    """Trace context for one query request: W3C ``traceparent`` first,
    then the reference's ``x-greptime-trace-id`` header; malformed values
    are ignored (fresh trace), never errors.  With the tracer on and no
    client context, a fresh trace id is minted so the response header
    always names the trace the query's spans landed in."""
    ctx = parse_traceparent(request.headers.get("traceparent"))
    if ctx is None:
        ctx = parse_trace_id(request.headers.get("x-greptime-trace-id"))
    if ctx is None and TRACER.enabled:
        ctx = (TRACER.new_trace_id(), "")
    return ctx


def _trace_headers(ctx: tuple[str, str] | None) -> dict:
    return {"x-greptime-trace-id": ctx[0]} if ctx else {}


def _result_to_json(res: QueryResult, t0: float) -> dict:
    if res.column_names:
        types = res.column_types or ["String"] * len(res.column_names)
        records = {
            "schema": {
                "column_schemas": [
                    {"name": n, "data_type": t}
                    for n, t in zip(res.column_names, types)
                ]
            },
            "rows": res.rows,
            "total_rows": len(res.rows),
        }
        output = [{"records": records}]
    else:
        output = [{"affectedrows": res.affected_rows}]
    return {
        "code": 0,
        "output": output,
        "execution_time_ms": int((time.perf_counter() - t0) * 1000),
    }


def _error_json(e: Exception) -> tuple[dict, int]:
    if isinstance(e, GreptimeError):
        code = e.status_code
        http = {
            StatusCode.TABLE_NOT_FOUND: 404,
            StatusCode.DATABASE_NOT_FOUND: 404,
            StatusCode.FLOW_NOT_FOUND: 404,
            StatusCode.INVALID_SYNTAX: 400,
            StatusCode.INVALID_ARGUMENTS: 400,
            StatusCode.PLAN_QUERY: 400,
            StatusCode.UNSUPPORTED: 400,
            StatusCode.TABLE_ALREADY_EXISTS: 409,
            StatusCode.DATABASE_ALREADY_EXISTS: 409,
            # deliberate backpressure (memory quota), not a server fault
            StatusCode.RUNTIME_RESOURCES_EXHAUSTED: 503,
            # per-tenant flow control (serving/admission.py): the client
            # should back off, not fail over
            StatusCode.RATE_LIMITED: 429,
            # scheduler deadline shed under overload
            StatusCode.DEADLINE_EXCEEDED: 503,
        }.get(code, 500)
        return {"code": int(code), "error": e.msg, "execution_time_ms": 0}, http
    return {"code": int(StatusCode.INTERNAL), "error": str(e)}, 500


class ThreadedAiohttpApp:
    """The ONE loop-hosting recipe for aiohttp servers on a daemon
    thread: build_app() on the loop thread, bind (port 0 = pick free),
    fail loudly if boot does not complete or errors, stop via the
    loop's own teardown. HttpServer and the frontend-role server both
    use this — boot/shutdown fixes land in one place."""

    thread_name = "greptime-http"

    def build_app(self):  # pragma: no cover — subclass contract
        raise NotImplementedError

    def start(self) -> None:
        if getattr(self, "_started", None) is None:
            self._started = threading.Event()

        def run_loop():
            loop = asyncio.new_event_loop()
            asyncio.set_event_loop(loop)
            self._loop = loop
            try:
                app = self.build_app()
                runner = web.AppRunner(app)
                loop.run_until_complete(runner.setup())
                site = web.TCPSite(
                    runner, self.host, self.port,
                    ssl_context=getattr(self, "ssl_context", None))
                loop.run_until_complete(site.start())
                self._runner = runner
                if self.port == 0:
                    self.port = runner.addresses[0][1]
            except BaseException as e:  # noqa: BLE001 — surfaced by start()
                self._start_error = e
                self._started.set()
                return
            self._started.set()
            loop.run_forever()
            loop.run_until_complete(runner.cleanup())
            loop.close()

        self._start_error = None
        self._thread = threading.Thread(target=run_loop, daemon=True,
                                        name=self.thread_name)
        self._thread.start()
        if not self._started.wait(timeout=10):
            raise RuntimeError("http server failed to start (boot timeout)")
        if self._start_error is not None:
            raise self._start_error

    def stop(self) -> None:
        if getattr(self, "_loop", None) is not None:
            self._loop.call_soon_threadsafe(self._loop.stop)
        if getattr(self, "_thread", None) is not None:
            self._thread.join(timeout=5)


class HttpServer(ThreadedAiohttpApp):
    def __init__(self, db, host: str = "127.0.0.1", port: int = 4000, *,
                 ssl_context=None):
        self.db = db
        self.host = host
        self.port = port
        self.ssl_context = ssl_context
        self._thread: threading.Thread | None = None
        self._loop: asyncio.AbstractEventLoop | None = None
        self._started = threading.Event()
        self._runner = None
        # the database is single-writer (region sequence assignment and
        # memtable mutation are unsynchronized, like mito2's per-region
        # worker loop) — serialize all DB work on one executor thread.
        # Registry-only statements (KILL, SHOW PROCESSLIST) bypass the
        # pool via db.try_fast_sql so they cannot queue behind the very
        # query they target.
        from concurrent.futures import ThreadPoolExecutor

        self._db_executor = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="greptime-db"
        )
        # with the serving scheduler enabled, query requests block in
        # scheduler.submit instead of executing here — a wider pool lets
        # concurrent clients queue into the scheduler (where priorities,
        # quotas and batching decide order) rather than serialize in
        # front of it.  Created lazily: scheduler-off servers never
        # allocate it.
        self._submit_pool: ThreadPoolExecutor | None = None
        # metric-ingest handlers get their own small pool: region writes
        # serialize per REGION (Region._write_lock), so concurrent
        # batches for different tables/regions decode+append in parallel
        # instead of queueing behind one db-executor thread.  Width 1
        # (GREPTIME_INGEST_WORKERS=1) restores the strictly serialized
        # seed behavior.
        import os as _os

        self._ingest_pool = ThreadPoolExecutor(
            max_workers=max(1, int(_os.environ.get(
                "GREPTIME_INGEST_WORKERS", "4"))),
            thread_name_prefix="greptime-ingest")

    # ------------------------------------------------------------------
    def build_app(self) -> web.Application:
        @web.middleware
        async def auth_middleware(request: web.Request, handler):
            provider = getattr(self.db, "user_provider", None)
            if (
                provider is not None
                and provider.enabled
                and request.path not in ("/health", "/ready", "/metrics")
            ):
                if not provider.check_http_basic(
                    request.headers.get("Authorization")
                ):
                    return web.json_response(
                        {"code": int(StatusCode.USER_PASSWORD_MISMATCH),
                         "error": "authentication failed"},
                        status=401,
                        headers={"WWW-Authenticate": 'Basic realm="greptime"'},
                    )
            return await handler(request)

        app = web.Application(client_max_size=64 * 1024 * 1024,
                              middlewares=[auth_middleware])
        r = app.router
        r.add_route("*", "/v1/sql", self.h_sql)
        r.add_route("*", "/v1/promql", self.h_promql)
        r.add_route("*", "/v1/prometheus/api/v1/query", self.h_prom_query)
        r.add_route("*", "/v1/prometheus/api/v1/query_range", self.h_prom_range)
        r.add_route("*", "/v1/prometheus/api/v1/labels", self.h_prom_labels)
        r.add_get("/v1/prometheus/api/v1/label/{name}/values", self.h_prom_label_values)
        r.add_route("*", "/v1/prometheus/api/v1/series", self.h_prom_series)
        r.add_post("/v1/prometheus/write", self.h_remote_write)
        r.add_post("/v1/prometheus/read", self.h_remote_read)
        r.add_post("/v1/influxdb/api/v2/write", self.h_influx_write)
        r.add_post("/v1/influxdb/write", self.h_influx_write)
        r.add_post("/v1/arrow/write", self.h_arrow_write)
        r.add_post("/v1/otlp/v1/metrics", self.h_otlp_metrics)
        r.add_post("/v1/otlp/v1/logs", self.h_otlp_logs)
        r.add_post("/v1/otel-arrow/v1/metrics", self.h_otel_arrow_metrics)
        r.add_post("/v1/loki/api/v1/push", self.h_loki_push)
        r.add_route("*", "/v1/loki/api/v1/query", self.h_loki_query)
        r.add_route("*", "/v1/loki/api/v1/query_range",
                    self.h_loki_query_range)
        r.add_route("*", "/v1/loki/api/v1/labels", self.h_loki_labels)
        r.add_get("/v1/loki/api/v1/label/{name}/values",
                  self.h_loki_label_values)
        r.add_route("*", "/v1/loki/api/v1/series", self.h_loki_series)
        r.add_post("/v1/logs", self.h_log_query)
        r.add_post("/v1/otlp/v1/traces", self.h_otlp_traces)
        r.add_get("/v1/jaeger/api/services", self.h_jaeger_services)
        r.add_get("/v1/jaeger/api/operations", self.h_jaeger_operations)
        r.add_get("/v1/jaeger/api/services/{service}/operations",
                  self.h_jaeger_service_operations)
        r.add_get("/v1/jaeger/api/traces/{trace_id}", self.h_jaeger_trace)
        r.add_get("/v1/jaeger/api/traces", self.h_jaeger_find)
        r.add_post("/v1/opentsdb/api/put", self.h_opentsdb_put)
        r.add_post("/v1/elasticsearch/_bulk", self.h_es_bulk)
        r.add_post("/v1/elasticsearch/{index}/_bulk", self.h_es_bulk)
        r.add_get("/v1/elasticsearch/", self.h_es_info)
        r.add_get("/v1/elasticsearch/_license", self.h_es_license)
        r.add_post("/v1/splunk/services/collector", self.h_splunk_hec)
        r.add_post("/v1/splunk/services/collector/event", self.h_splunk_hec)
        r.add_post("/v1/pipelines/{name}", self.h_pipeline_upsert)
        r.add_delete("/v1/pipelines/{name}", self.h_pipeline_delete)
        r.add_get("/v1/pipelines", self.h_pipeline_list)
        r.add_post("/v1/ingest", self.h_ingest)
        r.add_get("/health", self.h_health)
        r.add_route("*", "/debug/log_level", self.h_log_level)
        r.add_get("/debug/prof/cpu", self.h_prof_cpu)
        r.add_route("*", "/debug/prof/mem", self.h_prof_mem)
        r.add_get("/ready", self.h_health)
        r.add_get("/metrics", self.h_metrics)
        r.add_get("/config", self.h_config)
        r.add_get("/status", self.h_status)
        r.add_get("/v1/slo", self.h_slo)
        r.add_get("/dashboard", self.h_dashboard)
        r.add_get("/dashboard/", self.h_dashboard)
        return app

    async def _call(self, fn, *args):
        return await asyncio.get_running_loop().run_in_executor(
            self._db_executor, fn, *args
        )

    async def _call_ingest(self, fn, *args):
        return await asyncio.get_running_loop().run_in_executor(
            self._ingest_pool, fn, *args
        )

    def _admit_ingest(self, request: web.Request, wire_bytes: int,
                      tenant: str | None = None):
        """Per-tenant write admission (PR 7 discipline, applied to the
        write path): reserve the batch's estimated decoded footprint
        against the tenant's memory budget and count it in flight, so
        sustained ingest cannot starve interactive queries of their
        memory/concurrency quotas.  Returns a release callable (pair it
        in a finally); raises RateLimited (429) / ResourcesExhausted
        (503) — the same error surface queries get."""
        sched = self.db.scheduler
        if sched is None:
            return lambda: None
        adm = sched.admission
        if tenant is None:
            tenant = self._tenant(request)
        # decoded columnar batches run ~4x the wire bytes (numbers widen
        # to float64/int64, tag codes add int32 per row)
        est = wire_bytes * 4
        adm.admit(tenant, est)
        return lambda: adm.release(tenant, est)

    async def _call_query(self, fn, *args):
        """Query-path executor hop: the scheduler-submit pool when the
        serving scheduler is on (submit blocks until the worker finishes
        the entry), the single db worker otherwise."""
        ex = self._db_executor
        if self.db.scheduler is not None:
            if self._submit_pool is None:
                from concurrent.futures import ThreadPoolExecutor

                self._submit_pool = ThreadPoolExecutor(
                    max_workers=32, thread_name_prefix="greptime-submit")
            ex = self._submit_pool
        return await asyncio.get_running_loop().run_in_executor(
            ex, fn, *args)

    def _tenant(self, request: web.Request) -> str:
        """Tenant identity for admission: the authenticated basic-auth
        username wins (a client must not be able to shed its quotas by
        sending a different x-greptime-tenant header); the header is the
        fallback for unauthenticated deployments, else "default"."""
        auth = request.headers.get("Authorization", "")
        if auth.startswith("Basic "):
            import base64

            try:
                creds = base64.b64decode(auth[6:]).decode("utf-8")
                user = creds.split(":", 1)[0]
                if user:
                    return user
            except Exception:  # noqa: BLE001 — auth middleware rejects
                pass
        return request.headers.get("x-greptime-tenant") or "default"

    def _loki_tenant(self, request: web.Request) -> str:
        """Loki surfaces speak multi-tenancy via ``X-Scope-OrgID``
        (Loki's org header): it maps onto the SAME per-tenant admission
        budgets as every other surface.  Authenticated identity still
        wins — a client must not shed its quotas by sending a different
        org id."""
        auth = request.headers.get("Authorization", "")
        if auth.startswith("Basic "):
            return self._tenant(request)
        org = request.headers.get("X-Scope-OrgID")
        if org:
            return str(org)
        return self._tenant(request)

    @staticmethod
    def _priority(request: web.Request) -> str | None:
        p = request.headers.get("x-greptime-priority")
        return p if p in ("interactive", "normal", "background") else None

    async def _param(self, request: web.Request, name: str, default=None):
        if name in request.query:
            return request.query[name]
        if request.method == "POST" and request.content_type in (
            "application/x-www-form-urlencoded", "multipart/form-data",
        ):
            form = await request.post()
            if name in form:
                return form[name]
        return default

    # ---- handlers ------------------------------------------------------
    def _traced_sql(self, sql: str, ctx: tuple[str, str] | None):
        """Executor-thread entry for /v1/sql: installs the request's
        trace context on the DB thread (thread-locals do not cross the
        run_in_executor boundary) so the statement's span tree is rooted
        under the client's traceparent."""
        with TRACER.trace_context(ctx):
            return self.db.sql(sql)

    async def h_sql(self, request: web.Request) -> web.Response:
        t0 = time.perf_counter()
        sql = await self._param(request, "sql")
        ctx = _request_trace_context(request)
        hold: list = []  # caller-held SLO sample (see scheduler._finish)
        with M_LATENCY.labels("/v1/sql").time():
            if not sql:
                M_REQUESTS.labels("/v1/sql", "400").inc()
                return web.json_response(
                    {"code": int(StatusCode.INVALID_ARGUMENTS),
                     "error": "missing sql parameter"}, status=400)
            try:
                # KILL / SHOW PROCESSLIST run inline (sub-ms, registry
                # lock only) so they never queue behind the statement
                # they target on the single-worker db executor
                res = self.db.try_fast_sql(sql)
                timed = res is None
                if res is None:
                    sched = self.db.scheduler
                    if sched is not None:
                        tenant = self._tenant(request)
                        prio = self._priority(request)
                        client = request.remote or ""
                        res = await self._call_query(
                            lambda: sched.submit(
                                sql, tenant=tenant, priority=prio,
                                client=client, trace_ctx=ctx,
                                protocol="http", slo_hold=hold))
                    else:
                        res = await self._call(
                            self._traced_sql, sql, ctx)
                # serialize BEFORE observing (ISSUE 18 fix): the JSON
                # envelope build is part of what the client waits for,
                # and the histogram previously closed at submit-return —
                # under-reporting exactly the rows-heavy responses.  The
                # scheduler's SLO sample is caller-held over the same
                # span (record_held below), so sketch and histogram
                # agree by construction.
                body = _result_to_json(res, t0)
                if timed:
                    M_PROTOCOL_QUERY.labels("http").observe(
                        time.perf_counter() - t0)
                    sched = self.db.scheduler
                    if sched is not None and hold:
                        sched.record_held(hold)
                M_REQUESTS.labels("/v1/sql", "200").inc()
                return web.json_response(body,
                                         headers=_trace_headers(ctx))
            except Exception as e:  # noqa: BLE001
                sched = self.db.scheduler
                if sched is not None and hold:
                    # serialization failed after a clean execution: the
                    # held sample still records (exactly-one invariant)
                    sched.record_held(hold)
                body, status = _error_json(e)
                M_REQUESTS.labels("/v1/sql", str(status)).inc()
                return web.json_response(body, status=status,
                                         headers=_trace_headers(ctx))

    async def _eval_promql(self, query: str, start: float, end: float,
                           step: float, lookback: float | None = None,
                           trace_ctx: tuple[str, str] | None = None,
                           tenant: str = "default"):
        from greptimedb_tpu.promql.engine import DEFAULT_LOOKBACK_S, PromEvaluator
        from greptimedb_tpu.promql.parser import parse_promql

        expr = parse_promql(query)

        def run():
            with M_PROTOCOL_QUERY.labels("prometheus").time():
                with TRACER.trace_context(trace_ctx):
                    ev = PromEvaluator(self.db, start, end, step,
                                       lookback or DEFAULT_LOOKBACK_S)
                    res = ev.eval(expr)
            return res, ev.steps_ms()

        sched = self.db.scheduler
        if sched is not None:
            # PromQL evaluations submit like SQL queries: per-tenant
            # admission, interactive priority, deadline shedding (no
            # cross-query batching — the PromQL layout caches already
            # dedupe the heavy state)
            return await self._call_query(
                lambda: sched.submit_fn(run, tenant=tenant,
                                        label=query[:256],
                                        protocol="prometheus"))
        return await self._call(run)

    async def h_prom_range(self, request: web.Request) -> web.Response:
        ctx = _request_trace_context(request)
        try:
            query = await self._param(request, "query")
            start = _parse_prom_time(await self._param(request, "start"))
            end = _parse_prom_time(await self._param(request, "end"))
            step = _parse_prom_duration(await self._param(request, "step", "60"))
            with M_LATENCY.labels("/v1/prometheus/api/v1/query_range").time():
                res, steps = await self._eval_promql(
                    query, start, end, step, trace_ctx=ctx,
                    tenant=self._tenant(request))
            from greptimedb_tpu.promql.format import range_payload

            M_REQUESTS.labels("/v1/prometheus/api/v1/query_range", "200").inc()
            return web.json_response(range_payload(res, steps),
                                     headers=_trace_headers(ctx))
        except Exception as e:  # noqa: BLE001
            M_REQUESTS.labels("/v1/prometheus/api/v1/query_range", "400").inc()
            return web.json_response(
                {"status": "error", "errorType": "bad_data", "error": str(e)},
                status=400)

    async def h_prom_query(self, request: web.Request) -> web.Response:
        ctx = _request_trace_context(request)
        try:
            query = await self._param(request, "query")
            t = _parse_prom_time(await self._param(request, "time", str(time.time())))
            with M_LATENCY.labels("/v1/prometheus/api/v1/query").time():
                res, steps = await self._eval_promql(
                    query, t, t, 1, trace_ctx=ctx,
                    tenant=self._tenant(request))
            from greptimedb_tpu.promql.format import instant_payload

            M_REQUESTS.labels("/v1/prometheus/api/v1/query", "200").inc()
            return web.json_response(instant_payload(res, steps),
                                     headers=_trace_headers(ctx))
        except Exception as e:  # noqa: BLE001
            M_REQUESTS.labels("/v1/prometheus/api/v1/query", "400").inc()
            return web.json_response(
                {"status": "error", "errorType": "bad_data", "error": str(e)},
                status=400)

    async def h_prom_labels(self, request: web.Request) -> web.Response:
        def run():
            names = {"__name__"}
            for t in self.db.catalog.list_tables(self.db.current_db):
                for c in t.schema.tag_columns:
                    names.add(c.name)
            return sorted(names)

        data = await self._call(run)
        return web.json_response({"status": "success", "data": data})

    async def h_prom_label_values(self, request: web.Request) -> web.Response:
        name = request.match_info["name"]

        def run():
            if name == "__name__":
                return sorted(
                    t.name for t in self.db.catalog.list_tables(self.db.current_db)
                )
            values = set()
            for t in self.db.catalog.list_tables(self.db.current_db):
                if any(c.name == name for c in t.schema.tag_columns):
                    # _table_view merges all partitions' dictionaries
                    view = self.db._table_view(t.name)
                    enc = view.encoders.get(name)
                    if enc:
                        values.update(str(v) for v in enc.values())
            return sorted(values)

        data = await self._call(run)
        return web.json_response({"status": "success", "data": data})

    async def h_prom_series(self, request: web.Request) -> web.Response:
        matches = request.query.getall("match[]", [])
        if not matches and request.method == "POST":
            form = await request.post()
            matches = form.getall("match[]", [])

        def run():
            from greptimedb_tpu.promql.engine import SelectorData
            from greptimedb_tpu.promql.parser import parse_promql, VectorSelector

            out = []
            for m in matches:
                e = parse_promql(m)
                if not isinstance(e, VectorSelector):
                    continue
                try:
                    d = SelectorData(self.db, e.metric)
                except GreptimeError:
                    continue
                _tsids, _sel_dev, labels = d.select_series(e.matchers)
                for lab in labels:
                    item = {"__name__": e.metric}
                    item.update({k: str(v) for k, v in lab.items()})
                    out.append(item)
            return out

        data = await self._call(run)
        return web.json_response({"status": "success", "data": data})

    async def h_remote_write(self, request: web.Request) -> web.Response:
        from greptimedb_tpu.servers.protocols import parse_remote_write

        body = await request.read()
        if request.headers.get("Content-Encoding", "snappy").lower() == "snappy":
            try:
                body = snappy_decompress(body)
            except ValueError as e:
                return web.json_response({"error": f"snappy: {e}"}, status=400)

        def run():
            from greptimedb_tpu.errors import InvalidArguments

            tables = parse_remote_write(body)
            total = 0
            for table, cols in tables.items():
                # Prometheus metrics multiplex onto the metric engine's
                # physical region (reference default for remote write);
                # names already taken by plain tables fall back to them so
                # one conflicting metric can't wedge the whole batch.
                # The DDL lock serializes ONLY logical-table/label-set
                # growth across the ingest pool — the append itself runs
                # outside it (the shared physical region's own write lock
                # serializes appends), so one batch's WAL flush never
                # stalls unrelated tables' ingest on the DDL lock.
                name = _safe_table(table)
                try:
                    with _INGEST_DDL_LOCK:
                        self.db.metric_engine.ensure_logical(
                            name, list(cols.get("__tags__") or []))
                    total += self.db.metric_engine.write(name, cols,
                                                         ensure=False)
                except InvalidArguments:
                    total += _ingest_columns(self.db, name, cols)
            cache = getattr(self.db, "cache", None)
            if tables and cache is not None:
                # hot-tail: freshly acked samples scatter into the
                # physical region's resident grid tail (if any)
                cache.extend_hot_tail(self.db.metric_engine.physical_region())
            if self.db.flow_engine.flows:
                with _INGEST_DDL_LOCK:
                    for table, cols in tables.items():
                        # metric-engine writes multiplex regions;
                        # conservative appendable=False is handled upstream
                        # via dirtying, so pass the chunk and let pure
                        # appends stream
                        self.db.flow_engine.on_write(_safe_table(table),
                                                     cols["ts"], data=cols)
                    self.db.flow_engine.run_all()
            return total

        M_INGEST_BYTES.labels("prom_remote_write").inc(len(body))
        try:
            release = self._admit_ingest(request, len(body))
            try:
                n = await self._call_ingest(run)
            finally:
                release()
            M_INGEST_ROWS.labels("prom_remote_write").inc(n)
            return web.Response(status=204)
        except Exception as e:  # noqa: BLE001
            body_json, status = _error_json(e)
            return web.json_response(body_json, status=status)

    async def h_influx_write(self, request: web.Request) -> web.Response:
        from greptimedb_tpu.servers.protocols import parse_line_protocol

        # raw bytes: the vectorized parser consumes them directly (one
        # C-level transform + pyarrow CSV); the legacy path decodes
        body = await request.read()
        precision = request.query.get("precision", "ns")
        M_INGEST_BYTES.labels("influxdb").inc(len(body))

        def run():
            tables = parse_line_protocol(body, precision)
            total = 0
            for table, cols in tables.items():
                total += _ingest_columns(self.db, table, cols)
            return total

        try:
            release = self._admit_ingest(request, len(body))
            try:
                n = await self._call_ingest(run)
            finally:
                release()
            M_INGEST_ROWS.labels("influxdb").inc(n)
            return web.Response(status=204)
        except Exception as e:  # noqa: BLE001
            body_json, status = _error_json(e)
            return web.json_response(body_json, status=status)

    async def h_arrow_write(self, request: web.Request) -> web.Response:
        """Arrow IPC bulk insert — the standalone HTTP surface of the
        in-cluster Flight do_put plane (reference gRPC bulk inserts).
        Body: one Arrow IPC stream; ``?table=`` names the target.  The
        highest-rate wire format: columns land as NumPy arrays /
        dictionary codes with zero per-row decode (protocols.py
        ``parse_arrow_bulk``)."""
        from greptimedb_tpu.servers.protocols import parse_arrow_bulk

        table = request.query.get("table", "")
        body = await request.read()
        M_INGEST_BYTES.labels("arrow").inc(len(body))

        def run():
            if not table:
                raise InvalidArguments("arrow write needs ?table=")
            cols = parse_arrow_bulk(body)
            return _ingest_columns(self.db, table, cols)

        try:
            release = self._admit_ingest(request, len(body))
            try:
                n = await self._call_ingest(run)
            finally:
                release()
            M_INGEST_ROWS.labels("arrow").inc(n)
            return web.json_response({"rows": n})
        except Exception as e:  # noqa: BLE001
            body_json, status = _error_json(e)
            return web.json_response(body_json, status=status)

    async def h_otlp_metrics(self, request: web.Request) -> web.Response:
        from greptimedb_tpu.servers.otlp import parse_otlp_metrics

        # aiohttp transparently inflates Content-Encoding: gzip on read()
        try:
            body = await request.read()
        except Exception as e:  # noqa: BLE001 (bad content encoding etc.)
            return web.json_response({"error": f"body: {e}"}, status=400)

        def run():
            tables = parse_otlp_metrics(body)
            total = 0
            for table, cols in tables.items():
                total += _ingest_columns(self.db, table, cols)
            return total

        M_INGEST_BYTES.labels("otlp_metrics").inc(len(body))
        try:
            release = self._admit_ingest(request, len(body))
            try:
                n = await self._call_ingest(run)
            finally:
                release()
            M_INGEST_ROWS.labels("otlp_metrics").inc(n)
            return web.json_response({"partialSuccess": {}})
        except Exception as e:  # noqa: BLE001
            body_json, status = _error_json(e)
            return web.json_response(body_json, status=status)


    async def h_remote_read(self, request: web.Request) -> web.Response:
        """Prometheus remote read (reference src/servers/src/http/
        prom_store.rs): snappy ReadRequest in, snappy ReadResponse of raw
        samples out — series resolved by the same inverted-index matcher
        machinery the PromQL engine uses."""
        from greptimedb_tpu.promql.engine import SelectorData
        from greptimedb_tpu.promql.parser import LabelMatcher
        from greptimedb_tpu.servers.protocols import (
            encode_read_response, parse_remote_read,
        )
        from greptimedb_tpu.storage.memtable import TSID
        from greptimedb_tpu.utils.snappy import compress as snappy_compress

        body = await request.read()
        if request.headers.get("Content-Encoding", "snappy").lower() == "snappy":
            try:
                body = snappy_decompress(body)
            except Exception as e:  # noqa: BLE001
                return web.json_response({"error": f"snappy: {e}"}, status=400)

        def run():
            queries = parse_remote_read(body)
            results = []
            for q in queries:
                metric = next(
                    (v for op, n, v in q["matchers"]
                     if n == "__name__" and op == "="), None)
                if metric is None:
                    raise InvalidArguments(
                        "remote read needs an equality __name__ matcher")
                matchers = [LabelMatcher(n, op, v)
                            for op, n, v in q["matchers"]
                            if n != "__name__"]
                try:
                    data = SelectorData(self.db, metric)
                except TableNotFound:
                    results.append([])  # unknown metric: empty, not 5xx
                    continue
                tsids, _sel_dev, labels = data.select_series(matchers)
                field = data.field_column(matchers)
                # equality matchers prune SSTs via the bloom sidecars
                tag_filters = {
                    m.name: {m.value} for m in matchers
                    if m.op == "=" and m.name != "__field__"
                } or None
                host = data.region.scan_host(
                    (q["start_ms"], q["end_ms"] + 1),
                    tag_filters=tag_filters)
                import numpy as _np

                row_tsid = _np.asarray(host[TSID])
                keep = _np.isin(row_tsid, tsids)
                row_tsid = row_tsid[keep]
                ts_col = _np.asarray(
                    host[data.schema.time_index.name])[keep]
                val_col = _np.asarray(host[field])[keep]
                # scan_host rows are (tsid, ts)-sorted already: one
                # unique() split instead of a per-row Python loop
                uniq, starts = _np.unique(row_tsid, return_index=True)
                bounds = _np.append(starts, len(row_tsid))
                by_tsid = {int(t): i for i, t in enumerate(tsids)}
                series = []
                for j, t in enumerate(uniq):
                    li = by_tsid.get(int(t))
                    if li is None:
                        continue
                    sl = slice(bounds[j], bounds[j + 1])
                    vals, tss = val_col[sl], ts_col[sl]
                    ok = vals == vals  # NaN = absent
                    if not ok.any():
                        continue
                    lab = dict(labels[li])
                    lab["__name__"] = metric
                    series.append((lab, list(zip(
                        vals[ok].tolist(), tss[ok].tolist()))))
                results.append(series)
            return snappy_compress(encode_read_response(results))

        try:
            payload = await self._call(run)
            return web.Response(
                body=payload,
                content_type="application/x-protobuf",
                headers={"Content-Encoding": "snappy"},
            )
        except Exception as e:  # noqa: BLE001
            body_json, status = _error_json(e)
            return web.json_response(body_json, status=status)

    async def h_otlp_logs(self, request: web.Request) -> web.Response:
        """OTLP/HTTP logs ingest (reference src/servers/src/otlp/logs.rs):
        protobuf ExportLogsServiceRequest → rows in the log table
        (x-greptime-log-table-name, default opentelemetry_logs), optionally
        shaped by a named pipeline (x-greptime-pipeline-name; the default
        identity mapping mirrors greptime_identity)."""
        from greptimedb_tpu.servers.otlp import parse_otlp_logs

        table = request.headers.get("x-greptime-log-table-name",
                                    "opentelemetry_logs")
        pname = request.headers.get("x-greptime-pipeline-name")
        try:
            body = await request.read()
        except Exception as e:  # noqa: BLE001
            return web.json_response({"error": f"body: {e}"}, status=400)

        def run():
            rows = parse_otlp_logs(body)
            if not rows:
                return 0
            if pname and pname != "greptime_identity":
                pipe = self._pipelines().get(pname)
                cols = pipe.run(rows)
            else:
                names = list(rows[0].keys())
                cols = {k: [r.get(k) for r in rows] for k in names}
                cols["__tags__"] = []
                cols["__fields__"] = [n for n in names if n != "ts"]
            if not cols.get("ts"):
                return 0
            return _ingest_columns(self.db, table, cols, append_mode=True)

        try:
            n = await self._call(run)
            M_INGEST_ROWS.labels("otlp_logs").inc(n)
            return web.json_response({"partialSuccess": {}})
        except Exception as e:  # noqa: BLE001
            body_json, status = _error_json(e)
            return web.json_response(body_json, status=status)

    async def h_otel_arrow_metrics(self, request: web.Request) -> web.Response:
        """OTel-Arrow (OTAP) columnar metrics ingest (reference
        src/servers/src/otel_arrow.rs + otel-arrow-rust).  The body is
        an Arrow IPC stream of flattened univariate metric batches —
        columns: metric name (``name``/``metric_name``), a time column
        (``time_unix_nano``/``ts``/``timestamp``), a value column
        (``value``/``double_value``/``int_value``), every other column
        an attribute (tag).  Transport differs from the reference (HTTP
        body instead of a gRPC ArrowMetricsService stream — this server
        is HTTP-first; the in-cluster bulk path is Flight do_put), the
        data model is the same: one record batch, zero row-wise decode.
        """
        import pyarrow.ipc as pa_ipc

        try:
            body = await request.read()
        except Exception as e:  # noqa: BLE001
            return web.json_response({"error": f"body: {e}"}, status=400)

        def run():
            import io

            try:
                reader = pa_ipc.open_stream(io.BytesIO(body))
                tbl = reader.read_all()
            except Exception as e:
                raise InvalidArguments(f"bad arrow ipc stream: {e}")
            names = set(tbl.column_names)
            name_col = next(
                (c for c in ("name", "metric_name") if c in names), None)
            time_col = next(
                (c for c in ("time_unix_nano", "ts", "timestamp")
                 if c in names), None)
            val_col = next(
                (c for c in ("value", "double_value", "int_value")
                 if c in names), None)
            if not (name_col and time_col and val_col):
                raise InvalidArguments(
                    "otel-arrow batch needs name, time and value columns")
            metric_names = tbl.column(name_col).to_pylist()
            times = tbl.column(time_col).to_pylist()
            vals = tbl.column(val_col).to_pylist()
            if any(v is None for v in metric_names) or any(
                    t is None for t in times) or any(
                    v is None for v in vals):
                raise InvalidArguments(
                    "otel-arrow batch has null name/time/value cells")
            if time_col == "time_unix_nano":
                times = [t // 1_000_000 for t in times]
            attr_cols = {
                c: tbl.column(c).to_pylist() for c in tbl.column_names
                if c not in (name_col, time_col, val_col)
            }
            per_table: dict[str, list[int]] = {}
            for i, m in enumerate(metric_names):
                # prometheus-style name normalization (reference
                # translate_metric_name/normalize_metric_name): dots and
                # other specials → '_' so names never split as db.table
                safe = re.sub(r"[^a-zA-Z0-9_:]", "_", str(m))
                per_table.setdefault(safe, []).append(i)
            total = 0
            for table, idxs in per_table.items():
                tags = sorted(attr_cols)
                cols: dict[str, list] = {
                    k: [str(attr_cols[k][i]) if attr_cols[k][i] is not None
                        else "" for i in idxs]
                    for k in tags
                }
                cols["ts"] = [times[i] for i in idxs]
                cols["val"] = [vals[i] for i in idxs]
                cols["__tags__"] = tags
                cols["__fields__"] = ["val"]
                total += _ingest_columns(self.db, table, cols)
            return total

        try:
            n = await self._call(run)
            M_INGEST_ROWS.labels("otel_arrow").inc(n)
            return web.json_response({"status": {"status_code": 0},
                                      "rows": n})
        except Exception as e:  # noqa: BLE001
            body_json, status = _error_json(e)
            return web.json_response(body_json, status=status)

    async def h_loki_push(self, request: web.Request) -> web.Response:
        """Loki push (reference src/servers/src/http/loki.rs), BOTH wire
        forms: JSON and snappy-compressed protobuf (logproto.PushRequest
        — what promtail/the Grafana agent actually send).  Streams land
        in ``loki_logs`` with stream labels as tags, the line in ``line``
        (string field), and the admitted tenant (``X-Scope-OrgID``) as a
        ``tenant`` tag — queryable and joinable like any other label."""
        try:
            body = await request.read()
        except Exception as e:  # noqa: BLE001 (bad content encoding etc.)
            return web.json_response({"error": f"body: {e}"}, status=400)
        ctype = request.content_type or ""
        tenant = self._loki_tenant(request)

        def run():
            # decompress/decode on the executor thread, never the event
            # loop — promtail batches can be tens of MB
            rows: list[tuple[dict, str, int]] = []
            if "json" in ctype:
                try:
                    payload = json.loads(body)
                except json.JSONDecodeError as e:
                    raise InvalidArguments(f"bad json: {e}")
                for stream in payload.get("streams", []):
                    labels = (stream.get("stream") or {}).items()
                    labels = {str(k): str(v) for k, v in labels}
                    for entry in stream.get("values", []):
                        try:
                            ts_ns = int(entry[0])
                            line = str(entry[1])
                        except (ValueError, TypeError, IndexError) as e:
                            raise InvalidArguments(
                                f"bad loki entry {entry!r}: {e}")
                        rows.append((labels, line, ts_ns // 1_000_000))
            else:  # protobuf variant: snappy(logproto.PushRequest)
                from greptimedb_tpu.servers.protocols import parse_loki_push

                try:
                    raw = snappy_decompress(body)
                except Exception:  # noqa: BLE001 — some clients skip snappy
                    raw = body
                try:
                    rows = parse_loki_push(raw)
                except Exception as e:  # noqa: BLE001
                    raise InvalidArguments(f"bad protobuf push: {e}")

            # labels named like reserved columns are renamed
            rows = [
                ({(k + "_label" if k in ("ts", "line", "tenant") else k): v
                  for k, v in labels.items()}, line, ts)
                for labels, line, ts in rows
            ]
            if not rows:
                return 0
            tag_names = sorted({k for lab, _l, _t in rows for k in lab}
                               | {"tenant"})
            cols: dict[str, list] = {k: [] for k in tag_names}
            cols["ts"] = []
            cols["line"] = []
            for lab, line, ts in rows:
                for k in tag_names:
                    cols[k].append(tenant if k == "tenant"
                                   else lab.get(k, ""))
                cols["ts"].append(ts)
                cols["line"].append(line)
            cols["__tags__"] = tag_names
            cols["__fields__"] = ["line"]
            n = _ingest_columns(self.db, "loki_logs", cols,
                                append_mode=True)
            # ingest-side fingerprint hot tail: if the fulltext matrix is
            # already resident, extend it with this batch's new distinct
            # lines now (best-effort, non-blocking)
            from greptimedb_tpu.fulltext.loki import prewarm_ingest

            prewarm_ingest(self.db, "loki_logs")
            return n

        M_INGEST_BYTES.labels("loki").inc(len(body))
        try:
            release = self._admit_ingest(request, len(body), tenant=tenant)
            try:
                n = await self._call_ingest(run)
            finally:
                release()
            M_INGEST_ROWS.labels("loki").inc(n)
            return web.Response(status=204)
        except Exception as e:  # noqa: BLE001
            body_json, status = _error_json(e)
            return web.json_response(body_json, status=status)

    async def _loki_params(self, request: web.Request) -> dict:
        params = dict(request.query)
        if request.method == "POST" and request.content_type in (
            "application/x-www-form-urlencoded", "multipart/form-data",
        ):
            form = await request.post()
            for k in form:
                params.setdefault(k, form[k])
        return params

    async def _loki_eval(self, request: web.Request, path: str, fn):
        """Shared Loki read-endpoint plumbing: params, the query
        scheduler (tenant admission from ``X-Scope-OrgID``, interactive
        priority, deadline shedding), Loki-style error envelopes."""
        ctx = _request_trace_context(request)
        try:
            params = await self._loki_params(request)

            def run():
                with M_PROTOCOL_QUERY.labels("loki").time():
                    with TRACER.trace_context(ctx):
                        return fn(params)

            with M_LATENCY.labels(path).time():
                sched = self.db.scheduler
                if sched is not None:
                    tenant = self._loki_tenant(request)
                    payload = await self._call_query(
                        lambda: sched.submit_fn(
                            run, tenant=tenant,
                            label=f"logql: {params.get('query', path)}"
                            [:256], protocol="loki"))
                else:
                    payload = await self._call(run)
            M_REQUESTS.labels(path, "200").inc()
            return web.json_response(payload, headers=_trace_headers(ctx))
        except Exception as e:  # noqa: BLE001
            _body, status = _error_json(e)
            M_REQUESTS.labels(path, str(status)).inc()
            return web.json_response(
                {"status": "error", "errorType": "bad_data", "error": str(e)},
                status=status, headers=_trace_headers(ctx))

    async def h_loki_query(self, request: web.Request) -> web.Response:
        from greptimedb_tpu.fulltext.loki import loki_query_instant

        return await self._loki_eval(
            request, "/v1/loki/api/v1/query",
            lambda params: loki_query_instant(self.db, params))

    async def h_loki_query_range(self, request: web.Request) -> web.Response:
        from greptimedb_tpu.fulltext.loki import loki_query_range

        return await self._loki_eval(
            request, "/v1/loki/api/v1/query_range",
            lambda params: loki_query_range(self.db, params))

    async def h_loki_labels(self, request: web.Request) -> web.Response:
        from greptimedb_tpu.fulltext.loki import loki_labels

        return await self._loki_eval(
            request, "/v1/loki/api/v1/labels",
            lambda params: loki_labels(self.db, params))

    async def h_loki_label_values(self, request: web.Request) -> web.Response:
        from greptimedb_tpu.fulltext.loki import loki_label_values

        name = request.match_info["name"]
        return await self._loki_eval(
            request, "/v1/loki/api/v1/label_values",
            lambda params: loki_label_values(self.db, name, params))

    async def h_loki_series(self, request: web.Request) -> web.Response:
        from greptimedb_tpu.fulltext.loki import loki_series

        matches = request.query.getall("match[]", [])
        if not matches and request.method == "POST":
            form = await request.post()
            matches = form.getall("match[]", [])
        return await self._loki_eval(
            request, "/v1/loki/api/v1/series",
            lambda params: loki_series(self.db, matches, params))

    async def h_log_query(self, request: web.Request) -> web.Response:
        from greptimedb_tpu.servers.logquery import execute_log_query

        t0 = time.perf_counter()
        try:
            query = json.loads(await request.read())
        except json.JSONDecodeError as e:
            return web.json_response({"error": f"bad json: {e}"}, status=400)
        try:
            res = await self._call(execute_log_query, self.db, query)
            return web.json_response(_result_to_json(res, t0))
        except (AttributeError, TypeError, KeyError) as e:
            # malformed-but-parseable request shapes are client errors
            return web.json_response({"error": f"bad log query: {e}"},
                                     status=400)
        except Exception as e:  # noqa: BLE001
            body_json, status = _error_json(e)
            return web.json_response(body_json, status=status)

    async def h_otlp_traces(self, request: web.Request) -> web.Response:
        from greptimedb_tpu.servers.trace import TRACE_TABLE, parse_otlp_traces

        try:
            body = await request.read()
        except Exception as e:  # noqa: BLE001
            return web.json_response({"error": f"body: {e}"}, status=400)

        def run():
            cols = parse_otlp_traces(body)
            if not cols:
                return 0
            return _ingest_columns(self.db, TRACE_TABLE, cols,
                                   append_mode=True)

        try:
            n = await self._call(run)
            M_INGEST_ROWS.labels("otlp_traces").inc(n)
            return web.json_response({"partialSuccess": {}})
        except Exception as e:  # noqa: BLE001
            body_json, status = _error_json(e)
            return web.json_response(body_json, status=status)

    async def h_jaeger_services(self, request: web.Request) -> web.Response:
        from greptimedb_tpu.servers.trace import jaeger_services

        try:
            data = await self._call(jaeger_services, self.db)
            return web.json_response({"data": data, "total": len(data),
                                      "limit": 0, "offset": 0, "errors": None})
        except Exception as e:  # noqa: BLE001
            body_json, status = _error_json(e)
            return web.json_response(body_json, status=status)

    async def h_jaeger_operations(self, request: web.Request) -> web.Response:
        from greptimedb_tpu.servers.trace import jaeger_operations

        service = request.query.get("service", "")
        try:
            data = await self._call(jaeger_operations, self.db, service)
            return web.json_response({"data": data, "total": len(data),
                                      "errors": None})
        except Exception as e:  # noqa: BLE001
            body_json, status = _error_json(e)
            return web.json_response(body_json, status=status)

    async def h_jaeger_service_operations(self, request: web.Request) -> web.Response:
        from greptimedb_tpu.servers.trace import jaeger_operations

        service = request.match_info["service"]
        try:
            data = await self._call(jaeger_operations, self.db, service)
            names = [d["name"] for d in data]
            return web.json_response({"data": names, "total": len(names),
                                      "errors": None})
        except Exception as e:  # noqa: BLE001
            body_json, status = _error_json(e)
            return web.json_response(body_json, status=status)

    async def h_jaeger_trace(self, request: web.Request) -> web.Response:
        from greptimedb_tpu.servers.trace import jaeger_trace

        trace_id = request.match_info["trace_id"]
        try:
            data = await self._call(jaeger_trace, self.db, trace_id)
        except Exception as e:  # noqa: BLE001
            body_json, status = _error_json(e)
            return web.json_response(body_json, status=status)
        if not data:
            return web.json_response(
                {"data": [], "errors": [{"code": 404, "msg": "trace not found"}]},
                status=404)
        return web.json_response({"data": data, "errors": None})

    async def h_jaeger_find(self, request: web.Request) -> web.Response:
        from greptimedb_tpu.servers.trace import jaeger_find_traces

        q = request.query

        def run():
            return jaeger_find_traces(
                self.db,
                service=q.get("service"),
                operation=q.get("operation"),
                start_us=int(q["start"]) if "start" in q else None,
                end_us=int(q["end"]) if "end" in q else None,
                min_duration_us=(
                    _parse_go_duration_us(q["minDuration"])
                    if "minDuration" in q else None
                ),
                limit=int(q.get("limit", "20")),
            )

        try:
            data = await self._call(run)
            return web.json_response({"data": data, "errors": None})
        except ValueError as e:
            return web.json_response({"error": str(e)}, status=400)
        except Exception as e:  # noqa: BLE001
            body_json, status = _error_json(e)
            return web.json_response(body_json, status=status)

    async def h_opentsdb_put(self, request: web.Request) -> web.Response:
        """OpenTSDB /api/put (reference src/servers/src/opentsdb.rs): JSON
        datapoints {metric, timestamp, value, tags} — single or array."""
        try:
            payload = json.loads(await request.read())
        except json.JSONDecodeError as e:
            return web.json_response({"error": f"bad json: {e}"}, status=400)
        points = payload if isinstance(payload, list) else [payload]

        def run():
            from collections import defaultdict

            from greptimedb_tpu.errors import InvalidArguments

            per_table: dict[str, list] = defaultdict(list)
            for p in points:
                if not isinstance(p, dict) or "metric" not in p:
                    raise InvalidArguments(f"bad datapoint: {p!r}")
                try:
                    ts = int(p.get("timestamp", 0))
                    value = float(p.get("value", 0))
                except (TypeError, ValueError) as e:
                    raise InvalidArguments(f"bad datapoint {p!r}: {e}") from None
                ts_ms = ts * 1000 if ts < 10**12 else ts  # s or ms heuristic
                tags = {
                    (str(k) + "_tag" if str(k) in ("ts", "val") else str(k)):
                        str(v)
                    for k, v in (p.get("tags") or {}).items()
                }
                # metric names commonly contain dots (sys.cpu.user), which
                # SQL would read as db.table — sanitize to an identifier
                per_table[_safe_table(str(p["metric"]))].append(
                    (tags, value, ts_ms)
                )
            total = 0
            for table, rows in per_table.items():
                tag_names = sorted({k for t, _v, _ts in rows for k in t})
                cols: dict[str, list] = {k: [] for k in tag_names}
                cols["ts"] = []
                cols["val"] = []
                for tags, val, ts in rows:
                    for k in tag_names:
                        cols[k].append(tags.get(k, ""))
                    cols["ts"].append(ts)
                    cols["val"].append(val)
                cols["__tags__"] = tag_names
                cols["__fields__"] = ["val"]
                total += _ingest_columns(self.db, table, cols)
            return total

        try:
            n = await self._call(run)
            M_INGEST_ROWS.labels("opentsdb").inc(n)
            return web.Response(status=204)
        except Exception as e:  # noqa: BLE001
            body_json, status = _error_json(e)
            return web.json_response(body_json, status=status)

    async def h_es_info(self, request: web.Request) -> web.Response:
        return web.json_response({
            "name": "greptimedb-tpu", "cluster_name": "greptimedb",
            "version": {"number": "8.15.0"}, "tagline": "You Know, for Search",
        })

    async def h_es_license(self, request: web.Request) -> web.Response:
        return web.json_response(
            {"license": {"status": "active", "type": "basic"}})

    async def h_es_bulk(self, request: web.Request) -> web.Response:
        """Elasticsearch _bulk emulation for Logstash/Filebeat (reference
        src/servers/src/elasticsearch.rs): NDJSON action/document pairs;
        documents land in a table named after the index."""
        raw = (await request.read()).decode("utf-8")
        default_index = request.match_info.get("index") or request.query.get(
            "index", "es_logs")
        t0 = time.perf_counter()

        def run():
            from collections import defaultdict

            per_table: dict[str, list[dict]] = defaultdict(list)
            index = default_index
            expect_doc = False
            for line in raw.splitlines():
                line = line.strip()
                if not line:
                    continue
                try:
                    doc = json.loads(line)
                except json.JSONDecodeError:
                    # a bad document line must consume its action slot, or
                    # the next action line would be misread as a document
                    expect_doc = False
                    continue
                if not expect_doc:
                    action = next(iter(doc), "")
                    if action in ("index", "create"):
                        index = (doc[action] or {}).get("_index", default_index)
                        expect_doc = True
                    continue
                expect_doc = False
                per_table[_safe_table(index)].append(doc)
            total = 0
            now_ms = int(time.time() * 1000)
            for table, docs in per_table.items():
                rows = []
                for d in docs:
                    ts = now_ms
                    for key in ("@timestamp", "timestamp"):
                        if key in d:
                            try:
                                from greptimedb_tpu.query.parser import (
                                    parse_timestamp_str,
                                )

                                ts = parse_timestamp_str(
                                    str(d[key]).replace("T", " ").rstrip("Z"))
                            except Exception:  # noqa: BLE001
                                pass
                            break
                    rows.append((ts, json.dumps(d)))
                cols = {
                    "__tags__": [], "__fields__": ["doc"],
                    "ts": [r[0] for r in rows],
                    "doc": [r[1] for r in rows],
                }
                total += _ingest_columns(self.db, table, cols,
                                         append_mode=True)
            return total

        try:
            n = await self._call(run)
            M_INGEST_ROWS.labels("elasticsearch").inc(n)
            took = int((time.perf_counter() - t0) * 1000)
            return web.json_response({"took": took, "errors": False,
                                      "items": []})
        except Exception as e:  # noqa: BLE001
            body_json, status = _error_json(e)
            return web.json_response(body_json, status=status)

    async def h_splunk_hec(self, request: web.Request) -> web.Response:
        """Splunk HTTP Event Collector (reference src/servers/src/http/
        splunk.rs): concatenated JSON events {time, event, fields,
        sourcetype}."""
        raw = (await request.read()).decode("utf-8")

        def run():
            from greptimedb_tpu.errors import InvalidArguments

            dec = json.JSONDecoder()
            events = []
            pos = 0
            s = raw.strip()
            while pos < len(s):
                while pos < len(s) and s[pos].isspace():
                    pos += 1
                if pos >= len(s):
                    break
                try:
                    obj, end = dec.raw_decode(s, pos)
                except json.JSONDecodeError as e:
                    raise InvalidArguments(f"bad HEC payload: {e}") from None
                events.append(obj)
                pos = end
            rows = []
            for e in events:
                if not isinstance(e, dict):
                    continue
                t = e.get("time")
                ts_ms = (
                    int(float(t) * 1000) if t is not None
                    else int(time.time() * 1000)
                )
                ev = e.get("event")
                line = ev if isinstance(ev, str) else json.dumps(ev)
                rows.append((str(e.get("sourcetype", "")), line, ts_ms))
            if not rows:
                return 0
            cols = {
                "__tags__": ["sourcetype"], "__fields__": ["event"],
                "sourcetype": [r[0] for r in rows],
                "ts": [r[2] for r in rows],
                "event": [r[1] for r in rows],
            }
            return _ingest_columns(self.db, "splunk_events", cols,
                                   append_mode=True)

        try:
            n = await self._call(run)
            M_INGEST_ROWS.labels("splunk").inc(n)
            return web.json_response({"text": "Success", "code": 0})
        except Exception as e:  # noqa: BLE001
            body_json, status = _error_json(e)
            return web.json_response(body_json, status=status)

    def _pipelines(self):
        from greptimedb_tpu.servers.pipeline import PipelineManager

        return PipelineManager(self.db)

    async def h_pipeline_upsert(self, request: web.Request) -> web.Response:
        name = request.match_info["name"]
        body = (await request.read()).decode("utf-8")
        try:
            pipe = await self._call(self._pipelines().upsert, name, body)
            return web.json_response(
                {"name": name, "version": pipe.version})
        except Exception as e:  # noqa: BLE001
            body_json, status = _error_json(e)
            return web.json_response(body_json, status=status)

    async def h_pipeline_delete(self, request: web.Request) -> web.Response:
        name = request.match_info["name"]
        ok = await self._call(self._pipelines().delete, name)
        if not ok:
            return web.json_response({"error": f"pipeline {name} not found"},
                                     status=404)
        return web.json_response({"name": name})

    async def h_pipeline_list(self, request: web.Request) -> web.Response:
        out = await self._call(self._pipelines().list)
        return web.json_response(
            {"pipelines": [{"name": n, "version": v} for n, v in out]})

    async def h_ingest(self, request: web.Request) -> web.Response:
        """Log ingestion through a pipeline (reference /v1/ingest +
        http/event.rs): body is NDJSON or a JSON array of objects; the
        pipeline shapes rows into table columns."""
        table = request.query.get("table")
        pname = request.query.get("pipeline_name")
        if not table or not pname:
            return web.json_response(
                {"error": "table and pipeline_name query params required"},
                status=400)
        raw = (await request.read()).decode("utf-8")

        def run():
            from greptimedb_tpu.errors import InvalidArguments

            rows: list[dict] = []
            stripped = raw.strip()
            if stripped.startswith("["):
                try:
                    parsed = json.loads(stripped)
                except json.JSONDecodeError as e:
                    raise InvalidArguments(f"bad json body: {e}") from None
                rows = [r for r in parsed if isinstance(r, dict)]
            else:
                for line in stripped.splitlines():
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        parsed = json.loads(line)
                    except json.JSONDecodeError:
                        parsed = None
                    rows.append(
                        parsed if isinstance(parsed, dict)
                        else {"message": line}
                    )
            pipe = self._pipelines().get(pname)
            cols = pipe.run(rows)
            if not cols["ts"]:
                return 0
            return _ingest_columns(self.db, table, cols, append_mode=True)

        try:
            n = await self._call(run)
            M_INGEST_ROWS.labels("pipeline").inc(n)
            return web.json_response({"rows": n})
        except Exception as e:  # noqa: BLE001
            body_json, status = _error_json(e)
            return web.json_response(body_json, status=status)

    async def h_health(self, request: web.Request) -> web.Response:
        return web.json_response({})

    async def h_metrics(self, request: web.Request) -> web.Response:
        return web.Response(text=telemetry.REGISTRY.render(),
                            content_type="text/plain")

    async def h_config(self, request: web.Request) -> web.Response:
        cfg = {
            "data_home": self.db.data_home,
            "http": {"addr": f"{self.host}:{self.port}"},
            "version": "greptimedb-tpu-0.1.0",
        }
        return web.Response(text=json.dumps(cfg, indent=2),
                            content_type="text/plain")

    async def h_dashboard(self, request: web.Request) -> web.Response:
        """Embedded web UI (reference src/servers/src/http.rs:1252)."""
        from greptimedb_tpu.servers.dashboard import DASHBOARD_HTML

        return web.Response(text=DASHBOARD_HTML, content_type="text/html")

    async def h_status(self, request: web.Request) -> web.Response:
        import jax

        payload = {
            "version": "greptimedb-tpu-0.1.0",
            "devices": [str(d) for d in jax.devices()],
            "tables": len(self.db.catalog.list_tables(self.db.current_db)),
            "memory": self.db.memory.usage(),
        }
        ft = getattr(getattr(self.db, "engine", None), "executor", None)
        ft = getattr(ft, "fulltext_cache", None)
        if ft is not None and len(ft):
            payload["fulltext"] = ft.stats()
        return web.json_response(payload)

    async def h_slo(self, request: web.Request) -> web.Response:
        """Closed-loop SLO observatory (ISSUE 18): per-(tenant, class,
        protocol) sketch status, firing burn-rate alerts, and the idle
        economy's consumer ledgers — the same rows as
        ``information_schema.slo_status``."""
        slo = getattr(self.db, "slo", None)
        if slo is None:
            return web.json_response(
                {"enabled": False,
                 "hint": "set GREPTIME_SLO=on (default) with the "
                         "scheduler enabled"})
        eco = getattr(self.db, "idle_economy", None)
        payload = {
            "enabled": True,
            "status": slo.status_rows(),
            "alerts": slo.alerts(),
            "idle": eco.consumers() if eco is not None else [],
        }
        return web.json_response(payload)

    async def h_promql(self, request: web.Request) -> web.Response:
        """Greptime-native PromQL endpoint: query/start/end/step params,
        greptime JSON envelope output (reference /v1/promql)."""
        t0 = time.perf_counter()
        ctx = _request_trace_context(request)
        try:
            query = await self._param(request, "query")
            start = _parse_prom_time(await self._param(request, "start", "0"))
            end = _parse_prom_time(await self._param(request, "end", "0"))
            step = _parse_prom_duration(await self._param(request, "step", "60"))
            res, steps = await self._eval_promql(query, start, end, step,
                                                 trace_ctx=ctx,
                                                 tenant=self._tenant(request))
            vals = np.asarray(res.values, dtype=np.float64)
            label_keys = sorted({k for lab in res.labels for k in lab})
            rows = []
            for s, lab in enumerate(res.labels):
                for t in range(len(steps)):
                    v = vals[s, t]
                    if not np.isnan(v):
                        rows.append(
                            [str(lab.get(k, "")) for k in label_keys]
                            + [int(steps[t]), float(v)]
                        )
            qr = QueryResult(label_keys + ["ts", "val"], rows)
            return web.json_response(_result_to_json(qr, t0),
                                     headers=_trace_headers(ctx))
        except Exception as e:  # noqa: BLE001
            body, status = _error_json(e)
            return web.json_response(body, status=status)

    # ---- lifecycle -----------------------------------------------------
    async def h_log_level(self, request):
        """Dynamic log level (reference src/servers/src/http/dyn_log.rs:
        POST /debug/log_level with the new level in the body)."""
        import logging

        root = logging.getLogger()
        if request.method in ("POST", "PUT"):
            level = (await request.text()).strip().upper()
            if level not in ("DEBUG", "INFO", "WARNING", "WARN", "ERROR",
                             "CRITICAL"):
                return web.json_response(
                    {"error": f"unknown level {level!r}"}, status=400)
            root.setLevel("WARNING" if level == "WARN" else level)
        return web.json_response(
            {"level": logging.getLevelName(root.level)})

    async def h_prof_mem(self, request):
        """Heap + HBM memory profile (reference
        src/servers/src/http/mem_prof.rs, which dumps a jemalloc heap
        profile; the python analog is tracemalloc).  Actions:

        - ``?action=start``: activate tracemalloc (``frames=N`` stack
          depth, default 1) and snapshot the baseline;
        - ``?action=snapshot`` (default): top-N allocation sites
          (``top=N``, default 20) and, once a baseline exists, the
          DIFF against it (what grew since start / the last snapshot);
        - ``?action=stop``: deactivate tracing and drop the baseline.

        Every response also reports the device side: per-workload
        used/quota/peak bytes from the workload-manager budgets
        (utils/memory.py) with HBM-kind workloads summed separately —
        the resident grids, layout caches and flow state live there,
        invisible to any host allocator profile."""
        import tracemalloc

        action = request.query.get("action", "snapshot")
        try:
            top_n = max(1, min(int(request.query.get("top", "20")), 100))
            frames = max(1, min(int(request.query.get("frames", "1")), 32))
        except ValueError:
            return web.json_response(
                {"error": "top/frames must be integers"}, status=400)

        def workloads():
            usage = self.db.memory.usage()
            hbm = sum(w["used_bytes"] for w in usage.values()
                      if w["kind"] == "hbm")
            return {"workloads": usage, "hbm_used_bytes": hbm}

        if action == "start":
            if not tracemalloc.is_tracing():
                tracemalloc.start(frames)
            self._mem_baseline = tracemalloc.take_snapshot()
            return web.json_response(
                {"tracing": True, "action": "start", **workloads()})
        if action == "stop":
            self._mem_baseline = None
            if tracemalloc.is_tracing():
                tracemalloc.stop()
            return web.json_response(
                {"tracing": False, "action": "stop", **workloads()})
        if action != "snapshot":
            return web.json_response(
                {"error": f"unknown action {action!r}"}, status=400)
        payload = {"tracing": tracemalloc.is_tracing(), **workloads()}
        if tracemalloc.is_tracing():
            snap = tracemalloc.take_snapshot()
            traced, peak = tracemalloc.get_traced_memory()
            payload["traced_bytes"] = traced
            payload["traced_peak_bytes"] = peak
            payload["top"] = [
                {"site": str(s.traceback), "size_bytes": s.size,
                 "count": s.count}
                for s in snap.statistics("lineno")[:top_n]
            ]
            base = getattr(self, "_mem_baseline", None)
            if base is not None:
                payload["diff"] = [
                    {"site": str(s.traceback), "size_diff": s.size_diff,
                     "count_diff": s.count_diff}
                    for s in snap.compare_to(base, "lineno")[:top_n]
                ]
            self._mem_baseline = snap
        return web.json_response(payload)

    async def h_prof_cpu(self, request):
        """Statistical CPU profile (reference src/servers/src/http/pprof.rs
        samples for N seconds and returns a report): samples every thread's
        stack at ~100Hz for ?seconds=N (default 2), returns aggregated
        frame counts, hottest first."""
        import asyncio
        import collections as _collections
        import sys as _sys
        import time as _time
        import traceback as _traceback

        try:
            seconds = min(float(request.query.get("seconds", "2")), 30.0)
        except ValueError:
            return web.json_response(
                {"error": "seconds must be a number"}, status=400)
        if getattr(self, "_profiling", False):
            return web.json_response(
                {"error": "a profile is already running"}, status=429)
        self._profiling = True

        def sample():
            counts: "_collections.Counter[str]" = _collections.Counter()
            deadline = _time.time() + seconds
            nsamples = 0
            while _time.time() < deadline:
                for frames in _sys._current_frames().values():
                    stack = _traceback.extract_stack(frames)
                    if stack:
                        f = stack[-1]
                        counts[f"{f.filename}:{f.lineno} {f.name}"] += 1
                nsamples += 1
                _time.sleep(0.01)
            return counts, nsamples

        try:
            counts, nsamples = await asyncio.get_event_loop(
            ).run_in_executor(None, sample)
        finally:
            self._profiling = False
        top = counts.most_common(50)
        body = "\n".join(
            f"{c:6d} {frame}" for frame, c in top
        )
        return web.Response(
            text=f"samples={nsamples} interval=10ms\n{body}\n",
            content_type="text/plain")

    # start()/stop() come from ThreadedAiohttpApp


def _parse_prom_time(raw) -> float:
    if raw is None:
        raise GreptimeError("missing time parameter",
                            code=StatusCode.INVALID_ARGUMENTS)
    try:
        return float(raw)
    except (TypeError, ValueError):
        pass
    from greptimedb_tpu.query.parser import parse_timestamp_str

    return parse_timestamp_str(str(raw).replace("T", " ").rstrip("Z")) / 1000.0


def _parse_prom_duration(raw) -> float:
    try:
        return float(raw)
    except (TypeError, ValueError):
        from greptimedb_tpu.query.parser import parse_interval_str

        return parse_interval_str(str(raw)) / 1000.0


def _parse_go_duration_us(raw: str) -> int:
    """Go-style duration (Jaeger minDuration): '100ms', '2s', '50us', '1m'."""
    s = raw.strip().lower()
    for suffix, mult in (("us", 1), ("µs", 1), ("ms", 1000),
                         ("m", 60_000_000), ("s", 1_000_000), ("h", 3_600_000_000)):
        if s.endswith(suffix):
            return int(float(s[: -len(suffix)]) * mult)
    return int(float(s))  # bare number: microseconds


def _safe_table(name: str) -> str:
    out = "".join(ch if (ch.isalnum() or ch == "_") else "_" for ch in name)
    return out or "es_logs"


# serializes catalog/schema mutation (table auto-create, alter-on-demand,
# flow notification) across the ingest pool's workers — region WRITES run
# outside it under their own per-region locks, so the common steady-state
# path (schema already in place) takes this only for two dict probes
_INGEST_DDL_LOCK = threading.RLock()


def _ingest_field_type(values):
    """Field column → ConcreteDataType; dtype-dispatch for the vectorized
    (ndarray/DictColumn) columns, first-non-null scan for legacy lists."""
    from greptimedb_tpu.datatypes.batch import DictColumn
    from greptimedb_tpu.datatypes.types import ConcreteDataType

    if isinstance(values, DictColumn):
        return ConcreteDataType.STRING
    if isinstance(values, np.ndarray) and values.dtype != object:
        if values.dtype == np.bool_:
            return ConcreteDataType.BOOL
        if np.issubdtype(values.dtype, np.integer):
            return ConcreteDataType.INT64
        if np.issubdtype(values.dtype, np.floating):
            return ConcreteDataType.FLOAT64
    for v in values:
        if isinstance(v, (bool, np.bool_)):
            return ConcreteDataType.BOOL
        if isinstance(v, str):
            return ConcreteDataType.STRING
        if isinstance(v, (float, np.floating)):
            return ConcreteDataType.FLOAT64
        if isinstance(v, (int, np.integer)):
            return ConcreteDataType.INT64
    return ConcreteDataType.FLOAT64


def _ingest_columns(db, table: str, cols: dict,
                    append_mode: bool = False) -> int:
    """Auto-creating ingest (reference Inserter auto table creation,
    src/operator/src/insert.rs:178-304): create the table from the first
    batch's shape, add columns on demand, then write.  ``append_mode``
    creates log/trace-style tables that keep EVERY row (no (series, ts)
    dedup — reference CREATE TABLE WITH (append_mode='true')).

    Columns may be legacy Python lists or vectorized ndarray/DictColumn
    batches; the write path never materializes per-row objects for the
    latter (partition routing slices by index at C level).  Safe for
    concurrent callers: schema setup serializes on ``_INGEST_DDL_LOCK``,
    row appends on each region's own write lock."""
    from greptimedb_tpu.datatypes.batch import DictColumn
    from greptimedb_tpu.datatypes.schema import ColumnSchema, Schema
    from greptimedb_tpu.datatypes.types import ConcreteDataType, SemanticType
    from greptimedb_tpu.query.ast import AlterTable, ColumnDef

    tag_names = cols.pop("__tags__", [])
    field_names = cols.pop("__fields__", [])
    # raw wire bytes usable as the WAL payload verbatim (arrow bulk);
    # only valid when the whole batch lands in ONE region intact
    wire_ipc = cols.pop("__wire_ipc__", None)
    n = len(cols["ts"])
    field_type = _ingest_field_type

    dbname, name = db._split_name(table)
    with _INGEST_DDL_LOCK:
        if not db.catalog.table_exists(dbname, name):
            defs = [ColumnSchema(t, ConcreteDataType.STRING, SemanticType.TAG)
                    for t in tag_names]
            defs.append(ColumnSchema(
                "ts", ConcreteDataType.TIMESTAMP_MILLISECOND,
                SemanticType.TIMESTAMP, nullable=False))
            defs += [ColumnSchema(f, field_type(cols[f]), SemanticType.FIELD)
                     for f in field_names]
            info = db.catalog.create_table(
                dbname, name, Schema(tuple(defs)),
                options={"append_mode": "true"} if append_mode else None,
                if_not_exists=True)
            if info is not None:
                opts = None
                if append_mode:
                    import dataclasses as _dc

                    opts = _dc.replace(db.regions.default_options,
                                       append_mode=True)
                db.regions.create_region(info.region_ids[0], info.schema,
                                         options=opts)
        else:
            info = db.catalog.get_table(dbname, name)
            missing_tags = [t for t in tag_names
                            if not info.schema.has_column(t)]
            if missing_tags:
                # online tag addition (reference alter-on-demand,
                # src/operator/src/insert.rs): existing series extend their
                # key with the empty-string label — same machinery as the
                # metric engine's label growth
                tag_regions = db._regions_of(f"{dbname}.{name}")
                for region in tag_regions:
                    for t in missing_tags:
                        region.add_tag_column(t)
                info.schema = tag_regions[0].schema
                db.catalog.update_table(info)
            for f in field_names:
                if not info.schema.has_column(f):
                    db.execute_statement(AlterTable(
                        f"{dbname}.{name}", "add_column",
                        column=ColumnDef(f, field_type(cols[f]).value),
                    ))
                    info = db.catalog.get_table(dbname, name)
        regions = db._regions_of(f"{dbname}.{name}")
    if len(regions) == 1:
        regions[0].write(cols, wire_payload=wire_ipc)
    else:
        # partition routing, ONCE per batch (same as SQL INSERT; skipping
        # it would dump all rows into region 0 and break cross-region
        # dedup/DELETE): evaluate the rule over materialized key columns,
        # then slice every column per target region by index — fancy
        # indexing / DictColumn.take, no per-row Python loop
        from greptimedb_tpu.parallel.partition import split_rows

        rule = db._partition_rule(f"{dbname}.{name}")
        # the rule only reads its key columns — materializing every
        # column to per-row objects here would undo the vectorized
        # parse's zero-object discipline on exactly the sharded path
        # (split_rows boxes the key columns itself)
        cols_np = {
            c: (cols[c].materialize() if isinstance(cols[c], DictColumn)
                else cols[c])
            for c in (rule.columns or list(cols))
            if c in cols
        }
        parts = split_rows(rule, cols_np, n)
        for pidx, row_idx in parts.items():
            idx = np.asarray(row_idx, dtype=np.int64)
            sub = {}
            for c, v in cols.items():
                if isinstance(v, DictColumn):
                    sub[c] = v.take(idx)
                elif isinstance(v, np.ndarray):
                    sub[c] = v[idx]
                else:
                    sub[c] = [v[i] for i in row_idx]
            regions[pidx].write(sub)
    # hot-tail grid catch-up: freshly acked rows scatter into the
    # resident grid's not-yet-covered tail right now (when one is
    # resident and the delta is worth a dispatch) — the next query sees
    # them without any flush/rebuild
    cache = getattr(db, "cache", None)
    if cache is not None:
        for region in regions:
            cache.extend_hot_tail(region)
    if db.flow_engine.flows:
        with _INGEST_DDL_LOCK:
            appendable = all(
                getattr(r, "last_write_appendable", True) for r in regions
            )
            db.flow_engine.on_write(name, cols["ts"], data=cols,
                                    appendable=appendable)
            db.flow_engine.run_all()
    return n
