"""PromQL parser (reference uses the promql-parser crate, Cargo.toml:201).

Grammar per the Prometheus spec: vector selectors with label matchers,
range/offset/@ modifiers, functions, aggregation operators with
by/without, binary operators with precedence, vector matching modifiers
(on/ignoring, group_left/group_right), number/string literals.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from greptimedb_tpu.errors import SyntaxError_
from greptimedb_tpu.query.parser import parse_interval_str


# ---- AST -------------------------------------------------------------------

class PromExpr:
    pass


@dataclass(frozen=True)
class LabelMatcher:
    name: str
    op: str  # = != =~ !~
    value: str


@dataclass
class VectorSelector(PromExpr):
    metric: str
    matchers: list[LabelMatcher] = field(default_factory=list)
    range_s: float | None = None  # range vector [5m]
    offset_s: float = 0.0
    at_ts: float | None = None  # @ modifier

    def __str__(self):
        m = ",".join(f"{x.name}{x.op}\"{x.value}\"" for x in self.matchers)
        s = self.metric + (f"{{{m}}}" if m else "")
        if self.range_s is not None:
            s += f"[{self.range_s}s]"
        if self.offset_s:
            s += f" offset {self.offset_s}s"
        return s


@dataclass
class NumberLit(PromExpr):
    value: float

    def __str__(self):
        return str(self.value)


@dataclass
class StringLit(PromExpr):
    value: str

    def __str__(self):
        return repr(self.value)


@dataclass
class FunctionCall(PromExpr):
    func: str
    args: list[PromExpr]

    def __str__(self):
        return f"{self.func}({', '.join(map(str, self.args))})"


@dataclass
class Aggregation(PromExpr):
    op: str  # sum avg min max count topk bottomk quantile stddev stdvar group count_values
    expr: PromExpr
    param: PromExpr | None = None  # k for topk, q for quantile
    grouping: list[str] = field(default_factory=list)
    without: bool = False

    def __str__(self):
        by = (" without" if self.without else " by") + f" ({', '.join(self.grouping)})" if self.grouping or self.without else ""
        p = f"{self.param}, " if self.param is not None else ""
        return f"{self.op}{by}({p}{self.expr})"


@dataclass
class BinaryExpr(PromExpr):
    op: str
    lhs: PromExpr
    rhs: PromExpr
    bool_modifier: bool = False
    on: list[str] | None = None  # vector matching labels (on) or None
    ignoring: list[str] | None = None
    group_left: list[str] | None = None  # include labels; None = no group_left
    group_right: list[str] | None = None

    def __str__(self):
        return f"({self.lhs} {self.op} {self.rhs})"


@dataclass
class UnaryExpr(PromExpr):
    op: str
    expr: PromExpr

    def __str__(self):
        return f"{self.op}{self.expr}"


@dataclass
class SubqueryExpr(PromExpr):
    expr: PromExpr
    range_s: float
    step_s: float | None
    offset_s: float = 0.0

    def __str__(self):
        return f"{self.expr}[{self.range_s}s:{self.step_s or ''}s]"


AGG_OPS = {
    "sum", "avg", "min", "max", "count", "topk", "bottomk", "quantile",
    "stddev", "stdvar", "group", "count_values",
}
PARAM_AGGS = {"topk", "bottomk", "quantile", "count_values"}

# precedence: ^ > * / % atan2 > + - > == != <= < >= > > and unless > or
_PREC = {
    "or": 1,
    "and": 2, "unless": 2,
    "==": 3, "!=": 3, "<=": 3, "<": 3, ">=": 3, ">": 3,
    "+": 4, "-": 4,
    "*": 5, "/": 5, "%": 5, "atan2": 5,
    "^": 6,
}


class PromParser:
    def __init__(self, s: str):
        self.s = s
        self.i = 0

    # ---- lexing helpers -------------------------------------------------
    def _ws(self) -> None:
        while self.i < len(self.s):
            c = self.s[self.i]
            if c.isspace():
                self.i += 1
            elif c == "#":
                nl = self.s.find("\n", self.i)
                self.i = len(self.s) if nl < 0 else nl + 1
            else:
                break

    def peek_char(self) -> str:
        self._ws()
        return self.s[self.i] if self.i < len(self.s) else ""

    def eat(self, text: str) -> bool:
        self._ws()
        if self.s.startswith(text, self.i):
            self.i += len(text)
            return True
        return False

    def expect(self, text: str) -> None:
        if not self.eat(text):
            raise SyntaxError_(f"expected {text!r} at {self.i} in promql: {self.s[self.i:self.i+30]!r}")

    def ident(self) -> str:
        self._ws()
        j = self.i
        while j < len(self.s) and (self.s[j].isalnum() or self.s[j] in "_:"):
            j += 1
        if j == self.i:
            raise SyntaxError_(f"expected identifier at {self.i}")
        out = self.s[self.i:j]
        self.i = j
        return out

    def peek_ident(self) -> str:
        save = self.i
        self._ws()
        j = self.i
        while j < len(self.s) and (self.s[j].isalnum() or self.s[j] in "_:"):
            j += 1
        out = self.s[self.i:j]
        self.i = save
        return out

    def string(self) -> str:
        self._ws()
        if self.i >= len(self.s):
            raise SyntaxError_("unexpected end of promql (expected string)")
        q = self.s[self.i]
        if q not in "'\"`":
            raise SyntaxError_(f"expected string at {self.i}")
        j = self.i + 1
        buf = []
        while j < len(self.s):
            c = self.s[j]
            if c == "\\" and j + 1 < len(self.s):
                nxt = self.s[j + 1]
                buf.append({"n": "\n", "t": "\t", "\\": "\\", q: q}.get(nxt, "\\" + nxt))
                j += 2
                continue
            if c == q:
                self.i = j + 1
                return "".join(buf)
            buf.append(c)
            j += 1
        raise SyntaxError_(f"unterminated string at {self.i}")

    def duration(self) -> float:
        """duration like 5m, 1h30m, or bare number (seconds) → seconds."""
        self._ws()
        j = self.i
        while j < len(self.s) and (self.s[j].isalnum() or self.s[j] == "."):
            j += 1
        raw = self.s[self.i:j]
        if not raw:
            raise SyntaxError_(f"expected duration at {self.i}")
        self.i = j
        return parse_interval_str(raw) / 1000.0

    def number(self) -> float:
        self._ws()
        j = self.i
        if j < len(self.s) and self.s[j] in "+-":
            j += 1
        if self.s.startswith(("0x", "0X"), j):
            k = j + 2
            while k < len(self.s) and self.s[k] in "0123456789abcdefABCDEF":
                k += 1
            v = float(int(self.s[j:k], 16))
            self.i = k
            return v
        k = j
        while k < len(self.s) and (self.s[k].isdigit() or self.s[k] in ".eE+-"):
            if self.s[k] in "+-" and k > j and self.s[k - 1] not in "eE":
                break
            k += 1
        raw = self.s[j:k]
        try:
            v = float(raw)
        except ValueError:
            # Inf / NaN keywords
            word = self.peek_ident().lower()
            if word == "inf":
                self.ident()
                return float("inf")
            if word == "nan":
                self.ident()
                return float("nan")
            raise SyntaxError_(f"bad number {raw!r} at {self.i}")
        self.i = k
        return v

    # ---- grammar ---------------------------------------------------------
    def parse(self) -> PromExpr:
        e = self.expr(1)
        self._ws()
        if self.i < len(self.s):
            raise SyntaxError_(f"trailing input at {self.i}: {self.s[self.i:self.i+20]!r}")
        return e

    def expr(self, min_prec: int) -> PromExpr:
        lhs = self.unary()
        while True:
            op = self._peek_binop()
            if op is None or _PREC[op] < min_prec:
                return lhs
            self._eat_binop(op)
            bool_mod = False
            if self.peek_ident() == "bool":
                self.ident()
                bool_mod = True
            on = ignoring = None
            if self.peek_ident() in ("on", "ignoring"):
                kw = self.ident()
                labels = self._label_list()
                if kw == "on":
                    on = labels
                else:
                    ignoring = labels
            gl = gr = None
            if self.peek_ident() in ("group_left", "group_right"):
                kw = self.ident()
                labels = []
                if self.peek_char() == "(":
                    labels = self._label_list()
                if kw == "group_left":
                    gl = labels
                else:
                    gr = labels
            # right-assoc for ^, left otherwise
            nxt = _PREC[op] + (0 if op == "^" else 1)
            rhs = self.expr(nxt)
            lhs = BinaryExpr(op, lhs, rhs, bool_mod, on, ignoring, gl, gr)

    def _peek_binop(self) -> str | None:
        self._ws()
        for op in ("==", "!=", "<=", ">=", "<", ">", "+", "-", "*", "/", "%", "^"):
            if self.s.startswith(op, self.i):
                return op
        w = self.peek_ident()
        if w in ("and", "or", "unless", "atan2"):
            return w
        return None

    def _eat_binop(self, op: str) -> None:
        self._ws()
        if op in ("and", "or", "unless", "atan2"):
            self.ident()
        else:
            self.i += len(op)

    def _label_list(self) -> list[str]:
        self.expect("(")
        out = []
        if not self.eat(")"):
            out.append(self.ident())
            while self.eat(","):
                out.append(self.ident())
            self.expect(")")
        return out

    def unary(self) -> PromExpr:
        if self.eat("-"):
            return UnaryExpr("-", self.unary())
        if self.eat("+"):
            return self.unary()
        return self.postfix(self.atom())

    def postfix(self, e: PromExpr) -> PromExpr:
        while True:
            self._ws()
            if self.peek_char() == "[":
                self.expect("[")
                rng = self.duration()
                if self.eat(":"):
                    step = None
                    self._ws()
                    if self.peek_char() != "]":
                        step = self.duration()
                    self.expect("]")
                    e = SubqueryExpr(e, rng, step)
                else:
                    self.expect("]")
                    if isinstance(e, VectorSelector):
                        e.range_s = rng
                    else:
                        raise SyntaxError_("range on non-selector")
                continue
            w = self.peek_ident()
            if w == "offset":
                self.ident()
                neg = self.eat("-")
                off = self.duration()
                off = -off if neg else off
                if isinstance(e, VectorSelector):
                    e.offset_s = off
                elif isinstance(e, SubqueryExpr):
                    e.offset_s = off
                else:
                    raise SyntaxError_("offset on non-selector")
                continue
            if self.peek_char() == "@":
                self.expect("@")
                at = self.number()
                if isinstance(e, VectorSelector):
                    e.at_ts = at
                else:
                    raise SyntaxError_("@ on non-selector")
                continue
            return e

    def atom(self) -> PromExpr:
        self._ws()
        c = self.peek_char()
        if c == "(":
            self.expect("(")
            e = self.expr(1)
            self.expect(")")
            return e
        if c in "'\"":
            return StringLit(self.string())
        if c.isdigit() or (c == "." and self.i + 1 < len(self.s)):
            return NumberLit(self.number())
        if c == "{":
            # metric-less selector {__name__=...}
            matchers = self._matchers()
            metric = ""
            for m in matchers:
                if m.name == "__name__" and m.op == "=":
                    metric = m.value
            matchers = [m for m in matchers if m.name != "__name__"]
            return self.postfix(VectorSelector(metric, matchers))
        name = self.ident()
        low = name.lower()
        if low in ("inf", "nan"):
            return NumberLit(float(low))
        self._ws()
        if low in AGG_OPS and self.peek_char() in "(bw":
            # aggregation: op [by/without (...)] (expr) | op(...) [by/without]
            grouping: list[str] = []
            without = False
            if self.peek_ident() in ("by", "without"):
                kw = self.ident()
                without = kw == "without"
                grouping = self._label_list()
            self.expect("(")
            param = None
            first = self.expr(1)
            if low in PARAM_AGGS:
                param = first
                self.expect(",")
                inner = self.expr(1)
            else:
                inner = first
            self.expect(")")
            if not grouping and not without and self.peek_ident() in ("by", "without"):
                kw = self.ident()
                without = kw == "without"
                grouping = self._label_list()
            return Aggregation(low, inner, param, grouping, without)
        if self.peek_char() == "(" and low not in AGG_OPS:
            self.expect("(")
            args: list[PromExpr] = []
            self._ws()
            if self.peek_char() != ")":
                args.append(self.expr(1))
                while self.eat(","):
                    args.append(self.expr(1))
            self.expect(")")
            return FunctionCall(low, args)
        matchers = []
        if self.peek_char() == "{":
            matchers = self._matchers()
        return VectorSelector(name, matchers)

    def _matchers(self) -> list[LabelMatcher]:
        self.expect("{")
        out: list[LabelMatcher] = []
        self._ws()
        if self.peek_char() == "}":
            self.expect("}")
            return out
        while True:
            name = self.ident()
            self._ws()
            op = None
            for cand in ("=~", "!~", "!=", "="):
                if self.s.startswith(cand, self.i):
                    op = cand
                    self.i += len(cand)
                    break
            if op is None:
                raise SyntaxError_(f"expected matcher op at {self.i}")
            if op == "=" and self.s.startswith("=", self.i):  # ==
                raise SyntaxError_(f"bad matcher at {self.i}")
            value = self.string()
            out.append(LabelMatcher(name, op, value))
            if not self.eat(","):
                break
            self._ws()
            if self.peek_char() == "}":
                break
        self.expect("}")
        return out


def parse_promql(s: str) -> PromExpr:
    return PromParser(s).parse()
