"""PromQL engine: Prometheus query language over TPU tensors.

The reference compiles PromQL to DataFusion plans with custom extension
operators (SURVEY.md §2.3: SeriesNormalize, RangeManipulate, SeriesDivide,
ExtrapolatedRate...). Here the whole range-vector pipeline lowers to one
XLA computation over a dense ``[series, steps]`` value matrix (SURVEY.md
§3.3: "exactly the loop the TPU build turns into an XLA computation"):
window boundaries by composite-key searchsorted, rate/increase by
counter-reset-adjusted cumulative sums, cross-series aggregation by
segment reduction over the series axis.
"""

from greptimedb_tpu.promql.parser import parse_promql

__all__ = ["parse_promql"]
