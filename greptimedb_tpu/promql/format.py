"""Prometheus API response payloads — the ONE formatting definition,
shared by the HTTP API (servers/http.py) and the gRPC PromQL gateway
(rpc/promgateway.py; reference src/servers/src/grpc/prom_query_gateway.rs
reuses the HTTP handlers' types the same way)."""

from __future__ import annotations

import numpy as np


def fmt_val(v: float) -> str:
    if np.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    return repr(float(v))


def instant_payload(res, steps) -> dict:
    vals = np.asarray(res.values, dtype=np.float64)
    result = []
    for s, lab in enumerate(res.labels):
        v = vals[s, -1]
        if not np.isnan(v):
            result.append({
                "metric": {k: str(x) for k, x in lab.items()},
                "value": [steps[-1] / 1000.0, fmt_val(v)],
            })
    return {"status": "success",
            "data": {"resultType": "vector", "result": result}}


def range_payload(res, steps) -> dict:
    vals = np.asarray(res.values, dtype=np.float64)
    result = []
    for s, lab in enumerate(res.labels):
        pts = [
            [steps[t] / 1000.0, fmt_val(vals[s, t])]
            for t in range(len(steps))
            if not np.isnan(vals[s, t])
        ]
        if pts:
            result.append({"metric": {k: str(v) for k, v in lab.items()},
                           "values": pts})
    return {"status": "success",
            "data": {"resultType": "matrix", "result": result}}


def evaluate(db, query: str, start_s: float, end_s: float,
             step_s: float, lookback_s: float | None = None) -> dict:
    """Parse + evaluate + format in one call (instant when start == end)."""
    from greptimedb_tpu.promql.engine import (
        DEFAULT_LOOKBACK_S, PromEvaluator,
    )
    from greptimedb_tpu.promql.parser import parse_promql

    expr = parse_promql(query)
    ev = PromEvaluator(db, start_s, end_s, step_s,
                       lookback_s or DEFAULT_LOOKBACK_S)
    res = ev.eval(expr)
    steps = ev.steps_ms()
    if start_s == end_s:
        return instant_payload(res, steps)
    return range_payload(res, steps)
