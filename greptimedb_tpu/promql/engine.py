"""PromQL evaluation: range queries as dense [series, steps] tensor programs.

Pipeline per selector (SURVEY.md §3.3's hot loop, TPU-shaped):
1. host: match series against label matchers over the region's series
   registry (dictionary codes, no string work on device);
2. device: one jitted window kernel per (table shape-class, range, steps)
   computes per-(series, step) window stats — boundaries by composite-key
   searchsorted over the (tsid, ts)-sorted resident table, sums by
   counter-reset-adjusted cumulative sums (exact Prometheus extrapolation,
   reference src/promql/src/functions/extrapolate_rate.rs:56), min/max by
   multi-bucket segment scatter;
3. device: cross-series aggregation = segment reduction over the series
   axis; binary-op vector matching joins series on host, aligns rows on
   device.

NaN encodes "absent" throughout (Prometheus staleness semantics).
"""

from __future__ import annotations

import collections
import collections.abc
import math
import os
import re
import time
from dataclasses import dataclass, field
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from greptimedb_tpu.errors import PlanError, TableNotFound, Unsupported
from greptimedb_tpu.promql.parser import (
    Aggregation, BinaryExpr, FunctionCall, LabelMatcher, NumberLit, PromExpr,
    StringLit, SubqueryExpr, UnaryExpr, VectorSelector, parse_promql,
)
from greptimedb_tpu.storage.memtable import TSID
from greptimedb_tpu.utils.telemetry import REGISTRY
from greptimedb_tpu.utils.tracing import TRACER

DEFAULT_LOOKBACK_S = 300.0

# Per-stage wall time of the PromQL hot loop (selection → sort_layout →
# window_kernel → group_agg → label_decode), the PromQL twin of the SQL
# engine's stage marks.  Observed per evaluation; the disabled-tracer
# path costs one perf_counter pair per stage.
M_PROMQL_STAGE = REGISTRY.histogram(
    "greptime_promql_stage_seconds",
    "PromQL evaluation stage wall time",
    labels=("stage",),
)

_I64_MAX = np.int64(np.iinfo(np.int64).max)


class LazySeriesLabels(collections.abc.Sequence):
    """Label dicts for a matched series set, decoded ON DEMAND.

    The round-5 profile showed per-eval O(series) host work dominating the
    1M-series PromQL bench; the single largest term was select_series
    materializing one Python dict per matched series.  This sequence keeps
    only the tsid vector plus references into the region's dictionary
    state (codes + vocabularies) and builds a dict only when someone
    actually indexes it — aggregation never does (group ids come from the
    code columns), so a `sum by(pod) (rate(m[5m]))` run decodes exactly
    the output groups.

    Also carries the selection's provenance (region id, generation,
    matcher key) so eval_aggregation can key its resident group-id cache.
    ``materializations`` counts dict constructions process-wide — the
    tier-1 guard test pins it to O(output groups).
    """

    materializations = 0

    def __init__(self, idx, tag_names, values, tsids, region_id: int,
                 generation: int, matcher_key: tuple, cache):
        self.idx = idx  # SeriesInvertedIndex (codes + vocabs)
        self.tag_names = tag_names
        self.values = values  # column -> raw encoder values (code-indexed)
        self.tsids = tsids  # np.int32 [S]
        self.region_id = region_id
        self.generation = generation
        self.matcher_key = matcher_key
        self.cache = cache  # PromLayoutCache or None

    def _label_at(self, i: int) -> dict:
        LazySeriesLabels.materializations += 1
        tsid = int(self.tsids[i])
        codes = self.idx.codes
        values = self.values
        return {
            name: values[name][int(codes[name][tsid])]
            for name in self.tag_names
            if 0 <= codes[name][tsid] < len(values[name])
        }

    def __len__(self) -> int:
        return len(self.tsids)

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [self._label_at(j) for j in range(*i.indices(len(self)))]
        return self._label_at(i)

    def __eq__(self, other):
        if not isinstance(other, (list, tuple, collections.abc.Sequence)):
            return NotImplemented
        return len(self) == len(other) and all(
            a == b for a, b in zip(self, other))

    def __ne__(self, other):
        eq = self.__eq__(other)
        return eq if eq is NotImplemented else not eq

    def __repr__(self) -> str:
        return f"<LazySeriesLabels n={len(self)}>"


class LazyGroupLabels(collections.abc.Sequence):
    """Aggregation output labels, decoded per GROUP on demand: group g's
    dict comes from its representative (first-appearance) input series via
    the host group-key rule, so semantics are identical to the eager loop
    while only ng dicts are ever built."""

    def __init__(self, source, rep_rows, key_fn):
        self.source = source  # input labels (usually LazySeriesLabels)
        self.rep_rows = rep_rows  # np [ng] row index of each group's rep
        self.key_fn = key_fn  # lab dict -> ((k, str v), ...) group key

    def __len__(self) -> int:
        return len(self.rep_rows)

    def _label_at(self, g: int) -> dict:
        return dict(self.key_fn(self.source[int(self.rep_rows[g])]))

    def __getitem__(self, g):
        if isinstance(g, slice):
            return [self._label_at(j) for j in range(*g.indices(len(self)))]
        return self._label_at(g)

    def __eq__(self, other):
        if not isinstance(other, (list, tuple, collections.abc.Sequence)):
            return NotImplemented
        return len(self) == len(other) and all(
            a == b for a, b in zip(self, other))

    def __ne__(self, other):
        eq = self.__eq__(other)
        return eq if eq is NotImplemented else not eq

    def __repr__(self) -> str:
        return f"<LazyGroupLabels n={len(self)}>"


@dataclass
class EvalResult:
    """A (possibly scalar) instant-vector time series matrix."""

    values: jnp.ndarray  # [S, T] f32; NaN = absent
    labels: "list[dict] | LazySeriesLabels | LazyGroupLabels"  # len S
    is_scalar: bool = False

    @property
    def num_series(self) -> int:
        return len(self.labels)


def matcher_pred(matcher: LabelMatcher):
    """Matcher → (term predicate, negate) — the single definition of
    PromQL matcher semantics, evaluated per DISTINCT term by the inverted
    index (=~ is fully anchored, as in Prometheus)."""
    if matcher.op == "=":
        return (lambda t, mv=matcher.value: t == mv), False
    if matcher.op == "!=":
        return (lambda t, mv=matcher.value: t == mv), True
    if matcher.op in ("=~", "!~"):
        rx = re.compile(matcher.value)
        return (lambda t, rx=rx: rx.fullmatch(t) is not None), (
            matcher.op == "!~"
        )
    raise PlanError(f"bad matcher {matcher.op}")


def _series_group_ids(idx, tsids: np.ndarray, grouping, without: bool):
    """Vectorized by/without group assignment from dictionary-encoded tag
    codes — no per-series Python.  Per relevant column, codes remap to
    canonical str-level term ids (missing merges with "" for ``by``,
    stays distinct for ``without`` — exactly the information the host
    group-key tuple carries); columns combine mixed-radix with dense
    re-encoding before any possible int64 overflow; final ids renumber by
    first appearance so group order matches the host enumeration.

    Returns (gid_dev [S] i32, ng, rep_rows np [ng], row_order_dev [S],
    seg_start np [ng])."""
    if without:
        use = sorted(n for n in idx.tag_names if n not in grouping)
    else:
        use = sorted(n for n in grouping if n in idx.codes)
    S = len(tsids)
    tsids64 = tsids.astype(np.int64)
    combined = np.zeros(S, dtype=np.int64)
    ncomb = 1
    for name in use:
        codes = idx.codes_for(name, tsids64)
        V = len(idx.vocabs.get(name, []))
        remap, ncanon = idx.canonical_codes(name, merge_missing_empty=not without)
        pres = (codes >= 0) & (codes < V)
        comp = remap[np.where(pres, codes, V)]
        if ncanon > 1 and ncomb > (1 << 62) // ncanon:
            _u, combined = np.unique(combined, return_inverse=True)
            ncomb = len(_u)
        combined = combined * ncanon + comp
        ncomb *= max(ncanon, 1)
    _uniq, first_idx, inv = np.unique(
        combined, return_index=True, return_inverse=True)
    order = np.argsort(first_idx, kind="stable")
    rank = np.empty(len(_uniq), dtype=np.int64)
    rank[order] = np.arange(len(_uniq))
    gids = rank[inv].astype(np.int32)
    ng = len(_uniq)
    rep_rows = first_idx[order]
    row_order = np.argsort(gids, kind="stable")
    seg_start = np.searchsorted(gids[row_order], np.arange(ng))
    return (jnp.asarray(gids), ng, rep_rows, jnp.asarray(row_order),
            seg_start)


# ---------------------------------------------------------------------------
# Window kernels
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class WindowParams:
    """Static shape-class key for window kernels. start_ms is deliberately
    NOT here — it is a traced argument, so repeated queries at different
    times reuse one compiled program."""

    step_ms: int
    num_steps: int
    range_ms: int  # window width (lookback for instant selectors)
    num_sel: int  # padded selected series count
    total_series: int
    kind: str  # which stats to compute
    # padded max samples-per-series when the resident per-series bounds
    # matrix serves window geometry (None = searchsorted over the full
    # sorted key array); part of the key because the two geometries
    # compile to different programs
    bounds_l: int | None = None


_KERNEL_CACHE: dict[WindowParams, object] = {}


@jax.jit
def _build_sort_layout(ts, val, tsid, mask):
    """Composite-key sort of a resident table, QUERY-INDEPENDENT: the key
    packs (tsid, ts − ts_min) with a stride covering the table's full time
    span, so the permutation (and the gathered ts/val/tsid/valid arrays)
    depends only on the data — it is built once per (region generation,
    field column) and served resident by PromLayoutCache instead of being
    re-derived inside every window kernel call.  Invalid rows (padding,
    NULL values) sort to the end via a +inf key.

    Returns (key_s, ts_s, val_s, tsid_s, valid_s, ts_min, kp); ts_min/kp
    are 0-d device scalars, traced through the kernels so one compiled
    program serves every region of the same shape class.
    """
    valid = mask & ~jnp.isnan(val)
    any_valid = valid.any()
    ts_min = jnp.where(
        any_valid, jnp.min(jnp.where(valid, ts, _I64_MAX)), jnp.int64(0))
    ts_max = jnp.where(
        any_valid,
        jnp.max(jnp.where(valid, ts, jnp.int64(-(1 << 62)))), jnp.int64(0))
    # stride: rel = ts - ts_min ∈ [0, kp-2], so clip-to-(kp-1) bounds stay
    # strictly above every data key (searchsorted side="right" correctness)
    kp = ts_max - ts_min + 2
    key = jnp.where(valid, tsid.astype(jnp.int64) * kp + (ts - ts_min),
                    _I64_MAX)
    order = jnp.argsort(key)
    return (key[order], ts[order], val[order], tsid[order], valid[order],
            ts_min, kp)


def _sorted_window_bounds(p: WindowParams, key_s, ts_min, kp, sel_tsids,
                          start_ms, bounds=None):
    """Shared window geometry for all window kernels over a PRESORTED
    resident layout (_build_sort_layout): per-(series, step) half-open
    sample ranges [lo, hi) with LEFT-EXCLUSIVE window semantics
    (t - range, t] — the ONE definition the stats kernel and the matrix
    kernels build on.

    Two interchangeable geometries (identical integer bounds, so results
    are bit-exact either way):

    - searchsorted (default): composite-key binary search over the full
      sorted array — O(S·T·log N) RANDOM accesses, the right shape for
      many steps;
    - per-series bounds matrix (``bounds`` = (series_start [S], cnt_s [S],
      ts_mat [S, L]), resident per selection): each window boundary is a
      count of that series' timestamps ≤ threshold — O(S·T·L) SEQUENTIAL
      compares, ~10× faster for instant-style queries where the
      binary search is DRAM-latency-bound.

    Returns (lo, hi, cnt, has, sel_ok, n)."""
    T = p.num_steps
    S = p.num_sel
    n = key_s.shape[0]
    steps = start_ms + p.step_ms * jnp.arange(T, dtype=jnp.int64)  # [T]
    sel_ok = sel_tsids >= 0
    if bounds is not None:
        series_start, cnt_s, ts_mat = bounds
        # lo offset = #samples with ts ≤ t − range (left-exclusive window
        # starts right after them); hi offset = #samples with ts ≤ t.
        # Padding slots hold I64_MAX so they never count.
        lo_off = jnp.sum(
            ts_mat[:, None, :] <= (steps - p.range_ms)[None, :, None],
            axis=-1, dtype=jnp.int32)
        hi_off = jnp.sum(
            ts_mat[:, None, :] <= steps[None, :, None],
            axis=-1, dtype=jnp.int32)
        lo = series_start[:, None] + lo_off
        hi = series_start[:, None] + hi_off
        cnt = hi_off - lo_off
        has = (cnt > 0) & sel_ok[:, None]
        return lo, hi, cnt, has, sel_ok, n
    sel64 = sel_tsids.astype(jnp.int64)  # [S]
    skey = jnp.where(sel_ok, sel64, 0) * kp  # [S]
    # window (t - range, t]: left-exclusive.  rel_hi clips to -1 (a key
    # strictly below this series' first sample) so windows entirely before
    # the data come out empty; both clips cap at kp-1 > every data rel.
    rel_lo = jnp.clip(steps[None, :] - p.range_ms + 1 - ts_min, 0, kp - 1)
    rel_hi = jnp.clip(steps[None, :] - ts_min, -1, kp - 1)
    lo = jnp.searchsorted(
        key_s, (skey[:, None] + rel_lo).reshape(-1), side="left"
    ).reshape(S, T)
    hi = jnp.searchsorted(
        key_s, (skey[:, None] + rel_hi).reshape(-1), side="right"
    ).reshape(S, T)
    cnt = jnp.maximum(hi - lo, 0).astype(jnp.int32)
    has = (cnt > 0) & sel_ok[:, None]
    return lo, hi, cnt, has, sel_ok, n


@jax.jit
def _series_ranges(key_s, kp, sel_tsids):
    """Query-independent row range of each selected series in the sorted
    layout: [start, start+cnt).  skey+kp−1 exceeds every key of the series
    (rel ≤ kp−2) and undercuts the next series' first key (skey+kp)."""
    sel_ok = sel_tsids >= 0
    skey = jnp.where(sel_ok, sel_tsids.astype(jnp.int64), 0) * kp
    start = jnp.searchsorted(key_s, skey, side="left")
    end = jnp.searchsorted(key_s, skey + (kp - 1), side="right")
    return start, jnp.where(sel_ok, (end - start).astype(jnp.int32), 0)


@partial(jax.jit, static_argnums=3)
def _gather_ts_mat(ts_s, start, cnt_s, L: int):
    """[S, L] per-series timestamp matrix (padding = I64_MAX so threshold
    compares never count it); rows gathered from the sorted layout."""
    n = ts_s.shape[0]
    j = jnp.arange(L, dtype=jnp.int32)
    idx = jnp.clip(start[:, None] + j[None, :], 0, n - 1)
    mat = ts_s[idx]
    return jnp.where(j[None, :] < cnt_s[:, None], mat, _I64_MAX)


def _window_kernel(p: WindowParams):  # gl: warm-path
    """Build the jitted kernel computing window stats for selected series.

    Inputs: the presorted resident layout (key_s [N] i64, ts_s [N] i64,
            val_s [N] f32, tsid_s [N] i32, valid_s [N] bool, ts_min, kp —
            see _build_sort_layout), sel_tsids [S] i32 (padded with -1),
            start_ms scalar i64.
    Output dict of [S, T] arrays depending on p.kind.
    """
    return jax.jit(_window_body(p))


def _window_body(p: WindowParams):  # gl: warm-path
    """The UNJITTED window-stats program for one shape class — the exact
    function ``_window_kernel`` jits.  Exposed separately so the
    whole-plan fused programs (compile/fused.py) can compose it with the
    function epilogue and group reduction inside ONE jit: a single
    program source means fused and unfused window math can never
    diverge."""

    T = p.num_steps
    S = p.num_sel

    def kernel(key_s, ts_s, val_s, tsid_s, valid_s, ts_min, kp, *rest):
        if p.bounds_l is not None:
            series_start, cnt_s, ts_mat, sel_tsids, start_ms = rest
            bounds = (series_start, cnt_s, ts_mat)
        else:
            sel_tsids, start_ms = rest
            bounds = None
        lo, hi, cnt, has, sel_ok, n = _sorted_window_bounds(
            p, key_s, ts_min, kp, sel_tsids, start_ms, bounds)

        # per-series counter-reset adjustment (for counter kinds)
        prev_same = jnp.concatenate(
            [jnp.array([False]), (tsid_s[1:] == tsid_s[:-1]) & valid_s[1:] & valid_s[:-1]]
        )
        prev_val = jnp.concatenate([val_s[:1] * 0, val_s[:-1]])
        drop = jnp.where(prev_same & (prev_val > val_s), prev_val, 0.0)
        gdrop = jnp.cumsum(drop.astype(jnp.float64))
        # offset at series start: first valid index per selected series found
        # via searchsorted of tsid*K
        adj = val_s.astype(jnp.float64) + gdrop  # minus series-start gdrop via window diff

        # cumulative sums (leading zero) over sorted order
        def cs(x):
            x64 = x.astype(jnp.float64)
            return jnp.concatenate([jnp.zeros(1, jnp.float64), jnp.cumsum(x64)])

        cs_v = cs(jnp.where(valid_s, val_s, 0.0))
        cs_v2 = cs(jnp.where(valid_s, val_s.astype(jnp.float64) ** 2, 0.0))
        tsec = (ts_s - start_ms).astype(jnp.float64) / 1000.0
        cs_t = cs(jnp.where(valid_s, tsec, 0.0))
        cs_tv = cs(jnp.where(valid_s, tsec * val_s.astype(jnp.float64), 0.0))
        cs_t2 = cs(jnp.where(valid_s, tsec * tsec, 0.0))

        has2 = (cnt >= 2) & sel_ok[:, None]

        first_i = jnp.clip(lo, 0, n - 1)
        last_i = jnp.clip(hi - 1, 0, n - 1)
        out = {}
        fcnt = cnt.astype(jnp.float32)
        nan = jnp.float32(jnp.nan)

        if p.kind in ("counter", "counter_rc", "gauge_window", "regression",
                      "instant"):
            out["count"] = jnp.where(has, fcnt, 0.0)
        if p.kind == "instant":
            lastv = val_s[last_i]
            out["last"] = jnp.where(has, lastv, nan)
            out["last_ts"] = jnp.where(has, ts_s[last_i], 0)
        if p.kind == "counter":
            ft = ts_s[first_i]
            lt = ts_s[last_i]
            fv = val_s[first_i]
            d_adj = (adj[last_i] - adj[first_i]).astype(jnp.float32)
            out["first_ts"] = jnp.where(has, ft, 0)
            out["last_ts"] = jnp.where(has, lt, 0)
            out["first_val"] = jnp.where(has, fv, nan)
            out["last_val"] = jnp.where(has, val_s[last_i], nan)
            out["delta_adj"] = jnp.where(has2, d_adj, nan)
            out["delta_raw"] = jnp.where(
                has2, val_s[last_i] - val_s[first_i], nan
            )
        if p.kind == "counter_rc":
            # resets/changes counts via indicator cumsums — a SEPARATE
            # kind so the (much hotter) rate/increase/delta path doesn't
            # pay two extra full-table cumsums it never reads
            ind_reset = jnp.where(prev_same & (prev_val > val_s), 1.0, 0.0)
            ind_change = jnp.where(prev_same & (prev_val != val_s), 1.0, 0.0)
            cs_r = cs(ind_reset)
            cs_c = cs(ind_change)
            # exclude the boundary pair crossing into the window: indicator at
            # index i compares i-1,i; window pairs are (lo+1..hi-1)
            lo1 = jnp.clip(lo + 1, 0, n)
            out["resets"] = jnp.where(has, (cs_r[hi] - cs_r[lo1]).astype(jnp.float32), nan)
            out["changes"] = jnp.where(has, (cs_c[hi] - cs_c[lo1]).astype(jnp.float32), nan)
        if p.kind in ("gauge_window",):
            s = (cs_v[hi] - cs_v[lo]).astype(jnp.float32)
            s2 = (cs_v2[hi] - cs_v2[lo]).astype(jnp.float32)
            out["sum"] = jnp.where(has, s, nan)
            out["avg"] = jnp.where(has, s / jnp.maximum(fcnt, 1), nan)
            mean = s.astype(jnp.float64) / jnp.maximum(cnt, 1)
            var = (cs_v2[hi] - cs_v2[lo]) / jnp.maximum(cnt, 1) - mean * mean
            out["var"] = jnp.where(has, jnp.maximum(var, 0.0).astype(jnp.float32), nan)
            out["last"] = jnp.where(has, val_s[last_i], nan)
            out["first"] = jnp.where(has, val_s[first_i], nan)
            out["first_ts"] = jnp.where(has, ts_s[first_i], 0)
            out["last_ts"] = jnp.where(has, ts_s[last_i], 0)
        if p.kind == "regression":
            sw = (cs_v[hi] - cs_v[lo])
            st = cs_t[hi] - cs_t[lo]
            stv = cs_tv[hi] - cs_tv[lo]
            st2 = cs_t2[hi] - cs_t2[lo]
            cn = cnt.astype(jnp.float64)
            denom = cn * st2 - st * st
            slope = jnp.where(denom != 0, (cn * stv - st * sw) / denom, jnp.nan)
            intercept = jnp.where(cn > 0, (sw - slope * st) / cn, jnp.nan)
            out["slope"] = jnp.where(has2, slope.astype(jnp.float32), nan)
            out["intercept"] = jnp.where(has2, intercept.astype(jnp.float32), nan)
            out["last_ts"] = jnp.where(has, ts_s[last_i], 0)
        if p.kind == "irate":
            lastv = val_s[last_i]
            prev_i = jnp.clip(hi - 2, 0, n - 1)
            prevv = val_s[prev_i]
            out["last_ts"] = jnp.where(has2, ts_s[last_i], 0)
            out["prev_ts"] = jnp.where(has2, ts_s[prev_i], 0)
            out["last_val"] = jnp.where(has2, lastv, nan)
            out["prev_val"] = jnp.where(has2, prevv, nan)
        if p.kind == "minmax":
            # multi-bucket scatter: sample contributes to ceil(r/step)+1
            # windows; fori_loop keeps compile size O(1) in range/step ratio
            kmax = int(p.range_ms // p.step_ms + 1)  # gl: allow[GL-H001] -- static WindowParams config, folded at trace time
            row_of = jnp.full((p.total_series + 1,), -1, dtype=jnp.int32)
            row_of = row_of.at[jnp.where(sel_ok, sel_tsids, p.total_series)].set(
                jnp.arange(S, dtype=jnp.int32)
            )
            rows = row_of[jnp.clip(tsid_s, 0, p.total_series)]
            rows = jnp.where(valid_s & (tsid_s >= 0), rows, -1)
            # first window index receiving this sample: smallest i with
            # start + i*step >= ts  →  i = ceil((ts-start)/step)
            i0 = -((start_ms - ts_s) // p.step_ms)  # ceil div

            def body(k, carry):
                mn, mx = carry
                i_k = i0 + k
                in_win = (
                    (rows >= 0)
                    & (i_k >= 0)
                    & (i_k < T)
                    & ((start_ms + i_k * p.step_ms) - ts_s < p.range_ms)
                    & ((start_ms + i_k * p.step_ms) >= ts_s)
                )
                gid = jnp.where(in_win, rows.astype(jnp.int64) * T + i_k, S * T)
                mn = mn.at[gid].min(jnp.where(in_win, val_s, jnp.inf))
                mx = mx.at[gid].max(jnp.where(in_win, val_s, -jnp.inf))
                return mn, mx

            mn0 = jnp.full((S * T + 1,), jnp.inf, dtype=jnp.float32)
            mx0 = jnp.full((S * T + 1,), -jnp.inf, dtype=jnp.float32)
            mn, mx = jax.lax.fori_loop(0, kmax, body, (mn0, mx0))
            mn = mn[:-1].reshape(S, T)
            mx = mx[:-1].reshape(S, T)
            out["min"] = jnp.where(jnp.isfinite(mn), mn, nan)
            out["max"] = jnp.where(jnp.isfinite(mx), mx, nan)
        return out

    return kernel


def _count_max_kernel(p: WindowParams):  # gl: warm-path
    """Max samples in any (series, step) window — sizes the matrix
    kernels' static padded width (one cheap pass, cached per shape)."""

    @jax.jit
    def kernel(key_s, ts_s, val_s, tsid_s, valid_s, ts_min, kp, sel_tsids,
               start_ms):
        _lo, _hi, cnt, _has, sel_ok, _n = _sorted_window_bounds(
            p, key_s, ts_min, kp, sel_tsids, start_ms)
        return jnp.max(jnp.where(sel_ok[:, None], cnt, 0))

    return kernel


def _matrix_kernel(p: WindowParams, lmax: int, kind: str):  # gl: warm-path
    """Window-matrix kernels: gather each (series, step) window's samples
    (time-ordered, padded to the static width ``lmax``) into a
    [S*T, lmax] matrix, then

    - ``quantile``: per-row sort + Prometheus linear-interpolation
      quantile (reference src/promql/src/functions/quantile.rs semantics)
    - ``mad``: median, then median of |x − median| (mad_over_time)
    - ``holt``: Holt's linear (double) exponential smoothing scan over
      the window (reference
      src/promql/src/functions/double_exponential_smoothing.rs)

    Scalar parameters (φ / sf, tf) arrive as traced [T] f32 vectors so
    repeated queries share one compiled program.
    """
    T, S = p.num_steps, p.num_sel

    @jax.jit
    def kernel(key_s, ts_s, val_s, tsid_s, valid_s, ts_min, kp, sel_tsids,
               start_ms, a1, a2):
        lo, hi, cnt, has, sel_ok, n = _sorted_window_bounds(
            p, key_s, ts_min, kp, sel_tsids, start_ms)
        lof = lo.reshape(-1)  # [W] with W = S*T
        cntf = cnt.reshape(-1)
        j = jnp.arange(lmax, dtype=jnp.int32)
        idx = jnp.clip(lof[:, None] + j[None, :], 0, n - 1)
        rows = val_s[idx]  # [W, L] time-ordered window samples
        ok = j[None, :] < cntf[:, None]
        nan = jnp.float32(jnp.nan)
        inf = jnp.float32(jnp.inf)

        def q_of(sorted_rows, q):
            """Prometheus quantile over per-row ascending values: linear
            interpolation between the two straddling order statistics."""
            rank = q * jnp.maximum(cntf - 1, 0).astype(jnp.float32)
            lo_r = jnp.clip(jnp.floor(rank).astype(jnp.int32), 0, lmax - 1)
            hi_r = jnp.clip(jnp.ceil(rank).astype(jnp.int32), 0, lmax - 1)
            vlo = jnp.take_along_axis(sorted_rows, lo_r[:, None], axis=1)[:, 0]
            vhi = jnp.take_along_axis(sorted_rows, hi_r[:, None], axis=1)[:, 0]
            return vlo + (vhi - vlo) * (rank - lo_r.astype(jnp.float32))

        if kind == "quantile":
            srt = jnp.sort(jnp.where(ok, rows, inf), axis=1)
            qv = jnp.broadcast_to(a1[None, :], (S, T)).reshape(-1)
            res = q_of(srt, qv)
            # Prometheus: φ < 0 → -Inf, φ > 1 → +Inf (NaN propagates)
            res = jnp.where(qv < 0, -inf, jnp.where(qv > 1, inf, res))
        elif kind == "mad":
            srt = jnp.sort(jnp.where(ok, rows, inf), axis=1)
            med = q_of(srt, jnp.float32(0.5))
            dev = jnp.sort(
                jnp.where(ok, jnp.abs(rows - med[:, None]), inf), axis=1)
            res = q_of(dev, jnp.float32(0.5))
        elif kind == "holt":
            sf = jnp.broadcast_to(a1[None, :], (S, T)).reshape(-1)
            tf = jnp.broadcast_to(a2[None, :], (S, T)).reshape(-1)
            s0 = rows[:, 0]
            b0 = rows[:, min(1, lmax - 1)] - s0

            def body(i, carry):
                s, b = carry
                x = jax.lax.dynamic_slice_in_dim(rows, i, 1, axis=1)[:, 0]
                act = i < cntf
                s1 = sf * x + (1 - sf) * (s + b)
                b1 = tf * (s1 - s) + (1 - tf) * b
                return jnp.where(act, s1, s), jnp.where(act, b1, b)

            s_fin, _b = jax.lax.fori_loop(1, lmax, body, (s0, b0))
            # Prometheus needs ≥2 samples and factors in (0, 1)
            param_ok = (sf > 0) & (sf < 1) & (tf > 0) & (tf < 1)
            res = jnp.where((cntf >= 2) & param_ok, s_fin, nan)
        else:  # pragma: no cover
            raise ValueError(f"matrix kind {kind}")
        out = jnp.where(cntf > 0, res, nan).reshape(S, T)
        return jnp.where(has, out, nan)

    return kernel


class SelectorData:
    """Host-side prepared state for one table used by selectors."""

    def __init__(self, db, table: str, events=None):
        # partitioned tables come back as a CombinedRegionView duck-typing
        # the Region surface (encoders/_series/scan_host/num_series)
        region = (
            db._table_view(table) if hasattr(db, "_table_view")
            else db._region_of(table)
        )
        self.db = db
        self.region = region
        self.table = db.cache.get(region)
        self.schema = region.schema
        self.ts_name = region.schema.time_index.name
        self.tag_names = region.tag_names
        self.encoders = region.encoders
        # per-eval cache event counter shared with the evaluator (bench
        # observability: selection/sort/group hit/miss/reject/uncached)
        self.events = events if events is not None else collections.Counter()

    def promql_cache(self):
        """The db's resident PromLayoutCache, or None when caching is off
        (GREPTIME_PROMQL_CACHE=off A/B knob) or the db has none.  Both
        states serve evals from the identical transient-build code path,
        so cached and uncached results are bit-exact by construction."""
        if os.environ.get("GREPTIME_PROMQL_CACHE", "on") == "off":
            return None
        return getattr(self.db, "promql_cache", None)

    def field_column(self, matchers: list[LabelMatcher]) -> str:
        fields = [c.name for c in self.schema.field_columns]
        for m in matchers:
            if m.name == "__field__":
                if m.value not in fields:
                    raise PlanError(f"field {m.value} not in {self.table!r}")
                return m.value
        for cand in ("greptime_value", "val", "value"):
            if cand in fields:
                return cand
        if len(fields) == 1:
            return fields[0]
        raise PlanError(
            f"table has {len(fields)} fields; use __field__ matcher: {fields}"
        )

    def select_series(
        self, matchers: list[LabelMatcher]
    ) -> tuple[np.ndarray, jnp.ndarray, LazySeriesLabels]:
        """Returns (tsids, padded device tsids, lazy labels) matching the
        label matchers.

        Inverted-index evaluation (storage/inverted.py): each matcher runs
        once per DISTINCT term of its label and selects via posting lists —
        O(vocabulary) string work, not O(series).  The reference gets the
        same effect from its FST+bitmap inverted index
        (src/index/src/inverted_index/).  The matched tsid set (and its
        pow2-padded device copy) is resident per (region generation,
        matcher set); labels are NOT materialized here — LazySeriesLabels
        decodes a dict only when indexed, so aggregations touch zero
        per-series Python objects."""
        from greptimedb_tpu.storage.inverted import get_series_index

        tag_matchers = [m for m in matchers if m.name != "__field__"]
        mkey = tuple(sorted((m.name, m.op, m.value) for m in tag_matchers))
        # registry-only version: selections (and the group ids derived
        # from them) survive data appends of existing series
        gen = getattr(self.region, "series_generation",
                      self.region.generation)
        idx = get_series_index(self.region)
        cache = self.promql_cache()
        rid = getattr(self.region, "region_id", None)
        sel = None
        if cache is not None and rid is not None:
            sel = cache.lookup("selection", rid, mkey, gen)
            self.events["selection_hit" if sel is not None
                        else "selection_miss"] += 1
        if sel is None:
            sel_tsids = idx.all_tsids
            for m in tag_matchers:
                if sel_tsids.size == 0:
                    break
                pred, neg = matcher_pred(m)
                matched = idx.select(m.name, pred, negate=neg)
                sel_tsids = np.intersect1d(sel_tsids, matched,
                                           assume_unique=True)
            sel_tsids = sel_tsids.astype(np.int32)
            S = max(1, 1 << (max(len(sel_tsids), 1) - 1).bit_length())
            padded = np.full(S, -1, dtype=np.int32)
            padded[: len(sel_tsids)] = sel_tsids
            sel_dev = jnp.asarray(padded)
            if cache is not None and cache.mesh is not None:
                from greptimedb_tpu.parallel.dist import promql_row_shardings

                sh = promql_row_shardings(cache.mesh, S)
                if sh is not None:
                    sel_dev = jax.device_put(sel_dev, sh["rows"])
            sel = (sel_tsids, sel_dev)
            if cache is not None and rid is not None:
                nbytes = sel_tsids.nbytes + int(sel_dev.nbytes)
                if cache.admit(nbytes):
                    cache.store("selection", rid, mkey, gen, sel, nbytes)
                else:
                    self.events["selection_reject"] += 1
        sel_tsids, sel_dev = sel
        # label values decode from the index's shared per-region raw
        # vocabularies — selections hold no per-matcher-set copies
        labels = LazySeriesLabels(
            idx, self.tag_names, idx.raw_values, sel_tsids,
            rid if rid is not None else -1, gen, mkey, cache)
        return sel_tsids, sel_dev, labels

    def sort_layout(self, fieldcol: str) -> tuple:
        """The resident composite-key sort of this table for ``fieldcol``
        (see _build_sort_layout): served from PromLayoutCache per
        (resident-table dicts_version, field column); a miss builds and —
        if admission under the promql_cache workload quota succeeds —
        stores it.  A rejected build serves this eval transiently from
        the same arrays (reject-to-fallback, bit-exact either way)."""
        cache = self.promql_cache()
        rid = getattr(self.region, "region_id", None)
        version = self.table.dicts_version
        if cache is not None and rid is not None:
            payload = cache.lookup("sort", rid, (fieldcol,), version)
            if payload is not None:
                self.events["sort_hit"] += 1
                return payload
            self.events["sort_miss"] += 1
        cols = self.table.columns
        arrays = _build_sort_layout(
            cols[self.ts_name], cols[fieldcol], cols[TSID],
            self.table.row_mask)
        if cache is not None and rid is not None:
            nbytes = sum(int(a.nbytes) for a in arrays)
            if cache.admit(nbytes):
                if cache.mesh is not None:
                    from greptimedb_tpu.parallel.dist import (
                        promql_row_shardings,
                    )

                    sh = promql_row_shardings(cache.mesh,
                                              int(arrays[0].shape[0]))
                    if sh is not None:
                        arrays = tuple(
                            jax.device_put(a, sh["rows"]) if a.ndim else a
                            for a in arrays
                        )
                cache.store("sort", rid, (fieldcol,), version, arrays,
                            nbytes)
            else:
                self.events["sort_reject"] += 1
        return arrays

    def window_bounds(self, fieldcol: str, layout: tuple, sel_dev,
                      matcher_key: tuple):
        """Resident per-(selection, field) window-geometry state: each
        selected series' row range in the sorted layout plus its [S, L]
        timestamp matrix (L = padded max samples/series).  Window
        boundaries then cost O(T·L) sequential compares per series
        instead of an O(T·log N) DRAM-latency-bound binary search —
        ~10× on instant queries at 1M series.  Returns
        (series_start, cnt_s, ts_mat, L) or None (cache off / reject):
        callers fall back to the searchsorted geometry, which produces
        the same integer bounds bit-exactly."""
        cache = self.promql_cache()
        rid = getattr(self.region, "region_id", None)
        if cache is None or rid is None:
            return None  # resident-only accelerator; transient builds
            # would cost more than the searchsorted they replace
        version = self.table.dicts_version
        ckey = (matcher_key, fieldcol)
        payload = cache.lookup("bounds", rid, ckey, version)
        if payload is not None:
            self.events["bounds_hit"] += 1
            return payload
        self.events["bounds_miss"] += 1
        key_s, ts_s = layout[0], layout[1]
        kp = layout[6]
        start, cnt_s = _series_ranges(key_s, kp, sel_dev)
        lmax = int(jnp.max(cnt_s)) if cnt_s.size else 0
        L = max(1, 1 << (max(lmax, 1) - 1).bit_length())
        nbytes = int(start.nbytes) + int(cnt_s.nbytes) + \
            int(sel_dev.shape[0]) * L * 8
        if not cache.admit(nbytes):
            self.events["bounds_reject"] += 1
            return None
        ts_mat = _gather_ts_mat(ts_s, start, cnt_s, L)
        payload = (start, cnt_s, ts_mat, L)
        cache.store("bounds", rid, ckey, version, payload, nbytes)
        return payload


class PromEvaluator:
    def __init__(self, db, start_s: float, end_s: float, step_s: float,
                 lookback_s: float = DEFAULT_LOOKBACK_S):
        self.db = db
        if end_s < start_s:
            raise PlanError(f"invalid time range: end {end_s} < start {start_s}")
        if step_s <= 0:
            raise PlanError(f"invalid step: {step_s}")
        self.start_ms = int(round(start_s * 1000))
        self.step_ms = max(int(round(step_s * 1000)), 1)
        # integer-ms math: float division can drop the final (inclusive) step
        end_ms = int(round(end_s * 1000))
        self.num_steps = (end_ms - self.start_ms) // self.step_ms + 1
        self.lookback_ms = int(lookback_s * 1000)
        self._data: dict[str, SelectorData] = {}
        self._kernels: dict[tuple, object] = {}
        # NOTE: replay-context hygiene is a statement-boundary concern,
        # handled where statements end (_sql_locked's finally, the batch
        # entry, warmup replays) — an evaluator must NOT clear it here:
        # nested evaluators (subquery operands) are constructed MID-
        # statement and would strip the outer TQL's replay, leaving its
        # kernel classes permanently unwarmable.
        # resident-cache event counter for this evaluation (selection /
        # sort / group × hit / miss / reject) — surfaced to bench_promql
        self.cache_events: collections.Counter = collections.Counter()
        # per-stage wall ms for this evaluation (selection → sort_layout →
        # window_kernel → group_agg → label_decode): mirrored into the
        # registry histogram and, through execute_tql, into the standalone
        # stage sink so slow TQL queries self-report their breakdown
        self.stage_ms: dict[str, float] = {}

    def _stage_mark(self, name: str, t0: float) -> None:
        dt = time.perf_counter() - t0
        M_PROMQL_STAGE.labels(name).observe(dt)
        self.stage_ms[name] = round(
            self.stage_ms.get(name, 0.0) + dt * 1000, 3)

    def _compiler(self):
        """The db's PlanCompiler (persistent AOT store + usage journal),
        or the process default (memory-only classification) for embedded
        evaluators without one."""
        comp = getattr(self.db, "plan_compiler", None)
        if comp is None:
            from greptimedb_tpu.compile.service import default_compiler

            comp = default_compiler()
        return comp

    # ---- plumbing -------------------------------------------------------
    def data_for(self, metric: str) -> SelectorData:
        if metric not in self._data:
            self._data[metric] = SelectorData(self.db, metric,
                                              self.cache_events)
        return self._data[metric]

    def steps_ms(self) -> np.ndarray:
        return self.start_ms + self.step_ms * np.arange(self.num_steps, dtype=np.int64)

    _KIND_KEYS = {
        "instant": ("count", "last", "last_ts"),
        "counter": ("count", "first_ts", "last_ts", "first_val", "last_val",
                    "delta_adj", "delta_raw"),
        "counter_rc": ("count", "resets", "changes"),
        "gauge_window": ("count", "sum", "avg", "var", "last", "first",
                         "first_ts", "last_ts"),
        "regression": ("count", "slope", "intercept", "last_ts"),
        "irate": ("last_ts", "prev_ts", "last_val", "prev_val"),
        "minmax": ("min", "max"),
    }

    def _prep_window(self, sel: VectorSelector, kind: str,
                     range_ms: int | None = None,
                     allow_bounds: bool = True):
        """Shared selector→kernel-args prep for the stats and matrix
        kernels (ONE definition of pow2 series padding, range/offset/@
        resolution, and the kernel argument tuple).  Returns
        (args, p, tsids, labels, pinned, start, rng); raises
        TableNotFound for unknown metrics (callers map it to an empty
        vector, Prometheus semantics)."""
        d = self.data_for(sel.metric)
        fieldcol = d.field_column(sel.matchers)
        t0 = time.perf_counter()
        with TRACER.stage("selection"):
            tsids, sel_dev, labels = d.select_series(sel.matchers)
        self._stage_mark("selection", t0)
        S = int(sel_dev.shape[0])
        rng = range_ms
        if rng is None:
            rng = int(sel.range_s * 1000) if sel.range_s else self.lookback_ms
        offset_ms = int(sel.offset_s * 1000)
        # @ modifier pins evaluation time: compute ONE step at at_ts (minus
        # offset, per Prometheus), then broadcast across the output grid
        pinned = sel.at_ts is not None
        if pinned:
            start = int(sel.at_ts * 1000) - offset_ms
            num_steps = 1
        else:
            start = self.start_ms - offset_ms
            num_steps = self.num_steps
        t0 = time.perf_counter()
        with TRACER.stage("sort_layout"):
            layout = d.sort_layout(fieldcol)
            bounds_l = None
            extra: tuple = ()
            # per-series bounds matrix: resident-only accelerator for
            # few-step windows (the S·T·L compare sweep must stay cheaper
            # than the S·T·log N binary search it replaces)
            if allow_bounds and num_steps <= 64:
                b = d.window_bounds(fieldcol, layout, sel_dev,
                                    labels.matcher_key)
                if b is not None and S * num_steps * b[3] <= (1 << 27):
                    bounds_l = b[3]
                    extra = b[:3]
        self._stage_mark("sort_layout", t0)
        p = WindowParams(
            step_ms=self.step_ms,
            num_steps=num_steps,
            range_ms=int(rng),
            num_sel=S,
            total_series=max(d.region.num_series, 1),
            kind=kind,
            bounds_l=bounds_l,
        )
        args = layout + extra + (sel_dev, np.int64(start))
        return args, p, tsids, labels, pinned, start, int(rng)

    def _run_window(
        self, sel: VectorSelector, kind: str, range_ms: int | None = None
    ) -> tuple[dict, list[dict]]:
        try:
            prep = self._prep_window(sel, kind, range_ms)
        except TableNotFound:
            # unknown metric = empty vector (Prometheus semantics); the
            # grid must still be recorded — rate/increase read it
            # unconditionally right after (seed bug: AttributeError when
            # the FIRST selector of an evaluator was an unknown metric)
            self._last_window_grid = (self.start_ms, range_ms or 0, False)
            empty = jnp.zeros((0, self.num_steps), jnp.float32)
            return {k: empty for k in self._KIND_KEYS[kind]}, []
        args, p, tsids, labels, pinned, start, rng = prep
        kern = _KERNEL_CACHE.get(p)
        jit_miss = kern is None
        if kern is None:
            kern = self._compiler().get_or_build(
                "promql", p, lambda: _window_kernel(p), persist=True)
            _KERNEL_CACHE[p] = kern
        # an AOT-store hit deserializes the executable — no XLA compile
        # happened, so the first call must not be attributed as one
        # (the promql twin of physical.aot_kernel_call's discipline)
        compiling = jit_miss and not getattr(kern, "aot", False)
        t0 = time.perf_counter()
        with TRACER.stage("window_kernel", kind=kind):
            out = kern(*args)
            if jit_miss or TRACER.enabled or (
                getattr(self.db, "stage_sink", None) is not None
            ):
                # device sync only when someone reads the split: the first
                # call (compile) is worth attributing always; steady-state
                # evals keep the async dispatch pipeline
                out = jax.block_until_ready(out)
        self._stage_mark("xla_compile" if compiling else "window_kernel", t0)
        out = {k: v[: len(tsids)] for k, v in out.items()}
        if pinned:
            out = {
                k: jnp.broadcast_to(v, (v.shape[0], self.num_steps))
                for k, v in out.items()
            }
        self._last_window_grid = (start, rng, pinned)
        return out, labels

    def _run_matrix(self, sel: VectorSelector, kind: str,
                    extras: tuple = ()) -> tuple[jnp.ndarray, list[dict]]:
        """Matrix-kernel twin of _run_window for the window functions that
        need per-window order statistics or a sequential scan
        (quantile_over_time / mad_over_time /
        double_exponential_smoothing).  ``extras`` are [num_steps] f32
        parameter vectors (φ / sf, tf)."""
        import dataclasses

        try:
            prep = self._prep_window(sel, kind, allow_bounds=False)
        except TableNotFound:
            return jnp.zeros((0, self.num_steps), jnp.float32), []
        args, p, tsids, labels, pinned, _start, _rng = prep
        num_steps = p.num_steps
        # the sizing pass reads geometry only — share one compiled count
        # kernel across matrix kinds
        ck = dataclasses.replace(p, kind="cnt_max")
        cnt_kern = _KERNEL_CACHE.get(ck)
        if cnt_kern is None:
            cnt_kern = _count_max_kernel(ck)
            _KERNEL_CACHE[ck] = cnt_kern
        cnt_max = int(cnt_kern(*args))
        lmax = max(2, 1 << (max(cnt_max, 1) - 1).bit_length())
        mk = (p, "matrix", lmax)
        kern = _KERNEL_CACHE.get(mk)
        jit_miss = kern is None
        if kern is None:
            kern = self._compiler().get_or_build(
                "promql", mk, lambda: _matrix_kernel(p, lmax, kind),
                persist=True)
            _KERNEL_CACHE[mk] = kern
        compiling = jit_miss and not getattr(kern, "aot", False)
        ones = jnp.ones(num_steps, jnp.float32)
        a1 = (jnp.broadcast_to(jnp.asarray(extras[0], jnp.float32),
                               (self.num_steps,))[:num_steps]
              if len(extras) > 0 else ones)
        a2 = (jnp.broadcast_to(jnp.asarray(extras[1], jnp.float32),
                               (self.num_steps,))[:num_steps]
              if len(extras) > 1 else ones)
        t0 = time.perf_counter()
        with TRACER.stage("window_kernel", kind=kind):
            vals = kern(*args, a1, a2)[: len(tsids)]
        self._stage_mark("xla_compile" if compiling else "window_kernel",
                         t0)
        if pinned:
            vals = jnp.broadcast_to(vals, (vals.shape[0], self.num_steps))
        return vals, labels

    # ---- eval -----------------------------------------------------------
    def eval(self, e: PromExpr) -> EvalResult:
        if isinstance(e, NumberLit):
            v = jnp.full((1, self.num_steps), e.value, dtype=jnp.float32)
            return EvalResult(v, [{}], is_scalar=True)
        if isinstance(e, StringLit):
            raise Unsupported("bare string expression")
        if isinstance(e, VectorSelector):
            if e.range_s is not None:
                raise PlanError(f"range vector {e} needs a function")
            out, labels = self._run_window(e, "instant")
            # staleness enforced by the window kernel: value is the last
            # sample within (t - lookback, t]
            vals = out["last"] if labels else jnp.zeros((0, self.num_steps), jnp.float32)
            return EvalResult(vals, labels)
        if isinstance(e, UnaryExpr):
            r = self.eval(e.expr)
            return EvalResult(-r.values if e.op == "-" else r.values, r.labels,
                              r.is_scalar)
        if isinstance(e, FunctionCall):
            return self.eval_function(e)
        if isinstance(e, Aggregation):
            return self.eval_aggregation(e)
        if isinstance(e, BinaryExpr):
            return self.eval_binary(e)
        if isinstance(e, SubqueryExpr):
            raise Unsupported(
                "bare subquery needs an *_over_time function")
        raise Unsupported(f"promql node {type(e).__name__}")

    # ---- functions --------------------------------------------------------
    def eval_function(self, e: FunctionCall) -> EvalResult:
        f = e.func
        simple = {
            "abs": jnp.abs, "ceil": jnp.ceil, "floor": jnp.floor,
            "exp": jnp.exp, "ln": jnp.log, "log2": jnp.log2,
            "log10": jnp.log10, "sqrt": jnp.sqrt, "sgn": jnp.sign,
            "acos": jnp.arccos, "asin": jnp.arcsin, "atan": jnp.arctan,
            "cos": jnp.cos, "sin": jnp.sin, "tan": jnp.tan,
            "cosh": jnp.cosh, "sinh": jnp.sinh, "tanh": jnp.tanh,
            "deg": jnp.degrees, "rad": jnp.radians,
        }
        if f in simple:
            r = self.eval(e.args[0])
            return EvalResult(simple[f](r.values), r.labels, r.is_scalar)
        if f == "round":
            r = self.eval(e.args[0])
            to = 1.0
            if len(e.args) > 1 and isinstance(e.args[1], NumberLit):
                to = e.args[1].value
            return EvalResult(jnp.round(r.values / to) * to, r.labels, r.is_scalar)
        if f in ("clamp", "clamp_min", "clamp_max"):
            r = self.eval(e.args[0])
            v = r.values
            if f == "clamp":
                v = jnp.clip(v, e.args[1].value, e.args[2].value)
            elif f == "clamp_min":
                v = jnp.maximum(v, e.args[1].value)
            else:
                v = jnp.minimum(v, e.args[1].value)
            return EvalResult(v, r.labels)
        if f == "scalar":
            r = self.eval(e.args[0])
            if r.num_series == 1:
                return EvalResult(r.values, [{}], is_scalar=True)
            v = jnp.full((1, self.num_steps), jnp.nan, jnp.float32)
            return EvalResult(v, [{}], is_scalar=True)
        if f == "vector":
            r = self.eval(e.args[0])
            return EvalResult(r.values, [{}])
        if f == "time":
            t = (jnp.asarray(self.steps_ms()) / 1000.0).astype(jnp.float32)
            return EvalResult(t[None, :], [{}], is_scalar=True)
        if f == "timestamp":
            sel = self._selector_arg(e, 0, want_range=False)
            out, labels = self._run_window(sel, "instant")
            # divide in f64: f32 quantizes epoch-ms to ~minutes
            ts = (out["last_ts"].astype(jnp.float64) / 1000.0)
            ts = jnp.where(jnp.isnan(out["last"]), jnp.nan, ts)
            return EvalResult(ts, labels)
        if f == "absent":
            r = self.eval(e.args[0])
            present = jnp.any(~jnp.isnan(r.values), axis=0) if r.num_series else (
                jnp.zeros(self.num_steps, bool)
            )
            v = jnp.where(present, jnp.nan, 1.0).astype(jnp.float32)
            lab = {}
            if isinstance(e.args[0], VectorSelector):
                lab = {
                    m.name: m.value
                    for m in e.args[0].matchers
                    if m.op == "=" and m.name != "__field__"
                }
            return EvalResult(v[None, :], [lab])
        if f in self._SUBQ_REDUCERS:
            sel_i = 1 if f == "quantile_over_time" else 0
            arg = e.args[sel_i] if len(e.args) > sel_i else None
            if isinstance(arg, SubqueryExpr):
                q = (self.eval(e.args[0]).values[0]
                     if f == "quantile_over_time" else None)
                return self._eval_subquery_window(f, arg, q)
        if (f in ("rate", "increase", "delta", "irate", "idelta")
                and e.args and isinstance(e.args[0], SubqueryExpr)):
            return self._eval_subquery_counter(f, e.args[0])
        if f in ("rate", "increase", "delta"):
            sel = self._selector_arg(e, 0)
            out, labels = self._run_window(sel, "counter")
            start, _rng, pinned = self._last_window_grid
            if pinned:
                range_end = np.full(self.num_steps, start, dtype=np.float64)
            else:
                range_end = start + self.step_ms * np.arange(
                    self.num_steps, dtype=np.float64
                )
            vals = _extrapolated(
                out, sel.range_s, range_end, counter=f != "delta",
                is_rate=f == "rate",
            )
            return EvalResult(vals, labels)
        if f in ("irate", "idelta"):
            sel = self._selector_arg(e, 0)
            out, labels = self._run_window(sel, "irate")
            vals = _instant_pair(
                f, out["last_ts"], out["prev_ts"],
                out["last_val"], out["prev_val"])
            return EvalResult(vals, labels)
        if f in ("resets", "changes"):
            sel = self._selector_arg(e, 0)
            out, labels = self._run_window(sel, "counter_rc")
            return EvalResult(out[f], labels)
        if f in ("avg_over_time", "sum_over_time", "count_over_time",
                 "last_over_time", "first_over_time", "stddev_over_time",
                 "stdvar_over_time", "present_over_time"):
            sel = self._selector_arg(e, 0)
            out, labels = self._run_window(sel, "gauge_window")
            present = ~jnp.isnan(out["last"])
            table = {
                "avg_over_time": out["avg"],
                "sum_over_time": out["sum"],
                "count_over_time": jnp.where(present, out["count"], jnp.nan),
                "last_over_time": out["last"],
                "first_over_time": out["first"],
                "stddev_over_time": jnp.sqrt(out["var"]),
                "stdvar_over_time": out["var"],
                "present_over_time": jnp.where(present, 1.0, jnp.nan),
            }
            return EvalResult(table[f], labels)
        if f in ("min_over_time", "max_over_time"):
            sel = self._selector_arg(e, 0)
            out, labels = self._run_window(sel, "minmax")
            return EvalResult(out["min" if f == "min_over_time" else "max"], labels)
        if f == "deriv":
            sel = self._selector_arg(e, 0)
            out, labels = self._run_window(sel, "regression")
            return EvalResult(out["slope"], labels)
        if f == "predict_linear":
            sel = self._selector_arg(e, 0)
            horizon = self.eval(e.args[1]).values[0]  # scalar [T]
            out, labels = self._run_window(sel, "regression")
            # regression t is seconds relative to each step's start_ms grid;
            # predict at t_step + horizon
            t_at = (jnp.asarray(self.steps_ms()) - self.start_ms).astype(
                jnp.float32
            ) / 1000.0
            vals = out["intercept"] + out["slope"] * (t_at[None, :] + horizon[None, :])
            return EvalResult(vals, labels)
        if f == "histogram_quantile":
            return self._histogram_quantile(e)
        if f == "label_replace":
            r = self.eval(e.args[0])
            dst, repl, src, regex = (a.value for a in e.args[1:5])
            rx = re.compile(str(regex))
            # Prometheus $1 / ${1} group refs → python \1 / \g<1>
            template = re.sub(r"\$\{(\w+)\}", r"\\g<\1>", str(repl))
            template = re.sub(r"\$(\d+)", r"\\\1", template)
            labels = []
            for lab in r.labels:
                m = rx.fullmatch(str(lab.get(src, "")))
                lab = dict(lab)
                if m is not None:
                    lab[dst] = m.expand(template)
                    if lab[dst] == "":
                        lab.pop(dst, None)
                labels.append(lab)
            return EvalResult(r.values, labels)
        if f == "label_join":
            r = self.eval(e.args[0])
            dst = e.args[1].value
            sep = e.args[2].value
            srcs = [a.value for a in e.args[3:]]
            labels = []
            for lab in r.labels:
                lab = dict(lab)
                lab[dst] = str(sep).join(str(lab.get(s, "")) for s in srcs)
                labels.append(lab)
            return EvalResult(r.values, labels)
        if f == "sort" or f == "sort_desc":
            return self.eval(e.args[0])  # ordering is a presentation concern
        if f == "quantile_over_time":
            if len(e.args) != 2:
                raise PlanError("quantile_over_time(φ, series[range])")
            q = self.eval(e.args[0]).values[0]
            sel = self._selector_arg(e, 1)
            vals, labels = self._run_matrix(sel, "quantile", (q,))
            return EvalResult(vals, labels)
        if f == "mad_over_time":
            sel = self._selector_arg(e, 0)
            vals, labels = self._run_matrix(sel, "mad")
            return EvalResult(vals, labels)
        if f == "double_exponential_smoothing":
            if len(e.args) != 3:
                raise PlanError(
                    "double_exponential_smoothing(series[range], sf, tf)")
            sel = self._selector_arg(e, 0)
            sf = self.eval(e.args[1]).values[0]
            tf = self.eval(e.args[2]).values[0]
            vals, labels = self._run_matrix(sel, "holt", (sf, tf))
            return EvalResult(vals, labels)
        raise Unsupported(f"promql function {f}")

    # *_over_time reducers applicable to a subquery window matrix
    _SUBQ_REDUCERS = {
        "avg_over_time", "sum_over_time", "min_over_time", "max_over_time",
        "count_over_time", "last_over_time", "first_over_time",
        "stddev_over_time", "stdvar_over_time", "present_over_time",
        "quantile_over_time", "mad_over_time",
    }

    def _subquery_matrix(self, sq: SubqueryExpr):
        """Shared window-matrix construction for subquery evaluation:
        inner expr evaluated on the sub-step grid, gathered into
        [S, T, K] windows.  Returns (win, mask, ts_tk [T, K] ms,
        steps [T] ms, labels) or None for an empty inner vector."""
        range_ms = int(sq.range_s * 1000)
        sub_ms = max(int((sq.step_s or self.step_ms / 1000.0) * 1000), 1)
        offset_ms = int(sq.offset_s * 1000)
        end_ms = (self.start_ms - offset_ms
                  + self.step_ms * (self.num_steps - 1))
        lo_ms = self.start_ms - offset_ms - range_ms
        # inner grid: absolute multiples of sub_ms in (lo, end]
        t0 = (lo_ms // sub_ms + 1) * sub_ms
        if t0 > end_ms:
            t0 = end_ms
        inner = PromEvaluator(
            self.db, t0 / 1000.0, end_ms / 1000.0, sub_ms / 1000.0,
            self.lookback_ms / 1000.0)
        res = inner.eval(sq.expr)
        vals = res.values  # [S, TI]
        if vals.shape[0] == 0:
            return None
        ti = vals.shape[1]
        K = range_ms // sub_ms + 1
        steps = (self.start_ms - offset_ms
                 + self.step_ms * np.arange(self.num_steps, dtype=np.int64))
        j_lo = (steps - range_ms - t0) // sub_ms + 1  # first j inside
        k = np.arange(K, dtype=np.int64)
        idx = j_lo[:, None] + k[None, :]  # [T, K]
        ts_tk = t0 + idx * sub_ms
        in_win = (idx >= 0) & (idx < ti) & (ts_tk <= steps[:, None])
        idxc = jnp.asarray(np.clip(idx, 0, max(ti - 1, 0)))
        win = vals[:, idxc]  # [S, T, K]
        m = jnp.asarray(in_win)[None, :, :] & ~jnp.isnan(win)
        return win, m, ts_tk, steps, res.labels

    def _eval_subquery_counter(self, f: str, sq: SubqueryExpr) -> EvalResult:
        """rate/increase/delta/irate/idelta over a subquery matrix: the
        'samples' are the inner evaluations; counter-reset adjustment
        scans the window axis (fori over K — K is small), then the SAME
        _extrapolated as the selector path finishes rate/increase."""
        mat = self._subquery_matrix(sq)
        if mat is None:
            return EvalResult(
                jnp.zeros((0, self.num_steps), jnp.float32), [])
        win, m, ts_tk, steps, labels = mat
        S = win.shape[0]
        K = win.shape[2]
        ks = jnp.arange(K)
        cnt = m.sum(axis=-1)
        first_k = jnp.where(m, ks, K).min(-1)
        last_k = jnp.where(m, ks, -1).max(-1)
        fkc = jnp.clip(first_k, 0, K - 1)
        lkc = jnp.clip(last_k, 0, K - 1)
        fv = jnp.take_along_axis(win, fkc[..., None], -1)[..., 0]
        lv = jnp.take_along_axis(win, lkc[..., None], -1)[..., 0]
        ts_b = jnp.broadcast_to(
            jnp.asarray(ts_tk)[None, :, :], win.shape)
        ft = jnp.take_along_axis(ts_b, fkc[..., None], -1)[..., 0]
        lt = jnp.take_along_axis(ts_b, lkc[..., None], -1)[..., 0]

        if f in ("irate", "idelta"):
            prev_k = jnp.where(m & (ks < last_k[..., None]), ks, -1).max(-1)
            pkc = jnp.clip(prev_k, 0, K - 1)
            pv = jnp.take_along_axis(win, pkc[..., None], -1)[..., 0]
            pt = jnp.take_along_axis(ts_b, pkc[..., None], -1)[..., 0]
            vals = _instant_pair(f, lt, pt, lv, pv, guard=cnt >= 2)
            return EvalResult(vals.astype(jnp.float32), labels)

        def body(k, carry):
            prev, has_prev, dropsum = carry
            v = jax.lax.dynamic_slice_in_dim(win, k, 1, axis=2)[..., 0]
            valid = jax.lax.dynamic_slice_in_dim(m, k, 1, axis=2)[..., 0]
            reset = valid & has_prev & (prev > v)
            dropsum = dropsum + jnp.where(reset, prev, 0.0)
            prev = jnp.where(valid, v, prev)
            has_prev = has_prev | valid
            return prev, has_prev, dropsum

        zeros = jnp.zeros(win.shape[:2], win.dtype)
        _p, _h, drops = jax.lax.fori_loop(
            0, K, body, (zeros, jnp.zeros(win.shape[:2], bool), zeros))
        out = {
            "first_ts": ft, "last_ts": lt,
            "first_val": fv, "count": cnt.astype(jnp.float32),
            "delta_adj": lv - fv + drops,
            "delta_raw": lv - fv,
        }
        vals = _extrapolated(
            out, sq.range_s, steps.astype(np.float64),
            counter=f != "delta", is_rate=f == "rate")
        return EvalResult(vals, labels)

    def _eval_subquery_window(self, f: str, sq: SubqueryExpr,
                              q=None) -> EvalResult:
        """fn_over_time(expr[range:step]) — PromQL subqueries: evaluate
        the inner expression on the sub-step grid covering
        (start − range, end], then reduce each outer step's window of
        inner evaluations (reference src/promql/src/planner.rs subquery
        lowering; Prometheus aligns inner steps to absolute multiples of
        the sub-step)."""
        mat = self._subquery_matrix(sq)
        if mat is None:
            return EvalResult(
                jnp.zeros((0, self.num_steps), jnp.float32), [])
        win, m, _ts_tk, _steps, labels = mat
        K = win.shape[2]
        cnt = m.sum(axis=-1)
        has = cnt > 0
        nan = jnp.float32(jnp.nan)
        z = jnp.where(m, win, 0.0)
        if f == "sum_over_time":
            out = jnp.where(has, z.sum(-1), nan)
        elif f == "count_over_time":
            out = jnp.where(has, cnt.astype(jnp.float32), nan)
        elif f == "present_over_time":
            out = jnp.where(has, 1.0, nan)
        elif f == "avg_over_time":
            out = jnp.where(has, z.sum(-1) / jnp.maximum(cnt, 1), nan)
        elif f in ("stddev_over_time", "stdvar_over_time"):
            mean = z.sum(-1) / jnp.maximum(cnt, 1)
            var = (jnp.where(m, (win - mean[..., None]) ** 2, 0.0).sum(-1)
                   / jnp.maximum(cnt, 1))
            out = jnp.where(
                has, jnp.sqrt(var) if f == "stddev_over_time" else var, nan)
        elif f == "min_over_time":
            out = jnp.where(
                has, jnp.where(m, win, jnp.inf).min(-1), nan)
        elif f == "max_over_time":
            out = jnp.where(
                has, jnp.where(m, win, -jnp.inf).max(-1), nan)
        elif f in ("last_over_time", "first_over_time"):
            # index of the last/first valid sub-evaluation in the window
            ks = jnp.arange(K)
            if f == "last_over_time":
                pick = jnp.where(m, ks, -1).max(-1)
            else:
                pick = jnp.where(m, ks, K).min(-1)
            pickc = jnp.clip(pick, 0, K - 1)
            out = jnp.where(
                has, jnp.take_along_axis(win, pickc[..., None], -1)[..., 0],
                nan)
        elif f in ("quantile_over_time", "mad_over_time"):
            srt = jnp.sort(jnp.where(m, win, jnp.inf), axis=-1)

            def q_of(sorted_w, qq):
                rank = qq * jnp.maximum(cnt - 1, 0).astype(jnp.float32)
                lo_r = jnp.clip(jnp.floor(rank).astype(jnp.int32), 0, K - 1)
                hi_r = jnp.clip(jnp.ceil(rank).astype(jnp.int32), 0, K - 1)
                vlo = jnp.take_along_axis(sorted_w, lo_r[..., None], -1)[..., 0]
                vhi = jnp.take_along_axis(sorted_w, hi_r[..., None], -1)[..., 0]
                return vlo + (vhi - vlo) * (rank - lo_r.astype(jnp.float32))

            if f == "quantile_over_time":
                qv = jnp.broadcast_to(
                    jnp.asarray(q, jnp.float32)[None, :], cnt.shape)
                out = q_of(srt, qv)
                out = jnp.where(qv < 0, -jnp.inf,
                                jnp.where(qv > 1, jnp.inf, out))
            else:
                med = q_of(srt, jnp.float32(0.5))
                dev = jnp.sort(
                    jnp.where(m, jnp.abs(win - med[..., None]), jnp.inf),
                    axis=-1)
                out = q_of(dev, jnp.float32(0.5))
            out = jnp.where(has, out, nan)
        else:  # pragma: no cover — guarded by _SUBQ_REDUCERS
            raise Unsupported(f"{f} over subquery")
        return EvalResult(out.astype(jnp.float32), labels)

    def _selector_arg(self, e: FunctionCall, i: int, want_range: bool = True) -> VectorSelector:
        a = e.args[i]
        if not isinstance(a, VectorSelector):
            raise Unsupported(f"{e.func} needs a selector argument, got {a}")
        if want_range and a.range_s is None:
            raise PlanError(f"{e.func} needs a range vector (e.g. {a}[5m])")
        return a

    # ---- aggregation ------------------------------------------------------
    def _scalar_param(self, param: PromExpr | None, who: str) -> float:
        """Aggregation parameter (k, q): literal or constant scalar expr."""
        if param is None:
            raise PlanError(f"{who} needs a parameter")
        if isinstance(param, NumberLit):
            return float(param.value)
        r = self.eval(param)
        if not r.is_scalar:
            raise Unsupported(f"{who} parameter must be a scalar")
        vals = np.asarray(r.values[0])
        if len(vals) > 1 and not np.allclose(vals, vals[0], equal_nan=True):
            raise Unsupported(f"{who} parameter varying per step")
        v = float(vals[0])
        if np.isnan(v):
            raise PlanError(f"{who} parameter evaluates to NaN")
        return v

    def _group_series(self, e: Aggregation, r: EvalResult):
        """Group-id assignment for an aggregation input — the ONE
        definition of PromQL grouping semantics, with two providers:

        - resident path (input labels still ARE the selector's
          LazySeriesLabels): group ids are computed VECTORIZED from the
          region's dictionary-encoded tag codes (canonical str-level term
          ids per column, mixed-radix combine, first-appearance
          renumbering) and held resident per (selection, grouping) in
          PromLayoutCache — no per-series Python objects at all;
        - host fallback (label-transforming functions ran in between):
          the original dict loop.

        Returns (gid_dev [S] i32, ng, out_labels, row_order_dev [S],
        seg_start np [ng]) where row_order/seg_start give the
        group-contiguous row permutation used by the segment-sorted
        quantile/topk kernels.
        """
        return self._group_series_of(e, r.labels, r.num_series)

    def _group_series_of(self, e: Aggregation, labels, n: int):
        """_group_series over bare (labels, n) — the fused chain
        (compile/fused.py) groups straight off the selection, before any
        EvalResult exists.  Same providers, same caches, one definition."""

        def group_key(lab: dict) -> tuple:
            if e.without:
                keys = sorted(k for k in lab if k not in e.grouping)
            elif e.grouping:
                keys = [k for k in sorted(e.grouping)]
            else:
                keys = []
            return tuple((k, str(lab.get(k, ""))) for k in keys)

        gspec = ("without" if e.without else "by",
                 tuple(sorted(e.grouping or ())))
        if isinstance(labels, LazySeriesLabels) and n == len(labels.tsids):
            cache = labels.cache
            ckey = (labels.matcher_key, gspec)
            payload = None
            if cache is not None:
                payload = cache.lookup("group", labels.region_id, ckey,
                                       labels.generation)
                self.cache_events["group_hit" if payload is not None
                                  else "group_miss"] += 1
            if payload is None:
                payload = _series_group_ids(labels.idx, labels.tsids,
                                            e.grouping or [], e.without)
                if cache is not None:
                    nbytes = sum(
                        int(a.nbytes) for a in payload
                        if hasattr(a, "nbytes"))
                    if cache.admit(nbytes):
                        cache.store("group", labels.region_id, ckey,
                                    labels.generation, payload, nbytes)
                    else:
                        self.cache_events["group_reject"] += 1
            gid_dev, ng, rep_rows, row_order_dev, seg_start = payload
            out_labels = LazyGroupLabels(labels, rep_rows, group_key)
            return gid_dev, ng, out_labels, row_order_dev, seg_start

        groups: dict[tuple, int] = {}
        gids = np.zeros(n, dtype=np.int32)
        out_labels: list[dict] = []
        for i, lab in enumerate(labels):
            k = group_key(lab)
            if k not in groups:
                groups[k] = len(groups)
                out_labels.append(dict(k))
            gids[i] = groups[k]
        ng = len(groups)
        row_order = np.argsort(gids, kind="stable")
        seg_start = np.searchsorted(gids[row_order], np.arange(ng))
        return (jnp.asarray(gids), ng, out_labels, jnp.asarray(row_order),
                seg_start)

    def eval_aggregation(self, e: Aggregation) -> EvalResult:
        from greptimedb_tpu.compile import fusion_enabled

        if fusion_enabled():
            # whole-plan fusion: selection→window→group as ONE device
            # dispatch when the chain matches the fused surface
            # (compile/fused.py); None falls through to the multi-kernel
            # path below, which GREPTIME_PLAN_FUSION=off also restores
            # byte-for-byte
            from greptimedb_tpu.compile.fused import try_fused_aggregation

            fused = try_fused_aggregation(self, e)
            if fused is not None:
                return fused
        r = self.eval(e.expr)
        if r.num_series == 0:
            return r
        t0 = time.perf_counter()
        with TRACER.stage("group_agg", op=e.op):
            gid_dev, ng, out_labels, row_order_dev, seg_start = (
                self._group_series(e, r))
        self._stage_mark("group_agg", t0)
        v = r.values
        S = v.shape[0]
        present = ~jnp.isnan(v)
        # int32 count accumulator: float32 segment sums lose exactness
        # past 2^24 members per group (mirrors PR 1's mesh int-SUM fix)
        cnt = jax.ops.segment_sum(present.astype(jnp.int32), gid_dev,
                                  num_segments=ng)
        fcnt = cnt.astype(jnp.float32)
        has = cnt > 0

        if e.op in ("sum", "avg", "count", "group", "stddev", "stdvar"):
            s = jax.ops.segment_sum(jnp.where(present, v, 0), gid_dev, num_segments=ng)
            if e.op == "sum":
                out = jnp.where(has, s, jnp.nan)
            elif e.op == "avg":
                out = jnp.where(has, s / jnp.maximum(fcnt, 1), jnp.nan)
            elif e.op == "count":
                out = jnp.where(has, fcnt, jnp.nan)
            elif e.op == "group":
                out = jnp.where(has, 1.0, jnp.nan)
            else:
                s2 = jax.ops.segment_sum(
                    jnp.where(present, v * v, 0), gid_dev, num_segments=ng
                )
                mean = s / jnp.maximum(fcnt, 1)
                var = jnp.maximum(s2 / jnp.maximum(fcnt, 1) - mean * mean, 0)
                out = jnp.where(has, var if e.op == "stdvar" else jnp.sqrt(var),
                                jnp.nan)
            return EvalResult(out, out_labels)
        if e.op in ("min", "max"):
            fill = jnp.inf if e.op == "min" else -jnp.inf
            fn = jax.ops.segment_min if e.op == "min" else jax.ops.segment_max
            out = fn(jnp.where(present, v, fill), gid_dev, num_segments=ng)
            return EvalResult(jnp.where(has, out, jnp.nan), out_labels)
        if e.op == "quantile":
            # segment-sorted ranks: ONE device dispatch for all groups —
            # rows permuted group-contiguous, a two-key lexicographic sort
            # orders values within each segment per step (NaNs sort last),
            # then the two straddling order statistics interpolate
            # (Prometheus linear quantile, same rule as quantile_over_time)
            q = self._scalar_param(e.param, "quantile")
            gs = gid_dev[row_order_dev]
            gb = jnp.broadcast_to(gs[:, None], v.shape)
            _, sv = jax.lax.sort((gb, v[row_order_dev]), dimension=0,
                                 num_keys=2)
            base = jnp.asarray(seg_start, dtype=jnp.int32)[:, None]  # [ng,1]
            rank = jnp.float32(q) * jnp.maximum(fcnt - 1, 0)  # [ng, T]
            lo_r = jnp.floor(rank).astype(jnp.int32)
            hi_r = jnp.ceil(rank).astype(jnp.int32)
            vlo = jnp.take_along_axis(sv, jnp.clip(base + lo_r, 0, S - 1), 0)
            vhi = jnp.take_along_axis(sv, jnp.clip(base + hi_r, 0, S - 1), 0)
            out = vlo + (vhi - vlo) * (rank - lo_r.astype(jnp.float32))
            if q < 0:
                out = jnp.full_like(out, -jnp.inf)
            elif q > 1:
                out = jnp.full_like(out, jnp.inf)
            out = jnp.where(has, out, jnp.nan)
            return EvalResult(out.astype(jnp.float32), out_labels)
        if e.op in ("topk", "bottomk"):
            k = int(self._scalar_param(e.param, e.op))
            if k <= 0:
                return EvalResult(jnp.zeros((0, self.num_steps), jnp.float32), [])
            sign = 1.0 if e.op == "topk" else -1.0
            work = jnp.where(present, sign * v, -jnp.inf)
            if ng == 1 and not e.grouping and not e.without:
                kth = -jnp.sort(-work, axis=0)[jnp.minimum(k - 1, v.shape[0] - 1)]
                keep = work >= kth[None, :]
            else:
                # per-group k-th value via ONE segment-sorted dispatch:
                # sort (gid, -work) lexicographically per step, read each
                # group's (min(k, size)-1)-th row, then keep every row at
                # or above its group's threshold (ties kept, as before)
                gs = gid_dev[row_order_dev]
                gb = jnp.broadcast_to(gs[:, None], v.shape)
                _, sw = jax.lax.sort((gb, -work[row_order_dev]), dimension=0,
                                     num_keys=2)
                sizes = np.diff(np.append(seg_start, S))
                kth_row = jnp.asarray(
                    seg_start + np.minimum(k, sizes) - 1, dtype=jnp.int32)
                kth = -jnp.take_along_axis(
                    sw, jnp.broadcast_to(kth_row[:, None], (ng, v.shape[1])),
                    0)
                keep = work >= kth[gid_dev]
            out = jnp.where(keep & present, v, jnp.nan)
            return EvalResult(out, r.labels)
        raise Unsupported(f"aggregation {e.op}")

    # ---- binary ops ---------------------------------------------------------
    def eval_binary(self, e: BinaryExpr) -> EvalResult:
        l = self.eval(e.lhs)
        r = self.eval(e.rhs)
        op = e.op

        # for filter comparisons the surviving sample value comes from the
        # vector side (Prometheus keeps LHS for vector-vector)
        keep_rhs_value = l.is_scalar and not r.is_scalar

        def apply(a, b):
            if op == "+":
                return a + b
            if op == "-":
                return a - b
            if op == "*":
                return a * b
            if op == "/":
                return a / b
            if op == "%":
                return jnp.mod(a, b)
            if op == "^":
                return jnp.power(a, b)
            if op == "atan2":
                return jnp.arctan2(a, b)
            cmp = {
                "==": a == b, "!=": a != b, "<": a < b,
                "<=": a <= b, ">": a > b, ">=": a >= b,
            }[op]
            if e.bool_modifier:
                return jnp.where(jnp.isnan(a) | jnp.isnan(b), jnp.nan,
                                 cmp.astype(jnp.float32))
            return jnp.where(cmp, b if keep_rhs_value else a, jnp.nan)

        if op in ("and", "or", "unless"):
            return self._set_op(e, l, r)

        if l.is_scalar and r.is_scalar:
            return EvalResult(apply(l.values, r.values), [{}], is_scalar=True)
        if l.is_scalar:
            return EvalResult(apply(l.values[0][None, :], r.values), r.labels)
        if r.is_scalar:
            return EvalResult(apply(l.values, r.values[0][None, :]), l.labels)

        li, ri, labels = self._match_series(e, l, r)
        out = apply(l.values[jnp.asarray(li)], r.values[jnp.asarray(ri)])
        return EvalResult(out, labels)

    def _match_key(self, e: BinaryExpr, lab: dict) -> tuple:
        if e.on is not None:
            keys = sorted(e.on)
        else:
            drop = set(e.ignoring or [])
            drop.add("__name__")
            keys = sorted(k for k in lab if k not in drop)
        return tuple((k, str(lab.get(k, ""))) for k in keys)

    def _match_series(self, e: BinaryExpr, l: EvalResult, r: EvalResult):
        rmap: dict[tuple, int] = {}
        for j, lab in enumerate(r.labels):
            k = self._match_key(e, lab)
            if k in rmap:
                raise PlanError(f"many-to-many vector match on {k}")
            rmap[k] = j
        li, ri, labels = [], [], []
        for i, lab in enumerate(l.labels):
            k = self._match_key(e, lab)
            j = rmap.get(k)
            if j is None:
                continue
            li.append(i)
            ri.append(j)
            if e.on is not None:
                labels.append(dict(k))
            else:
                labels.append({kk: vv for kk, vv in lab.items()
                               if kk not in (e.ignoring or [])})
        if not li:
            return [0], [0], []  # empty result
        return li, ri, labels

    def _set_op(self, e: BinaryExpr, l: EvalResult, r: EvalResult) -> EvalResult:
        rkeys = {self._match_key(e, lab) for lab in r.labels}
        if e.op == "and":
            keep = [i for i, lab in enumerate(l.labels)
                    if self._match_key(e, lab) in rkeys]
            if not keep:
                return EvalResult(jnp.zeros((0, self.num_steps), jnp.float32), [])
            idx = jnp.asarray(keep)
            rrows = {self._match_key(e, lab): j for j, lab in enumerate(r.labels)}
            rsel = jnp.asarray([rrows[self._match_key(e, l.labels[i])] for i in keep])
            vals = jnp.where(~jnp.isnan(r.values[rsel]), l.values[idx], jnp.nan)
            return EvalResult(vals, [l.labels[i] for i in keep])
        if e.op == "unless":
            rrows = {self._match_key(e, lab): j for j, lab in enumerate(r.labels)}
            vals_list = []
            labels = []
            for i, lab in enumerate(l.labels):
                j = rrows.get(self._match_key(e, lab))
                if j is None:
                    vals_list.append(l.values[i])
                else:
                    vals_list.append(
                        jnp.where(jnp.isnan(r.values[j]), l.values[i], jnp.nan)
                    )
                labels.append(lab)
            if not labels:
                return EvalResult(jnp.zeros((0, self.num_steps), jnp.float32), [])
            return EvalResult(jnp.stack(vals_list), labels)
        # or: left rows plus right rows whose key is absent on the left
        lkeys = {self._match_key(e, lab) for lab in l.labels}
        extra = [j for j, lab in enumerate(r.labels)
                 if self._match_key(e, lab) not in lkeys]
        vals = l.values
        labels = list(l.labels)
        if extra:
            vals = jnp.concatenate([vals, r.values[jnp.asarray(extra)]], axis=0)
            labels += [r.labels[j] for j in extra]
        return EvalResult(vals, labels)

    # ---- histogram_quantile -------------------------------------------------
    def _histogram_quantile(self, e: FunctionCall) -> EvalResult:
        q = e.args[0].value if isinstance(e.args[0], NumberLit) else 0.5
        r = self.eval(e.args[1])
        groups: dict[tuple, list[tuple[float, int]]] = {}
        glabels: dict[tuple, dict] = {}
        for i, lab in enumerate(r.labels):
            le_raw = str(lab.get("le", ""))
            try:
                le = float(le_raw.replace("+Inf", "inf"))
            except ValueError:
                continue
            key = tuple(sorted((k, str(v)) for k, v in lab.items() if k != "le"))
            groups.setdefault(key, []).append((le, i))
            glabels[key] = {k: v for k, v in lab.items() if k != "le"}
        out_vals = []
        out_labels = []
        for key, buckets in groups.items():
            buckets.sort()
            les = np.array([b[0] for b in buckets], dtype=np.float64)
            rows = jnp.asarray([b[1] for b in buckets])
            counts = r.values[rows]  # [B, T] cumulative
            if not math.isinf(les[-1]):
                continue  # spec: need +Inf bucket
            total = counts[-1]
            rank = q * total
            # first bucket with count >= rank
            ge = counts >= rank[None, :]
            idx = jnp.argmax(ge, axis=0)
            idx = jnp.clip(idx, 0, len(buckets) - 1)
            lo_le = jnp.asarray(
                np.concatenate([[0.0], les[:-1]]), dtype=jnp.float32
            )[idx]
            hi_le = jnp.asarray(les, dtype=jnp.float32)[idx]
            lo_cnt = jnp.concatenate(
                [jnp.zeros((1, counts.shape[1]), counts.dtype), counts[:-1]], axis=0
            )[idx, jnp.arange(counts.shape[1])]
            hi_cnt = counts[idx, jnp.arange(counts.shape[1])]
            frac = jnp.where(hi_cnt > lo_cnt, (rank - lo_cnt) / (hi_cnt - lo_cnt), 1.0)
            val = lo_le + (hi_le - lo_le) * jnp.clip(frac, 0, 1)
            # highest bucket: return lower bound of +Inf bucket
            val = jnp.where(jnp.isinf(hi_le), lo_le, val)
            val = jnp.where(total > 0, val, jnp.nan)
            out_vals.append(val.astype(jnp.float32))
            out_labels.append(glabels[key])
        if not out_vals:
            return EvalResult(jnp.zeros((0, self.num_steps), jnp.float32), [])
        return EvalResult(jnp.stack(out_vals), out_labels)


def _instant_pair(f: str, last_ts, prev_ts, last_val, prev_val,
                  guard=None) -> jnp.ndarray:
    """irate/idelta from the last two samples — the ONE definition of
    the instant-pair reset rule, shared by the selector kernel path and
    the subquery matrix path (Prometheus instantValue semantics)."""
    dt = (last_ts - prev_ts).astype(jnp.float32) / 1000.0
    dv = last_val - prev_val
    if f == "irate":
        dv = jnp.where(dv < 0, last_val, dv)  # counter reset
    ok = dt > 0
    if guard is not None:
        ok = ok & guard
    return jnp.where(ok, dv / dt if f == "irate" else dv, jnp.nan)


def _extrapolated(out: dict, range_s: float, range_end_ms: np.ndarray,
                  counter: bool, is_rate: bool) -> jnp.ndarray:
    """Prometheus extrapolatedRate (reference extrapolate_rate.rs:56)."""
    rng_ms = range_s * 1000.0
    ft = out["first_ts"].astype(jnp.float64)
    lt = out["last_ts"].astype(jnp.float64)
    cnt = out["count"]
    delta = out["delta_adj"] if counter else out["delta_raw"]
    range_end = jnp.asarray(range_end_ms)[None, :]  # [1, T]
    range_start = range_end - rng_ms

    sampled = (lt - ft) / 1000.0
    avg_dur = sampled / jnp.maximum(cnt - 1, 1)
    dur_to_start = (ft - range_start) / 1000.0
    dur_to_end = (range_end - lt) / 1000.0
    threshold = avg_dur * 1.1
    dur_to_start = jnp.where(dur_to_start >= threshold, avg_dur / 2, dur_to_start)
    dur_to_end = jnp.where(dur_to_end >= threshold, avg_dur / 2, dur_to_end)
    if counter:
        fv = out["first_val"].astype(jnp.float64)
        d64 = delta.astype(jnp.float64)
        dur_to_zero = jnp.where(d64 > 0, sampled * (fv / jnp.maximum(d64, 1e-30)),
                                jnp.inf)
        dur_to_start = jnp.minimum(dur_to_start, dur_to_zero)
    factor = (sampled + dur_to_start + dur_to_end) / jnp.maximum(sampled, 1e-30)
    result = delta.astype(jnp.float64) * factor
    if is_rate:
        result = result / range_s
    return jnp.where(cnt >= 2, result.astype(jnp.float32), jnp.nan)


# ---------------------------------------------------------------------------
# TQL entry (called from standalone)
# ---------------------------------------------------------------------------

def execute_tql(db, stmt):
    from greptimedb_tpu.query.engine import QueryResult

    with TRACER.stage("parse"):
        expr = parse_promql(stmt.query)
    ev = PromEvaluator(
        db, stmt.start, stmt.end, stmt.step,
        stmt.lookback or DEFAULT_LOOKBACK_S,
    )
    comp = getattr(db, "plan_compiler", None)
    if comp is not None:
        # shape-class usage journal replay context (compile/journal.py):
        # captured lazily, only when this statement builds a NEW kernel
        # class — a fresh process replays the same TQL window to warm it
        comp.set_replay(lambda: {
            "kind": "tql", "query": stmt.query, "start": stmt.start,
            "end": stmt.end, "step": stmt.step, "lookback": stmt.lookback,
            "db": getattr(db, "current_db", None)})
    if stmt.command in ("EXPLAIN",):
        return QueryResult(["plan"], [[f"PromQL: {expr}"]])
    res = ev.eval(expr)
    vals = np.asarray(res.values)
    steps = ev.steps_ms()
    t0 = time.perf_counter()
    with TRACER.stage("label_decode"):
        label_keys = sorted({k for lab in res.labels for k in lab})
        names = label_keys + ["ts", "val"]
        rows = []
        for s, lab in enumerate(res.labels):
            col = vals[s]
            for t in range(len(steps)):
                v = float(col[t])
                if np.isnan(v):
                    continue
                rows.append([str(lab.get(k, "")) for k in label_keys]
                            + [int(steps[t]), v])
    ev._stage_mark("label_decode", t0)
    sink = getattr(db, "stage_sink", None)
    if sink is not None:
        # slow-query self-reporting: the TQL stage breakdown rides the
        # same sink the SQL engine's mark() writes into
        sink.update(
            {f"promql_{k}_ms": v for k, v in ev.stage_ms.items()})
        sink["output_rows"] = len(rows)
        if ev.cache_events:
            sink["promql_cache_events"] = dict(ev.cache_events)
    return QueryResult(names, rows)
