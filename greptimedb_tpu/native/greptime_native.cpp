// Native IO hot paths for greptimedb_tpu.
//
// The reference implements its entire runtime in Rust; here the compute
// path is JAX/XLA and the IO-bound runtime pieces that profile hot in
// Python move to C++ (SURVEY.md §7.1: storage/WAL stay CPU-side, native):
//   - CRC32 (zlib polynomial) for WAL record integrity
//   - Snappy raw-format decompression (Prometheus remote write bodies)
//   - WAL segment scanning: frame validation + torn-tail detection
//
// Build: make -C greptimedb_tpu/native      (produces libgreptime_native.so)
// Bound via ctypes (greptimedb_tpu/native/__init__.py); every entry point
// has a pure-python fallback so the library is an accelerator, not a
// dependency.

#include <cstddef>
#include <cstdint>
#include <cstring>

extern "C" {

// ---------------------------------------------------------------------------
// CRC32 (IEEE 802.3, zlib-compatible)
// ---------------------------------------------------------------------------

static uint32_t crc_table[8][256];
static bool crc_init_done = false;

static void crc_init() {
  if (crc_init_done) return;
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = i;
    for (int k = 0; k < 8; k++) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
    crc_table[0][i] = c;
  }
  // slicing-by-8 tables
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = crc_table[0][i];
    for (int s = 1; s < 8; s++) {
      c = crc_table[0][c & 0xFF] ^ (c >> 8);
      crc_table[s][i] = c;
    }
  }
  crc_init_done = true;
}

uint32_t gt_crc32(const uint8_t* data, size_t len) {
  crc_init();
  uint32_t crc = 0xFFFFFFFFu;
  // slicing-by-8 main loop
  while (len >= 8) {
    uint32_t lo;
    uint32_t hi;
    memcpy(&lo, data, 4);
    memcpy(&hi, data + 4, 4);
    lo ^= crc;
    crc = crc_table[7][lo & 0xFF] ^ crc_table[6][(lo >> 8) & 0xFF] ^
          crc_table[5][(lo >> 16) & 0xFF] ^ crc_table[4][lo >> 24] ^
          crc_table[3][hi & 0xFF] ^ crc_table[2][(hi >> 8) & 0xFF] ^
          crc_table[1][(hi >> 16) & 0xFF] ^ crc_table[0][hi >> 24];
    data += 8;
    len -= 8;
  }
  while (len--) crc = crc_table[0][(crc ^ *data++) & 0xFF] ^ (crc >> 8);
  return crc ^ 0xFFFFFFFFu;
}

// ---------------------------------------------------------------------------
// Snappy raw format decompression
// ---------------------------------------------------------------------------

// Returns decompressed length from the header uvarint, or -1 on error.
int64_t gt_snappy_length(const uint8_t* in, size_t in_len) {
  uint64_t result = 0;
  int shift = 0;
  size_t pos = 0;
  while (pos < in_len && shift <= 63) {
    uint8_t b = in[pos++];
    result |= static_cast<uint64_t>(b & 0x7F) << shift;
    if (!(b & 0x80)) return static_cast<int64_t>(result);
    shift += 7;
  }
  return -1;
}

// 0 = ok; negative = error. out must hold gt_snappy_length() bytes.
int gt_snappy_decompress(const uint8_t* in, size_t in_len, uint8_t* out,
                         size_t out_cap, size_t* out_len) {
  size_t pos = 0;
  // skip the length varint
  while (pos < in_len && (in[pos] & 0x80)) pos++;
  if (pos >= in_len) return -1;
  pos++;
  size_t o = 0;
  while (pos < in_len) {
    uint8_t tag = in[pos++];
    uint32_t elem = tag & 0x03;
    if (elem == 0) {  // literal
      size_t len = (tag >> 2) + 1;
      if (len > 60) {
        size_t extra = len - 60;
        if (pos + extra > in_len) return -2;
        len = 0;
        for (size_t i = 0; i < extra; i++) len |= static_cast<size_t>(in[pos + i]) << (8 * i);
        len += 1;
        pos += extra;
      }
      if (pos + len > in_len || o + len > out_cap) return -3;
      memcpy(out + o, in + pos, len);
      pos += len;
      o += len;
      continue;
    }
    size_t len;
    size_t offset;
    if (elem == 1) {
      len = ((tag >> 2) & 0x07) + 4;
      if (pos >= in_len) return -4;
      offset = (static_cast<size_t>(tag >> 5) << 8) | in[pos++];
    } else if (elem == 2) {
      len = (tag >> 2) + 1;
      if (pos + 2 > in_len) return -5;
      offset = in[pos] | (static_cast<size_t>(in[pos + 1]) << 8);
      pos += 2;
    } else {
      len = (tag >> 2) + 1;
      if (pos + 4 > in_len) return -6;
      offset = 0;
      for (int i = 0; i < 4; i++) offset |= static_cast<size_t>(in[pos + i]) << (8 * i);
      pos += 4;
    }
    if (offset == 0 || offset > o || o + len > out_cap) return -7;
    if (offset >= len) {
      memcpy(out + o, out + o - offset, len);
      o += len;
    } else {
      // overlapping: byte-wise (run-length semantics)
      for (size_t i = 0; i < len; i++) {
        out[o] = out[o - offset];
        o++;
      }
    }
  }
  *out_len = o;
  return 0;
}

// ---------------------------------------------------------------------------
// WAL segment scan: [u32 len][u32 crc(payload)][u64 seq][u32 crc(hdr)]
// [payload] frames.  The header CRC covers the 16-byte prefix so a bit
// flip anywhere in a record (including the sequence field) is detected.
// ---------------------------------------------------------------------------

struct GtWalSpan {
  uint64_t seq;
  uint64_t payload_off;
  uint64_t payload_len;
};

// Scans v2 frames, validating header + payload CRCs. Returns the number of
// valid frames with seq >= min_seq written to spans (up to max_spans), and
// sets *good_end to the byte offset after the last valid frame (corruption
// triage resumes from there). A negative return means spans overflowed
// (call again with more room).
int64_t gt_wal_scan2(const uint8_t* buf, size_t len, uint64_t min_seq,
                     GtWalSpan* spans, size_t max_spans, size_t* good_end) {
  size_t off = 0;
  size_t n = 0;
  *good_end = 0;
  while (off + 20 <= len) {
    uint32_t rec_len;
    uint32_t crc;
    uint64_t seq;
    uint32_t hcrc;
    memcpy(&rec_len, buf + off, 4);
    memcpy(&crc, buf + off + 4, 4);
    memcpy(&seq, buf + off + 8, 8);
    memcpy(&hcrc, buf + off + 16, 4);
    if (gt_crc32(buf + off, 16) != hcrc) break;
    size_t end = off + 20 + rec_len;
    if (end > len) break;
    if (gt_crc32(buf + off + 20, rec_len) != crc) break;
    if (seq >= min_seq) {
      if (n >= max_spans) return -static_cast<int64_t>(n);
      spans[n].seq = seq;
      spans[n].payload_off = off + 20;
      spans[n].payload_len = rec_len;
      n++;
    }
    off = end;
    *good_end = end;
  }
  return static_cast<int64_t>(n);
}

// Byte-scan forward from `start` for the next offset holding a fully valid
// v2 frame — the interior-corruption resync point. Returns the offset, or
// -1 when no valid frame follows (damage reaches EOF).
int64_t gt_wal_find_boundary2(const uint8_t* buf, size_t len, size_t start) {
  if (len < 20) return -1;
  for (size_t off = start; off + 20 <= len; off++) {
    uint32_t hcrc;
    memcpy(&hcrc, buf + off + 16, 4);
    if (gt_crc32(buf + off, 16) != hcrc) continue;
    uint32_t rec_len;
    uint32_t crc;
    memcpy(&rec_len, buf + off, 4);
    memcpy(&crc, buf + off + 4, 4);
    size_t end = off + 20 + rec_len;
    if (end > len) continue;
    if (gt_crc32(buf + off + 20, rec_len) != crc) continue;
    return static_cast<int64_t>(off);
  }
  return -1;
}

}  // extern "C"
