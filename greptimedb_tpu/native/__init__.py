"""ctypes bindings for the native IO library (optional accelerator).

``lib()`` returns the loaded library or None; callers keep pure-python
fallbacks. Build with ``make -C greptimedb_tpu/native`` (g++ only, no
external deps — see greptime_native.cpp).
"""

from __future__ import annotations

import ctypes
import os
import subprocess

_LIB = None
_TRIED = False

_DIR = os.path.dirname(__file__)
_SO = os.path.join(_DIR, "libgreptime_native.so")


class GtWalSpan(ctypes.Structure):
    _fields_ = [
        ("seq", ctypes.c_uint64),
        ("payload_off", ctypes.c_uint64),
        ("payload_len", ctypes.c_uint64),
    ]


def build(quiet: bool = True) -> bool:
    """Compile the library in place; returns success."""
    try:
        r = subprocess.run(
            ["make", "-C", _DIR],
            capture_output=quiet, timeout=120,
        )
        return r.returncode == 0 and os.path.exists(_SO)
    except Exception:  # noqa: BLE001
        return False


def lib():
    global _LIB, _TRIED
    if _LIB is not None or _TRIED:
        return _LIB
    _TRIED = True
    if not os.path.exists(_SO):
        # never compile on a hot path (region open, request handling) —
        # the library is built by `make -C greptimedb_tpu/native` or an
        # explicit native.build() call
        return None
    try:
        l = ctypes.CDLL(_SO)
        l.gt_crc32.restype = ctypes.c_uint32
        l.gt_crc32.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
        l.gt_snappy_length.restype = ctypes.c_int64
        l.gt_snappy_length.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
        l.gt_snappy_decompress.restype = ctypes.c_int
        l.gt_snappy_decompress.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t,
            ctypes.c_char_p, ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_size_t),
        ]
        # v2 WAL frame scan (header-checksummed records); an older .so
        # without these symbols still serves crc32/snappy — the WAL
        # wrappers just return None and pure-python scanning takes over
        try:
            l.gt_wal_scan2.restype = ctypes.c_int64
            l.gt_wal_scan2.argtypes = [
                ctypes.c_char_p, ctypes.c_size_t, ctypes.c_uint64,
                ctypes.POINTER(GtWalSpan), ctypes.c_size_t,
                ctypes.POINTER(ctypes.c_size_t),
            ]
            l.gt_wal_find_boundary2.restype = ctypes.c_int64
            l.gt_wal_find_boundary2.argtypes = [
                ctypes.c_char_p, ctypes.c_size_t, ctypes.c_size_t,
            ]
        except AttributeError:
            l._gt_no_wal = True
        _LIB = l
    except OSError:
        _LIB = None
    return _LIB


# ---- typed wrappers (None-safe: callers check availability) ---------------

def crc32(data: bytes) -> int | None:
    l = lib()
    if l is None:
        return None
    return l.gt_crc32(data, len(data))


def snappy_decompress(data: bytes) -> bytes | None:
    l = lib()
    if l is None or not data:
        return None
    n = l.gt_snappy_length(data, len(data))
    if n < 0 or n > 1 << 31:
        raise ValueError("bad snappy header")
    out = ctypes.create_string_buffer(max(int(n), 1))
    out_len = ctypes.c_size_t(0)
    rc = l.gt_snappy_decompress(data, len(data), out, n, ctypes.byref(out_len))
    if rc != 0:
        raise ValueError(f"snappy decompress failed ({rc})")
    if out_len.value != n:
        raise ValueError(
            f"snappy length mismatch: got {out_len.value}, expected {n}"
        )
    return out.raw[: out_len.value]


def wal_scan(buf: bytes, min_seq: int) -> tuple[list[tuple[int, int, int]], int] | None:
    """Returns ([(seq, payload_off, payload_len)], good_end) or None."""
    l = lib()
    if l is None or getattr(l, "_gt_no_wal", False):
        return None
    cap = max(len(buf) // 20, 16)
    while True:
        spans = (GtWalSpan * cap)()
        good_end = ctypes.c_size_t(0)
        n = l.gt_wal_scan2(buf, len(buf), min_seq, spans, cap,
                           ctypes.byref(good_end))
        if n < 0:
            cap *= 2
            continue
        return (
            [(spans[i].seq, spans[i].payload_off, spans[i].payload_len)
             for i in range(n)],
            good_end.value,
        )


def wal_find_boundary(buf: bytes, start: int) -> int | None:
    """Next fully-valid record offset at/after ``start``; None when the
    damage reaches EOF, or when the native library is unavailable (the
    caller must fall back to the pure-python byte scan, NOT treat the
    miss as torn tail)."""
    l = lib()
    if l is None or getattr(l, "_gt_no_wal", False):
        return None
    off = l.gt_wal_find_boundary2(buf, len(buf), start)
    return None if off < 0 else int(off)
