"""Procedure framework: persistent, resumable multi-step state machines.

Equivalent of the reference's common-procedure crate
(src/common/procedure/src/procedure.rs:37,194 + local.rs journaling, RFC
2023-01-03-procedure-framework): every DDL/migration step persists its
state to the kv store before executing, so a crashed coordinator resumes
exactly where it stopped; poison keys mark procedures that died on
corrupted state so they are not blindly retried (procedure.rs:37-91).
"""

from __future__ import annotations

import enum
import json
import time
import uuid
from dataclasses import dataclass

from greptimedb_tpu.errors import GreptimeError
from greptimedb_tpu.meta.kv import KvBackend


class ProcedureState(enum.Enum):
    RUNNING = "running"
    DONE = "done"
    FAILED = "failed"
    POISONED = "poisoned"


@dataclass
class Status:
    """Result of one execute step."""

    kind: str  # "executing" | "done" | "poison"
    persist: bool = True
    output: object = None

    @staticmethod
    def executing(persist: bool = True) -> "Status":
        return Status("executing", persist)

    @staticmethod
    def done(output: object = None) -> "Status":
        return Status("done", output=output)

    @staticmethod
    def poison() -> "Status":
        return Status("poison")


class Procedure:
    """Subclass contract: ``type_name`` registered with the manager;
    ``state`` is a json-serializable dict mutated by execute(); execute()
    advances one step per call and returns a Status."""

    type_name = "procedure"

    def __init__(self, state: dict | None = None):
        self.state = state or {}

    def execute(self, ctx: "ProcedureContext") -> Status:
        raise NotImplementedError

    def lock_keys(self) -> list[str]:
        """Exclusive keys (reference DDL key locks, rwlock.rs)."""
        return []


@dataclass
class ProcedureContext:
    kv: KvBackend
    manager: "ProcedureManager"
    procedure_id: str
    # host services a procedure may touch (datanodes, catalog...) are
    # injected by the embedding application
    services: dict = None


class ProcedureManager:
    """Journaled executor (reference LocalManager + StateStore)."""

    _PREFIX = "__procedure/"

    def __init__(self, kv: KvBackend, services: dict | None = None):
        import threading

        self.kv = kv
        self.services = services or {}
        self._registry: dict[str, type[Procedure]] = {}
        self._locks: set[str] = set()
        # guards check-and-acquire of lock keys: standalone serializes DDL
        # behind the db lock, but the manager must be safe on its own
        # (metasrv handlers, direct submit() from tests/tools)
        self._locks_mu = threading.Lock()

    def register(self, cls: type[Procedure]) -> None:
        self._registry[cls.type_name] = cls

    # ------------------------------------------------------------------
    def _journal_key(self, pid: str) -> str:
        return f"{self._PREFIX}{pid}"

    def _poison_key(self, key: str) -> str:
        return f"__poison/{key}"

    def submit(self, proc: Procedure, max_steps: int = 1000) -> object:
        """Run a procedure to completion, journaling every step. Returns
        the final output; raises on failure after journaling FAILED."""
        pid = uuid.uuid4().hex
        return self._drive(pid, proc, max_steps)

    def _drive(self, pid: str, proc: Procedure, max_steps: int) -> object:
        key = self._journal_key(pid)
        locks = proc.lock_keys()
        for lk in locks:
            if self.kv.get(self._poison_key(lk)) is not None:
                raise GreptimeError(
                    f"resource {lk} is poisoned by a failed procedure"
                )
        with self._locks_mu:  # atomic check-and-acquire of ALL keys
            busy = [lk for lk in locks if lk in self._locks]
            if busy:
                raise GreptimeError(f"procedure lock busy: {busy[0]}")
            self._locks.update(locks)
        try:
            ctx = ProcedureContext(self.kv, self, pid, self.services)
            # write-ahead journal BEFORE the first step: a crash during step 1
            # must leave a RUNNING record for recover() to resume
            self.kv.put_json(key, {
                "type": proc.type_name, "state": proc.state,
                "status": ProcedureState.RUNNING.value, "ts": time.time(),
            })
            step = 0
            while step < max_steps:
                step += 1
                try:
                    status = proc.execute(ctx)
                except Exception as e:  # noqa: BLE001
                    self.kv.put_json(key, {
                        "type": proc.type_name, "state": proc.state,
                        "status": ProcedureState.FAILED.value,
                        "error": str(e), "ts": time.time(),
                    })
                    raise
                if status.kind == "poison":
                    for lk in locks:
                        self.kv.put_json(self._poison_key(lk), {"pid": pid})
                    self.kv.put_json(key, {
                        "type": proc.type_name, "state": proc.state,
                        "status": ProcedureState.POISONED.value, "ts": time.time(),
                    })
                    raise GreptimeError(f"procedure {proc.type_name} poisoned")
                if status.persist or status.kind == "done":
                    self.kv.put_json(key, {
                        "type": proc.type_name, "state": proc.state,
                        "status": (
                            ProcedureState.DONE.value if status.kind == "done"
                            else ProcedureState.RUNNING.value
                        ),
                        "ts": time.time(),
                    })
                if status.kind == "done":
                    self._prune_finished()
                    return status.output
            raise GreptimeError(f"procedure {proc.type_name} exceeded {max_steps} steps")
        finally:
            with self._locks_mu:
                for lk in locks:
                    self._locks.discard(lk)

    def _prune_finished(self, keep: int = 200) -> None:
        """Bound journal growth: now that every DDL is a procedure, keep
        only the most recent finished (DONE/FAILED) journals for
        information_schema.procedure_info; RUNNING/POISONED stay."""
        finished = []
        for k, raw in self.kv.range(self._PREFIX):
            rec = json.loads(raw)
            if rec.get("status") in (ProcedureState.DONE.value,
                                     ProcedureState.FAILED.value):
                finished.append((rec.get("ts", 0), k))
        if len(finished) > keep:
            finished.sort()
            for _ts, k in finished[:len(finished) - keep]:
                self.kv.delete(k)

    # ------------------------------------------------------------------
    def recover(self) -> list[object]:
        """Resume procedures journaled RUNNING (coordinator restart path).
        Returns outputs of resumed procedures. One failing resume must not
        starve the rest — with every DDL journaled, several RUNNING
        journals after a crash are normal; failures stay journaled FAILED
        and the first error is re-raised only after all were attempted."""
        out = []
        first_err: Exception | None = None
        for k, raw in self.kv.range(self._PREFIX):
            rec = json.loads(raw)
            if rec["status"] != ProcedureState.RUNNING.value:
                continue
            cls = self._registry.get(rec["type"])
            if cls is None:
                # an unknown type must never stay RUNNING forever: its
                # half-applied state would never converge (the chaos
                # fuzzer caught exactly this for an unregistered class).
                # Journal it FAILED so operators see it in
                # information_schema.procedure_info instead of a
                # permanent stuck runner.
                rec["status"] = ProcedureState.FAILED.value
                rec["error"] = f"type {rec['type']!r} not registered"
                self.kv.put_json(k, rec)
                if first_err is None:
                    first_err = GreptimeError(rec["error"])
                continue
            proc = cls(state=rec["state"])
            pid = k[len(self._PREFIX):]
            try:
                out.append(self._drive(pid, proc, max_steps=1000))
            except Exception as e:  # noqa: BLE001
                # _drive journals FAILED for step errors, but pre-step
                # rejections (poisoned lock, lock busy) raise BEFORE any
                # journal write — finalize here so no record stays RUNNING
                cur = json.loads(self.kv.get(k) or b"{}")
                if cur.get("status") == ProcedureState.RUNNING.value:
                    cur["status"] = ProcedureState.FAILED.value
                    cur["error"] = str(e)
                    self.kv.put_json(k, cur)
                if first_err is None:
                    first_err = e
        if first_err is not None:
            raise first_err
        return out

    def history(self) -> list[dict]:
        return [json.loads(v) for _k, v in self.kv.range(self._PREFIX)]

    def clear_poison(self, key: str) -> None:
        self.kv.delete(self._poison_key(key))
