"""Phi-accrual failure detector (reference src/meta-srv/src/failure_detector.rs:31-178).

Akka-lineage detector: keeps a bounded history of heartbeat inter-arrival
times and computes phi = -log10(P(no heartbeat by now | history)) under a
normal approximation. phi crosses the threshold smoothly as heartbeats go
missing, avoiding binary timeout flapping. Defaults mirror the reference
(threshold 8, min_std 100ms, acceptable_pause 10s, first_estimate 1s).
"""

from __future__ import annotations

import math
from collections import deque
from dataclasses import dataclass, field


@dataclass
class PhiAccrualFailureDetector:
    threshold: float = 8.0
    min_std_deviation_ms: float = 100.0
    acceptable_heartbeat_pause_ms: float = 10_000.0
    first_heartbeat_estimate_ms: float = 1_000.0
    max_sample_size: int = 1000
    _intervals: deque = None
    _last_heartbeat_ms: float | None = None

    def __post_init__(self):
        if self._intervals is None:
            self._intervals = deque(maxlen=self.max_sample_size)

    def heartbeat(self, now_ms: float) -> None:
        if self._last_heartbeat_ms is not None:
            interval = now_ms - self._last_heartbeat_ms
            if interval >= 0:
                self._intervals.append(interval)
        else:
            # seed with the bootstrap estimate (reference :92-104)
            std = self.first_heartbeat_estimate_ms / 4
            self._intervals.append(self.first_heartbeat_estimate_ms - std)
            self._intervals.append(self.first_heartbeat_estimate_ms + std)
        self._last_heartbeat_ms = now_ms

    def phi(self, now_ms: float) -> float:
        if self._last_heartbeat_ms is None or not self._intervals:
            return 0.0
        elapsed = now_ms - self._last_heartbeat_ms
        mean = sum(self._intervals) / len(self._intervals)
        var = sum((x - mean) ** 2 for x in self._intervals) / max(
            len(self._intervals), 1
        )
        std = max(math.sqrt(var), self.min_std_deviation_ms)
        mean_adj = mean + self.acceptable_heartbeat_pause_ms
        y = (elapsed - mean_adj) / std
        # P(X > elapsed) for N(mean_adj, std), logistic approximation of the
        # normal CDF (same as Akka / reference :150-166)
        exponent = -y * (1.5976 + 0.070566 * y * y)
        if exponent > 700:  # elapsed far below mean: certainly alive
            return 0.0
        if exponent < -700:  # elapsed far past mean: certainly dead
            return 300.0
        e = math.exp(exponent)
        if elapsed > mean_adj:
            p = e / (1.0 + e)
        else:
            p = 1.0 - 1.0 / (1.0 + e)
        if p <= 1e-300:
            return 300.0
        return -math.log10(p)

    def is_available(self, now_ms: float) -> bool:
        return self.phi(now_ms) < self.threshold
