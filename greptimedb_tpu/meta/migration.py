"""Live region migration + failover: journaled, resumable procedures.

Reference: src/meta-srv/src/procedure/region_migration/ (the
OpenCandidate → Downgrade → Upgrade → UpdateMetadata → CloseOld state
machine, migration_start.rs … migration_end.rs) and the fault-tolerance
RFC (docs/rfcs/2023-03-08-region-fault-tolerance.md).  Two additions
over the reference's open-from-shared-storage flow:

- **Snapshot shipping.**  When source and target datanodes do NOT share
  an object store, the region's objects (SSTs, skipping indexes,
  manifest files — and WAL segments when the WAL lives under the data
  home) are bulk-copied source→target over the Flight object plane on a
  bounded thread pool (the PR 5 streaming-pipeline discipline: fetch and
  install overlap across files).  Shared storage is detected with a
  probe object and the copy collapses to a no-op.
- **Two-round copy.**  The bulk ship runs while the source still serves
  writes; the source is only then fenced (downgrade: reject writes,
  flush) and a small delta sync mirrors whatever landed during the ship.
  The target's open/catch-up replays the remaining WAL tail from the
  shared broker (remote WAL) or the shipped segments (local WAL), so a
  migration under live writes is bit-exact vs a quiesced copy.

Every phase journals its state through the procedure framework before
executing, so a metasrv crash at ANY phase resumes to a consistent
route: re-running a phase is idempotent by construction (mirror copies
skip already-installed immutable files, fencing and opening are
re-appliable, the route swap is last).

``RegionFailoverProcedure`` drives the same machinery with the source
presumed dead (phi-accrual detector tripped): ship/fence/delta collapse
and the target — preferably a node already holding a follower replica —
opens from shared storage and replays the remote-WAL tail.  This is the
"datanodes are (nearly) stateless" payoff the remote WAL promises
(storage/remote_wal.py): nothing on the dead machine is needed.
"""

from __future__ import annotations

import re
from concurrent.futures import ThreadPoolExecutor

from greptimedb_tpu.errors import GreptimeError
from greptimedb_tpu.meta.procedure import Procedure, ProcedureContext, Status
from greptimedb_tpu.utils.telemetry import REGISTRY

# SSTs and their skipping indexes are immutable and uniquely named
# (uuid file ids): one already on the target is done forever.  Manifest
# json / WAL segments / watermark markers re-copy every round (append-
# or version-mutated).
_IMMUTABLE = re.compile(r"\.(parquet|idx)$")

_COPY_WORKERS = 4

M_MIGRATION_PHASE = REGISTRY.counter(
    "greptime_region_migration_phase_total",
    "Region migration/failover phases executed",
    labels=("procedure", "phase"),
)
M_MIGRATION_OBJECTS = REGISTRY.counter(
    "greptime_region_migration_objects_total",
    "Objects shipped (or pruned) by region migration bulk copy",
    labels=("kind",),
)


def _alive(dn) -> bool:
    if dn is None:
        return False
    try:
        return bool(dn.alive)
    except Exception:  # noqa: BLE001 — an unreachable proxy is dead
        return False


class RegionMigrationProcedure(Procedure):
    """state: {region_id, from_node, to_node, schema, now_ms, phase,
    source_dead, shared_store, shipped, delta_shipped, fenced_seq}."""

    type_name = "region_migration"

    def lock_keys(self) -> list[str]:
        return [f"region/{self.state['region_id']}"]

    # ---- bulk copy -----------------------------------------------------
    @staticmethod
    def _same_store(src, dst, rid: int, pid: str) -> bool:
        """Probe whether the two nodes see one object store: write a
        marker through the source, look for it through the target."""
        probe = f"region_{rid}/.migprobe-{pid}"
        src.put_object(probe, b"1")
        try:
            return probe in set(dst.list_region_objects(rid))
        finally:
            src.delete_object(probe)

    @staticmethod
    def _mirror_copy(src, dst, rid: int) -> int:
        """Make the target's ``region_<rid>/`` tree a mirror of the
        source's: ship missing/mutable objects (overlapped on a bounded
        pool), prune target objects the source no longer has (stale
        manifest deltas from an earlier tenure would otherwise be applied
        on open).  Idempotent — a resumed phase re-ships only deltas."""
        src_objs = src.list_region_objects(rid)
        dst_objs = set(dst.list_region_objects(rid))
        to_copy = [p for p in src_objs
                   if not (_IMMUTABLE.search(p) and p in dst_objs)]
        if to_copy:
            with ThreadPoolExecutor(
                min(_COPY_WORKERS, len(to_copy))
            ) as pool:
                list(pool.map(
                    lambda p: dst.put_object(p, src.fetch_object(p)),
                    to_copy,
                ))
            M_MIGRATION_OBJECTS.labels("shipped").inc(len(to_copy))
        src_set = set(src_objs)
        stale = [p for p in dst_objs if p not in src_set]
        for p in stale:
            dst.delete_object(p)
        if stale:
            M_MIGRATION_OBJECTS.labels("pruned").inc(len(stale))
        return len(to_copy)

    # ---- state machine -------------------------------------------------
    def execute(self, ctx: ProcedureContext) -> Status:
        s = self.state
        datanodes = ctx.services["datanodes"]
        metasrv = ctx.services["metasrv"]
        rid = s["region_id"]
        dst = datanodes.get(s["to_node"])
        src = datanodes.get(s["from_node"])
        if dst is None:
            raise GreptimeError(f"unknown target datanode {s['to_node']}")
        now = s.get("now_ms", 0.0)
        phase = s.setdefault("phase", "prepare")
        M_MIGRATION_PHASE.labels(self.type_name, phase).inc()

        if phase == "prepare":
            # ALWAYS probe the source, even on the detector-driven
            # failover path: a phi false-positive (GC pause, partition to
            # the metasrv only) leaves a leader that still answers
            # clients — it must be fenced through the full
            # ship→downgrade→delta pipeline, or writes it acks during
            # the takeover are lost (split brain).  Only a source that
            # really does not answer skips the copy/fence story: its
            # regions must live on shared storage + shared WAL.
            s["source_dead"] = not _alive(src)
            if not s["source_dead"] and s.get("schema") is None:
                region = src.engine.regions.get(rid)
                if region is not None:
                    s["schema"] = region.schema.to_dict()
            s["phase"] = ("upgrade_target" if s["source_dead"]
                          else "snapshot_ship")
            return Status.executing()

        if phase == "snapshot_ship":
            # bulk copy under live writes (the big transfer happens while
            # the source still serves; the fence window stays small)
            if s.get("shared_store") is None:
                s["shared_store"] = self._same_store(
                    src, dst, rid, ctx.procedure_id)
            if not s["shared_store"]:
                s["shipped"] = self._mirror_copy(src, dst, rid)
            s["phase"] = "fence_source"
            return Status.executing()

        if phase == "fence_source":
            # downgrade: reject writes first, then flush, so everything
            # acked by the source is in SSTs or the shared WAL tail
            if _alive(src):
                out = src.handle_instruction(
                    {"kind": "downgrade_region", "region_id": rid}, now)
                s["fenced_seq"] = int(out.get("last_seq", 0))
            s["phase"] = "delta_sync"
            return Status.executing()

        if phase == "delta_sync":
            # second, small mirror round: SSTs flushed and manifest deltas
            # committed since the snapshot ship
            if not s.get("shared_store") and _alive(src):
                s["delta_shipped"] = self._mirror_copy(src, dst, rid)
            s["phase"] = "upgrade_target"
            return Status.executing()

        if phase == "upgrade_target":
            # open-or-promote: a fresh target opens from the shipped (or
            # shared) manifest and replays the WAL tail; an already-open
            # follower runs a full ownership catch-up before leadership
            # (cluster.py open_region handler); an already-leader target
            # (resume after crash) is a no-op.  The leader EPOCH is
            # minted once and journaled (a resumed phase re-claims the
            # SAME epoch — minting twice would fence our own target):
            # the target claims shared-storage write surfaces under it,
            # so the fenced-out source's delayed writes fail loudly
            # (ISSUE 15 — the phi-false-positive split-brain backstop)
            if s.get("epoch") is None:
                s["epoch"] = metasrv.mint_epoch(rid)
            dst.handle_instruction(
                {"kind": "open_region", "region_id": rid, "role": "leader",
                 "schema": s.get("schema"), "epoch": s["epoch"]}, now)
            s["phase"] = "update_metadata"
            return Status.executing()

        if phase == "update_metadata":
            metasrv.set_region_route(rid, s["to_node"])
            # a promoted replica is no longer a follower of anything
            metasrv.remove_follower_route(rid, s["to_node"])
            # durability repair plumbing (ISSUE 9): re-point the new
            # leader's corruption-repair hooks at its surviving follower
            # replicas (best-effort — repair is an extra safety net, and
            # its wiring must never fail a migration)
            try:
                metasrv.wire_repair_sources(rid)
            except Exception:  # noqa: BLE001
                pass
            s["phase"] = "close_old"
            return Status.executing()

        if phase == "close_old":
            if not s.get("source_dead") and _alive(src):
                src.handle_instruction(
                    {"kind": "close_region", "region_id": rid}, now)
            return Status.done({"region_id": rid, "to_node": s["to_node"]})

        raise GreptimeError(f"unknown migration phase {phase}")


class RegionFailoverProcedure(RegionMigrationProcedure):
    """The detector-driven variant: same journaled machinery and the
    same liveness probe in prepare — the detector's suspicion picks the
    moment and the target, but only an actually-unreachable source is
    treated as dead (reference region_failover → region_migration
    unification; the supervisor submits these from Metasrv.tick)."""

    type_name = "region_failover"
