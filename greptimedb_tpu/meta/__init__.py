"""Metadata & cluster control plane (reference SURVEY.md §2.8/§2.9 layer 9).

CPU-side by design: kv backend, catalog, procedures, heartbeats, failure
detection port nearly verbatim from the reference's architecture — no TPU
involvement (SURVEY.md §7.1).
"""
