"""Cluster control plane: datanodes, heartbeats, leases, failover, migration.

In-process model of the reference's control loop (SURVEY.md §3.5): every
datanode heartbeats the metasrv; the metasrv's handler chain updates lease
keys, feeds the phi-accrual failure detectors and piggybacks mailbox
instructions on responses (reference src/meta-srv/src/handler/*.rs,
instruction.rs). Region failover runs the region-migration procedure —
a persisted, resumable state machine (reference
src/meta-srv/src/procedure/region_migration/*.rs).

Time is an explicit parameter everywhere (now_ms) so tests drive the loop
deterministically — the reference gets the same property from its mock
clusters (tests-integration/src/cluster.rs).
"""

from __future__ import annotations

import json

from greptimedb_tpu.datatypes.schema import Schema
from greptimedb_tpu.errors import GreptimeError, InvalidArguments, RegionNotFound
from greptimedb_tpu.meta.failure_detector import PhiAccrualFailureDetector
from greptimedb_tpu.meta.kv import KvBackend
from greptimedb_tpu.meta.procedure import ProcedureManager
from greptimedb_tpu.storage.region import RegionEngine
from greptimedb_tpu.utils.telemetry import REGISTRY

REGION_LEASE_MS = 20_000.0


def mint_epoch(kv: KvBackend, region_id: int) -> int:
    """Mint the next leader epoch for a region (shared by Metasrv and
    the dist frontend's initial placement — EVERY leadership grant must
    carry one, or the first-generation leader would run unfenced and a
    later failover's zombie could write epoch-less).  A CAS loop, not
    read-modify-write: two concurrent grants (reconciliation racing a
    placement) minting the SAME epoch would defeat every fence check —
    equal epochs pass as 'our own claim'."""
    key = f"__meta/epoch/region/{region_id}"
    for _ in range(64):
        raw = kv.get(key)
        cur = 0 if raw is None else int(json.loads(raw).get("epoch", 0))
        epoch = cur + 1
        if kv.compare_and_put(key, raw,
                              json.dumps({"epoch": epoch}).encode()):
            return epoch
    raise GreptimeError(
        f"region {region_id}: epoch mint kept losing its CAS")

# Replication lag of follower replicas, published from heartbeats (ISSUE 6:
# the bounded-staleness read contract reads these through the kv follower
# routes; /metrics shows the same numbers so the two can never disagree).
M_REPL_LAG_S = REGISTRY.gauge(
    "greptime_replication_lag_seconds",
    "Seconds since a follower replica last synced from shared storage",
    labels=("region", "node"),
)
M_REPL_LAG_E = REGISTRY.gauge(
    "greptime_replication_lag_entries",
    "WAL entries a follower replica trails its leader by",
    labels=("region", "node"),
)


class Datanode:
    """One storage node: a RegionEngine plus the node-side control loop
    (heartbeat emission, mailbox execution, lease self-fencing — reference
    src/datanode/src/{heartbeat.rs,alive_keeper.rs})."""

    def __init__(self, node_id: int, data_home: str, wal_broker=None):
        self.node_id = node_id
        # wal_broker: SharedLogBroker → remote WAL mode (the reference's
        # Kafka WAL): the node keeps NO required local state; its regions
        # replay from the shared log on any node after failover
        factory = None
        if wal_broker is not None:
            from greptimedb_tpu.storage.remote_wal import RemoteLogStore

            factory = lambda rid: RemoteLogStore(wal_broker, rid)  # noqa: E731
        self.engine = RegionEngine(data_home, log_store_factory=factory)
        self.roles: dict[int, str] = {}  # region_id -> leader|follower|downgrading
        self.lease_until_ms: dict[int, float] = {}
        self.alive = True
        self._sync_fingerprints: dict[int, tuple] = {}
        self.replica_sync_ms: dict[int, float] = {}  # follower last sync

    # ---- data plane ----------------------------------------------------
    def read(self, region_id: int, ts_range=(None, None), columns=None):
        """Serve a scan from leader OR follower (read replica). Followers
        return data as of their last sync (reference read-preference +
        follower regions, store-api region_engine.rs RegionRole)."""
        if not self.alive:
            raise GreptimeError(f"datanode {self.node_id} is down")
        region = self.engine.regions.get(region_id)
        if region is None:
            raise RegionNotFound(f"region {region_id} not on node {self.node_id}")
        return region.scan_host(ts_range, columns)

    def sync_region(self, region_id: int, now_ms: float = 0.0) -> None:
        """Follower catch-up from shared storage (reference
        SyncRegionFromRequest); no-op when storage hasn't changed since the
        last sync (a full manifest+WAL re-read per heartbeat would be pure
        waste on idle clusters)."""
        region = self.engine.regions.get(region_id)
        if region is None:
            raise RegionNotFound(f"region {region_id} not on node {self.node_id}")
        fp = region.storage_fingerprint()
        if self._sync_fingerprints.get(region_id) == fp:
            self.replica_sync_ms[region_id] = now_ms  # up to date IS a sync
            return
        region.catch_up()
        self._sync_fingerprints[region_id] = region.storage_fingerprint()
        self.replica_sync_ms[region_id] = now_ms

    # ---- object plane (region snapshot shipping) -----------------------
    # The migration procedure's bulk-copy surface: region objects (SSTs,
    # skipping indexes, manifest files — and WAL segments when the WAL
    # lives under the data home) move between data homes through these.
    # RemoteDatanode mirrors the same four methods over Flight, so the
    # procedure drives in-process and OS-process nodes identically.
    def _check_object_path(self, path: str) -> str:
        if not self.alive:
            raise GreptimeError(f"datanode {self.node_id} is down")
        if not path.startswith("region_") or ".." in path:
            raise InvalidArguments(f"not a region object path: {path}")
        return path

    def list_region_objects(self, region_id: int) -> list[str]:
        if not self.alive:
            raise GreptimeError(f"datanode {self.node_id} is down")
        return list(self.engine.store.list(f"region_{region_id}/"))

    def fetch_object(self, path: str) -> bytes:
        return self.engine.store.read(self._check_object_path(path))

    def put_object(self, path: str, data: bytes) -> None:
        self.engine.store.write(self._check_object_path(path), data)

    def delete_object(self, path: str) -> None:
        self.engine.store.delete(self._check_object_path(path))

    def write(self, region_id: int, data: dict, now_ms: float) -> int:
        if not self.alive:
            raise GreptimeError(f"datanode {self.node_id} is down")
        role = self.roles.get(region_id)
        if role != "leader":
            raise GreptimeError(
                f"region {region_id} on node {self.node_id} is {role}, not leader"
            )
        if self.lease_until_ms.get(region_id, 0) < now_ms:
            # self-fencing (reference alive_keeper.rs:50): an expired lease
            # means the metasrv may have moved the region elsewhere
            raise GreptimeError(
                f"region {region_id} lease expired on node {self.node_id}"
            )
        return self.engine.regions[region_id].write(data)

    # ---- control plane -------------------------------------------------
    def heartbeat(self, now_ms: float) -> dict:
        if not self.alive:
            raise GreptimeError(f"datanode {self.node_id} is down")
        regions = []
        for rid, region in self.engine.regions.items():
            info = {
                "region_id": rid,
                "role": self.roles.get(rid, "follower"),
                "num_rows": region.memtable.num_rows
                + sum(m.num_rows for m in region.sst_files),
                "last_seq": region.next_seq - 1,
            }
            if info["role"] == "follower":
                synced = self.replica_sync_ms.get(rid)
                info["sync_lag_ms"] = (
                    None if synced is None else max(now_ms - synced, 0.0)
                )
            regions.append(info)
        return {"node_id": self.node_id, "regions": regions, "ts": now_ms}

    def handle_instruction(self, instr: dict, now_ms: float) -> dict:
        """Mailbox instruction execution (reference instruction.rs)."""
        if not self.alive:
            raise GreptimeError(
                f"datanode {self.node_id} is down (instruction {instr['kind']})"
            )
        kind = instr["kind"]
        rid = instr.get("region_id")
        if kind == "open_region":
            schema = (
                Schema.from_dict(instr["schema"])
                if instr.get("schema") else None  # key may exist with None
            )
            role = instr.get("role", "follower")
            was_open = rid in self.engine.regions
            try:
                # followers open read-only: the WAL dir is shared with the
                # live leader, whose in-flight append must not be repaired
                self.engine.open_region(rid, take_ownership=(role == "leader"))
            except RegionNotFound:
                if schema is None:
                    raise
                self.engine.create_region(rid, schema)
            if role == "leader" and was_open and self.roles.get(rid) != "leader":
                # promoting an already-open follower region: its read-only
                # replay left torn tails unrepaired and state possibly stale;
                # a full ownership catch-up is mandatory before leadership
                self.engine.regions[rid].catch_up(take_ownership=True)
            self.roles[rid] = role
            if self.roles[rid] == "leader":
                self.lease_until_ms[rid] = now_ms + REGION_LEASE_MS
                if instr.get("epoch") is not None:
                    # storage-level fencing (ISSUE 15): the minted epoch
                    # claims the shared manifest/broker write surfaces,
                    # so a fenced-out predecessor's delayed write fails
                    # loudly even if its clock-based lease lies to it
                    self.engine.regions[rid].install_fence(instr["epoch"])
            return {"ok": True}
        if kind == "close_region":
            region = self.engine.regions.pop(rid, None)
            if region is not None:
                region.wal.close()
            self.roles.pop(rid, None)
            self.lease_until_ms.pop(rid, None)
            return {"ok": True}
        if kind == "downgrade_region":
            region = self.engine.regions.get(rid)
            # fence FIRST, then flush: a write racing the downgrade must
            # either be rejected or land before the flush — never in the
            # gap where only the WAL tail would carry it off a local disk
            self.roles[rid] = "downgrading"
            if region is not None and instr.get("flush", True):
                region.flush()
            return {"ok": True, "last_seq": region.next_seq - 1 if region else 0}
        if kind == "upgrade_region":
            region = self.engine.regions.get(rid)
            if region is None:
                raise RegionNotFound(f"region {rid} not open on {self.node_id}")
            # catch-up before taking leadership (reference handle_catchup.rs)
            region.catch_up(take_ownership=True)
            self.roles[rid] = "leader"
            self.lease_until_ms[rid] = now_ms + REGION_LEASE_MS
            if instr.get("epoch") is not None:
                region.install_fence(instr["epoch"])
            return {"ok": True}
        if kind == "flush_region":
            region = self.engine.regions.get(rid)
            if region is not None:
                region.flush()
            return {"ok": True}
        if kind == "renew_lease":
            if self.roles.get(rid) == "leader":
                self.lease_until_ms[rid] = now_ms + REGION_LEASE_MS
            return {"ok": True}
        if kind == "sync_region":
            self.sync_region(rid, now_ms)
            return {"ok": True}
        raise GreptimeError(f"unknown instruction {kind}")

    def tick_alive_keeper(self, now_ms: float) -> list[int]:
        """Self-fence regions whose lease expired; returns closed ids."""
        expired = [
            rid for rid, until in self.lease_until_ms.items()
            if until < now_ms and self.roles.get(rid) == "leader"
        ]
        for rid in expired:
            self.roles[rid] = "follower"
        return expired


class Metasrv:
    """Cluster brain (reference src/meta-srv/src/metasrv.rs:556): heartbeat
    handler chain, failure detection, region routes, migration driving."""

    def __init__(self, kv: KvBackend):
        from greptimedb_tpu.meta.migration import (
            RegionFailoverProcedure, RegionMigrationProcedure,
        )

        self.kv = kv
        self.datanodes: dict[int, Datanode] = {}
        self.detectors: dict[int, PhiAccrualFailureDetector] = {}
        self.procedures = ProcedureManager(
            kv, services={"datanodes": self.datanodes, "metasrv": self}
        )
        self.procedures.register(RegionMigrationProcedure)
        self.procedures.register(RegionFailoverProcedure)
        from greptimedb_tpu.meta.reconciliation import (
            ReconcileCatalogProcedure, ReconcileDatabaseProcedure,
            ReconcileTableProcedure,
        )

        self.procedures.register(ReconcileTableProcedure)
        self.procedures.register(ReconcileDatabaseProcedure)
        self.procedures.register(ReconcileCatalogProcedure)
        self.maintenance_mode = False
        self._leader_seq: dict[int, int] = {}  # from leader heartbeats

    # ---- membership ----------------------------------------------------
    def register_datanode(self, dn: Datanode) -> None:
        self.datanodes[dn.node_id] = dn
        self.detectors[dn.node_id] = PhiAccrualFailureDetector()

    # ---- routes --------------------------------------------------------
    def set_region_route(self, region_id: int, node_id: int) -> None:
        self.kv.put_json(f"__meta/route/region/{region_id}", {"node": node_id})

    def region_route(self, region_id: int) -> int | None:
        rec = self.kv.get_json(f"__meta/route/region/{region_id}")
        return None if rec is None else rec["node"]

    def routes(self) -> dict[int, int]:
        out = {}
        for k, v in self.kv.range("__meta/route/region/"):
            out[int(k.rsplit("/", 1)[-1])] = json.loads(v)["node"]
        return out

    # ---- leader epochs (storage-level fencing, ISSUE 15) ---------------
    def mint_epoch(self, region_id: int) -> int:
        """Mint the next leader epoch for a region — one per leadership
        grant (open/failover/migration-upgrade).  The new leader claims
        shared-storage write surfaces under it (Region.install_fence),
        so a fenced-out predecessor's delayed manifest delta or broker
        append fails loudly instead of forking history."""
        return mint_epoch(self.kv, region_id)

    # ---- follower routes (read replicas) -------------------------------
    # Follower placement + freshness live in the kv store next to the
    # leader routes, so stateless frontends can route bounded-staleness
    # reads without talking to the metasrv (reference: RegionRoute
    # follower_peers in the table route value, src/common/meta/src/rpc/
    # router.rs + the read-preference RFC).
    def _followers_key(self, region_id: int) -> str:
        return f"__meta/route/followers/{region_id}"

    def follower_routes(self, region_id: int) -> dict[int, dict]:
        rec = self.kv.get_json(self._followers_key(region_id)) or {}
        return {int(n): meta for n, meta in rec.get("nodes", {}).items()}

    def _put_follower_routes(self, region_id: int,
                             nodes: dict[int, dict]) -> None:
        if nodes:
            self.kv.put_json(self._followers_key(region_id),
                             {"nodes": {str(n): m for n, m in nodes.items()}})
        else:
            self.kv.delete(self._followers_key(region_id))

    def remove_follower_route(self, region_id: int, node_id: int) -> None:
        nodes = self.follower_routes(region_id)
        if node_id in nodes:
            del nodes[node_id]
            self._put_follower_routes(region_id, nodes)

    def wire_repair_sources(self, region_id: int) -> int:
        """Durability repair plumbing (ISSUE 9, Taurus repair-from-replica):
        point each open LEADER region's corruption-repair hooks at an
        alive follower replica — ``repair_source`` fetches the replica's
        copy of an SST over the object plane, ``wal_resync`` scans the
        replica's replayable WAL objects for a lost sequence range.  With
        no alive follower the hooks clear, so an uncovered loss stays a
        loud failure instead of hanging on a dead peer.  Returns the
        number of leader regions wired."""
        from greptimedb_tpu.storage.durability import (
            repair_sst_from_peer, resync_from_peer_wal,
        )

        routes = self.follower_routes(region_id)
        wired = 0
        for nid, dn in self.datanodes.items():
            if (dn.roles.get(region_id) != "leader"
                    or region_id not in dn.engine.regions):
                continue
            region = dn.engine.regions[region_id]
            peer = None
            for fnid in routes:
                f = self.datanodes.get(int(fnid))
                if f is not None and f.alive and f.node_id != nid:
                    peer = f
                    break
            if peer is None:
                region.repair_source = None
                region.wal_resync = None
                continue
            region.repair_source = repair_sst_from_peer(peer)
            region.wal_resync = resync_from_peer_wal(peer, region_id)
            wired += 1
        return wired

    # ---- heartbeat chain (reference handler.rs:322) --------------------
    def handle_heartbeat(self, hb: dict, now_ms: float) -> list[dict]:
        node_id = hb["node_id"]
        det = self.detectors.get(node_id)
        if det is None:
            return []
        det.heartbeat(now_ms)
        instructions = []
        for r in hb.get("regions", []):
            rid = r["region_id"]
            if r["role"] == "leader" and self.region_route(rid) == node_id:
                # lease renewal for leader regions this node legitimately routes
                self._leader_seq[rid] = int(r.get("last_seq", 0))
                instructions.append(
                    {"kind": "renew_lease", "region_id": rid}
                )
            elif r["role"] == "follower":
                self._note_follower_lag(rid, node_id, r, now_ms)
                # read replicas catch up from shared storage each beat
                instructions.append(
                    {"kind": "sync_region", "region_id": rid}
                )
        return instructions

    def _note_follower_lag(self, region_id: int, node_id: int, r: dict,
                           now_ms: float) -> None:
        """Publish follower freshness to the registry and the kv follower
        route (the frontend's bounded-staleness input)."""
        lag_ms = r.get("sync_lag_ms")
        entries = max(
            self._leader_seq.get(region_id, 0) - int(r.get("last_seq", 0)), 0
        )
        if lag_ms is not None:
            # a replica that has NEVER synced makes no freshness claim:
            # exporting 0 here would show a stuck replica as perfect
            M_REPL_LAG_S.labels(str(region_id), str(node_id)).set(
                lag_ms / 1000.0)
        M_REPL_LAG_E.labels(str(region_id), str(node_id)).set(entries)
        nodes = self.follower_routes(region_id)
        if node_id in nodes or self.region_route(region_id) is not None:
            nodes[node_id] = {"lag_ms": lag_ms, "entries_behind": entries,
                              "ts": now_ms}
            self._put_follower_routes(region_id, nodes)

    def add_follower(self, region_id: int, node_id: int, now_ms: float) -> None:
        """Open a read replica of a region on another node."""
        if node_id not in self.datanodes:
            raise GreptimeError(f"unknown datanode {node_id}")
        leader_node = self.region_route(region_id)
        if node_id == leader_node:
            # re-opening the region as follower on its own leader node would
            # silently demote the active leader and fail all writes
            raise InvalidArguments(
                f"node {node_id} is the leader for region {region_id}; "
                f"cannot also host it as follower"
            )
        dn = self.datanodes[node_id]
        if dn.roles.get(region_id) == "follower":
            return  # already a follower there
        leader = self.datanodes.get(leader_node)
        region = leader.engine.regions.get(region_id) if leader else None
        instr = {"kind": "open_region", "region_id": region_id,
                 "role": "follower"}
        if region is not None:
            instr["schema"] = region.schema.to_dict()
        # without a schema the follower can still open a region that exists
        # on shared storage; a truly unknown region raises RegionNotFound
        self.datanodes[node_id].handle_instruction(instr, now_ms)
        nodes = self.follower_routes(region_id)
        nodes[node_id] = {"lag_ms": None, "entries_behind": None,
                          "ts": now_ms}
        self._put_follower_routes(region_id, nodes)

    # ---- supervision (reference region/supervisor.rs:280) --------------
    def select_target(self, exclude: set[int]) -> int | None:
        """Least-loaded alive node (reference selector/load_based.rs)."""
        best = None
        best_load = None
        for nid, dn in self.datanodes.items():
            if nid in exclude or not dn.alive:
                continue
            load = len([r for r, role in dn.roles.items() if role == "leader"])
            if best_load is None or load < best_load:
                best, best_load = nid, load
        return best

    def select_failover_target(self, region_id: int,
                               exclude: set[int]) -> int | None:
        """Prefer an alive node already hosting the region as a follower
        replica (its data is warm and nearly caught up — reference
        region_failover candidate selection); else least-loaded alive."""
        for nid, dn in self.datanodes.items():
            if nid in exclude:
                continue
            try:
                if dn.alive and dn.roles.get(region_id) == "follower":
                    return nid
            except GreptimeError:
                continue
        return self.select_target(exclude)

    def tick(self, now_ms: float) -> list[dict]:
        """Failure detection sweep; returns completed failovers."""
        if self.maintenance_mode:
            return []
        migrated = []
        for nid, det in self.detectors.items():
            dn = self.datanodes[nid]
            if det.phi(now_ms) < det.threshold:
                continue
            # node suspected dead: move its leader regions away
            for rid, node in self.routes().items():
                if node != nid:
                    continue
                target = self.select_failover_target(rid, exclude={nid})
                if target is None:
                    continue
                migrated.append(
                    self._submit_migration(rid, nid, target, now_ms,
                                           failover=True)
                )
        return migrated

    def _submit_migration(self, region_id: int, from_node: int, to_node: int,
                          now_ms: float, failover: bool = False) -> dict:
        from greptimedb_tpu.meta.migration import (
            RegionFailoverProcedure, RegionMigrationProcedure,
        )

        # schema peek is best-effort: a dead from-node's proxy reports no
        # regions (rpc client swallows transport errors) and the candidate
        # then opens from shared storage via the manifest
        region = self.datanodes[from_node].engine.regions.get(region_id)
        schema = region.schema.to_dict() if region is not None else None
        cls = RegionFailoverProcedure if failover else RegionMigrationProcedure
        proc = cls(state={
            "region_id": region_id, "from_node": from_node, "to_node": to_node,
            "schema": schema, "now_ms": now_ms,
        })
        return self.procedures.submit(proc)

    def migrate_region(self, region_id: int, from_node: int, to_node: int,
                       now_ms: float) -> dict:
        """Manual migration (reference admin migrate_region function)."""
        return self._submit_migration(region_id, from_node, to_node, now_ms)

    def failover_region(self, region_id: int, now_ms: float) -> dict:
        """Force-promote the best replica of a region whose leader is
        gone (admin analog of the supervisor's automatic path)."""
        from_node = self.region_route(region_id)
        if from_node is None:
            raise GreptimeError(f"no route for region {region_id}")
        target = self.select_failover_target(region_id, exclude={from_node})
        if target is None:
            raise GreptimeError("no failover target available")
        return self._submit_migration(region_id, from_node, target, now_ms,
                                      failover=True)

    # ---- reconciliation (reference reconciliation/manager.rs) ----------
    def reconcile_table(self, db: str, table: str,
                        strategy: str = "use_latest") -> dict:
        from greptimedb_tpu.meta.reconciliation import ReconcileTableProcedure

        return self.procedures.submit(ReconcileTableProcedure(state={
            "db": db, "table": table, "strategy": strategy,
        }))

    def reconcile_database(self, db: str,
                           strategy: str = "use_latest") -> dict:
        from greptimedb_tpu.meta.reconciliation import (
            ReconcileDatabaseProcedure,
        )

        return self.procedures.submit(ReconcileDatabaseProcedure(state={
            "db": db, "strategy": strategy,
        }))

    def reconcile_catalog(self, strategy: str = "use_latest") -> dict:
        from greptimedb_tpu.meta.reconciliation import (
            ReconcileCatalogProcedure,
        )

        return self.procedures.submit(ReconcileCatalogProcedure(state={
            "strategy": strategy,
        }))
