"""Cluster control plane: datanodes, heartbeats, leases, failover, migration.

In-process model of the reference's control loop (SURVEY.md §3.5): every
datanode heartbeats the metasrv; the metasrv's handler chain updates lease
keys, feeds the phi-accrual failure detectors and piggybacks mailbox
instructions on responses (reference src/meta-srv/src/handler/*.rs,
instruction.rs). Region failover runs the region-migration procedure —
a persisted, resumable state machine (reference
src/meta-srv/src/procedure/region_migration/*.rs).

Time is an explicit parameter everywhere (now_ms) so tests drive the loop
deterministically — the reference gets the same property from its mock
clusters (tests-integration/src/cluster.rs).
"""

from __future__ import annotations

import json

from greptimedb_tpu.datatypes.schema import Schema
from greptimedb_tpu.errors import GreptimeError, InvalidArguments, RegionNotFound
from greptimedb_tpu.meta.failure_detector import PhiAccrualFailureDetector
from greptimedb_tpu.meta.kv import KvBackend
from greptimedb_tpu.meta.procedure import (
    Procedure, ProcedureContext, ProcedureManager, Status,
)
from greptimedb_tpu.storage.region import RegionEngine

REGION_LEASE_MS = 20_000.0


class Datanode:
    """One storage node: a RegionEngine plus the node-side control loop
    (heartbeat emission, mailbox execution, lease self-fencing — reference
    src/datanode/src/{heartbeat.rs,alive_keeper.rs})."""

    def __init__(self, node_id: int, data_home: str, wal_broker=None):
        self.node_id = node_id
        # wal_broker: SharedLogBroker → remote WAL mode (the reference's
        # Kafka WAL): the node keeps NO required local state; its regions
        # replay from the shared log on any node after failover
        factory = None
        if wal_broker is not None:
            from greptimedb_tpu.storage.remote_wal import RemoteLogStore

            factory = lambda rid: RemoteLogStore(wal_broker, rid)  # noqa: E731
        self.engine = RegionEngine(data_home, log_store_factory=factory)
        self.roles: dict[int, str] = {}  # region_id -> leader|follower|downgrading
        self.lease_until_ms: dict[int, float] = {}
        self.alive = True
        self._sync_fingerprints: dict[int, tuple] = {}

    # ---- data plane ----------------------------------------------------
    def read(self, region_id: int, ts_range=(None, None), columns=None):
        """Serve a scan from leader OR follower (read replica). Followers
        return data as of their last sync (reference read-preference +
        follower regions, store-api region_engine.rs RegionRole)."""
        if not self.alive:
            raise GreptimeError(f"datanode {self.node_id} is down")
        region = self.engine.regions.get(region_id)
        if region is None:
            raise RegionNotFound(f"region {region_id} not on node {self.node_id}")
        return region.scan_host(ts_range, columns)

    def sync_region(self, region_id: int) -> None:
        """Follower catch-up from shared storage (reference
        SyncRegionFromRequest); no-op when storage hasn't changed since the
        last sync (a full manifest+WAL re-read per heartbeat would be pure
        waste on idle clusters)."""
        region = self.engine.regions.get(region_id)
        if region is None:
            raise RegionNotFound(f"region {region_id} not on node {self.node_id}")
        fp = region.storage_fingerprint()
        if self._sync_fingerprints.get(region_id) == fp:
            return
        region.catch_up()
        self._sync_fingerprints[region_id] = region.storage_fingerprint()

    def write(self, region_id: int, data: dict, now_ms: float) -> int:
        if not self.alive:
            raise GreptimeError(f"datanode {self.node_id} is down")
        role = self.roles.get(region_id)
        if role != "leader":
            raise GreptimeError(
                f"region {region_id} on node {self.node_id} is {role}, not leader"
            )
        if self.lease_until_ms.get(region_id, 0) < now_ms:
            # self-fencing (reference alive_keeper.rs:50): an expired lease
            # means the metasrv may have moved the region elsewhere
            raise GreptimeError(
                f"region {region_id} lease expired on node {self.node_id}"
            )
        return self.engine.regions[region_id].write(data)

    # ---- control plane -------------------------------------------------
    def heartbeat(self, now_ms: float) -> dict:
        if not self.alive:
            raise GreptimeError(f"datanode {self.node_id} is down")
        regions = []
        for rid, region in self.engine.regions.items():
            regions.append({
                "region_id": rid,
                "role": self.roles.get(rid, "follower"),
                "num_rows": region.memtable.num_rows
                + sum(m.num_rows for m in region.sst_files),
            })
        return {"node_id": self.node_id, "regions": regions, "ts": now_ms}

    def handle_instruction(self, instr: dict, now_ms: float) -> dict:
        """Mailbox instruction execution (reference instruction.rs)."""
        if not self.alive:
            raise GreptimeError(
                f"datanode {self.node_id} is down (instruction {instr['kind']})"
            )
        kind = instr["kind"]
        rid = instr.get("region_id")
        if kind == "open_region":
            schema = (
                Schema.from_dict(instr["schema"])
                if instr.get("schema") else None  # key may exist with None
            )
            role = instr.get("role", "follower")
            was_open = rid in self.engine.regions
            try:
                # followers open read-only: the WAL dir is shared with the
                # live leader, whose in-flight append must not be repaired
                self.engine.open_region(rid, take_ownership=(role == "leader"))
            except RegionNotFound:
                if schema is None:
                    raise
                self.engine.create_region(rid, schema)
            if role == "leader" and was_open and self.roles.get(rid) != "leader":
                # promoting an already-open follower region: its read-only
                # replay left torn tails unrepaired and state possibly stale;
                # a full ownership catch-up is mandatory before leadership
                self.engine.regions[rid].catch_up(take_ownership=True)
            self.roles[rid] = role
            if self.roles[rid] == "leader":
                self.lease_until_ms[rid] = now_ms + REGION_LEASE_MS
            return {"ok": True}
        if kind == "close_region":
            region = self.engine.regions.pop(rid, None)
            if region is not None:
                region.wal.close()
            self.roles.pop(rid, None)
            self.lease_until_ms.pop(rid, None)
            return {"ok": True}
        if kind == "downgrade_region":
            region = self.engine.regions.get(rid)
            if region is not None:
                region.flush()
            self.roles[rid] = "downgrading"
            return {"ok": True, "last_seq": region.next_seq - 1 if region else 0}
        if kind == "upgrade_region":
            region = self.engine.regions.get(rid)
            if region is None:
                raise RegionNotFound(f"region {rid} not open on {self.node_id}")
            # catch-up before taking leadership (reference handle_catchup.rs)
            region.catch_up(take_ownership=True)
            self.roles[rid] = "leader"
            self.lease_until_ms[rid] = now_ms + REGION_LEASE_MS
            return {"ok": True}
        if kind == "flush_region":
            region = self.engine.regions.get(rid)
            if region is not None:
                region.flush()
            return {"ok": True}
        if kind == "renew_lease":
            if self.roles.get(rid) == "leader":
                self.lease_until_ms[rid] = now_ms + REGION_LEASE_MS
            return {"ok": True}
        if kind == "sync_region":
            self.sync_region(rid)
            return {"ok": True}
        raise GreptimeError(f"unknown instruction {kind}")

    def tick_alive_keeper(self, now_ms: float) -> list[int]:
        """Self-fence regions whose lease expired; returns closed ids."""
        expired = [
            rid for rid, until in self.lease_until_ms.items()
            if until < now_ms and self.roles.get(rid) == "leader"
        ]
        for rid in expired:
            self.roles[rid] = "follower"
        return expired


class RegionMigrationProcedure(Procedure):
    """OpenCandidate → Downgrade → Upgrade → UpdateMetadata → CloseOld
    (reference migration_start.rs ... migration_end.rs)."""

    type_name = "region_migration"

    def execute(self, ctx: ProcedureContext) -> Status:
        s = self.state
        datanodes: dict[int, Datanode] = ctx.services["datanodes"]
        metasrv: Metasrv = ctx.services["metasrv"]
        rid = s["region_id"]
        src = s["from_node"]
        dst = s["to_node"]
        now = s.get("now_ms", 0.0)
        phase = s.setdefault("phase", "open_candidate")

        if phase == "open_candidate":
            dn = datanodes[dst]
            dn.handle_instruction(
                {"kind": "open_region", "region_id": rid, "role": "follower",
                 "schema": s.get("schema")}, now,
            )
            s["phase"] = "downgrade_leader"
            return Status.executing()
        if phase == "downgrade_leader":
            src_dn = datanodes.get(src)
            if src_dn is not None and src_dn.alive:
                src_dn.handle_instruction(
                    {"kind": "downgrade_region", "region_id": rid}, now
                )
            s["phase"] = "upgrade_candidate"
            return Status.executing()
        if phase == "upgrade_candidate":
            datanodes[dst].handle_instruction(
                {"kind": "upgrade_region", "region_id": rid}, now
            )
            s["phase"] = "update_metadata"
            return Status.executing()
        if phase == "update_metadata":
            metasrv.set_region_route(rid, dst)
            s["phase"] = "close_old"
            return Status.executing()
        if phase == "close_old":
            src_dn = datanodes.get(src)
            if src_dn is not None and src_dn.alive:
                src_dn.handle_instruction(
                    {"kind": "close_region", "region_id": rid}, now
                )
            return Status.done({"region_id": rid, "to_node": dst})
        raise GreptimeError(f"unknown migration phase {phase}")

    def lock_keys(self) -> list[str]:
        return [f"region/{self.state['region_id']}"]


class Metasrv:
    """Cluster brain (reference src/meta-srv/src/metasrv.rs:556): heartbeat
    handler chain, failure detection, region routes, migration driving."""

    def __init__(self, kv: KvBackend):
        self.kv = kv
        self.datanodes: dict[int, Datanode] = {}
        self.detectors: dict[int, PhiAccrualFailureDetector] = {}
        self.procedures = ProcedureManager(
            kv, services={"datanodes": self.datanodes, "metasrv": self}
        )
        self.procedures.register(RegionMigrationProcedure)
        from greptimedb_tpu.meta.reconciliation import (
            ReconcileCatalogProcedure, ReconcileDatabaseProcedure,
            ReconcileTableProcedure,
        )

        self.procedures.register(ReconcileTableProcedure)
        self.procedures.register(ReconcileDatabaseProcedure)
        self.procedures.register(ReconcileCatalogProcedure)
        self.maintenance_mode = False

    # ---- membership ----------------------------------------------------
    def register_datanode(self, dn: Datanode) -> None:
        self.datanodes[dn.node_id] = dn
        self.detectors[dn.node_id] = PhiAccrualFailureDetector()

    # ---- routes --------------------------------------------------------
    def set_region_route(self, region_id: int, node_id: int) -> None:
        self.kv.put_json(f"__meta/route/region/{region_id}", {"node": node_id})

    def region_route(self, region_id: int) -> int | None:
        rec = self.kv.get_json(f"__meta/route/region/{region_id}")
        return None if rec is None else rec["node"]

    def routes(self) -> dict[int, int]:
        out = {}
        for k, v in self.kv.range("__meta/route/region/"):
            out[int(k.rsplit("/", 1)[-1])] = json.loads(v)["node"]
        return out

    # ---- heartbeat chain (reference handler.rs:322) --------------------
    def handle_heartbeat(self, hb: dict, now_ms: float) -> list[dict]:
        node_id = hb["node_id"]
        det = self.detectors.get(node_id)
        if det is None:
            return []
        det.heartbeat(now_ms)
        instructions = []
        for r in hb.get("regions", []):
            if r["role"] == "leader" and self.region_route(r["region_id"]) == node_id:
                # lease renewal for leader regions this node legitimately routes
                instructions.append(
                    {"kind": "renew_lease", "region_id": r["region_id"]}
                )
            elif r["role"] == "follower":
                # read replicas catch up from shared storage each beat
                instructions.append(
                    {"kind": "sync_region", "region_id": r["region_id"]}
                )
        return instructions

    def add_follower(self, region_id: int, node_id: int, now_ms: float) -> None:
        """Open a read replica of a region on another node."""
        if node_id not in self.datanodes:
            raise GreptimeError(f"unknown datanode {node_id}")
        leader_node = self.region_route(region_id)
        if node_id == leader_node:
            # re-opening the region as follower on its own leader node would
            # silently demote the active leader and fail all writes
            raise InvalidArguments(
                f"node {node_id} is the leader for region {region_id}; "
                f"cannot also host it as follower"
            )
        dn = self.datanodes[node_id]
        if dn.roles.get(region_id) == "follower":
            return  # already a follower there
        leader = self.datanodes.get(leader_node)
        region = leader.engine.regions.get(region_id) if leader else None
        instr = {"kind": "open_region", "region_id": region_id,
                 "role": "follower"}
        if region is not None:
            instr["schema"] = region.schema.to_dict()
        # without a schema the follower can still open a region that exists
        # on shared storage; a truly unknown region raises RegionNotFound
        self.datanodes[node_id].handle_instruction(instr, now_ms)

    # ---- supervision (reference region/supervisor.rs:280) --------------
    def select_target(self, exclude: set[int]) -> int | None:
        """Least-loaded alive node (reference selector/load_based.rs)."""
        best = None
        best_load = None
        for nid, dn in self.datanodes.items():
            if nid in exclude or not dn.alive:
                continue
            load = len([r for r, role in dn.roles.items() if role == "leader"])
            if best_load is None or load < best_load:
                best, best_load = nid, load
        return best

    def tick(self, now_ms: float) -> list[dict]:
        """Failure detection sweep; returns completed migrations."""
        if self.maintenance_mode:
            return []
        migrated = []
        for nid, det in self.detectors.items():
            dn = self.datanodes[nid]
            if det.phi(now_ms) < det.threshold:
                continue
            # node suspected dead: move its leader regions away
            for rid, node in self.routes().items():
                if node != nid:
                    continue
                target = self.select_target(exclude={nid})
                if target is None:
                    continue
                migrated.append(
                    self._submit_migration(rid, nid, target, now_ms)
                )
        return migrated

    def _submit_migration(self, region_id: int, from_node: int, to_node: int,
                          now_ms: float) -> dict:
        # schema peek is best-effort: a dead from-node's proxy reports no
        # regions (rpc client swallows transport errors) and the candidate
        # then opens from shared storage via the manifest
        region = self.datanodes[from_node].engine.regions.get(region_id)
        schema = region.schema.to_dict() if region is not None else None
        proc = RegionMigrationProcedure(state={
            "region_id": region_id, "from_node": from_node, "to_node": to_node,
            "schema": schema, "now_ms": now_ms,
        })
        return self.procedures.submit(proc)

    def migrate_region(self, region_id: int, from_node: int, to_node: int,
                       now_ms: float) -> dict:
        """Manual migration (reference admin migrate_region function)."""
        return self._submit_migration(region_id, from_node, to_node, now_ms)

    # ---- reconciliation (reference reconciliation/manager.rs) ----------
    def reconcile_table(self, db: str, table: str,
                        strategy: str = "use_latest") -> dict:
        from greptimedb_tpu.meta.reconciliation import ReconcileTableProcedure

        return self.procedures.submit(ReconcileTableProcedure(state={
            "db": db, "table": table, "strategy": strategy,
        }))

    def reconcile_database(self, db: str,
                           strategy: str = "use_latest") -> dict:
        from greptimedb_tpu.meta.reconciliation import (
            ReconcileDatabaseProcedure,
        )

        return self.procedures.submit(ReconcileDatabaseProcedure(state={
            "db": db, "strategy": strategy,
        }))

    def reconcile_catalog(self, strategy: str = "use_latest") -> dict:
        from greptimedb_tpu.meta.reconciliation import (
            ReconcileCatalogProcedure,
        )

        return self.procedures.submit(ReconcileCatalogProcedure(state={
            "strategy": strategy,
        }))
