"""Leader election over the kv backend (reference src/common/meta/src/election/).

Lease-based: candidates CAS the leader key with an expiry; the holder
renews before expiry; anyone observing an expired lease may take over.
The reference runs this over etcd leases / RDS rows — the CAS semantics
are identical.
"""

from __future__ import annotations

import json
from dataclasses import dataclass

from greptimedb_tpu.meta.kv import KvBackend

LEADER_KEY = "__election/leader"


@dataclass
class Election:
    kv: KvBackend
    node_id: str
    lease_s: float = 10.0

    def campaign(self, now_s: float) -> bool:
        """Try to become (or stay) leader; returns True when leading."""
        raw = self.kv.get(LEADER_KEY)
        record = json.dumps(
            {"leader": self.node_id, "expires_at": now_s + self.lease_s}
        ).encode()
        if raw is None:
            return self.kv.compare_and_put(LEADER_KEY, None, record)
        cur = json.loads(raw)
        if cur["leader"] == self.node_id or cur["expires_at"] <= now_s:
            return self.kv.compare_and_put(LEADER_KEY, raw, record)
        return False

    def leader(self, now_s: float) -> str | None:
        raw = self.kv.get(LEADER_KEY)
        if raw is None:
            return None
        cur = json.loads(raw)
        if cur["expires_at"] <= now_s:
            return None
        return cur["leader"]

    def is_leader(self, now_s: float) -> bool:
        return self.leader(now_s) == self.node_id

    def resign(self) -> None:
        # CAS-delete: a plain get-then-delete could remove a NEWER leader's
        # record written between our read and our delete
        raw = self.kv.get(LEADER_KEY)
        if raw is not None and json.loads(raw)["leader"] == self.node_id:
            self.kv.compare_and_delete(LEADER_KEY, raw)
