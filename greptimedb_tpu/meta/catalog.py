"""Catalog: databases and tables over a KvBackend.

Equivalent of the reference's KvBackendCatalogManager
(src/catalog/src/kvbackend/manager.rs:71) + the typed key space of
src/common/meta/src/key/: table info records live at
``__catalog/<db>/<table>`` with table-id allocation at ``__meta/next_ids``.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

from greptimedb_tpu.datatypes.schema import Schema
from greptimedb_tpu.errors import (
    DatabaseNotFound, GreptimeError, StatusCode, TableAlreadyExists,
    TableNotFound,
)
from greptimedb_tpu.meta.kv import KvBackend

DEFAULT_CATALOG = "greptime"
DEFAULT_DB = "public"


@dataclass
class TableInfo:
    table_id: int
    name: str
    database: str
    schema: Schema
    region_ids: list[int]
    engine: str = "mito"
    options: dict = field(default_factory=dict)
    partition_exprs: list[str] = field(default_factory=list)
    partition_columns: list[str] = field(default_factory=list)

    def to_dict(self) -> dict:
        return {
            "table_id": self.table_id,
            "name": self.name,
            "database": self.database,
            "schema": self.schema.to_dict(),
            "region_ids": self.region_ids,
            "engine": self.engine,
            "options": self.options,
            "partition_exprs": self.partition_exprs,
            "partition_columns": self.partition_columns,
        }

    @staticmethod
    def from_dict(d: dict) -> "TableInfo":
        return TableInfo(
            table_id=d["table_id"],
            name=d["name"],
            database=d["database"],
            schema=Schema.from_dict(d["schema"]),
            region_ids=d["region_ids"],
            engine=d.get("engine", "mito"),
            options=d.get("options", {}),
            partition_exprs=d.get("partition_exprs", []),
            partition_columns=d.get("partition_columns", []),
        )


class CatalogManager:
    def __init__(self, kv: KvBackend):
        self.kv = kv
        if self.kv.get(self._db_key(DEFAULT_DB)) is None:
            self.create_database(DEFAULT_DB, if_not_exists=True)

    # ---- keys ---------------------------------------------------------
    @staticmethod
    def _db_key(db: str) -> str:
        return f"__catalog/db/{db}"

    @staticmethod
    def _table_key(db: str, table: str) -> str:
        return f"__catalog/table/{db}/{table}"

    # ---- id allocation -------------------------------------------------
    def _next_id(self, kind: str) -> int:
        key = f"__meta/next_id/{kind}"
        while True:
            cur = self.kv.get(key)
            nxt = (int(cur) if cur else 1024) + 1
            if self.kv.compare_and_put(key, cur, str(nxt).encode()):
                return nxt

    # ---- databases -----------------------------------------------------
    def create_database(self, db: str, if_not_exists: bool = False) -> None:
        key = self._db_key(db)
        if self.kv.get(key) is not None:
            if if_not_exists:
                return
            raise GreptimeError(
                f"Database already exists: {db}",
                code=StatusCode.DATABASE_ALREADY_EXISTS,
            )
        self.kv.put_json(key, {"name": db})

    def drop_database(self, db: str, if_exists: bool = False) -> list[TableInfo]:
        if self.kv.get(self._db_key(db)) is None:
            if if_exists:
                return []
            raise DatabaseNotFound(db)
        tables = self.list_tables(db)
        for t in tables:
            self.kv.delete(self._table_key(db, t.name))
        self.kv.delete(self._db_key(db))
        return tables

    def list_databases(self) -> list[str]:
        return [
            json.loads(v)["name"] for _k, v in self.kv.range("__catalog/db/")
        ]

    def database_exists(self, db: str) -> bool:
        return self.kv.get(self._db_key(db)) is not None

    # ---- tables --------------------------------------------------------
    def create_table(
        self,
        db: str,
        name: str,
        schema: Schema,
        *,
        engine: str = "mito",
        options: dict | None = None,
        partition_exprs: list[str] | None = None,
        partition_columns: list[str] | None = None,
        num_regions: int = 1,
        if_not_exists: bool = False,
    ) -> TableInfo | None:
        if not self.database_exists(db):
            raise DatabaseNotFound(db)
        key = self._table_key(db, name)
        if self.kv.get(key) is not None:
            if if_not_exists:
                return None
            raise TableAlreadyExists(f"{db}.{name}")
        table_id = self._next_id("table")
        region_ids = [table_id * 1024 + i for i in range(num_regions)]
        info = TableInfo(
            table_id=table_id,
            name=name,
            database=db,
            schema=schema,
            region_ids=region_ids,
            engine=engine,
            options=options or {},
            partition_exprs=partition_exprs or [],
            partition_columns=partition_columns or [],
        )
        self.kv.put_json(key, info.to_dict())
        return info

    def get_engine(self, db: str, name: str) -> str | None:
        """Engine name only, without rebuilding the Schema — the per-query
        view check on the hot SELECT path."""
        raw = self.kv.get_json(self._table_key(db, name))
        return None if raw is None else raw.get("engine", "mito")

    def get_table(self, db: str, name: str) -> TableInfo:
        raw = self.kv.get_json(self._table_key(db, name))
        if raw is None:
            raise TableNotFound(f"{db}.{name}")
        return TableInfo.from_dict(raw)

    def table_exists(self, db: str, name: str) -> bool:
        return self.kv.get(self._table_key(db, name)) is not None

    def update_table(self, info: TableInfo) -> None:
        self.kv.put_json(self._table_key(info.database, info.name), info.to_dict())

    def restore_table(self, info: TableInfo) -> None:
        """Re-register a previously dropped table verbatim (undrop —
        reference src/common/meta/src/ddl/drop_table.rs recycle bin):
        table_id and region_ids are preserved so the on-disk region data
        lines up."""
        key = self._table_key(info.database, info.name)
        if self.kv.get(key) is not None:
            raise TableAlreadyExists(f"{info.database}.{info.name}")
        self.kv.put_json(key, info.to_dict())

    # ---- recycle bin (reference purge_dropped_table.rs) ----------------
    @staticmethod
    def _recycle_key(db: str, name: str, table_id: int,
                     dropped_at_ms: int) -> str:
        # table_id disambiguates same-name drops landing in one ms
        return f"__recycle__/{db}.{name}/{table_id}/{dropped_at_ms}"

    def recycle_put(self, info: TableInfo, dropped_at_ms: int) -> None:
        self.kv.put_json(
            self._recycle_key(info.database, info.name, info.table_id,
                              dropped_at_ms),
            {"info": info.to_dict(), "dropped_at_ms": dropped_at_ms},
        )

    def recycle_list(self, db: str | None = None) -> list[dict]:
        """Entries newest-first: [{info, dropped_at_ms, key}]."""
        import json as _json

        out = []
        for key, raw_bytes in self.kv.range("__recycle__/"):
            raw = _json.loads(raw_bytes)
            if db is not None and raw["info"].get("database") != db:
                continue
            raw["key"] = key
            out.append(raw)
        out.sort(key=lambda e: -e["dropped_at_ms"])
        return out

    def recycle_take(self, db: str, name: str) -> dict | None:
        """Pop the NEWEST recycle entry for db.name (undrop restores the
        most recent drop)."""
        matches = [e for e in self.recycle_list(db)
                   if e["info"].get("name") == name]
        if not matches:
            return None
        entry = matches[0]
        self.kv.delete(entry["key"])
        return entry

    def recycle_remove(self, key: str) -> None:
        self.kv.delete(key)

    def drop_table(self, db: str, name: str, if_exists: bool = False) -> TableInfo | None:
        key = self._table_key(db, name)
        raw = self.kv.get_json(key)
        if raw is None:
            if if_exists:
                return None
            raise TableNotFound(f"{db}.{name}")
        self.kv.delete(key)
        return TableInfo.from_dict(raw)

    def rename_table(self, db: str, name: str, new_name: str) -> None:
        info = self.get_table(db, name)
        if self.table_exists(db, new_name):
            raise TableAlreadyExists(f"{db}.{new_name}")
        self.kv.delete(self._table_key(db, name))
        info.name = new_name
        self.kv.put_json(self._table_key(db, new_name), info.to_dict())

    def list_tables(self, db: str) -> list[TableInfo]:
        if not self.database_exists(db):
            raise DatabaseNotFound(db)
        out = []
        for _k, v in self.kv.range(f"__catalog/table/{db}/"):
            out.append(TableInfo.from_dict(json.loads(v)))
        return sorted(out, key=lambda t: t.name)
