"""KvBackend: the metadata substrate (reference src/common/meta/src/kv_backend.rs:53).

Range scans over sorted keys, atomic compare-and-put for the txn uses the
reference makes (metadata transactions RFC), and four implementations:

- MemoryKv — tests / ephemeral standalone.
- FileKv — write-through JSON file (standalone embedded metadata; the
  reference embeds raft-engine kv the same way,
  src/standalone/src/metadata.rs).
- SqliteKv — SQL-database-backed, the analog of the reference's RDS
  backends (src/common/meta/src/kv_backend/rds/{mysql,postgres}.rs):
  one `kv(k PRIMARY KEY, v)` table, CAS as a single UPDATE..WHERE
  transaction, range scans as indexed BETWEEN queries.
- RemoteKv (rpc/kvservice.py) — network client for a shared KvServer,
  the etcd analog (src/common/meta/src/kv_backend/etcd.rs): multiple
  metasrv/frontend processes share one metadata key-space.
"""

from __future__ import annotations

import json
import os
import sqlite3
import threading


class KvBackend:
    def get(self, key: str) -> bytes | None:
        raise NotImplementedError

    def put(self, key: str, value: bytes) -> None:
        raise NotImplementedError

    def delete(self, key: str) -> bool:
        raise NotImplementedError

    def bulk_replace(self, entries: dict[str, bytes]) -> None:
        """Replace the ENTIRE key-space with ``entries`` (snapshot
        restore).  Default: delete-all + put-all; backends override with
        one-shot persistence."""
        for k, _v in list(self.range("")):
            self.delete(k)
        for k, v in entries.items():
            self.put(k, v)

    def range(self, prefix: str) -> list[tuple[str, bytes]]:
        raise NotImplementedError

    def compare_and_put(
        self, key: str, expect: bytes | None, value: bytes
    ) -> bool:
        raise NotImplementedError

    def compare_and_delete(self, key: str, expect: bytes) -> bool:
        raise NotImplementedError

    # convenience json codecs
    def get_json(self, key: str):
        raw = self.get(key)
        return None if raw is None else json.loads(raw)

    def put_json(self, key: str, value) -> None:
        self.put(key, json.dumps(value).encode())


class MemoryKv(KvBackend):
    def __init__(self):
        self._data: dict[str, bytes] = {}
        self._lock = threading.Lock()

    def get(self, key: str) -> bytes | None:
        return self._data.get(key)

    def put(self, key: str, value: bytes) -> None:
        with self._lock:
            self._data[key] = bytes(value)

    def delete(self, key: str) -> bool:
        with self._lock:
            return self._data.pop(key, None) is not None

    def range(self, prefix: str) -> list[tuple[str, bytes]]:
        return sorted(
            (k, v) for k, v in self._data.items() if k.startswith(prefix)
        )

    def compare_and_put(self, key: str, expect: bytes | None, value: bytes) -> bool:
        with self._lock:
            cur = self._data.get(key)
            if cur != expect:
                return False
            self._data[key] = bytes(value)
            return True

    def compare_and_delete(self, key: str, expect: bytes) -> bool:
        with self._lock:
            if self._data.get(key) != expect:
                return False
            del self._data[key]
            return True


class SqliteKv(KvBackend):
    """SQL-database metadata backend (reference RDS kv_backend,
    src/common/meta/src/kv_backend/rds/): every operation is one SQL
    transaction against a `kv` table, so atomicity comes from the
    database, not process-local locks — the shape that ports directly
    to MySQL/PostgreSQL."""

    def __init__(self, path: str):
        if path != ":memory:":
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._db = sqlite3.connect(path, check_same_thread=False,
                                   isolation_level=None)  # autocommit
        self._lock = threading.Lock()  # sqlite conns aren't thread-safe
        with self._lock:
            self._db.execute("PRAGMA journal_mode=WAL")
            self._db.execute("PRAGMA synchronous=NORMAL")
            self._db.execute(
                "CREATE TABLE IF NOT EXISTS kv (k TEXT PRIMARY KEY, v BLOB)")

    def close(self) -> None:
        with self._lock:
            self._db.close()

    def get(self, key: str) -> bytes | None:
        with self._lock:
            row = self._db.execute(
                "SELECT v FROM kv WHERE k = ?", (key,)).fetchone()
        return None if row is None else bytes(row[0])

    def put(self, key: str, value: bytes) -> None:
        with self._lock:
            self._db.execute(
                "INSERT INTO kv (k, v) VALUES (?, ?)"
                " ON CONFLICT(k) DO UPDATE SET v = excluded.v",
                (key, bytes(value)))

    def delete(self, key: str) -> bool:
        with self._lock:
            cur = self._db.execute("DELETE FROM kv WHERE k = ?", (key,))
        return cur.rowcount > 0

    @staticmethod
    def _prefix_end(prefix: str) -> str | None:
        """Smallest string greater than every string with ``prefix``:
        increment the last non-maximal char, dropping trailing U+10FFFF
        (etcd's get_prefix_range_end, in unicode code points)."""
        for i in range(len(prefix) - 1, -1, -1):
            if ord(prefix[i]) < 0x10FFFF:
                nxt = ord(prefix[i]) + 1
                if 0xD800 <= nxt <= 0xDFFF:  # unencodable surrogates
                    nxt = 0xE000
                return prefix[:i] + chr(nxt)
        return None  # all-maximal prefix: no upper bound

    def range(self, prefix: str) -> list[tuple[str, bytes]]:
        end = self._prefix_end(prefix) if prefix else None
        with self._lock:
            if prefix and end is not None:
                # indexed [prefix, end) range: no LIKE escape pitfalls
                # with % / _ in keys
                rows = self._db.execute(
                    "SELECT k, v FROM kv WHERE k >= ? AND k < ? ORDER BY k",
                    (prefix, end)).fetchall()
            elif prefix:
                rows = self._db.execute(
                    "SELECT k, v FROM kv WHERE k >= ? ORDER BY k",
                    (prefix,)).fetchall()
            else:
                rows = self._db.execute(
                    "SELECT k, v FROM kv ORDER BY k").fetchall()
        return [(k, bytes(v)) for k, v in rows
                if k.startswith(prefix)]

    def bulk_replace(self, entries: dict[str, bytes]) -> None:
        with self._lock:
            self._db.execute("BEGIN IMMEDIATE")
            try:
                self._db.execute("DELETE FROM kv")
                self._db.executemany(
                    "INSERT INTO kv (k, v) VALUES (?, ?)",
                    [(k, bytes(v)) for k, v in entries.items()])
                self._db.execute("COMMIT")
            except BaseException:
                self._db.execute("ROLLBACK")
                raise

    def compare_and_put(
        self, key: str, expect: bytes | None, value: bytes
    ) -> bool:
        with self._lock:
            self._db.execute("BEGIN IMMEDIATE")
            try:
                row = self._db.execute(
                    "SELECT v FROM kv WHERE k = ?", (key,)).fetchone()
                cur = None if row is None else bytes(row[0])
                if cur != expect:
                    self._db.execute("ROLLBACK")
                    return False
                self._db.execute(
                    "INSERT INTO kv (k, v) VALUES (?, ?)"
                    " ON CONFLICT(k) DO UPDATE SET v = excluded.v",
                    (key, bytes(value)))
                self._db.execute("COMMIT")
                return True
            except BaseException:
                self._db.execute("ROLLBACK")
                raise

    def compare_and_delete(self, key: str, expect: bytes) -> bool:
        with self._lock:
            self._db.execute("BEGIN IMMEDIATE")
            try:
                row = self._db.execute(
                    "SELECT v FROM kv WHERE k = ?", (key,)).fetchone()
                if row is None or bytes(row[0]) != expect:
                    self._db.execute("ROLLBACK")
                    return False
                self._db.execute("DELETE FROM kv WHERE k = ?", (key,))
                self._db.execute("COMMIT")
                return True
            except BaseException:
                self._db.execute("ROLLBACK")
                raise


class FileKv(MemoryKv):
    """Write-through JSON file persistence (standalone embedded metadata).

    Values round-trip as UTF-8 with surrogateescape, so arbitrary bytes
    survive persistence (and files written by older versions still load).
    """

    def __init__(self, path: str):
        super().__init__()
        self.path = path
        self._plock = threading.Lock()  # serializes tmp-file writes
        if os.path.exists(path):
            with open(path) as f:
                raw = json.load(f)
            self._data = {
                k: v.encode("utf-8", "surrogateescape")
                for k, v in raw.items()
            }

    def _persist(self) -> None:
        # snapshot INSIDE the persist lock so a later writer can't be
        # overwritten by an earlier writer holding a stale snapshot;
        # the data lock guards against mutation during serialization
        with self._plock:
            with self._lock:
                snap = dict(self._data)
            tmp = self.path + ".tmp"
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            with open(tmp, "w") as f:
                json.dump(
                    {k: v.decode("utf-8", "surrogateescape")
                     for k, v in snap.items()}, f)
            os.replace(tmp, self.path)

    def put(self, key: str, value: bytes) -> None:
        super().put(key, value)
        self._persist()

    def bulk_replace(self, entries: dict[str, bytes]) -> None:
        self._data = dict(entries)
        self._persist()

    def delete(self, key: str) -> bool:
        ok = super().delete(key)
        if ok:
            self._persist()
        return ok

    def compare_and_put(self, key: str, expect: bytes | None, value: bytes) -> bool:
        ok = super().compare_and_put(key, expect, value)
        if ok:
            self._persist()
        return ok

    def compare_and_delete(self, key: str, expect: bytes) -> bool:
        ok = super().compare_and_delete(key, expect)
        if ok:
            self._persist()
        return ok
