"""KvBackend: the metadata substrate (reference src/common/meta/src/kv_backend.rs:53).

Range scans over sorted keys, atomic compare-and-put for the txn uses the
reference makes (metadata transactions RFC), and a file-backed
implementation standing in for etcd in standalone mode (the reference
embeds raft-engine kv the same way, src/standalone/src/metadata.rs).
"""

from __future__ import annotations

import json
import os
import threading


class KvBackend:
    def get(self, key: str) -> bytes | None:
        raise NotImplementedError

    def put(self, key: str, value: bytes) -> None:
        raise NotImplementedError

    def delete(self, key: str) -> bool:
        raise NotImplementedError

    def bulk_replace(self, entries: dict[str, bytes]) -> None:
        """Replace the ENTIRE key-space with ``entries`` (snapshot
        restore).  Default: delete-all + put-all; backends override with
        one-shot persistence."""
        for k, _v in list(self.range("")):
            self.delete(k)
        for k, v in entries.items():
            self.put(k, v)

    def range(self, prefix: str) -> list[tuple[str, bytes]]:
        raise NotImplementedError

    def compare_and_put(
        self, key: str, expect: bytes | None, value: bytes
    ) -> bool:
        raise NotImplementedError

    def compare_and_delete(self, key: str, expect: bytes) -> bool:
        raise NotImplementedError

    # convenience json codecs
    def get_json(self, key: str):
        raw = self.get(key)
        return None if raw is None else json.loads(raw)

    def put_json(self, key: str, value) -> None:
        self.put(key, json.dumps(value).encode())


class MemoryKv(KvBackend):
    def __init__(self):
        self._data: dict[str, bytes] = {}
        self._lock = threading.Lock()

    def get(self, key: str) -> bytes | None:
        return self._data.get(key)

    def put(self, key: str, value: bytes) -> None:
        with self._lock:
            self._data[key] = bytes(value)

    def delete(self, key: str) -> bool:
        with self._lock:
            return self._data.pop(key, None) is not None

    def range(self, prefix: str) -> list[tuple[str, bytes]]:
        return sorted(
            (k, v) for k, v in self._data.items() if k.startswith(prefix)
        )

    def compare_and_put(self, key: str, expect: bytes | None, value: bytes) -> bool:
        with self._lock:
            cur = self._data.get(key)
            if cur != expect:
                return False
            self._data[key] = bytes(value)
            return True

    def compare_and_delete(self, key: str, expect: bytes) -> bool:
        with self._lock:
            if self._data.get(key) != expect:
                return False
            del self._data[key]
            return True


class FileKv(MemoryKv):
    """Write-through JSON file persistence (standalone embedded metadata)."""

    def __init__(self, path: str):
        super().__init__()
        self.path = path
        if os.path.exists(path):
            with open(path) as f:
                raw = json.load(f)
            self._data = {k: v.encode("utf-8") for k, v in raw.items()}

    def _persist(self) -> None:
        tmp = self.path + ".tmp"
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        with open(tmp, "w") as f:
            json.dump({k: v.decode("utf-8") for k, v in self._data.items()}, f)
        os.replace(tmp, self.path)

    def put(self, key: str, value: bytes) -> None:
        super().put(key, value)
        self._persist()

    def bulk_replace(self, entries: dict[str, bytes]) -> None:
        self._data = dict(entries)
        self._persist()

    def delete(self, key: str) -> bool:
        ok = super().delete(key)
        if ok:
            self._persist()
        return ok

    def compare_and_put(self, key: str, expect: bytes | None, value: bytes) -> bool:
        ok = super().compare_and_put(key, expect, value)
        if ok:
            self._persist()
        return ok

    def compare_and_delete(self, key: str, expect: bytes) -> bool:
        ok = super().compare_and_delete(key, expect)
        if ok:
            self._persist()
        return ok
