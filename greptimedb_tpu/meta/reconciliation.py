"""Reconciliation: repair drift between metadata and region reality.

Reference: src/common/meta/src/reconciliation/{manager,reconcile_catalog,
reconcile_database,reconcile_table}.rs + the admin functions
src/common/function/src/admin/reconcile_*.rs.  Metadata can drift from
what datanodes actually host (crashed DDL, lost routes, online schema
growth in the metric engine, manual data moves); reconciliation walks
catalog → database → table, makes region reality match the routes, and
resolves schema disagreements by strategy:

- ``use_latest`` (default): the schema with the most columns wins when
  the candidates form a subset chain (online growth only ever adds
  columns); incomparable schemas are reported, never guessed.
- ``use_metasrv``: the catalog's schema is kept; drift is reported.
- ``use_datanode``: the hosting region's schema wins.

Cluster mode runs as journaled procedures (resumable, locked per
table); standalone mode reconciles the embedded catalog against the
local RegionEngine (``ADMIN reconcile_table(...)`` & friends).
"""

from __future__ import annotations

import time

from greptimedb_tpu.errors import GreptimeError, InvalidArguments
from greptimedb_tpu.meta.catalog import CatalogManager
from greptimedb_tpu.meta.procedure import Procedure, Status

STRATEGIES = ("use_latest", "use_metasrv", "use_datanode")


def _colnames(schema) -> set[str]:
    return {c.name for c in schema}


def resolve_schema(catalog_schema, region_schemas: list, strategy: str):
    """→ (resolved_schema | None, conflict: bool).  None = keep catalog."""
    if strategy not in STRATEGIES:
        raise InvalidArguments(f"unknown resolve strategy {strategy!r}")
    if strategy == "use_metasrv" or not region_schemas:
        return None, False
    candidates = [catalog_schema] + list(region_schemas)
    if strategy == "use_datanode":
        candidates = list(region_schemas)
    best = max(candidates, key=lambda s: len(list(s)))
    best_cols = _colnames(best)
    for s in candidates:
        if not _colnames(s) <= best_cols:
            return None, True  # incomparable: report, don't guess
    if _colnames(best) == _colnames(catalog_schema) and strategy != "use_datanode":
        return None, False
    if strategy == "use_datanode" and best.to_dict() == catalog_schema.to_dict():
        return None, False
    return best, False


def _reconcile_region(ms, rid: int, schema, now_ms: float) -> list[str]:
    """Make one region's reality match its route; returns fix labels."""
    fixes: list[str] = []
    routed = ms.region_route(rid)
    hosts = {
        nid: dn.roles.get(rid, "follower")
        for nid, dn in ms.datanodes.items()
        if dn.alive and rid in dn.engine.regions
    }
    leaders = [n for n, r in hosts.items() if r == "leader"]

    if routed is None or routed not in ms.datanodes or not ms.datanodes[routed].alive:
        new = (leaders[0] if leaders
               else next(iter(sorted(hosts)), None))
        if new is None:
            new = ms.select_target(exclude=set())
        if new is None:
            fixes.append(f"region {rid}: unplaceable (no alive node)")
            return fixes
        ms.set_region_route(rid, new)
        fixes.append(f"region {rid}: routed to node {new}")
        routed = new

    if routed not in hosts:
        instr = {"kind": "open_region", "region_id": rid, "role": "leader",
                 "epoch": ms.mint_epoch(rid)}
        if schema is not None:
            instr["schema"] = schema.to_dict()
        ms.datanodes[routed].handle_instruction(instr, now_ms)
        fixes.append(f"region {rid}: opened as leader on node {routed}")
    elif hosts[routed] != "leader":
        # promotion is a leadership grant: mint, so the demoted stray
        # leaders below are storage-fenced, not just role-flipped
        ms.datanodes[routed].handle_instruction(
            {"kind": "upgrade_region", "region_id": rid,
             "epoch": ms.mint_epoch(rid)}, now_ms)
        fixes.append(f"region {rid}: promoted on node {routed}")

    for nid in leaders:
        if nid != routed:
            # stray leader (split brain after bad failover): downgrade
            # (flushes its buffered writes durably) then re-open as a
            # read replica; the route is the source of truth
            dn = ms.datanodes[nid]
            dn.handle_instruction(
                {"kind": "downgrade_region", "region_id": rid}, now_ms)
            instr = {"kind": "open_region", "region_id": rid,
                     "role": "follower"}
            if schema is not None:
                instr["schema"] = schema.to_dict()
            dn.handle_instruction(instr, now_ms)
            fixes.append(f"region {rid}: demoted stray leader on node {nid}")
    return fixes


def reconcile_table_inline(ms, kv, db: str, table: str,
                           strategy: str = "use_latest") -> dict:
    """One full table reconciliation pass against a Metasrv."""
    if strategy not in STRATEGIES:
        raise InvalidArguments(f"unknown resolve strategy {strategy!r}")
    cat = CatalogManager(kv)
    info = cat.get_table(db, table)
    now_ms = time.time() * 1000.0
    fixes: list[str] = []
    for rid in info.region_ids:
        fixes.extend(_reconcile_region(ms, rid, info.schema, now_ms))

    region_schemas = []
    for rid in info.region_ids:
        routed = ms.region_route(rid)
        dn = ms.datanodes.get(routed)
        if dn is not None and rid in dn.engine.regions:
            region_schemas.append(dn.engine.regions[rid].schema)
    resolved, conflict = resolve_schema(info.schema, region_schemas, strategy)
    if conflict:
        fixes.append("schema conflict: candidates are not a subset chain"
                     " (left unresolved)")
    elif resolved is not None:
        info.schema = resolved
        cat.update_table(info)
        fixes.append("catalog schema updated from region reality")
    return {"table": f"{db}.{table}", "strategy": strategy, "fixes": fixes}


class ReconcileTableProcedure(Procedure):
    """Journaled per-table reconciliation (reference reconcile_table.rs):
    region steps persist progress so a crashed coordinator resumes."""

    type_name = "reconcile_table"

    def execute(self, ctx) -> Status:
        st = self.state
        ms = ctx.services["metasrv"]
        phase = st.get("phase", "start")
        if phase == "start":
            if st.get("strategy", "use_latest") not in STRATEGIES:
                raise InvalidArguments(
                    f"unknown resolve strategy {st['strategy']!r}")
            cat = CatalogManager(ctx.kv)
            info = cat.get_table(st["db"], st["table"])
            st["region_ids"] = list(info.region_ids)
            st["i"] = 0
            st["fixes"] = []
            st["phase"] = "regions"
            return Status.executing()
        if phase == "regions":
            cat = CatalogManager(ctx.kv)
            info = cat.get_table(st["db"], st["table"])
            if st["i"] < len(st["region_ids"]):
                rid = st["region_ids"][st["i"]]
                st["fixes"].extend(_reconcile_region(
                    ms, rid, info.schema, time.time() * 1000.0))
                st["i"] += 1
                return Status.executing()
            st["phase"] = "schema"
            return Status.executing()
        if phase == "schema":
            cat = CatalogManager(ctx.kv)
            info = cat.get_table(st["db"], st["table"])
            region_schemas = []
            for rid in st["region_ids"]:
                routed = ms.region_route(rid)
                dn = ms.datanodes.get(routed)
                if dn is not None and rid in dn.engine.regions:
                    region_schemas.append(dn.engine.regions[rid].schema)
            resolved, conflict = resolve_schema(
                info.schema, region_schemas, st.get("strategy", "use_latest"))
            if conflict:
                st["fixes"].append("schema conflict: candidates are not a"
                                   " subset chain (left unresolved)")
            elif resolved is not None:
                info.schema = resolved
                cat.update_table(info)
                st["fixes"].append("catalog schema updated from region"
                                   " reality")
            return Status.done({
                "table": f"{st['db']}.{st['table']}",
                "strategy": st.get("strategy", "use_latest"),
                "fixes": st["fixes"],
            })
        raise GreptimeError(f"unknown reconcile phase {phase}")

    def lock_keys(self) -> list[str]:
        return [f"table/{self.state['db']}/{self.state['table']}"]


class ReconcileDatabaseProcedure(Procedure):
    """All tables in one database, one table per journaled step."""

    type_name = "reconcile_database"

    def execute(self, ctx) -> Status:
        st = self.state
        ms = ctx.services["metasrv"]
        if "tables" not in st:
            cat = CatalogManager(ctx.kv)
            st["tables"] = [t.name for t in cat.list_tables(st["db"])]
            st["i"] = 0
            st["reports"] = []
            return Status.executing()
        if st["i"] < len(st["tables"]):
            st["reports"].append(reconcile_table_inline(
                ms, ctx.kv, st["db"], st["tables"][st["i"]],
                st.get("strategy", "use_latest")))
            st["i"] += 1
            return Status.executing()
        return Status.done({"database": st["db"], "reports": st["reports"]})

    def lock_keys(self) -> list[str]:
        return [f"database/{self.state['db']}"]


class ReconcileCatalogProcedure(Procedure):
    """Every database (reference reconcile_catalog.rs)."""

    type_name = "reconcile_catalog"

    def execute(self, ctx) -> Status:
        st = self.state
        ms = ctx.services["metasrv"]
        if "dbs" not in st:
            cat = CatalogManager(ctx.kv)
            st["dbs"] = cat.list_databases()
            st["i"] = 0
            st["reports"] = []
            return Status.executing()
        if st["i"] < len(st["dbs"]):
            cat = CatalogManager(ctx.kv)
            db = st["dbs"][st["i"]]
            for t in cat.list_tables(db):
                st["reports"].append(reconcile_table_inline(
                    ms, ctx.kv, db, t.name, st.get("strategy", "use_latest")))
            st["i"] += 1
            return Status.executing()
        return Status.done({"reports": st["reports"]})


# ---- standalone mode ----------------------------------------------------

def reconcile_standalone(db, database: str | None = None,
                         table: str | None = None,
                         strategy: str = "use_latest") -> dict:
    """Reconcile the embedded catalog against the local RegionEngine
    (standalone's analog of the cluster procedures): reopen referenced
    regions that exist on storage but aren't open, adopt region schema
    growth into the catalog, and report orphan region directories."""
    if strategy not in STRATEGIES:
        raise InvalidArguments(f"unknown resolve strategy {strategy!r}")
    from greptimedb_tpu.errors import RegionNotFound

    reports = []
    dbs = [database] if database else db.catalog.list_databases()
    referenced: set[int] = set()
    for dbname in dbs:
        tables = ([db.catalog.get_table(dbname, table)] if table
                  else db.catalog.list_tables(dbname))
        for info in tables:
            if info.engine == "file":
                continue  # external tables have no regions
            fixes: list[str] = []
            region_schemas = []
            for rid in info.region_ids:
                referenced.add(rid)
                region = db.regions.regions.get(rid)
                if region is None:
                    try:
                        region = db.regions.open_region(rid)
                        fixes.append(f"region {rid}: reopened from storage")
                    except RegionNotFound:
                        fixes.append(f"region {rid}: MISSING on storage")
                        continue
                region_schemas.append(region.schema)
            resolved, conflict = resolve_schema(
                info.schema, region_schemas, strategy)
            if conflict:
                fixes.append("schema conflict: candidates are not a subset"
                             " chain (left unresolved)")
            elif resolved is not None:
                info.schema = resolved
                db.catalog.update_table(info)
                fixes.append("catalog schema updated from region reality")
            reports.append({
                "table": f"{dbname}.{info.name}",
                "fixes": fixes,
            })
    report = {"strategy": strategy, "reports": reports}
    if table is None and database is None:
        # orphan sweep is only sound at full-catalog scope: a narrower
        # run's `referenced` set would flag other databases' live
        # regions as orphans
        orphans: set[int] = set(
            rid for rid in db.regions.regions
            if rid not in referenced and rid > 0)
        for path in db.regions.store.list(""):
            head = path.split("/", 1)[0]
            if head.startswith("region_"):
                try:
                    rid = int(head[len("region_"):])
                except ValueError:
                    continue
                if rid not in referenced and rid > 0:
                    orphans.add(rid)
        report["orphan_regions"] = sorted(orphans)
    return report
