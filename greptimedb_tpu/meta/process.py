"""Process manager: live query tracking + cooperative KILL.

Equivalent of the reference's ProcessManager
(src/catalog/src/process_manager.rs): every statement entering the
frontend registers a ticket (id, catalog, query, client, start time);
``information_schema.process_list`` / ``SHOW PROCESSLIST`` read the live
registry, and ``KILL <id>`` flips the ticket's cancellation flag, which
the engine checks at stage boundaries (statement starts, region scans).
Cancellation is cooperative — a query inside one fused XLA dispatch
finishes that dispatch first, exactly like one DataFusion operator batch.
"""

from __future__ import annotations

import itertools
import threading
import time
from dataclasses import dataclass, field

from greptimedb_tpu.errors import Cancelled


@dataclass
class ProcessTicket:
    id: int
    query: str
    database: str
    client: str
    start_ts: float = field(default_factory=time.time)
    cancelled: threading.Event = field(default_factory=threading.Event)

    def check(self) -> None:
        """Raise if this process was killed (called at stage boundaries)."""
        if self.cancelled.is_set():
            raise Cancelled(f"query {self.id} was killed")

    @property
    def elapsed_ms(self) -> float:
        return (time.time() - self.start_ts) * 1000


class ProcessManager:
    """Thread-safe registry of in-flight statements.

    Registration happens BEFORE the executor's serialization lock is
    taken, so queued statements are visible to (and killable from) other
    connections while they wait.
    """

    def __init__(self, server_addr: str = "standalone"):
        self.server_addr = server_addr
        self._lock = threading.Lock()
        self._ids = itertools.count(1)
        self._procs: dict[int, ProcessTicket] = {}

    def register(self, query: str, database: str, client: str = "") -> ProcessTicket:
        t = ProcessTicket(next(self._ids), query[:4096], database, client)
        with self._lock:
            self._procs[t.id] = t
        return t

    def deregister(self, ticket: ProcessTicket) -> None:
        with self._lock:
            self._procs.pop(ticket.id, None)

    def kill(self, process_id: int) -> bool:
        """Flip the cancel flag; returns False for unknown/finished ids."""
        with self._lock:
            t = self._procs.get(process_id)
        if t is None:
            return False
        t.cancelled.set()
        return True

    def list(self) -> list[ProcessTicket]:
        with self._lock:
            return sorted(self._procs.values(), key=lambda t: t.id)

    @staticmethod
    def parse_id(raw) -> int:
        """Accept 7, '7', and the reference's 'addr/7' display form."""
        s = str(raw)
        if "/" in s:
            s = s.rsplit("/", 1)[1]
        return int(s)
