"""information_schema: live system introspection tables.

Reference: src/catalog/src/system_schema/information_schema/ exposes 20+
virtual tables (SURVEY.md §2.3/§5.5). Round-1 set: schemata, tables,
columns, partitions, region_statistics, flows, build_info, cluster_info,
engines, key_column_usage.
"""

from __future__ import annotations

import time

from greptimedb_tpu.errors import TableNotFound, Unsupported
from greptimedb_tpu.query.ast import Select
from greptimedb_tpu.query.engine import QueryResult
from greptimedb_tpu.query.virtual import execute_virtual_select

INFORMATION_SCHEMA = "information_schema"


def is_information_schema(table: str | None) -> bool:
    return bool(table) and table.lower().startswith(INFORMATION_SCHEMA + ".")


def execute(db, sel: Select) -> QueryResult:
    name = sel.table.split(".", 1)[1].lower()
    builder = _TABLES.get(name)
    if builder is None:
        raise TableNotFound(f"information_schema.{name}")
    if sel.joins:
        # neither the host mini-engine nor the staging fallback can join
        # (the staged provider maps every name to one region) — loud
        raise Unsupported("JOIN over system tables")
    columns, types = builder(db)
    try:
        return execute_virtual_select(sel, columns, types)
    except Unsupported:
        # beyond the host mini-engine (GROUP BY, non-count aggregates,
        # expressions of aggregates): stage the virtual table as rows
        # and run through the REAL engine — system tables get the full
        # SQL surface at staging cost (they are tiny enumerations)
        stage = getattr(db, "_select_over_staged", None)
        if stage is None:
            raise
        import dataclasses

        names = list(columns.keys())
        rows = ([list(r) for r in zip(*(columns[n] for n in names))]
                if columns and names else [])
        base = QueryResult(
            names, rows,
            column_types=[types.get(n, "String") for n in names]
            if types else None)
        return stage(
            dataclasses.replace(sel, table="__virtual__"), base)


def _columns_of(rows: list[dict], names: list[str]) -> dict[str, list]:
    return {n: [r.get(n) for r in rows] for n in names}


def _schemata(db):
    rows = [
        {"catalog_name": "greptime", "schema_name": d, "default_character_set_name": "utf8",
         "default_collation_name": "utf8_bin"}
        for d in db.catalog.list_databases()
    ] + [{"catalog_name": "greptime", "schema_name": INFORMATION_SCHEMA,
          "default_character_set_name": "utf8", "default_collation_name": "utf8_bin"}]
    names = ["catalog_name", "schema_name", "default_character_set_name",
             "default_collation_name"]
    return _columns_of(rows, names), {n: "String" for n in names}


def _tables(db):
    rows = []
    for d in db.catalog.list_databases():
        for t in db.catalog.list_tables(d):
            rows.append({
                "table_catalog": "greptime", "table_schema": d,
                "table_name": t.name, "table_type": "BASE TABLE",
                "table_id": t.table_id, "engine": t.engine,
                "region_count": len(t.region_ids),
            })
    for vt in sorted(_TABLES):
        rows.append({
            "table_catalog": "greptime", "table_schema": INFORMATION_SCHEMA,
            "table_name": vt, "table_type": "LOCAL TEMPORARY",
            "table_id": None, "engine": None, "region_count": 0,
        })
    names = ["table_catalog", "table_schema", "table_name", "table_type",
             "table_id", "engine", "region_count"]
    types = {n: "String" for n in names}
    types.update({"table_id": "UInt32", "region_count": "Int64"})
    return _columns_of(rows, names), types


def _columns(db):
    rows = []
    for d in db.catalog.list_databases():
        for t in db.catalog.list_tables(d):
            for i, c in enumerate(t.schema):
                rows.append({
                    "table_catalog": "greptime", "table_schema": d,
                    "table_name": t.name, "column_name": c.name,
                    "ordinal_position": i + 1,
                    "data_type": c.dtype.value.lower(),
                    "semantic_type": c.semantic.value,
                    "is_nullable": "Yes" if c.nullable else "No",
                    "column_default": c.default,
                })
    names = ["table_catalog", "table_schema", "table_name", "column_name",
             "ordinal_position", "data_type", "semantic_type", "is_nullable",
             "column_default"]
    types = {n: "String" for n in names}
    types["ordinal_position"] = "Int64"
    return _columns_of(rows, names), types


def _region_statistics(db):
    rows = []
    for d in db.catalog.list_databases():
        for t in db.catalog.list_tables(d):
            for rid in t.region_ids:
                region = db.regions.regions.get(rid)
                if region is None:
                    try:
                        region = db.regions.open_region(rid)
                    except Exception:  # noqa: BLE001
                        continue
                sst_rows = sum(m.num_rows for m in region.sst_files)
                sst_size = sum(m.size_bytes for m in region.sst_files)
                rows.append({
                    "region_id": rid, "table_id": t.table_id,
                    "region_number": rid % 1024, "region_rows":
                        sst_rows + region.memtable.num_rows,
                    "disk_size": sst_size, "memtable_size": region.memtable.bytes,
                    "sst_size": sst_size, "sst_num": len(region.sst_files),
                    "index_size": 0, "manifest_size": 0, "engine": t.engine,
                    "region_role": "Leader",
                })
    names = ["region_id", "table_id", "region_number", "region_rows",
             "disk_size", "memtable_size", "sst_size", "sst_num", "index_size",
             "manifest_size", "engine", "region_role"]
    types = {n: "UInt64" for n in names}
    types.update({"engine": "String", "region_role": "String"})
    return _columns_of(rows, names), types


def _partitions(db):
    rows = []
    for d in db.catalog.list_databases():
        for t in db.catalog.list_tables(d):
            for i, rid in enumerate(t.region_ids):
                expr = (
                    t.partition_exprs[i]
                    if i < len(t.partition_exprs) else None
                )
                rows.append({
                    "table_catalog": "greptime", "table_schema": d,
                    "table_name": t.name, "partition_name": f"p{i}",
                    "partition_expression": expr, "greptime_partition_id": rid,
                })
    names = ["table_catalog", "table_schema", "table_name", "partition_name",
             "partition_expression", "greptime_partition_id"]
    types = {n: "String" for n in names}
    types["greptime_partition_id"] = "UInt64"
    return _columns_of(rows, names), types


def _flows(db):
    from greptimedb_tpu.flow.engine import flow_mode, select_to_sql

    eng = db.flow_engine
    rows = []
    for t in eng.list_flows():
        rows.append({
            "flow_name": t.name, "flow_id": None,
            "state_size": eng.state_bytes(t),
            "table_catalog": "greptime",
            "flow_definition": select_to_sql(t.query),
            "comment": t.comment, "expire_after":
                t.expire_after_ms // 1000 if t.expire_after_ms else None,
            "source_table_names": t.source_table, "sink_table_name": t.sink_table,
            "last_execution_time": t.last_run_ms or None,
            # device flow runtime columns (flow/device.py): which engine
            # folds this flow, where it lives, and how far its durable
            # checkpoint watermark has advanced
            "mode": flow_mode(t), "flownode_id": t.flownode_id,
            "checkpoint_watermark": eng.watermark_repr(t),
            "last_tick": t.last_tick_ms or None,
        })
    names = ["flow_name", "flow_id", "state_size", "table_catalog",
             "flow_definition", "comment", "expire_after",
             "source_table_names", "sink_table_name", "last_execution_time",
             "mode", "flownode_id", "checkpoint_watermark", "last_tick"]
    types = {n: "String" for n in names}
    types.update({"state_size": "UInt64", "flownode_id": "UInt32",
                  "last_tick": "UInt64", "last_execution_time": "UInt64"})
    return _columns_of(rows, names), types


def _build_info(db):
    rows = [{
        "git_branch": "main", "git_commit": "tpu-native", "git_commit_short":
            "tpu", "git_clean": "true", "pkg_version": "0.1.0",
    }]
    names = ["git_branch", "git_commit", "git_commit_short", "git_clean",
             "pkg_version"]
    return _columns_of(rows, names), {n: "String" for n in names}


def _cluster_info(db):
    import jax

    rows = [{
        "peer_id": 0, "peer_type": "STANDALONE", "peer_addr": "",
        "version": "0.1.0", "git_commit": "tpu-native",
        "start_time": None, "uptime": None, "active_time": None,
        "node_status": f"devices={len(jax.devices())}",
    }]
    names = ["peer_id", "peer_type", "peer_addr", "version", "git_commit",
             "start_time", "uptime", "active_time", "node_status"]
    types = {n: "String" for n in names}
    types["peer_id"] = "Int64"
    return _columns_of(rows, names), types


def _engines(db):
    rows = [
        {"engine": "mito", "support": "DEFAULT",
         "comment": "TPU-native LSM storage engine", "transactions": "NO",
         "xa": "NO", "savepoints": "NO"},
        {"engine": "metric", "support": "YES",
         "comment": "Metric multiplexing engine (planned)", "transactions": "NO",
         "xa": "NO", "savepoints": "NO"},
    ]
    names = ["engine", "support", "comment", "transactions", "xa", "savepoints"]
    return _columns_of(rows, names), {n: "String" for n in names}


def _key_column_usage(db):
    rows = []
    for d in db.catalog.list_databases():
        for t in db.catalog.list_tables(d):
            pos = 1
            for c in t.schema:
                if c.is_tag or c.is_time_index:
                    rows.append({
                        "constraint_catalog": "def", "constraint_schema": d,
                        "constraint_name": (
                            "TIME INDEX" if c.is_time_index else "PRIMARY"
                        ),
                        "table_catalog": "greptime", "table_schema": d,
                        "table_name": t.name, "column_name": c.name,
                        "ordinal_position": pos,
                    })
                    pos += 1
    names = ["constraint_catalog", "constraint_schema", "constraint_name",
             "table_catalog", "table_schema", "table_name", "column_name",
             "ordinal_position"]
    types = {n: "String" for n in names}
    types["ordinal_position"] = "UInt32"
    return _columns_of(rows, names), types


def _process_list(db):
    """Live statements (reference information_schema/process_list.rs)."""
    rows = []
    for t in db.processes.list():
        rows.append({
            "id": f"{db.processes.server_addr}/{t.id}",
            "catalog": "greptime", "schemas": t.database,
            "query": t.query, "client": t.client,
            "frontend": db.processes.server_addr,
            "start_timestamp": int(t.start_ts * 1000),
            "elapsed_time": int(t.elapsed_ms),
        })
    names = ["id", "catalog", "schemas", "query", "client", "frontend",
             "start_timestamp", "elapsed_time"]
    types = {n: "String" for n in names}
    types.update({"start_timestamp": "TimestampMillisecond",
                  "elapsed_time": "Int64"})
    return _columns_of(rows, names), types


def _region_peers(db):
    """Region placement (reference information_schema/region_peers.rs).
    Standalone hosts every region as local leader (peer 0); the cluster
    route table lives in the metasrv, not here."""
    rows = []
    for d in db.catalog.list_databases():
        for t in db.catalog.list_tables(d):
            for rid in t.region_ids:
                peer = 0
                rows.append({
                    "table_catalog": "greptime", "table_schema": d,
                    "table_name": t.name, "region_id": rid,
                    "peer_id": peer, "peer_addr": "",
                    "is_leader": "Yes", "status": "ALIVE",
                    "down_seconds": None,
                })
    names = ["table_catalog", "table_schema", "table_name", "region_id",
             "peer_id", "peer_addr", "is_leader", "status", "down_seconds"]
    types = {n: "String" for n in names}
    types.update({"region_id": "UInt64", "peer_id": "UInt64",
                  "down_seconds": "Int64"})
    return _columns_of(rows, names), types


def _ssts(db):
    """Per-region SST file inventory (reference information_schema/ssts).
    SstMeta stores raw ts in the table's native precision; normalize to
    milliseconds so one column type fits every table."""
    rows = []
    for d in db.catalog.list_databases():
        for t in db.catalog.list_tables(d):
            ts_col = next((c for c in t.schema if c.is_time_index), None)
            # per-unit factor raw→ms (ns: /1e6, us: /1e3, ms: 1, s: ×1e3)
            to_ms = 1
            if ts_col is not None:
                name = ts_col.dtype.value.lower()
                to_ms = {"timestampnanosecond": 1 / 1_000_000,
                         "timestampmicrosecond": 1 / 1_000,
                         "timestampsecond": 1_000}.get(name, 1)
            for rid in t.region_ids:
                region = db.regions.regions.get(rid)
                if region is None:
                    continue
                for m in region.sst_files:
                    rows.append({
                        "table_schema": d, "table_name": t.name,
                        "region_id": rid, "file_id": m.file_id,
                        "file_path": m.path, "level": m.level,
                        "file_size": m.size_bytes, "num_rows": m.num_rows,
                        "min_ts": int(m.ts_min * to_ms) if to_ms != 1
                        else m.ts_min,
                        "max_ts": int(m.ts_max * to_ms) if to_ms != 1
                        else m.ts_max,
                    })
    names = ["table_schema", "table_name", "region_id", "file_id",
             "file_path", "level", "file_size", "num_rows", "min_ts",
             "max_ts"]
    types = {n: "UInt64" for n in names}
    types.update({"table_schema": "String", "table_name": "String",
                  "file_id": "String", "file_path": "String",
                  "min_ts": "TimestampMillisecond",
                  "max_ts": "TimestampMillisecond"})
    return _columns_of(rows, names), types


def _procedure_info(db):
    """Journaled procedures (reference information_schema/procedure_info)."""
    import json as _json

    mgr = db.procedures
    rows = []
    for k, raw in mgr.kv.range(mgr._PREFIX):
        rec = _json.loads(raw)
        rows.append({
            "procedure_id": k[len(mgr._PREFIX):],
            "procedure_type": rec.get("type"),
            "start_time": None,
            "end_time": int(rec["ts"] * 1000) if "ts" in rec else None,
            "status": str(rec.get("status", "")).upper(),
            "lock_keys": None,
            "error": rec.get("error"),
        })
    names = ["procedure_id", "procedure_type", "start_time", "end_time",
             "status", "lock_keys", "error"]
    types = {n: "String" for n in names}
    types.update({"start_time": "TimestampMillisecond",
                  "end_time": "TimestampMillisecond"})
    return _columns_of(rows, names), types


def _runtime_metrics(db):
    """Snapshot of the telemetry registry (reference runtime_metrics)."""
    from greptimedb_tpu.utils.telemetry import REGISTRY

    now = int(time.time() * 1000)
    rows = []
    for name, kind, label_names, key, child in REGISTRY.snapshot():
        labels = ", ".join(
            f"{n}={v}" for n, v in zip(label_names, key)
        ) or None
        if kind == "histogram":
            value, extra = child.sum, [("_count", float(child.total))]
        else:
            value, extra = child.value, []
        rows.append({"metric_name": name, "value": float(value),
                     "labels": labels, "node": "standalone",
                     "node_type": "standalone", "timestamp": now})
        for suffix, v in extra:
            rows.append({"metric_name": name + suffix, "value": v,
                         "labels": labels, "node": "standalone",
                         "node_type": "standalone", "timestamp": now})
    names = ["metric_name", "value", "labels", "node", "node_type",
             "timestamp"]
    types = {n: "String" for n in names}
    types.update({"value": "Float64", "timestamp": "TimestampMillisecond"})
    return _columns_of(rows, names), types


def _self_monitor(db):
    """Self-monitoring loop state (utils/selfmonitor.py): whether the
    loopback span/metric exporter is running (GREPTIME_SELF_MONITOR) and
    what it has written — the introspection surface of the reference's
    ``export_metrics`` self_import timer."""
    from greptimedb_tpu.utils.tracing import TRACER

    mon = getattr(db, "self_monitor", None)
    rows = [{
        "enabled": "Yes" if mon is not None else "No",
        "tracer_enabled": "Yes" if TRACER.enabled else "No",
        "interval_s": float(mon.interval_s) if mon else None,
        "ticks": mon.ticks if mon else 0,
        "spans_exported": mon.spans_exported if mon else 0,
        "metric_rows_exported": mon.metric_rows_exported if mon else 0,
        "last_tick": (mon.last_tick_ms or None) if mon else None,
    }]
    names = ["enabled", "tracer_enabled", "interval_s", "ticks",
             "spans_exported", "metric_rows_exported", "last_tick"]
    types = {n: "String" for n in names}
    types.update({"interval_s": "Float64", "ticks": "Int64",
                  "spans_exported": "Int64", "metric_rows_exported": "Int64",
                  "last_tick": "TimestampMillisecond"})
    return _columns_of(rows, names), types


def _slo_status(db):
    """Closed-loop SLO observatory rows (ISSUE 18, serving/slo.py): one
    row per (tenant, priority class, protocol) latency sketch with its
    declared objective, error-budget remainder, burn rates and any
    firing alert — the SQL face of ``/v1/slo``."""
    slo = getattr(db, "slo", None)
    rows = []
    if slo is not None:
        for r in slo.status_rows():
            rows.append({
                "tenant": r["tenant"], "class": r["class"],
                "protocol": r["protocol"],
                "threshold_ms": float(r["threshold_ms"]),
                "objective": float(r["objective"]),
                "total": int(r["total"]), "breached": int(r["breached"]),
                "p50_ms": float(r["p50_ms"]), "p99_ms": float(r["p99_ms"]),
                "budget_remaining": float(r["budget_remaining"]),
                "burn_5m": float(r["burn_5m"]),
                "burn_1h": float(r["burn_1h"]),
                "burn_6h": float(r["burn_6h"]),
                "alert": r["alert"],
            })
    names = ["tenant", "class", "protocol", "threshold_ms", "objective",
             "total", "breached", "p50_ms", "p99_ms", "budget_remaining",
             "burn_5m", "burn_1h", "burn_6h", "alert"]
    types = {n: "Float64" for n in names}
    types.update({"tenant": "String", "class": "String",
                  "protocol": "String", "alert": "String",
                  "total": "Int64", "breached": "Int64"})
    return _columns_of(rows, names), types


def _views(db):
    """Reference src/catalog/src/system_schema/information_schema/views.rs."""
    rows = []
    for d in db.catalog.list_databases():
        for t in db.catalog.list_tables(d):
            if t.engine != "view":
                continue
            rows.append({
                "table_catalog": "greptime", "table_schema": d,
                "table_name": t.name,
                "view_definition": t.options.get("definition", ""),
                "check_option": None, "is_updatable": "NO",
                "definer": "greptime", "security_type": None,
                "character_set_client": "utf8",
                "collation_connection": "utf8_bin",
            })
    names = ["table_catalog", "table_schema", "table_name",
             "view_definition", "check_option", "is_updatable", "definer",
             "security_type", "character_set_client",
             "collation_connection"]
    return _columns_of(rows, names), {n: "String" for n in names}


def _triggers(db):
    # no trigger support (reference table exists but is likewise empty
    # for mito tables)
    names = ["trigger_catalog", "trigger_schema", "trigger_name",
             "event_manipulation", "event_object_table", "action_statement",
             "action_timing"]
    return _columns_of([], names), {n: "String" for n in names}


def _table_constraints(db):
    """PRIMARY KEY (tags) + TIME INDEX as constraints (reference
    information_schema/table_constraints.rs)."""
    rows = []
    for d in db.catalog.list_databases():
        for t in db.catalog.list_tables(d):
            if t.engine == "view":
                continue
            if any(c.is_tag for c in t.schema):
                rows.append({
                    "constraint_catalog": "def", "constraint_schema": d,
                    "constraint_name": "PRIMARY", "table_schema": d,
                    "table_name": t.name, "constraint_type": "PRIMARY KEY",
                    "enforced": "YES",
                })
            if t.schema.time_index is not None:
                rows.append({
                    "constraint_catalog": "def", "constraint_schema": d,
                    "constraint_name": "TIME INDEX", "table_schema": d,
                    "table_name": t.name, "constraint_type": "TIME INDEX",
                    "enforced": "YES",
                })
    names = ["constraint_catalog", "constraint_schema", "constraint_name",
             "table_schema", "table_name", "constraint_type", "enforced"]
    return _columns_of(rows, names), {n: "String" for n in names}


def _check_constraints(db):
    names = ["constraint_catalog", "constraint_schema", "constraint_name",
             "check_clause"]
    return _columns_of([], names), {n: "String" for n in names}


def _character_sets(db):
    rows = [{"character_set_name": "utf8", "default_collate_name":
             "utf8_bin", "description": "UTF-8 Unicode", "maxlen": 4}]
    names = ["character_set_name", "default_collate_name", "description",
             "maxlen"]
    types = {n: "String" for n in names}
    types["maxlen"] = "Int64"
    return _columns_of(rows, names), types


def _collations(db):
    rows = [{"collation_name": "utf8_bin", "character_set_name": "utf8",
             "id": 83, "is_default": "Yes", "is_compiled": "Yes",
             "sortlen": 1}]
    names = ["collation_name", "character_set_name", "id", "is_default",
             "is_compiled", "sortlen"]
    types = {n: "String" for n in names}
    types.update({"id": "Int64", "sortlen": "Int64"})
    return _columns_of(rows, names), types


def _recycle_bin(db):
    """Soft-dropped tables awaiting undrop/purge (reference
    greptime_private.recycle_bin, purge_dropped_table.rs)."""
    rows = []
    for e in db.catalog.recycle_list():
        info = e["info"]
        rows.append({
            "table_schema": info.get("database"),
            "table_name": info.get("name"),
            "table_id": info.get("table_id"),
            "engine": info.get("engine"),
            "dropped_at": e.get("dropped_at_ms"),
            "region_ids": ",".join(str(r) for r in
                                   info.get("region_ids", [])),
        })
    names = ["table_schema", "table_name", "table_id", "engine",
             "dropped_at", "region_ids"]
    types = {n: "String" for n in names}
    types.update({"table_id": "Int64", "dropped_at": "Int64"})
    return _columns_of(rows, names), types


_TABLES = {
    "schemata": _schemata,
    "tables": _tables,
    "columns": _columns,
    "region_statistics": _region_statistics,
    "partitions": _partitions,
    "flows": _flows,
    "build_info": _build_info,
    "cluster_info": _cluster_info,
    "engines": _engines,
    "key_column_usage": _key_column_usage,
    "process_list": _process_list,
    "region_peers": _region_peers,
    "ssts": _ssts,
    "procedure_info": _procedure_info,
    "runtime_metrics": _runtime_metrics,
    "self_monitor": _self_monitor,
    "slo_status": _slo_status,
    "views": _views,
    "triggers": _triggers,
    "table_constraints": _table_constraints,
    "check_constraints": _check_constraints,
    "character_sets": _character_sets,
    "collations": _collations,
    "recycle_bin": _recycle_bin,
}


# ---------------------------------------------------------------------------
# pg_catalog (reference src/catalog/src/system_schema/pg_catalog.rs):
# the handful of tables psql/BI tools probe on connect
# ---------------------------------------------------------------------------

PG_CATALOG = "pg_catalog"


def is_pg_catalog(table: str | None) -> bool:
    return bool(table) and table.lower().startswith(PG_CATALOG + ".")


def _namespace_oids(db) -> dict[str, int]:
    """Deterministic schema→oid map shared by pg_namespace and pg_class so
    the standard `relnamespace = n.oid` join works."""
    oids = {PG_CATALOG: 11, "public": 2200}
    nxt = 16384
    for d in sorted(db.catalog.list_databases()):
        if d not in oids:
            oids[d] = nxt
            nxt += 1
    return oids


def _pg_namespace(db):
    oids = _namespace_oids(db)
    rows = [{"oid": oid, "nspname": name} for name, oid in sorted(
        oids.items(), key=lambda kv: kv[1])]
    names = ["oid", "nspname"]
    return _columns_of(rows, names), {"oid": "UInt32", "nspname": "String"}


def _pg_class(db):
    oids = _namespace_oids(db)
    rows = []
    for d in db.catalog.list_databases():
        for t in db.catalog.list_tables(d):
            rows.append({"oid": t.table_id, "relname": t.name,
                         "relnamespace": oids.get(d, 2200),
                         "relkind": "r", "relowner": 10})
    names = ["oid", "relname", "relnamespace", "relkind", "relowner"]
    types = {n: "UInt32" for n in names}
    types.update({"relname": "String", "relkind": "String"})
    return _columns_of(rows, names), types


def _pg_tables(db):
    rows = []
    for d in db.catalog.list_databases():
        for t in db.catalog.list_tables(d):
            rows.append({"schemaname": d, "tablename": t.name,
                         "tableowner": "greptime"})
    names = ["schemaname", "tablename", "tableowner"]
    return _columns_of(rows, names), {n: "String" for n in names}


def _pg_database(db):
    oids = _namespace_oids(db)
    rows = [{"oid": oids.get(d, 1), "datname": d}
            for d in sorted(db.catalog.list_databases())]
    names = ["oid", "datname"]
    return _columns_of(rows, names), {"oid": "UInt32", "datname": "String"}


_PG_TABLES = {
    "pg_namespace": _pg_namespace,
    "pg_class": _pg_class,
    "pg_tables": _pg_tables,
    "pg_database": _pg_database,
}


def execute_pg_catalog(db, sel: Select) -> QueryResult:
    name = sel.table.split(".", 1)[1].lower()
    builder = _PG_TABLES.get(name)
    if builder is None:
        raise TableNotFound(f"pg_catalog.{name}")
    columns, types = builder(db)
    return execute_virtual_select(sel, columns, types)
