"""DDL procedures: journaled, resumable CREATE/DROP/ALTER TABLE.

Equivalent of the reference's DDL procedure layer
(src/common/meta/src/ddl/{create_table.rs,drop_table/,alter_table/} driven
by DdlManager, ddl_manager.rs:99): each DDL is a multi-step state machine
journaled through the procedure framework, so a crash between metadata
registration and region materialization resumes exactly where it stopped
instead of leaving a half-created table. Steps mirror the reference's
prepare → create-metadata → create-regions sequence; locks use the same
table-level exclusive keys as repartition (DDL key locks, rwlock.rs).
"""

from __future__ import annotations

import dataclasses

from greptimedb_tpu.datatypes.schema import Schema
from greptimedb_tpu.errors import RegionNotFound, StorageError
from greptimedb_tpu.meta.procedure import Procedure, ProcedureContext, Status


def _db_service(ctx: ProcedureContext):
    return ctx.services["db"]


class CreateTableProcedure(Procedure):
    """state: {step, db, name, schema, engine, options, partition_exprs,
    partition_columns, num_regions, append_mode, info}"""

    type_name = "ddl/create_table"

    def lock_keys(self) -> list[str]:
        return [f"table/{self.state['db']}.{self.state['name']}"]

    def execute(self, ctx: ProcedureContext) -> Status:
        db = _db_service(ctx)
        st = self.state
        step = st.get("step", "metadata")
        if step == "metadata":
            # single kv put = the commit point. On resume, an existing
            # entry means a previous attempt already committed — adopt it.
            if db.catalog.table_exists(st["db"], st["name"]):
                info = db.catalog.get_table(st["db"], st["name"])
            else:
                info = db.catalog.create_table(
                    st["db"], st["name"], Schema.from_dict(st["schema"]),
                    engine=st["engine"], options=st["options"],
                    partition_exprs=st["partition_exprs"],
                    partition_columns=st["partition_columns"],
                    num_regions=st["num_regions"],
                )
            st["info"] = info.to_dict()
            st["step"] = "regions"
            return Status.executing()
        if step == "regions":
            if st["engine"] != "file":
                schema = Schema.from_dict(st["schema"])
                opts = None
                overrides = {}
                if st.get("append_mode"):
                    overrides["append_mode"] = True
                if st.get("ttl_ms"):
                    overrides["ttl_ms"] = int(st["ttl_ms"])
                if overrides:
                    opts = dataclasses.replace(
                        db.regions.default_options, **overrides
                    )
                for rid in st["info"]["region_ids"]:
                    # idempotent: adopts a region materialized by a prior
                    # attempt; real storage failures propagate untouched
                    db.regions.ensure_region(rid, schema, options=opts)
            return Status.done(output=st["info"])
        raise StorageError(f"create_table: unknown step {step!r}")


class AlterOptionsProcedure(Procedure):
    """state: {step, db, name, options} — journaled ALTER TABLE SET/UNSET
    of table options (``options`` is the full post-change dict).  Same
    crash-resume contract as the other DDL procedures: catalog commit
    first, then idempotent per-region manifest commits — a crash between
    them resumes and re-applies the region step."""

    type_name = "ddl/alter_options"

    def lock_keys(self) -> list[str]:
        return [f"table/{self.state['db']}.{self.state['name']}"]

    def execute(self, ctx: ProcedureContext) -> Status:
        db = _db_service(ctx)
        st = self.state
        step = st.get("step", "metadata")
        opts = st["options"]
        if step == "metadata":
            info = db.catalog.get_table(st["db"], st["name"])
            info.options = dict(opts)
            db.catalog.update_table(info)
            st["step"] = "regions"
            return Status.executing()
        if step == "regions":
            from greptimedb_tpu.utils.config import parse_duration_ms

            overrides = {
                "ttl_ms": parse_duration_ms(opts["ttl"]) if opts.get("ttl")
                else None,
                "append_mode": str(opts.get("append_mode", "")).lower()
                in ("true", "1"),
            }
            if opts.get("compaction_window"):
                overrides["compaction_window_ms"] = parse_duration_ms(
                    opts["compaction_window"]) or 24 * 3600 * 1000
            info = db.catalog.get_table(st["db"], st["name"])
            for rid in info.region_ids:
                region = db.regions.regions.get(rid)
                if region is None:
                    try:
                        region = db.regions.open_region(rid)
                    except RegionNotFound:
                        continue  # file-engine/virtual: no LSM region
                region.options = dataclasses.replace(
                    region.options, **overrides)
                region.manifest.commit(
                    {"kind": "options",
                     "options": region.options.to_dict()}
                )
                region.apply_ttl()
                db.cache.invalidate_region(region.region_id)
            return Status.done()
        raise StorageError(f"alter_options: unknown step {step!r}")


class DropTableProcedure(Procedure):
    """state: {step, db, name, if_exists, info}"""

    type_name = "ddl/drop_table"

    def lock_keys(self) -> list[str]:
        return [f"table/{self.state['db']}.{self.state['name']}"]

    def execute(self, ctx: ProcedureContext) -> Status:
        import time as _time

        db = _db_service(ctx)
        st = self.state
        step = st.get("step", "metadata")
        if step == "metadata":
            # journal the victim's region list BEFORE deleting the catalog
            # entry — after the delete, only the journal knows what to drop
            if db.catalog.table_exists(st["db"], st["name"]):
                info = db.catalog.get_table(st["db"], st["name"])
                st["info"] = info.to_dict()
                st["step"] = "recycle"
                return Status.executing()
            if st.get("info") is not None:
                st["step"] = "regions"  # resume: entry already deleted
                return Status.executing(persist=False)
            return Status.done()  # if_exists pre-checked by the caller
        if step == "recycle":
            # soft delete (reference purge_dropped_table.rs): the catalog
            # entry moves to the recycle bin; region data stays on disk
            # until ADMIN undrop_table or a purge sweep.  Recycle-put is
            # idempotent on resume (same dropped_at key rewritten).
            from greptimedb_tpu.meta.catalog import TableInfo

            info = TableInfo.from_dict(st["info"])
            if info.engine in ("mito", "metric_physical"):
                if "dropped_at_ms" not in st:
                    st["dropped_at_ms"] = int(_time.time() * 1000)
                db.catalog.recycle_put(info, st["dropped_at_ms"])
            st["step"] = "delete"
            return Status.executing()
        if step == "delete":
            db.catalog.drop_table(st["db"], st["name"], if_exists=True)
            st["step"] = "regions"
            return Status.executing()
        if step == "regions":
            info = st["info"]
            soft = info["engine"] in ("mito", "metric_physical")
            for rid in info["region_ids"]:
                if info["engine"] != "file":
                    if soft:
                        db.regions.close_region(rid)
                    else:
                        try:
                            db.regions.drop_region(rid)
                        except RegionNotFound:
                            pass  # resume: already dropped
                db.cache.invalidate_region(rid)
            return Status.done(output=info)
        raise StorageError(f"drop_table: unknown step {step!r}")


class AlterTableProcedure(Procedure):
    """state: {step, db, name, new_schema} — add/drop column paths (rename
    is a pure metadata CAS handled directly by the catalog)."""

    type_name = "ddl/alter_table"

    def lock_keys(self) -> list[str]:
        return [f"table/{self.state['db']}.{self.state['name']}"]

    def execute(self, ctx: ProcedureContext) -> Status:
        db = _db_service(ctx)
        st = self.state
        step = st.get("step", "metadata")
        new_schema = Schema.from_dict(st["new_schema"])
        if step == "metadata":
            info = db.catalog.get_table(st["db"], st["name"])
            info.schema = new_schema
            db.catalog.update_table(info)
            st["step"] = "regions"
            return Status.executing()
        if step == "regions":
            # flush-then-swap per region; re-running after a crash is safe
            # (flush of an empty memtable is a no-op, schema swap is
            # idempotent). Regions are opened if need be — on crash-resume
            # at startup nothing is open yet, and skipping would leave the
            # manifest schema permanently behind the catalog's.
            info = db.catalog.get_table(st["db"], st["name"])
            for rid in info.region_ids:
                region = db.regions.regions.get(rid)
                if region is None:
                    try:
                        region = db.regions.open_region(rid)
                    except RegionNotFound:
                        continue  # file-engine/virtual: no LSM region
                # under the region's (reentrant) write lock: concurrent
                # ingest-pool writers must not observe a half-applied
                # flush/schema swap
                with region._write_lock:
                    region.flush()
                    region.schema = new_schema
                    region.manifest.commit(
                        {"kind": "schema", "schema": new_schema.to_dict()}
                    )
                    region.memtable.schema = new_schema
                db.cache.invalidate_region(region.region_id)
            view = db._views.pop(f"{st['db']}.{st['name']}", None)
            if view is not None:
                db.cache.invalidate_region(view.region_id)
            return Status.done()
        raise StorageError(f"alter_table: unknown step {step!r}")
