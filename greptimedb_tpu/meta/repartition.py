"""Repartitioning: change a table's partition rule via a journaled procedure.

Reference: src/meta-srv/src/procedure/repartition/ + RFC 2025-06-20.
The reference remaps manifests through staging states to stay online; the
standalone build takes the simpler-but-correct route: the procedure runs
UNDER the database write lock (create target regions → copy rows routed by
the NEW rule → swap the catalog entry → drop the old regions), so
concurrent DML waits instead of racing the copy. Each step persists its
state through the procedure framework; RUNNING journals are resumed by
GreptimeDB startup recovery. The catalog swap is the visibility point.
"""

from __future__ import annotations

from greptimedb_tpu.errors import GreptimeError, InvalidArguments
from greptimedb_tpu.meta.procedure import Procedure, ProcedureContext, Status


class RepartitionProcedure(Procedure):
    """state: {db, table, new_exprs, new_columns, phase, new_region_ids}."""

    type_name = "repartition"

    def lock_keys(self) -> list[str]:
        return [f"table/{self.state['db']}.{self.state['table']}"]

    def execute(self, ctx: ProcedureContext) -> Status:
        dbi = ctx.services["db"]
        s = self.state
        phase = s.setdefault("phase", "prepare")
        db, table = s["db"], s["table"]

        if phase == "prepare":
            info = dbi.catalog.get_table(db, table)
            if info.engine != "mito":
                raise InvalidArguments(
                    f"cannot repartition engine {info.engine}"
                )
            # validate the rule BEFORE creating regions: a bad expression
            # failing later would leak orphan region directories
            from greptimedb_tpu.parallel.partition import PartitionRule

            for col in s["new_columns"]:
                if not info.schema.has_column(col):
                    raise InvalidArguments(
                        f"partition column {col!r} not in table schema"
                    )
            if s["new_exprs"]:
                PartitionRule.from_sql(s["new_columns"], s["new_exprs"])
            n_new = max(len(s["new_exprs"]), 1)
            # region ids in a fresh sub-space of the table's id block
            base = info.table_id * 1024 + 512
            existing = set(info.region_ids)
            ids = []
            nxt = base
            while len(ids) < n_new:
                if nxt not in existing:
                    ids.append(nxt)
                nxt += 1
            s["new_region_ids"] = ids
            s["old_region_ids"] = list(info.region_ids)
            s["phase"] = "create_regions"
            return Status.executing()

        if phase == "create_regions":
            info = dbi.catalog.get_table(db, table)
            for rid in s["new_region_ids"]:
                try:
                    dbi.regions.create_region(rid, info.schema)
                except GreptimeError:
                    dbi.regions.open_region(rid)  # resume after crash
            s["phase"] = "copy"
            return Status.executing()

        if phase == "copy":
            from greptimedb_tpu.parallel.partition import (
                PartitionRule, split_rows,
            )
            from greptimedb_tpu.storage.memtable import SEQ

            info = dbi.catalog.get_table(db, table)
            if s["new_exprs"]:
                rule = PartitionRule.from_sql(s["new_columns"], s["new_exprs"])
            else:
                rule = PartitionRule.hash_rule(
                    len(s["new_region_ids"]),
                    [c.name for c in info.schema.tag_columns],
                )
            new_regions = [dbi.regions.open_region(r)
                           for r in s["new_region_ids"]]
            # idempotent on resume: truncate targets before re-copying
            for nr in new_regions:
                if nr.next_seq > 1 or nr.sst_files:
                    nr.truncate()
            col_names = [c.name for c in info.schema]
            for rid in s["old_region_ids"]:
                region = dbi.regions.open_region(rid)
                host = region.scan_host()
                n = len(host[SEQ])
                if n == 0:
                    continue
                data = {k: host[k] for k in col_names}
                parts = split_rows(rule, data, n)
                for pidx, row_idx in parts.items():
                    if pidx >= len(new_regions):
                        raise InvalidArguments(
                            f"partition index {pidx} out of range"
                        )
                    sub = {k: data[k][row_idx] for k in col_names}
                    new_regions[pidx].write(sub)
            for nr in new_regions:
                nr.flush()
            s["phase"] = "swap_catalog"
            return Status.executing()

        if phase == "swap_catalog":
            info = dbi.catalog.get_table(db, table)
            info.region_ids = list(s["new_region_ids"])
            info.partition_exprs = list(s["new_exprs"])
            info.partition_columns = list(s["new_columns"])
            dbi.catalog.update_table(info)
            dbi._views.pop(f"{db}.{table}", None)
            s["phase"] = "drop_old"
            return Status.executing()

        if phase == "drop_old":
            for rid in s["old_region_ids"]:
                dbi.regions.drop_region(rid)
                dbi.cache.invalidate_region(rid)
            return Status.done({
                "table": f"{db}.{table}",
                "regions": len(s["new_region_ids"]),
            })

        raise GreptimeError(f"unknown repartition phase {phase}")


def repartition_table(dbi, table: str, columns: list[str],
                      exprs: list[str]) -> dict:
    """Admin entry (the reference drives this from metasrv procedures).

    Runs under the database write lock: concurrent DML queues behind the
    copy instead of landing in regions that are about to be dropped."""
    db, name = dbi._split_name(table)
    dbi.catalog.get_table(db, name)  # existence check up front
    with dbi._lock:
        return dbi.procedures.submit(RepartitionProcedure(state={
            "db": db, "table": name,
            "new_columns": list(columns), "new_exprs": list(exprs),
        }))
