"""Error model: status-coded exceptions shared across all layers.

Equivalent of the reference's ``common_error`` crate (``ErrorExt`` + status
codes, reference src/common/error/src/status_code.rs): every user-visible
failure carries a stable status code so protocol servers can map errors to
HTTP/gRPC responses uniformly.
"""

from __future__ import annotations

import enum


class StatusCode(enum.IntEnum):
    # Success is 0 in responses; errors below.
    UNKNOWN = 1000
    UNSUPPORTED = 1001
    UNEXPECTED = 1002
    INTERNAL = 1003
    INVALID_ARGUMENTS = 1004
    CANCELLED = 1005
    DEADLINE_EXCEEDED = 1006

    INVALID_SYNTAX = 2000
    PLAN_QUERY = 3000
    ENGINE_EXECUTE_QUERY = 3001

    TABLE_ALREADY_EXISTS = 4000
    TABLE_NOT_FOUND = 4001
    TABLE_COLUMN_NOT_FOUND = 4002
    TABLE_COLUMN_EXISTS = 4003
    DATABASE_NOT_FOUND = 4004
    REGION_NOT_FOUND = 4005
    REGION_ALREADY_EXISTS = 4006
    REGION_READONLY = 4007
    FLOW_ALREADY_EXISTS = 4008
    FLOW_NOT_FOUND = 4009
    DATABASE_ALREADY_EXISTS = 4010

    STORAGE_UNAVAILABLE = 5000
    REQUEST_OUTDATED = 5001

    RUNTIME_RESOURCES_EXHAUSTED = 6000
    RATE_LIMITED = 6001

    USER_NOT_FOUND = 7000
    UNSUPPORTED_PASSWORD_TYPE = 7001
    USER_PASSWORD_MISMATCH = 7002
    AUTH_HEADER_NOT_FOUND = 7003
    INVALID_AUTH_HEADER = 7004
    ACCESS_DENIED = 7005
    PERMISSION_DENIED = 7006


class GreptimeError(Exception):
    """Base error; subclasses pin a default status code."""

    status_code: StatusCode = StatusCode.INTERNAL

    def __init__(self, msg: str, *, code: StatusCode | None = None):
        super().__init__(msg)
        if code is not None:
            self.status_code = code

    @property
    def msg(self) -> str:
        return str(self.args[0]) if self.args else self.__class__.__name__


class InvalidArguments(GreptimeError):
    status_code = StatusCode.INVALID_ARGUMENTS


class SyntaxError_(GreptimeError):
    status_code = StatusCode.INVALID_SYNTAX


class PlanError(GreptimeError):
    status_code = StatusCode.PLAN_QUERY


class ExecutionError(GreptimeError):
    status_code = StatusCode.ENGINE_EXECUTE_QUERY


class TableNotFound(GreptimeError):
    status_code = StatusCode.TABLE_NOT_FOUND

    def __init__(self, table: str):
        super().__init__(f"Table not found: {table}")
        self.table = table


class TableAlreadyExists(GreptimeError):
    status_code = StatusCode.TABLE_ALREADY_EXISTS

    def __init__(self, table: str):
        super().__init__(f"Table already exists: {table}")
        self.table = table


class ColumnNotFound(GreptimeError):
    status_code = StatusCode.TABLE_COLUMN_NOT_FOUND

    def __init__(self, column: str, table: str = ""):
        where = f" in table {table}" if table else ""
        super().__init__(f"Column not found: {column}{where}")
        self.column = column


class DatabaseNotFound(GreptimeError):
    status_code = StatusCode.DATABASE_NOT_FOUND

    def __init__(self, db: str):
        super().__init__(f"Database not found: {db}")
        self.database = db


class RegionNotFound(GreptimeError):
    status_code = StatusCode.REGION_NOT_FOUND


class FlowNotFound(GreptimeError):
    status_code = StatusCode.FLOW_NOT_FOUND


class FlowAlreadyExists(GreptimeError):
    status_code = StatusCode.FLOW_ALREADY_EXISTS


class Unsupported(GreptimeError):
    status_code = StatusCode.UNSUPPORTED


class StorageError(GreptimeError):
    status_code = StatusCode.STORAGE_UNAVAILABLE


class FencedError(StorageError):
    """A conditional (epoch-fenced) object-store write lost its CAS: a
    newer leader epoch owns the target, or the object already exists.
    The fenced-out writer must STOP — retrying or falling back to a
    plain write would interleave two leaders' histories on shared
    storage (split brain)."""


class ResourcesExhausted(GreptimeError):
    status_code = StatusCode.RUNTIME_RESOURCES_EXHAUSTED


class RateLimited(GreptimeError):
    """Per-tenant rate quota exceeded (serving/admission.py) — the
    deliberate flow-control rejection, distinct from memory pressure."""

    status_code = StatusCode.RATE_LIMITED


class DeadlineExceeded(GreptimeError):
    """Query shed by the scheduler before/while running because its
    deadline passed (serving/scheduler.py deadline-based shedding)."""

    status_code = StatusCode.DEADLINE_EXCEEDED


class Cancelled(GreptimeError):
    status_code = StatusCode.CANCELLED


class AccessDenied(GreptimeError):
    status_code = StatusCode.ACCESS_DENIED
