"""Dense time-grid resident layout: [series, time, field] tensors.

The TPU-first answer to the reference's two hot-loop layouts — mito2's
(tsid, ts)-sorted row batches (src/mito2/src/read/seq_scan.rs) and the
PromQL RangeArray dictionary-range view (src/promql/src/range_array.rs:65).
Metric data is (near-)regularly sampled, so instead of sorting rows and
scatter-reducing group aggregates, the region materializes a dense
``values[series, timestep, field]`` tensor plus a ``valid[series,
timestep]`` mask.  Aggregation by (tags × time bucket) then lowers to
reshape + reduce — no scatter, no gather, no sort — which is the shape
XLA:TPU tiles perfectly onto the VPU/MXU and which even a single CPU core
executes at memory bandwidth (SURVEY.md §5.7: "blockwise windowed
evaluation replaces RangeArray with gather-free rolling windows").

Eligibility is decided per region build: timestamps must share a coarse
enough GCD step (regular sampling), the dense grid must fit the byte
budget, and occupancy must clear a floor.  Irregular/sparse data keeps the
row-oriented DeviceTable path (storage/cache.py) — the grid is a second
resident representation, not a replacement.

Incremental protocol mirrors the DeviceTable one: pure time-forward
appends scatter into the padded tail of the resident tensors device-side;
structure changes (flush/compaction/upsert) rebuild.
"""

from __future__ import annotations

import math
import os
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from greptimedb_tpu.storage.durability import SstCorruption
from greptimedb_tpu.storage.memtable import OP, OP_DELETE, SEQ, TSID
from greptimedb_tpu.storage.object_store import _fsync_dir

# padding granularity: each distinct (Spad, Tpad) is a compile shape class.
# T gets coarse alignment (appends grow it constantly); S changes rarely.
_T_ALIGN = 2048
_S_ALIGN = 256
_MIN_DENSITY = float(os.environ.get("GREPTIME_GRID_MIN_DENSITY", "0.1"))
_BUDGET = int(os.environ.get("GREPTIME_GRID_BUDGET_BYTES", str(6 << 30)))


def _pad_to(n: int, align: int) -> int:
    """Small sizes get pow2 buckets, larger ones align to ``align``."""
    if n <= 0:
        return align if align < 64 else 64
    if n < align:
        return 1 << max(6, (n - 1).bit_length())
    return -(-n // align) * align


def _to_device_rows(arr: np.ndarray, sharding=None) -> jnp.ndarray:
    """Chunked host→device upload (relay-safe) with double buffering —
    the scan pipeline's shared streamer (storage/scan.py): bounded pieces
    with two dispatches in flight, reshaped on device (free — same
    layout).  With a sharding the array lands distributed across the mesh
    in one placement (multi-chip meshes have per-chip links, not the
    single-relay bottleneck)."""
    from greptimedb_tpu.storage.scan import stream_to_device

    return stream_to_device(arr, sharding)


@jax.tree_util.register_pytree_node_class
@dataclass
class GridTable:
    """One region's dense-grid resident tensors.

    ts of grid point t = ``ts0 + t * step`` for t < ``nt``; padding points
    (t >= nt) and padding series (s >= num_series) have valid=False.
    """

    values: jnp.ndarray              # [C, Spad, Tpad] float32 — field-major
    # planes keep the time axis contiguous, so per-bucket reductions and
    # rolling windows vectorize along memory order on both CPU and TPU
    valid: jnp.ndarray               # [Spad, Tpad] bool
    tag_codes: dict[str, jnp.ndarray]  # per-tag [Spad] int32 (pad = -1)
    ts0: int
    step: int
    nt: int                          # live timesteps
    num_series: int                  # live series
    field_names: tuple               # C order (float FIELD columns)
    dicts: dict[str, list] = field(default_factory=dict)
    # per-field "finite everywhere written" (no NaN *or* ±inf): count()
    # reuses the shared validity reduction, and sums may ride the
    # mask-free weighted reduce (inf would break its 0-weight products)
    no_nan: tuple = ()
    dicts_version: int = 0
    # owning region: derived-layout cache entries key on it so a rebuilt
    # grid (new dicts_version) REPLACES the region's stale layouts instead
    # of leaking them until LRU pressure
    region_id: int = -1

    @property
    def spad(self) -> int:
        return int(self.valid.shape[0])

    @property
    def tpad(self) -> int:
        return int(self.valid.shape[1])

    def nbytes(self) -> int:
        total = self.values.nbytes + self.valid.nbytes
        for v in self.tag_codes.values():
            total += v.nbytes
        return total

    def tree_flatten(self):
        names = sorted(self.tag_codes)
        children = (self.values, self.valid) + tuple(
            self.tag_codes[n] for n in names
        )
        aux = (
            tuple(names), self.ts0, self.step, self.nt, self.num_series,
            self.field_names,
            tuple((k, tuple(v)) for k, v in sorted(self.dicts.items())),
            self.no_nan, self.dicts_version, self.region_id,
        )
        return children, aux

    @classmethod
    def tree_unflatten(cls, aux, children):
        (names, ts0, step, nt, ns, fields, dict_items, no_nan, dver,
         rid) = aux
        values, valid = children[0], children[1]
        tags = dict(zip(names, children[2:]))
        return cls(values, valid, tags, ts0, step, nt, ns, fields,
                   {k: list(v) for k, v in dict_items}, no_nan, dver, rid)


def grid_float_fields(schema) -> list[str]:
    return [c.name for c in schema.field_columns if c.dtype.is_float]


def _series_tag_matrix(region, spad: int) -> dict[str, np.ndarray]:
    """Per-tag code arrays indexed by tsid, padded with the poison code -1."""
    tags = region.tag_names
    s = region.num_series
    out = {name: np.full(spad, -1, dtype=np.int32) for name in tags}
    for key, tsid in region._series.items():
        for j, name in enumerate(tags):
            out[name][tsid] = key[j]
    return out


def _gather_parts(region, fields: list[str]):
    """Region parts (SSTs then memtable chunks) in last-write-wins order.

    SSTs sort by seq_max: flush emits monotonically increasing sequence
    ranges, and TWCS-compacted files never share (series, ts) keys with
    files of other time windows, so per-key ordering reduces to per-file
    ordering.  Memtable chunks follow in append order.

    Decodes run concurrently on the scan pipeline's bounded pool with
    scan-driven readahead.  (Catch-up builds read their own ts-restricted
    slice in catch_up_grid_table — this is the full-region gather.)
    """
    from greptimedb_tpu.storage.scan import (
        estimate_staging_bytes, prefetch_store, read_parts,
    )
    from greptimedb_tpu.storage.sst import read_sst

    ts_name = region.ts_name
    want = [ts_name, TSID, SEQ, OP] + fields
    attempts = 0
    while True:
        metas = sorted(region.sst_files, key=lambda m: m.seq_max)
        prefetch_store(region.store, metas)
        est = estimate_staging_bytes(metas, len(want))
        try:
            parts = read_parts(
                [
                    (lambda m=m: read_sst(region.store, m, region.schema,
                                          columns=want))
                    for m in metas
                ],
                memory=getattr(region, "memory", None), est_bytes=est,
            )
            break
        except SstCorruption as e:
            # verified read failed: quarantine/repair, retry over the
            # refreshed live set (the grid build must never ingest
            # corrupt pages, and must keep building around a lost file)
            attempts += 1
            if attempts > 16:
                raise
            region._handle_sst_corruption(e)
    for chunk in region.memtable.snapshot_chunks():
        # within-chunk duplicates resolve by scatter order (later row wins),
        # matching keep-max-seq: rows in a chunk share one sequence and
        # arrive in insert order
        parts.append(chunk)
    return parts


def infer_grid_step(parts, ts_name: str, ts0: int) -> int:
    """GCD of (ts - ts0) across all rows — one vectorized pass, no sort."""
    g = np.int64(0)
    for p in parts:
        ts = p[ts_name]
        if len(ts):
            g = np.gcd(g, np.gcd.reduce(ts.astype(np.int64) - ts0))
    return int(g)


def grid_shardings(mesh, spad: int):
    """NamedShardings splitting the series axis across the mesh, or None
    when the padded series count does not tile the mesh.  The aggregate
    kernel (query/physical.py) is pure jnp over these arrays, so GSPMD
    partitions it automatically — per-shard bucket partials with XLA-
    inserted all-reduces over ICI at the tiny [groups, buckets] merge
    (the MergeScanExec fan-out/merge of the reference,
    src/query/src/dist_plan/merge_scan.rs:210,335, as compiler-inserted
    collectives instead of a Flight shuffle)."""
    if mesh is None:
        return None
    from jax.sharding import NamedSharding, PartitionSpec as P

    d = mesh.devices.size
    if d <= 1 or spad % d != 0:
        return None
    axis = mesh.axis_names[0]
    return {
        "values": NamedSharding(mesh, P(None, axis, None)),
        "valid": NamedSharding(mesh, P(axis, None)),
        "tags": NamedSharding(mesh, P(axis)),
    }


def build_grid_table(region, budget_bytes: int | None = None, mesh=None):
    """Attempt the dense-grid build; returns None when ineligible
    (irregular sampling, too sparse, over budget, stringly fields only).
    With a mesh, the resident tensors shard on the series axis."""
    fields = grid_float_fields(region.schema)
    if not fields or region.schema.time_index is None:
        return None
    if region.options.append_mode:
        # append mode preserves duplicate (series, ts) rows; the grid is
        # keyed by (series, timestep) and would silently dedup them
        return None
    bounds = region.ts_bounds()
    if bounds is None:
        return None  # empty region: nothing to accelerate
    ts0, ts_max = bounds
    s = region.num_series
    if s == 0:
        return None
    budget = budget_bytes if budget_bytes is not None else _BUDGET
    c = len(fields)
    ts_name = region.ts_name

    parts = _gather_parts(region, fields)
    total_rows = sum(len(p[TSID]) for p in parts)
    if total_rows == 0:
        return None
    step = infer_grid_step(parts, ts_name, ts0)
    if step <= 0:
        step = 1  # single distinct timestamp
    nt = (ts_max - ts0) // step + 1
    spad = _pad_to(s, _S_ALIGN)
    tpad = _pad_to(nt, _T_ALIGN)
    grid_bytes = spad * tpad * (4 * c + 1)
    if grid_bytes > budget:
        return None
    if total_rows / max(s * nt, 1) < _MIN_DENSITY:
        return None

    # zero-fill, not NaN: ``valid`` is the sole source of truth for cell
    # liveness, so never-written cells contribute +0 to sums and the hot
    # aggregate kernel can lower to a plain (mask-free) einsum/matmul —
    # MXU-shaped on TPU, ~3x fewer bytes on CPU (no where() temp).  Cells
    # holding a *written* NaN (tombstone fields, real NaN data) keep the
    # NaN and clear ``no_nan``, which routes queries to the masked path.
    values = np.zeros((c, spad, tpad), dtype=np.float32)
    valid = np.zeros((spad, tpad), dtype=bool)
    no_nan = [True] * c
    for p in parts:
        tsid = p[TSID].astype(np.int64)
        if not len(tsid):
            continue
        tidx = (p[ts_name].astype(np.int64) - ts0) // step
        op = p[OP]
        dels = op == OP_DELETE
        any_dels = bool(dels.any())
        for ci, name in enumerate(fields):
            col = p[name]
            if col.dtype != np.float32:
                col = col.astype(np.float32)
            if any_dels:
                # tombstones must land as 0.0 whatever their field payload
                # (schema DEFAULTs fill deleted rows with non-zero values):
                # the mask-free sum fast path relies on invalid cells
                # contributing exactly +0
                col = np.where(dels, np.float32(0.0), col)
            # no_nan really means "finite everywhere written": written NaN
            # breaks count-by-validity, and written ±inf would turn the
            # fast path's inf*0 weight products into NaN — either routes
            # the column to the masked kernel path
            if no_nan[ci] and not bool(np.isfinite(col).all()):
                no_nan[ci] = False
            values[ci][tsid, tidx] = col
        valid[tsid, tidx] = ~dels
    tag_codes = _series_tag_matrix(region, spad)
    dicts = {name: region.encoders[name].values() for name in region.tag_names}
    from greptimedb_tpu.storage.cache import next_dicts_version

    sh = grid_shardings(mesh, spad)
    return GridTable(
        values=_to_device_rows(values, sh and sh["values"]),
        valid=_to_device_rows(valid, sh and sh["valid"]),
        tag_codes={
            k: _to_device_rows(np.asarray(v), sh and sh["tags"])
            for k, v in tag_codes.items()
        },
        ts0=int(ts0),
        step=int(step),
        nt=int(nt),
        num_series=s,
        field_names=tuple(fields),
        dicts=dicts,
        no_nan=tuple(no_nan),
        dicts_version=next_dicts_version(),
        region_id=int(getattr(region, "region_id", -1)),
    )


def _region_fingerprint(region) -> dict:
    """Cheap identity of a region's resident data: SST set + memtable
    volume.  A snapshot built from the same fingerprint maps to identical
    grid tensors, so re-opening processes (bench re-runs, restarts) can
    mmap the host tensors instead of re-scanning every SST."""
    return {
        "ssts": sorted(
            (m.file_id, int(m.seq_max), int(m.num_rows))
            for m in region.sst_files
        ),
        "memtable_rows": int(region.memtable.num_rows),
        "num_series": int(region.num_series),
        "fields": grid_float_fields(region.schema),
    }


def save_grid_snapshot(table: GridTable, region, path: str) -> None:
    """Persist the dense host tensors next to the region data (mito2's
    write-through file cache idea, src/mito2/src/cache/write_cache.rs:1,
    applied to the resident layout): np arrays + a json manifest."""
    import json

    os.makedirs(path, exist_ok=True)
    np.save(os.path.join(path, "values.npy"), np.asarray(table.values))
    np.save(os.path.join(path, "valid.npy"), np.asarray(table.valid))
    np.savez(os.path.join(path, "tags.npz"),
             **{k: np.asarray(v) for k, v in table.tag_codes.items()})
    meta = {
        "ts0": table.ts0, "step": table.step, "nt": table.nt,
        "num_series": table.num_series,
        "field_names": list(table.field_names),
        "dicts": {k: list(v) for k, v in table.dicts.items()},
        "no_nan": list(table.no_nan),
        "fingerprint": _region_fingerprint(region),
    }
    tmp = os.path.join(path, "meta.json.tmp")
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(meta, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(path, "meta.json"))
    # rename durability: the directory entry must hit disk too, or a
    # power loss can resurrect the old meta.json against new .npy tensors
    # (fingerprint mismatch is caught, but the snapshot is silently lost)
    _fsync_dir(path)


def load_grid_snapshot(path: str, region, mesh=None):
    """Rebuild a resident GridTable from a snapshot, verifying the region
    fingerprint still matches; returns None on any mismatch/corruption
    (caller falls back to the SST scan build)."""
    import json

    from greptimedb_tpu.storage.cache import next_dicts_version

    try:
        with open(os.path.join(path, "meta.json")) as f:
            meta = json.load(f)
        fp = _region_fingerprint(region)
        saved = meta["fingerprint"]
        saved["ssts"] = [tuple(s) for s in saved["ssts"]]
        if saved != {**fp, "ssts": list(fp["ssts"])}:
            return None
        # the restored tag codes are decoded against the region's CURRENT
        # encoders at query time — a different code assignment (WAL
        # replay order, rebuilt dictionaries) must refuse the snapshot
        if {k: list(v) for k, v in meta["dicts"].items()} != {
            name: list(region.encoders[name].values())
            for name in region.tag_names
        }:
            return None
        values = np.load(os.path.join(path, "values.npy"), mmap_mode="r")
        valid = np.load(os.path.join(path, "valid.npy"), mmap_mode="r")
        tags = np.load(os.path.join(path, "tags.npz"))
    except Exception:  # noqa: BLE001 — any corruption (incl. BadZipFile
        # from a truncated .npz) must mean "no snapshot", never a crash
        return None
    sh = grid_shardings(mesh, int(valid.shape[0]))
    return GridTable(
        values=_to_device_rows(values, sh and sh["values"]),
        valid=_to_device_rows(valid, sh and sh["valid"]),
        tag_codes={
            k: _to_device_rows(np.asarray(tags[k]), sh and sh["tags"])
            for k in tags.files
        },
        ts0=int(meta["ts0"]),
        step=int(meta["step"]),
        nt=int(meta["nt"]),
        num_series=int(meta["num_series"]),
        field_names=tuple(meta["field_names"]),
        dicts={k: list(v) for k, v in meta["dicts"].items()},
        no_nan=tuple(meta["no_nan"]),
        dicts_version=next_dicts_version(),
        region_id=int(getattr(region, "region_id", -1)),
    )


def _grow_time_axis(values, valid, tpad: int, new_nt: int, spad: int,
                    c: int):
    """Grow the padded time axis (new cells zero-valued / invalid) so
    sustained time-forward ingest extends the resident grid in amortized
    O(1) per appended step instead of falling off a fixed-``tpad`` cliff
    into a full rebuild every linger window.  Doubles by default; falls
    back to an exact fit near the budget.  Returns ``(values, valid)``
    or None when even the exact fit exceeds the grid budget."""
    tpad2 = _pad_to(max(new_nt, 2 * tpad), _T_ALIGN)
    if spad * tpad2 * (4 * c + 1) > _BUDGET:
        tpad2 = _pad_to(new_nt, _T_ALIGN)
        if spad * tpad2 * (4 * c + 1) > _BUDGET:
            return None
    grow = tpad2 - tpad
    return (
        jnp.pad(values, ((0, 0), (0, 0), (0, grow))),
        jnp.pad(valid, ((0, 0), (0, grow))),
    )


def extend_grid_table(table: GridTable, region, chunks, mesh=None):  # gl: warm-path(host)
    """Scatter pure-append chunks into the resident grid device-side.

    Returns the extended GridTable, or None when the delta does not fit
    the resident shape/step (caller rebuilds).  Precondition (enforced by
    Region's append log): chunks are PUT-only with strictly newer
    timestamps, so no resident cell is overwritten — only new cells are
    set."""
    ts_name = region.ts_name
    fields = table.field_names
    new_series = region.num_series
    if new_series > table.spad:
        return None
    tsid = np.concatenate([c[TSID] for c in chunks]).astype(np.int64)
    if not len(tsid):
        return table
    ts = np.concatenate(
        [np.asarray(c[ts_name], dtype=np.int64) for c in chunks]
    )
    rel = ts - table.ts0
    step = table.step
    if step <= 0 or bool((rel % step != 0).any()):
        return None  # off-grid timestamps: sampling changed
    tidx = rel // step
    new_nt = int(tidx.max()) + 1
    values, valid = table.values, table.valid
    if new_nt > table.tpad:
        grown = _grow_time_axis(values, valid, table.tpad, new_nt,
                                table.spad, len(fields))
        if grown is None:
            return None
        values, valid = grown
    cols = []
    no_nan = list(table.no_nan)
    for ci, name in enumerate(fields):
        col = np.concatenate(
            [np.asarray(c[name], dtype=np.float32) for c in chunks]
        )
        if no_nan[ci] and not bool(np.isfinite(col).all()):
            no_nan[ci] = False
        cols.append(col)
    delta = np.stack(cols, axis=0)  # [C, n]
    values = values.at[
        :, jnp.asarray(tsid), jnp.asarray(tidx)
    ].set(jnp.asarray(delta))
    valid = valid.at[jnp.asarray(tsid), jnp.asarray(tidx)].set(True)
    tag_codes = table.tag_codes
    if new_series > table.num_series:
        host_tags = _series_tag_matrix(region, table.spad)
        sh = grid_shardings(mesh, table.spad)
        tag_codes = {
            k: _to_device_rows(v, sh and sh["tags"])
            for k, v in host_tags.items()
        }
    from greptimedb_tpu.storage.cache import next_dicts_version

    return GridTable(
        values=values,
        valid=valid,
        tag_codes=tag_codes,
        ts0=table.ts0,
        step=step,
        nt=max(table.nt, new_nt),
        num_series=new_series,
        field_names=fields,
        dicts={name: region.encoders[name].values()
               for name in region.tag_names},
        no_nan=tuple(no_nan),
        dicts_version=next_dicts_version(),
        region_id=table.region_id,
    )


def catch_up_grid_table(table: GridTable, region, new_metas, mesh=None):
    """Incremental grid build: extend a resident grid with freshly FLUSHED
    SSTs instead of re-reading the whole region.

    Only rows strictly after the resident coverage are read — the
    resident max timestamp bounds a ``ts_range`` that read_sst turns into
    Parquet row-group pruning, so a flushed file whose rows are already
    resident (they arrived via the append-log extend path) costs a footer
    read, not a full decode.  New cells scatter into the resident tensors
    device-side, per part in sequence order (keep-max-seq).

    Returns the extended GridTable, the SAME table when the new files
    carry nothing beyond the resident coverage, or None when the delta
    does not fit the resident shape/step (caller rebuilds).  Safety
    preconditions — no content-mutating structure change since the build
    (``Region.mutation_epoch`` unchanged), old SST set intact, memtable
    and append log empty — are enforced by the cache manager
    (storage/cache.py get_grid).
    """
    from greptimedb_tpu.storage.scan import (
        estimate_staging_bytes, prefetch_store, read_parts,
    )
    from greptimedb_tpu.storage.sst import read_sst

    fields = table.field_names
    if tuple(grid_float_fields(region.schema)) != tuple(fields):
        return None
    if region.num_series > table.spad:
        return None
    step = table.step
    if step <= 0:
        return None
    ts_name = region.ts_name
    lo = table.ts0 + (table.nt - 1) * step + 1  # strictly after resident
    want = [ts_name, TSID, SEQ, OP] + list(fields)
    metas = [
        m for m in sorted(new_metas, key=lambda m: m.seq_max)
        if m.ts_max >= lo
    ]
    prefetch_store(region.store, metas)
    est = estimate_staging_bytes(metas, len(want), (lo, None))
    try:
        parts = read_parts(
            [
                (lambda m=m: read_sst(region.store, m, region.schema,
                                      (lo, None), columns=want))
                for m in metas
            ],
            memory=getattr(region, "memory", None), est_bytes=est,
        )
    except SstCorruption as e:
        # quarantine/repair changes the SST set out from under this
        # incremental pass — hand back None so the cache does a full
        # (verified, corruption-retrying) rebuild instead
        region._handle_sst_corruption(e)
        return None
    parts = [p for p in parts if len(p[TSID])]
    if not parts:
        return table  # fully resident already (flush of consumed appends)
    all_ts = np.concatenate(
        [p[ts_name].astype(np.int64) for p in parts])
    rel = all_ts - table.ts0
    if bool((rel % step != 0).any()):
        return None  # off-grid timestamps: sampling changed
    new_nt = int(rel.max()) // step + 1
    values, valid = table.values, table.valid
    if new_nt > table.tpad:
        grown = _grow_time_axis(values, valid, table.tpad, new_nt,
                                table.spad, len(fields))
        if grown is None:
            return None
        values, valid = grown
    no_nan = list(table.no_nan)
    for p in parts:
        tsid = p[TSID].astype(np.int64)
        tidx = (p[ts_name].astype(np.int64) - table.ts0) // step
        op = p[OP]
        dels = op == OP_DELETE
        any_dels = bool(dels.any())
        cols = []
        for ci, name in enumerate(fields):
            col = p[name]
            if col.dtype != np.float32:
                col = col.astype(np.float32)
            if any_dels:
                col = np.where(dels, np.float32(0.0), col)
            if no_nan[ci] and not bool(np.isfinite(col).all()):
                no_nan[ci] = False
            cols.append(col)
        delta = np.stack(cols, axis=0)  # [C, n]
        ji, jj = jnp.asarray(tsid), jnp.asarray(tidx)
        values = values.at[:, ji, jj].set(jnp.asarray(delta))
        valid = valid.at[ji, jj].set(jnp.asarray(~dels))
    tag_codes = table.tag_codes
    if region.num_series > table.num_series:
        host_tags = _series_tag_matrix(region, table.spad)
        sh = grid_shardings(mesh, table.spad)
        tag_codes = {
            k: _to_device_rows(v, sh and sh["tags"])
            for k, v in host_tags.items()
        }
    from greptimedb_tpu.storage.cache import next_dicts_version

    return GridTable(
        values=values,
        valid=valid,
        tag_codes=tag_codes,
        ts0=table.ts0,
        step=step,
        nt=max(table.nt, new_nt),
        num_series=region.num_series,
        field_names=fields,
        dicts={name: region.encoders[name].values()
               for name in region.tag_names},
        no_nan=tuple(no_nan),
        dicts_version=next_dicts_version(),
        region_id=table.region_id,
    )
