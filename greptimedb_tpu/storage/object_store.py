"""Object store abstraction (reference: src/object-store over OpenDAL).

Only the operations the engine needs: atomic write, read, list, delete,
rename.  ``FsObjectStore`` is the local-disk backend; the interface is
narrow enough that an S3/GCS backend is a drop-in (multipart +
rename-free atomic write via temp object + copy).

Durability discipline (ISSUE 9): every FsObjectStore write is temp file
→ write → fsync → rename → parent-directory fsync, so a power loss
after ``write`` returns can lose neither the bytes nor the rename.  The
local-disk chaos points (``fs.write`` torn/bitflip, ``fs.fsync``) hook
this path with the zero-overhead-disabled ``CHAOS.enabled`` guard.
"""

from __future__ import annotations

import hashlib
import os
import tempfile
import threading

from greptimedb_tpu.errors import FencedError
from greptimedb_tpu.utils.chaos import CHAOS


def content_etag(data: bytes) -> str:
    """ETag of an object's content — md5 hex, matching what S3 returns
    for single-part PUTs, so the same token compares across backends."""
    return hashlib.md5(data).hexdigest()


class ObjectStore:
    def write(self, path: str, data: bytes) -> None:
        raise NotImplementedError

    # ---- conditional put (epoch fencing, ISSUE 15) --------------------
    # ``write_if`` is the fenced write surface: exactly one of
    # ``if_none_match`` (create-only: fail if the object exists — how two
    # split-brain leaders racing on one delta version resolve to ONE
    # winner) or ``if_match=<etag>`` (replace-only-if-unchanged: how an
    # epoch marker advances without clobbering a newer claim).  A lost
    # CAS raises FencedError; the caller must treat it as a fencing
    # event, never retry into a plain write.
    def write_if(self, path: str, data: bytes, *,
                 if_match: str | None = None,
                 if_none_match: bool = False) -> None:
        raise NotImplementedError

    def head(self, path: str) -> dict | None:
        """Object metadata without the body: ``{"etag", "length"}`` or
        None when the object does not exist.  The scrubber's cache
        revalidation and the CAS surface both key off the etag."""
        raise NotImplementedError

    def read(self, path: str) -> bytes:
        raise NotImplementedError

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def list(self, prefix: str) -> list[str]:
        raise NotImplementedError

    def delete(self, path: str) -> None:
        raise NotImplementedError

    # ---- conditional delete (checkpoint GC fencing, ISSUE 18) ---------
    # ``delete_if`` is the fenced HALF of garbage collection: delete the
    # object only while its content is still the version the caller
    # decided to GC (``if_match=<etag>``).  A fenced-out zombie leader
    # replaying a stale GC plan loses the CAS (FencedError) instead of
    # destroying a newer leader's checkpoint; the caller must treat the
    # loss as a fencing event, never retry into a plain delete.
    def delete_if(self, path: str, *, if_match: str) -> None:
        raise NotImplementedError

    def rename(self, src: str, dst: str) -> None:
        """Move an object (quarantine uses this: bytes must be PRESERVED
        under the new name, never deleted).  Default is copy+delete —
        fine for remote backends; disk backends override with a real
        rename."""
        self.write(dst, self.read(src))
        self.delete(src)

    def local_path(self, path: str) -> str | None:
        """Filesystem path if this store is disk-backed (lets pyarrow mmap),
        else None and callers fall back to read()."""
        return None

    def last_modified(self, path: str) -> float | None:
        """Store-level modification time (epoch seconds), or None when the
        backend cannot tell.  GC grace periods rely on this — never on
        cache-file mtimes."""
        return None

    def prefetch(self, paths: list[str]) -> int:
        """Scan-driven readahead hint: start pulling these objects toward
        local storage in the background so the decode pool finds them
        warm (storage/scan.py prefetch_store).  Returns the number of
        fetches actually queued; disk/memory backends have nothing to
        warm and return 0."""
        return 0


def _fsync_dir(path: str) -> None:
    """fsync a directory so a rename inside it survives power loss (the
    half of atomic-replace durability os.replace alone does not give)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return  # platforms without directory fds: best effort
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


# CAS serialization for disk-backed stores: one lock per REAL root path,
# shared by every FsObjectStore instance over that root in this process
# (two engines sharing a data home — the split-brain test shape — must
# contend on one lock, not two instance locks).
_FS_CAS_LOCKS: dict[str, threading.Lock] = {}
_FS_CAS_LOCKS_GUARD = threading.Lock()


def _cas_lock_for(root: str) -> threading.Lock:
    key = os.path.realpath(root)
    with _FS_CAS_LOCKS_GUARD:
        lock = _FS_CAS_LOCKS.get(key)
        if lock is None:
            lock = _FS_CAS_LOCKS[key] = threading.Lock()
        return lock


class FsObjectStore(ObjectStore):
    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)
        self._cas_lock = _cas_lock_for(self.root)

    def _abs(self, path: str) -> str:
        p = os.path.normpath(os.path.join(self.root, path.lstrip("/")))
        # commonpath, not startswith: '../rootB' must not pass for root
        # '/x/root' just because the string prefix matches
        if os.path.commonpath([p, self.root]) != self.root:
            raise ValueError(f"path escapes store root: {path}")
        return p

    def write(self, path: str, data: bytes) -> None:
        p = self._abs(path)
        os.makedirs(os.path.dirname(p), exist_ok=True)
        after = None
        if CHAOS.enabled:  # disk fault injection (zero-overhead disabled)
            data, after = CHAOS.filter_io("fs.write", data)
        # atomic: temp file + fsync + rename + parent dir fsync
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(p))
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(data)
                f.flush()
                if after is not None:
                    raise after  # torn write: prefix persisted, then die
                if CHAOS.enabled:
                    CHAOS.inject("fs.fsync")
                os.fsync(f.fileno())
            os.replace(tmp, p)
            # the rename itself must be durable: fsync the parent dir,
            # or a power loss can roll the directory entry back to the
            # old (or no) file even though write() returned success
            if CHAOS.enabled:
                CHAOS.inject("fs.fsync")
            _fsync_dir(os.path.dirname(p))
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise

    def write_if(self, path: str, data: bytes, *,
                 if_match: str | None = None,
                 if_none_match: bool = False) -> None:
        if if_none_match == (if_match is not None):
            raise ValueError("write_if needs exactly one of "
                             "if_match / if_none_match")
        p = self._abs(path)
        with self._cas_lock:
            exists = os.path.exists(p)
            if if_none_match:
                if exists:
                    raise FencedError(
                        f"conditional put lost: {path} already exists")
            else:
                if not exists:
                    raise FencedError(
                        f"conditional put lost: {path} is gone "
                        f"(expected etag {if_match})")
                with open(p, "rb") as f:
                    cur = content_etag(f.read())
                if cur != if_match:
                    raise FencedError(
                        f"conditional put lost: {path} etag {cur} != "
                        f"expected {if_match}")
            self.write(path, data)

    def head(self, path: str) -> dict | None:
        p = self._abs(path)
        try:
            with open(p, "rb") as f:
                data = f.read()
        except OSError:
            return None
        return {"etag": content_etag(data), "length": len(data)}

    def read(self, path: str) -> bytes:
        with open(self._abs(path), "rb") as f:
            return f.read()

    def exists(self, path: str) -> bool:
        return os.path.exists(self._abs(path))

    def list(self, prefix: str) -> list[str]:
        base = self._abs(prefix)
        if not os.path.isdir(base):
            return []
        out = []
        for dirpath, _dirs, files in os.walk(base):
            for fn in files:
                full = os.path.join(dirpath, fn)
                out.append(os.path.relpath(full, self.root))
        return sorted(out)

    def delete(self, path: str) -> None:
        p = self._abs(path)
        if os.path.exists(p):
            os.unlink(p)

    def delete_if(self, path: str, *, if_match: str) -> None:
        p = self._abs(path)
        with self._cas_lock:  # CAS check + unlink are atomic per root
            try:
                with open(p, "rb") as f:
                    cur = content_etag(f.read())
            except OSError:
                raise FencedError(
                    f"conditional delete lost: {path} is gone "
                    f"(expected etag {if_match})") from None
            if cur != if_match:
                raise FencedError(
                    f"conditional delete lost: {path} etag {cur} != "
                    f"expected {if_match}")
            os.unlink(p)

    def rename(self, src: str, dst: str) -> None:
        s, d = self._abs(src), self._abs(dst)
        os.makedirs(os.path.dirname(d), exist_ok=True)
        os.replace(s, d)
        _fsync_dir(os.path.dirname(d))
        if os.path.dirname(s) != os.path.dirname(d):
            _fsync_dir(os.path.dirname(s))

    def last_modified(self, path: str) -> float | None:
        try:
            return os.path.getmtime(self._abs(path))
        except OSError:
            return None

    def local_path(self, path: str) -> str | None:
        return self._abs(path)


class MemoryObjectStore(ObjectStore):
    """In-memory backend for tests (reference uses OpenDAL's memory service
    the same way, src/object-store/Cargo.toml:12)."""

    def __init__(self):
        self._data: dict[str, bytes] = {}
        self._cas_lock = threading.Lock()

    def write(self, path: str, data: bytes) -> None:
        self._data[path.lstrip("/")] = bytes(data)

    def write_if(self, path: str, data: bytes, *,
                 if_match: str | None = None,
                 if_none_match: bool = False) -> None:
        if if_none_match == (if_match is not None):
            raise ValueError("write_if needs exactly one of "
                             "if_match / if_none_match")
        key = path.lstrip("/")
        with self._cas_lock:
            cur = self._data.get(key)
            if if_none_match:
                if cur is not None:
                    raise FencedError(
                        f"conditional put lost: {path} already exists")
            else:
                if cur is None:
                    raise FencedError(
                        f"conditional put lost: {path} is gone "
                        f"(expected etag {if_match})")
                got = content_etag(cur)
                if got != if_match:
                    raise FencedError(
                        f"conditional put lost: {path} etag {got} != "
                        f"expected {if_match}")
            self._data[key] = bytes(data)

    def head(self, path: str) -> dict | None:
        data = self._data.get(path.lstrip("/"))
        if data is None:
            return None
        return {"etag": content_etag(data), "length": len(data)}

    def read(self, path: str) -> bytes:
        return self._data[path.lstrip("/")]

    def exists(self, path: str) -> bool:
        return path.lstrip("/") in self._data

    def list(self, prefix: str) -> list[str]:
        # directory semantics, matching FsObjectStore: prefix "r1" must
        # not match "r10/..." — a bare prefix only matches itself or
        # paths under "r1/" (manifest/GC listings must not bleed across
        # regions whose ids share a decimal prefix)
        p = prefix.lstrip("/")
        if not p or p.endswith("/"):
            return sorted(k for k in self._data if k.startswith(p))
        return sorted(k for k in self._data
                      if k == p or k.startswith(p + "/"))

    def delete(self, path: str) -> None:
        self._data.pop(path.lstrip("/"), None)

    def delete_if(self, path: str, *, if_match: str) -> None:
        key = path.lstrip("/")
        with self._cas_lock:
            cur = self._data.get(key)
            if cur is None:
                raise FencedError(
                    f"conditional delete lost: {path} is gone "
                    f"(expected etag {if_match})")
            if content_etag(cur) != if_match:
                raise FencedError(
                    f"conditional delete lost: {path} etag "
                    f"{content_etag(cur)} != expected {if_match}")
            del self._data[key]

    def rename(self, src: str, dst: str) -> None:
        self._data[dst.lstrip("/")] = self._data.pop(src.lstrip("/"))
