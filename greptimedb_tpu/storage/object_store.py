"""Object store abstraction (reference: src/object-store over OpenDAL).

Only the operations the engine needs: atomic write, read, list, delete,
rename.  ``FsObjectStore`` is the local-disk backend; the interface is
narrow enough that an S3/GCS backend is a drop-in (multipart +
rename-free atomic write via temp object + copy).

Durability discipline (ISSUE 9): every FsObjectStore write is temp file
→ write → fsync → rename → parent-directory fsync, so a power loss
after ``write`` returns can lose neither the bytes nor the rename.  The
local-disk chaos points (``fs.write`` torn/bitflip, ``fs.fsync``) hook
this path with the zero-overhead-disabled ``CHAOS.enabled`` guard.
"""

from __future__ import annotations

import os
import tempfile

from greptimedb_tpu.utils.chaos import CHAOS


class ObjectStore:
    def write(self, path: str, data: bytes) -> None:
        raise NotImplementedError

    def read(self, path: str) -> bytes:
        raise NotImplementedError

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def list(self, prefix: str) -> list[str]:
        raise NotImplementedError

    def delete(self, path: str) -> None:
        raise NotImplementedError

    def rename(self, src: str, dst: str) -> None:
        """Move an object (quarantine uses this: bytes must be PRESERVED
        under the new name, never deleted).  Default is copy+delete —
        fine for remote backends; disk backends override with a real
        rename."""
        self.write(dst, self.read(src))
        self.delete(src)

    def local_path(self, path: str) -> str | None:
        """Filesystem path if this store is disk-backed (lets pyarrow mmap),
        else None and callers fall back to read()."""
        return None

    def last_modified(self, path: str) -> float | None:
        """Store-level modification time (epoch seconds), or None when the
        backend cannot tell.  GC grace periods rely on this — never on
        cache-file mtimes."""
        return None

    def prefetch(self, paths: list[str]) -> int:
        """Scan-driven readahead hint: start pulling these objects toward
        local storage in the background so the decode pool finds them
        warm (storage/scan.py prefetch_store).  Returns the number of
        fetches actually queued; disk/memory backends have nothing to
        warm and return 0."""
        return 0


def _fsync_dir(path: str) -> None:
    """fsync a directory so a rename inside it survives power loss (the
    half of atomic-replace durability os.replace alone does not give)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return  # platforms without directory fds: best effort
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


class FsObjectStore(ObjectStore):
    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)

    def _abs(self, path: str) -> str:
        p = os.path.normpath(os.path.join(self.root, path.lstrip("/")))
        # commonpath, not startswith: '../rootB' must not pass for root
        # '/x/root' just because the string prefix matches
        if os.path.commonpath([p, self.root]) != self.root:
            raise ValueError(f"path escapes store root: {path}")
        return p

    def write(self, path: str, data: bytes) -> None:
        p = self._abs(path)
        os.makedirs(os.path.dirname(p), exist_ok=True)
        after = None
        if CHAOS.enabled:  # disk fault injection (zero-overhead disabled)
            data, after = CHAOS.filter_io("fs.write", data)
        # atomic: temp file + fsync + rename + parent dir fsync
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(p))
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(data)
                f.flush()
                if after is not None:
                    raise after  # torn write: prefix persisted, then die
                if CHAOS.enabled:
                    CHAOS.inject("fs.fsync")
                os.fsync(f.fileno())
            os.replace(tmp, p)
            # the rename itself must be durable: fsync the parent dir,
            # or a power loss can roll the directory entry back to the
            # old (or no) file even though write() returned success
            if CHAOS.enabled:
                CHAOS.inject("fs.fsync")
            _fsync_dir(os.path.dirname(p))
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise

    def read(self, path: str) -> bytes:
        with open(self._abs(path), "rb") as f:
            return f.read()

    def exists(self, path: str) -> bool:
        return os.path.exists(self._abs(path))

    def list(self, prefix: str) -> list[str]:
        base = self._abs(prefix)
        if not os.path.isdir(base):
            return []
        out = []
        for dirpath, _dirs, files in os.walk(base):
            for fn in files:
                full = os.path.join(dirpath, fn)
                out.append(os.path.relpath(full, self.root))
        return sorted(out)

    def delete(self, path: str) -> None:
        p = self._abs(path)
        if os.path.exists(p):
            os.unlink(p)

    def rename(self, src: str, dst: str) -> None:
        s, d = self._abs(src), self._abs(dst)
        os.makedirs(os.path.dirname(d), exist_ok=True)
        os.replace(s, d)
        _fsync_dir(os.path.dirname(d))
        if os.path.dirname(s) != os.path.dirname(d):
            _fsync_dir(os.path.dirname(s))

    def last_modified(self, path: str) -> float | None:
        try:
            return os.path.getmtime(self._abs(path))
        except OSError:
            return None

    def local_path(self, path: str) -> str | None:
        return self._abs(path)


class MemoryObjectStore(ObjectStore):
    """In-memory backend for tests (reference uses OpenDAL's memory service
    the same way, src/object-store/Cargo.toml:12)."""

    def __init__(self):
        self._data: dict[str, bytes] = {}

    def write(self, path: str, data: bytes) -> None:
        self._data[path.lstrip("/")] = bytes(data)

    def read(self, path: str) -> bytes:
        return self._data[path.lstrip("/")]

    def exists(self, path: str) -> bool:
        return path.lstrip("/") in self._data

    def list(self, prefix: str) -> list[str]:
        # directory semantics, matching FsObjectStore: prefix "r1" must
        # not match "r10/..." — a bare prefix only matches itself or
        # paths under "r1/" (manifest/GC listings must not bleed across
        # regions whose ids share a decimal prefix)
        p = prefix.lstrip("/")
        if not p or p.endswith("/"):
            return sorted(k for k in self._data if k.startswith(p))
        return sorted(k for k in self._data
                      if k == p or k.startswith(p + "/"))

    def delete(self, path: str) -> None:
        self._data.pop(path.lstrip("/"), None)

    def rename(self, src: str, dst: str) -> None:
        self._data[dst.lstrip("/")] = self._data.pop(src.lstrip("/"))
