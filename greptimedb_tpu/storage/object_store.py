"""Object store abstraction (reference: src/object-store over OpenDAL).

Only the operations the engine needs: atomic write, read, list, delete.
``FsObjectStore`` is the local-disk backend; the interface is narrow enough
that an S3/GCS backend is a drop-in (multipart + rename-free atomic write
via temp object + copy).
"""

from __future__ import annotations

import os
import tempfile


class ObjectStore:
    def write(self, path: str, data: bytes) -> None:
        raise NotImplementedError

    def read(self, path: str) -> bytes:
        raise NotImplementedError

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def list(self, prefix: str) -> list[str]:
        raise NotImplementedError

    def delete(self, path: str) -> None:
        raise NotImplementedError

    def local_path(self, path: str) -> str | None:
        """Filesystem path if this store is disk-backed (lets pyarrow mmap),
        else None and callers fall back to read()."""
        return None

    def last_modified(self, path: str) -> float | None:
        """Store-level modification time (epoch seconds), or None when the
        backend cannot tell.  GC grace periods rely on this — never on
        cache-file mtimes."""
        return None

    def prefetch(self, paths: list[str]) -> int:
        """Scan-driven readahead hint: start pulling these objects toward
        local storage in the background so the decode pool finds them
        warm (storage/scan.py prefetch_store).  Returns the number of
        fetches actually queued; disk/memory backends have nothing to
        warm and return 0."""
        return 0


class FsObjectStore(ObjectStore):
    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)

    def _abs(self, path: str) -> str:
        p = os.path.normpath(os.path.join(self.root, path.lstrip("/")))
        # commonpath, not startswith: '../rootB' must not pass for root
        # '/x/root' just because the string prefix matches
        if os.path.commonpath([p, self.root]) != self.root:
            raise ValueError(f"path escapes store root: {path}")
        return p

    def write(self, path: str, data: bytes) -> None:
        p = self._abs(path)
        os.makedirs(os.path.dirname(p), exist_ok=True)
        # atomic: temp file + rename
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(p))
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(data)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, p)
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise

    def read(self, path: str) -> bytes:
        with open(self._abs(path), "rb") as f:
            return f.read()

    def exists(self, path: str) -> bool:
        return os.path.exists(self._abs(path))

    def list(self, prefix: str) -> list[str]:
        base = self._abs(prefix)
        if not os.path.isdir(base):
            return []
        out = []
        for dirpath, _dirs, files in os.walk(base):
            for fn in files:
                full = os.path.join(dirpath, fn)
                out.append(os.path.relpath(full, self.root))
        return sorted(out)

    def delete(self, path: str) -> None:
        p = self._abs(path)
        if os.path.exists(p):
            os.unlink(p)

    def last_modified(self, path: str) -> float | None:
        try:
            return os.path.getmtime(self._abs(path))
        except OSError:
            return None

    def local_path(self, path: str) -> str | None:
        return self._abs(path)


class MemoryObjectStore(ObjectStore):
    """In-memory backend for tests (reference uses OpenDAL's memory service
    the same way, src/object-store/Cargo.toml:12)."""

    def __init__(self):
        self._data: dict[str, bytes] = {}

    def write(self, path: str, data: bytes) -> None:
        self._data[path.lstrip("/")] = bytes(data)

    def read(self, path: str) -> bytes:
        return self._data[path.lstrip("/")]

    def exists(self, path: str) -> bool:
        return path.lstrip("/") in self._data

    def list(self, prefix: str) -> list[str]:
        p = prefix.lstrip("/")
        return sorted(k for k in self._data if k.startswith(p))

    def delete(self, path: str) -> None:
        self._data.pop(path.lstrip("/"), None)
