"""Storage engine: the mito2-equivalent region store, CPU-side by design.

Parquet/WAL/manifest are I/O-bound — they stay host code (SURVEY.md §7.1
"storage stays CPU-side"); the engine's job is to land query-ready columnar
data in TPU HBM fast. Layout per region:

    <data_home>/<region_id>/
        wal/          segmented write-ahead log (replayed on open)
        sst/          Parquet files, time-sorted within series
        manifest/     action log + checkpoints (schema, SST list, dicts)

Write path (reference src/mito2/src/worker/handle_write.rs): WAL append →
memtable insert; flush freezes the memtable into a sorted, deduped Parquet
SST and records a manifest edit. Read path (reference scan_region.rs):
prune SSTs by time range → merge with memtable → dedup by (series, ts, seq)
→ upload to the device-resident RegionCache consumed by the query engine.
"""

from greptimedb_tpu.storage.region import RegionEngine, Region, RegionOptions

__all__ = ["RegionEngine", "Region", "RegionOptions"]
