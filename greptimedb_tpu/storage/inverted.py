"""Inverted index: tag term dictionaries + posting lists.

Reference: src/index/src/inverted_index/ (FST term dictionary + roaring
bitmaps per SST, RFC docs/rfcs/2023-11-03-inverted-index.md).  The TPU
build keeps all indexing host-side (pruning is control logic; the device
only ever sees the post-prune numeric tensors) and exploits a structural
advantage the reference lacks: every region already dictionary-encodes
tags into dense codes with a series registry (tsid -> code tuple), so

- the TERM DICTIONARY is the region's per-column encoder vocabulary, and
- POSTING LISTS are "code -> sorted tsid array", derivable in one argsort.

Matcher evaluation (equality, regex, negations) then costs O(vocabulary)
string work instead of O(series): a regex runs once per DISTINCT term and
the matching posting lists concatenate into the selected tsid set.  This
is what makes 1M–10M-series PromQL label matching feasible (round-1
weakness: Python re.fullmatch per series).
"""

from __future__ import annotations

from typing import Callable, Iterable

import numpy as np


class SeriesInvertedIndex:
    """Per-region (or combined-view) inverted index over the series
    registry.  Build cost: one argsort per tag column; cached on the
    region object keyed by generation (see ``get_series_index``)."""

    def __init__(self, tag_names: list[str], series_codes: list[tuple],
                 vocabs: dict[str, list[str]],
                 raw_values: dict[str, list] | None = None):
        self.tag_names = list(tag_names)
        self.vocabs = vocabs  # column -> term list (code == list index)
        # column -> RAW encoder values (labels decode to these, not the
        # str-coerced matcher terms); ONE copy per region registry
        # version, shared by every selection built against it
        self.raw_values = raw_values if raw_values is not None else vocabs
        n = len(series_codes)
        self.num_series = n
        # tsid t has codes self.codes[c][t]
        self.codes: dict[str, np.ndarray] = {}
        # posting lists: for column c, tsids sorted by code with offsets
        # per code: tsids_of(c, code) = postings[c][starts[code]:starts[code+1]]
        self.postings: dict[str, np.ndarray] = {}
        self.offsets: dict[str, np.ndarray] = {}
        key_arr = np.asarray([k for k, _t in series_codes], dtype=np.int64)
        tsid_arr = np.asarray([t for _k, t in series_codes], dtype=np.int64)
        for j, name in enumerate(self.tag_names):
            col = key_arr[:, j] if n else np.zeros(0, dtype=np.int64)
            self.codes[name] = np.zeros(
                int(tsid_arr.max()) + 1 if n else 0, dtype=np.int64
            )
            if n:
                self.codes[name][tsid_arr] = col
            order = np.argsort(col, kind="stable")
            self.postings[name] = tsid_arr[order]
            v = len(vocabs.get(name, []))
            # offsets[i] = first posting position of code i
            self.offsets[name] = np.searchsorted(
                col[order], np.arange(v + 1)
            )
        self.all_tsids = np.sort(tsid_arr)

    # ---- term-level ----------------------------------------------------
    def matching_codes(self, column: str,
                       pred: Callable[[str], bool]) -> np.ndarray:
        """Codes whose TERM satisfies pred — O(vocabulary) string work."""
        vocab = self.vocabs.get(column, [])
        return np.asarray(
            [i for i, term in enumerate(vocab) if pred(term)],
            dtype=np.int64,
        )

    def postings_for_codes(self, column: str,
                           codes: Iterable[int]) -> np.ndarray:
        """Union of posting lists for the given codes (sorted tsids)."""
        post = self.postings[column]
        offs = self.offsets[column]
        parts = [
            post[offs[c]:offs[c + 1]]
            for c in codes
            if 0 <= c < len(offs) - 1
        ]
        if not parts:
            return np.zeros(0, dtype=np.int64)
        return np.sort(np.concatenate(parts))

    # ---- vectorized code access (PromQL grouping) ----------------------
    def codes_for(self, column: str, tsids: np.ndarray) -> np.ndarray:
        """Dictionary codes of ``column`` for a tsid vector — one fancy-
        index gather, no per-series Python work.  Unknown columns yield
        all -1 (the "missing label" sentinel the callers already treat as
        out-of-vocabulary)."""
        col = self.codes.get(column)
        if col is None:
            return np.full(len(tsids), -1, dtype=np.int64)
        return col[tsids]

    def canonical_codes(self, column: str,
                        merge_missing_empty: bool) -> tuple[np.ndarray, int]:
        """code → canonical-term id remap for grouping: terms with equal
        ``str()`` collapse to one id (PromQL group keys are string-level),
        and the MISSING sentinel (index = vocabulary size) either merges
        with the empty-string term (``by`` semantics: absent label prints
        as "") or stays distinct (``without`` semantics: an absent label
        is omitted from the key, distinguishable from a present "").
        Returns (remap array of length vocab+1, number of canonical ids).
        """
        vocab = self.vocabs.get(column, [])
        terms = list(vocab)
        if merge_missing_empty:
            terms.append("")
        uniq, inv = (np.unique(np.asarray(terms, dtype=object),
                               return_inverse=True)
                     if terms else (np.zeros(0, object),
                                    np.zeros(0, np.int64)))
        n = len(uniq)
        remap = np.empty(len(vocab) + 1, dtype=np.int64)
        remap[:len(vocab)] = inv[:len(vocab)]
        if merge_missing_empty:
            remap[len(vocab)] = inv[len(vocab)]
        else:
            remap[len(vocab)] = n
            n += 1
        return remap, n

    # ---- matcher-level -------------------------------------------------
    def select(self, column: str, pred: Callable[[str], bool],
               negate: bool = False) -> np.ndarray:
        """Sorted tsids whose term for ``column`` satisfies pred."""
        if column not in self.postings:
            # label absent from the schema: every series has the empty
            # value; the predicate decides all-or-nothing
            keep = pred("")
            if negate:
                keep = not keep
            return self.all_tsids if keep else np.zeros(0, dtype=np.int64)
        codes = self.matching_codes(column, pred)
        tsids = self.postings_for_codes(column, codes)
        if negate:
            return np.setdiff1d(self.all_tsids, tsids, assume_unique=True)
        return tsids


def get_series_index(region) -> SeriesInvertedIndex:
    """Series-registry-cached index for a Region / CombinedRegionView
    duck: keyed on ``series_generation`` (registry version) when the
    region exposes it, so pure data appends of existing series don't pay
    an O(series) index rebuild per write — only registry growth or
    structure changes do."""
    _ = region.num_series  # CombinedRegionView: force a registry refresh
    gen = getattr(region, "series_generation", None)
    if gen is None:
        gen = region.generation
    cached = getattr(region, "_series_inv_cache", None)
    if cached is not None and cached[0] == gen:
        return cached[1]
    series_codes = sorted(region._series.items(), key=lambda kv: kv[1])
    # str-coerce: non-string tag columns store raw values in the encoder,
    # but matcher predicates (regex) are defined over strings; the raw
    # lists ride along for label decoding (one copy per registry version)
    raw_values = {
        name: region.encoders[name].values() for name in region.tag_names
    }
    vocabs = {
        name: [str(v) for v in raw_values[name]]
        for name in region.tag_names
    }
    idx = SeriesInvertedIndex(region.tag_names, series_codes, vocabs,
                              raw_values)
    try:
        region._series_inv_cache = (gen, idx)
    except AttributeError:
        pass  # slots/immutable duck: skip caching
    return idx
