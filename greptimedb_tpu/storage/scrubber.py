"""Online background integrity scrubber: find bit rot BEFORE a read does.

ISSUE 15 tentpole, closing the proactive half of the PR-9 crash-
consistency story (reference analogs: mito2's region scanner +
compaction-time verification, the scrub/repair half of every serious
LSM deployment).  PR 9 made every durability layer *verify on read* —
but verification-on-read finds a flipped bit only when a query finally
needs the data, which for cold SSTs may be months after the rot landed
and long after the repair donors (follower replicas, WAL coverage) have
moved on.  The scrubber walks every durable artifact on a low-priority
loop and routes findings into the EXISTING quarantine/repair machinery
while repair is still cheap:

====================  ================================================
artifact              verify / repair route
====================  ================================================
cold SSTs             full checksummed decode (``verify_sst_bytes``) →
                      ``Region._handle_sst_corruption`` (quarantine +
                      replica/WAL repair, or serve-around)
manifest files        GTM1 CRC envelope check → quarantine + forced
                      verified checkpoint (``Region.scrub_manifest``)
WAL segments          record-level scan incl. tail rot →
                      resync-from-source or flush-cover
                      (``Region.scrub_wal``; zero acked loss — the
                      memtable still holds every acked row)
grid snapshots        meta/tensor parseability → quarantine the
                      snapshot (restore falls back to the SST build)
S3 read cache         remote HEAD ETag/length revalidation → evict
                      stale entries (another node replaced/deleted the
                      object)
====================  ================================================

Scheduling: the scrubber is an idle-capacity consumer of the PR-7
scheduler (``add_idle_hook``) — a tick runs only when a worker finds no
queued query, does a bounded ``GREPTIME_SCRUB_BATCH`` of items, and
**preempts itself** whenever interactive queries are waiting
(``serving.scheduler.interactive_waiting``), composing with the scan
pool's ``background_yield_hook`` narrowing.  Sweeps repeat every
``GREPTIME_SCRUB_INTERVAL_S``; the per-sweep cursor persists
(``scrub/cursor.json`` in the object store) so a restart resumes
mid-sweep instead of re-verifying from zero.

The ``scrub.read`` chaos point fires per item, so the chaos tier can
error/kill mid-sweep and pin that a half-finished scrub never makes
anything worse.
"""

from __future__ import annotations

import json
import os
import threading
import time

from greptimedb_tpu.utils.chaos import CHAOS
from greptimedb_tpu.utils.telemetry import REGISTRY

M_SCRUB_ITEMS = REGISTRY.counter(
    "greptime_scrub_items_total",
    "Artifacts verified by the background scrubber",
    labels=("kind", "outcome"),
)
M_SCRUB_SWEEPS = REGISTRY.counter(
    "greptime_scrub_sweeps_total",
    "Completed full scrub sweeps",
)
M_SCRUB_YIELD = REGISTRY.counter(
    "greptime_scrub_yield_total",
    "Scrub ticks skipped because interactive queries were waiting",
)
M_SCRUB_LAST = REGISTRY.gauge(
    "greptime_scrub_last_sweep_unixtime",
    "Completion time of the last full scrub sweep",
)

_CURSOR_EVERY = 8  # persist the cursor every N items (and at sweep end)


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


class Scrubber:
    """One engine's background integrity sweep (see module docstring)."""

    def __init__(self, engine, *, interval_s: float | None = None,
                 batch: int | None = None,
                 snapshot_dirs: "tuple[str, ...] | list[str]" = (),
                 should_yield=None):
        self.engine = engine
        self.interval_s = (
            _env_float("GREPTIME_SCRUB_INTERVAL_S", 300.0)
            if interval_s is None else float(interval_s))
        self.batch = (int(os.environ.get("GREPTIME_SCRUB_BATCH", "4") or 4)
                      if batch is None else int(batch))
        self.snapshot_dirs = tuple(snapshot_dirs)
        self._should_yield = should_yield
        self._lock = threading.Lock()  # one scrub step at a time
        self._work = None              # active sweep iterator
        self._index = 0                # items consumed this sweep
        self._next_sweep = 0.0         # monotonic; first sweep is due now
        self._resume_skip = 0
        self._aborted = False          # last _step hit an enumeration race
        # mid-ITEM preemption (ISSUE 18 satellite): a large SST verifies
        # row group by row group; when interactive queries arrive between
        # groups the partially-drained verify generator stashes here and
        # the item re-enters on the next idle tick — the verify resumes
        # where it left off instead of restarting the whole decode
        self._pending_item = None
        self._sst_gen = None           # ((region_id, file_id), generator)
        # per-INSTANCE cursor object: nodes sharing one bucket must not
        # clobber each other's sweep position (keyed by the engine's
        # data home, which is unique per node)
        import hashlib

        tag = hashlib.sha1(
            os.path.abspath(str(getattr(engine, "data_home", "")))
            .encode()).hexdigest()[:12]
        self._cursor_path = f"scrub/cursor-{tag}.json"
        # local mirrors (tests/status read without a registry scrape)
        self.sweeps = 0
        self.items = 0
        self.corrupt = 0
        self.last_sweep: dict | None = None
        self._load_cursor()

    # ---- cursor persistence -------------------------------------------
    def _load_cursor(self) -> None:
        try:
            raw = self.engine.store.read(self._cursor_path)
            cur = json.loads(raw.decode())
            self._resume_skip = max(0, int(cur.get("index", 0)))
        except Exception:  # noqa: BLE001 — absent/corrupt cursor: from 0
            self._resume_skip = 0

    def _save_cursor(self, index: "int | None") -> None:
        try:
            if index is None:
                self.engine.store.delete(self._cursor_path)
            else:
                self.engine.store.write(
                    self._cursor_path,
                    json.dumps({"index": index}).encode())
        except Exception:  # noqa: BLE001 — cursor is an optimization;
            pass           # losing it restarts the sweep, never worse

    # ---- item enumeration ---------------------------------------------
    def _items(self):
        """Deterministically ordered sweep items.  Region sets and file
        sets are snapshot per phase; an item whose artifact vanished by
        scrub time (compaction, drop) verifies as 'skipped'."""
        # list() snapshots (atomic under the GIL): regions/file dicts
        # mutate concurrently with the sweep (CREATE/DROP, flush,
        # compaction) — iterating them live would raise mid-sweep
        for rid in sorted(list(self.engine.regions)):
            yield ("manifest", rid, None)
            yield ("wal", rid, None)
            region = self.engine.regions.get(rid)
            if region is None:
                continue
            for fid in sorted(list(region.manifest.state.files)):
                yield ("sst", rid, fid)
        for snap in self.snapshot_dirs:
            if os.path.isdir(snap):
                yield ("grid_snapshot", None, snap)
        store = self.engine.store
        cache_dir = getattr(store, "cache_dir", None)
        if cache_dir and hasattr(store, "head"):
            root = os.path.abspath(cache_dir)
            for dirpath, _dirs, files in os.walk(root):
                for fn in sorted(files):
                    rel = os.path.relpath(os.path.join(dirpath, fn), root)
                    yield ("s3_cache", None, rel)

    # ---- per-kind verification ----------------------------------------
    def _scrub_item(self, item, force: bool = False) -> str:
        kind, rid, payload = item
        CHAOS.inject("scrub.read")  # chaos tier: error/kill mid-sweep
        if kind in ("manifest", "wal", "sst"):
            region = self.engine.regions.get(rid)
            if region is None:
                return "skipped"
            if kind == "manifest":
                out = region.scrub_manifest()
                return "corrupt" if out.get("corrupt") else "ok"
            if kind == "wal":
                out = region.scrub_wal()
                return "corrupt" if out.get("damage") else "ok"
            return self._scrub_sst(region, payload, force=force)
        if kind == "grid_snapshot":
            return self._scrub_snapshot(payload)
        if kind == "s3_cache":
            return self._scrub_s3_cache(payload)
        return "skipped"

    def _scrub_sst(self, region, file_id: str, *,
                   force: bool = False) -> str:
        from greptimedb_tpu.storage.durability import (
            M_CORRUPTION, SstCorruption,
        )
        from greptimedb_tpu.storage.sst import iter_verify_sst_bytes

        meta = region.manifest.state.files.get(file_id)
        if meta is None:
            self._sst_gen = None  # a stashed verify of a dead file
            return "skipped"  # compacted/dropped since enumeration
        key = (region.region_id, file_id)
        gen = None
        if self._sst_gen is not None and self._sst_gen[0] == key:
            gen = self._sst_gen[1]  # resume the stashed partial verify
        self._sst_gen = None
        if gen is None:
            try:
                data = region.store.read(meta.path)
            except Exception:  # noqa: BLE001 — a transport blip (S3 5xx
                # storm, timeout) must NOT quarantine a healthy file:
                # skip; a genuinely missing object still fails the query-
                # time verified read, routing into the same repair path
                return "error"
            gen = iter_verify_sst_bytes(data)
        ok = True
        for good in gen:
            if not good:
                ok = False
                break
            # between row groups: give way to interactive queries — the
            # half-verified generator (it holds the bytes) stashes and
            # this item re-enters on the next idle tick.  The force path
            # (run_sweep, admin tooling) never yields mid-item.
            if not force and self._yielding():
                self._sst_gen = (key, gen)
                return "pending"
        if ok:
            return "ok"
        M_CORRUPTION.labels("sst", "scrub").inc()
        # we HOLD the bytes and they fail the checksummed decode: route
        # into the PR-9 quarantine/repair machinery — exactly what a
        # query-time verified read would have triggered, months sooner
        region._handle_sst_corruption(SstCorruption(
            meta, ValueError("scrub verification failed")))
        return "corrupt"

    def _scrub_snapshot(self, path: str) -> str:
        import numpy as np

        from greptimedb_tpu.storage.durability import M_QUARANTINED

        meta_p = os.path.join(path, "meta.json")
        if not os.path.exists(meta_p):
            return "skipped"
        try:
            with open(meta_p) as f:
                json.load(f)
            np.load(os.path.join(path, "values.npy"), mmap_mode="r")
            np.load(os.path.join(path, "valid.npy"), mmap_mode="r")
            z = np.load(os.path.join(path, "tags.npz"))
            for k in z.files:  # zip-CRC-verified decompression
                z[k]
            return "ok"
        except Exception:  # noqa: BLE001 — any parse failure is rot
            # quarantine the snapshot (meta aside = restore refuses and
            # falls back to the SST build; tensors preserved for triage)
            from greptimedb_tpu.storage.object_store import _fsync_dir

            try:
                os.replace(meta_p, meta_p + ".quarantine")
                _fsync_dir(path)
                M_QUARANTINED.labels("grid_snapshot").inc()
            except OSError:
                pass
            return "corrupt"

    def _scrub_s3_cache(self, rel: str) -> str:
        store = self.engine.store
        try:
            cp = store._cache_path(rel)
        except ValueError:
            return "skipped"
        try:
            with open(cp, "rb") as f:
                data = f.read()
        except OSError:
            return "skipped"  # evicted since enumeration
        h = store.head(rel)
        if h is None:
            # no such remote object: either another node deleted it, or
            # this is a _cache_fill mkstemp temp mid-install (its random
            # name never names a remote object) — a young file gets a
            # grace period so we never unlink a live temp out from under
            # the writer's os.replace
            try:
                if time.time() - os.path.getmtime(cp) < 120.0:
                    return "skipped"
            except OSError:
                return "skipped"
        if (h is not None and h["length"] == len(data)
                and store._etag_matches(h["etag"], data)):
            return "ok"
        # remote object replaced or deleted by another node: the stale
        # local copy must never serve again (the next read refetches)
        try:
            os.unlink(cp)
        except OSError:
            pass
        return "corrupt"

    # ---- pacing --------------------------------------------------------
    def _yielding(self) -> bool:
        if self._should_yield is not None:
            return bool(self._should_yield())
        try:
            from greptimedb_tpu.serving.scheduler import interactive_waiting
        except ImportError:  # scheduler off: nothing to preempt for
            return False
        return interactive_waiting() > 0

    def tick(self) -> bool:
        """Idle-hook member (serving/scheduler.py): one bounded unit of
        background verify per idle tick; always stays hooked (True) —
        interval gating and preemption happen inside.  Staying hooked
        keeps idle workers on the scheduler's 50ms bounded wait; the
        between-sweeps cost is one monotonic comparison per tick
        (measured negligible), which beats park/re-arm machinery and
        its unhook races."""
        if self._yielding():
            M_SCRUB_YIELD.inc()
            return True
        if not self._lock.acquire(blocking=False):
            return True  # another idle worker is mid-step
        try:
            self._step()
        finally:
            self._lock.release()
        return True

    def _step(self, force: bool = False) -> None:
        if self._work is None:
            if time.monotonic() < self._next_sweep:
                return
            self._work = self._items()
            self._index = 0
            self._sweep_counts = {"items": 0, "corrupt": 0, "skipped": 0}
        done = 0
        while done < self.batch:
            if not force and self._yielding():
                M_SCRUB_YIELD.inc()
                return
            item = self._pending_item  # mid-item preemption re-entry
            if item is not None:
                self._pending_item = None  # _index already counted it
            else:
                try:
                    item = next(self._work, None)
                except Exception:  # noqa: BLE001 — enumeration racing a
                    # concurrent drop/compaction must abort THIS sweep,
                    # not unhook the scrubber forever (the idle-hook
                    # dispatcher drops members whose call raises).
                    # Aborted ≠ completed: the sweep counter/last-sweep
                    # gauge must not report a 3-of-1000-items sweep as
                    # healthy coverage, and the resume cursor survives
                    # for the retry (shortly — not a full interval away,
                    # but never a hot loop either)
                    self._work = None
                    self._aborted = True
                    self._sst_gen = None
                    self._next_sweep = time.monotonic() + min(
                        self.interval_s, 5.0)
                    return
                if item is None:
                    self._finish_sweep()
                    return
                self._index += 1
                if self._resume_skip > 0:
                    # fast-forward past items a prior process already
                    # verified this sweep (restart resumes mid-sweep)
                    self._resume_skip -= 1
                    continue
            done += 1
            try:
                outcome = self._scrub_item(item, force=force)
            except Exception:  # noqa: BLE001 — one bad item must not
                outcome = "error"  # kill the sweep (chaos tier pins this)
            if outcome == "pending":
                # preempted mid-SST: the partial verify is stashed; this
                # item re-enters first on the next idle tick.  NOT
                # counted — the item has not finished verifying.
                self._pending_item = item
                M_SCRUB_YIELD.inc()
                return
            M_SCRUB_ITEMS.labels(item[0], outcome).inc()
            self.items += 1
            self._sweep_counts["items"] += 1
            if outcome == "corrupt":
                self.corrupt += 1
                self._sweep_counts["corrupt"] += 1
            elif outcome == "skipped":
                self._sweep_counts["skipped"] += 1
            if self._index % _CURSOR_EVERY == 0:
                self._save_cursor(self._index)

    def _finish_sweep(self) -> None:
        self._work = None
        self._resume_skip = 0
        self._next_sweep = time.monotonic() + self.interval_s
        self.sweeps += 1
        self.last_sweep = dict(self._sweep_counts)
        M_SCRUB_SWEEPS.inc()
        M_SCRUB_LAST.set(time.time())
        self._save_cursor(None)

    def run_sweep(self) -> dict:
        """Synchronous full sweep (tests, admin tooling): drives _step
        until the active sweep completes, ignoring the interval gate."""
        with self._lock:
            self._next_sweep = 0.0
            if self._work is None:
                self._work = self._items()
                self._index = 0
                self._sweep_counts = {"items": 0, "corrupt": 0,
                                      "skipped": 0}
            sweeps_before = self.sweeps
            while self.sweeps == sweeps_before:
                self._next_sweep = 0.0
                self._aborted = False
                self._step(force=True)
                if self._aborted:
                    break  # enumeration race: surface the partial sweep
        return dict(self.last_sweep or {})
