"""SST layer: sorted Parquet files with pruning stats.

Equivalent of the reference's flat SST format
(src/mito2/src/sst/parquet/flat_format.rs: raw key columns + __primary_key/
__sequence/__op_type internal columns): each SST stores the table's columns
(tags dictionary-encoded by Parquet itself) plus __tsid__/__seq__/__op__,
sorted by (tsid, ts, seq). File-level stats (time range, row count, seq
range) live in the manifest for pruning; row-group stats inside the Parquet
footer give a second pruning level (reference reader.rs row-group pruning).
"""

from __future__ import annotations

import io
import uuid
from dataclasses import dataclass, field

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from greptimedb_tpu.datatypes.schema import Schema, default_fill_array
from greptimedb_tpu.storage.memtable import OP, SEQ, TSID
from greptimedb_tpu.storage.object_store import ObjectStore


@dataclass(frozen=True)
class SstMeta:
    file_id: str
    path: str
    num_rows: int
    ts_min: int
    ts_max: int
    seq_min: int
    seq_max: int
    size_bytes: int
    level: int = 0
    # column names present in the file (schema evolution: old SSTs may lack
    # later-added columns); None only for metas persisted before this field
    columns: tuple[str, ...] | None = None

    def to_dict(self) -> dict:
        d = dict(self.__dict__)
        d["columns"] = list(self.columns) if self.columns is not None else None
        return d

    @staticmethod
    def from_dict(d: dict) -> "SstMeta":
        cols = d.get("columns")
        d = dict(d)
        d["columns"] = tuple(cols) if cols is not None else None
        return SstMeta(**d)

    def overlaps(self, ts_start: int | None, ts_end: int | None) -> bool:
        """Half-open [ts_start, ts_end) vs this file's closed [min,max]."""
        if ts_start is not None and self.ts_max < ts_start:
            return False
        if ts_end is not None and self.ts_min >= ts_end:
            return False
        return True


def _arrow_schema(schema: Schema) -> pa.Schema:
    fields = []
    for c in schema:
        f = c.to_arrow()
        if c.is_tag and pa.types.is_string(f.type):
            f = pa.field(f.name, pa.dictionary(pa.int32(), pa.utf8()), nullable=f.nullable)
        fields.append(f)
    fields.append(pa.field(TSID, pa.int64(), nullable=False))
    fields.append(pa.field(SEQ, pa.int64(), nullable=False))
    fields.append(pa.field(OP, pa.int8(), nullable=False))
    return pa.schema(fields)


def write_sst(
    store: ObjectStore,
    sst_dir: str,
    schema: Schema,
    columns: dict[str, np.ndarray],
    level: int = 0,
    row_group_size: int = 256 * 1024,
    tag_dicts: dict[str, list] | None = None,
) -> SstMeta:
    """Write one sorted SST; caller guarantees (tsid, ts, seq) order.

    ``tag_dicts`` + ``__tagcode_<name>__`` companion columns (write path)
    build the Parquet dictionary pages directly from region codes — no
    per-row string hashing; compaction inputs lack codes and take the
    hash-encode fallback."""
    from greptimedb_tpu.storage.memtable import tagcode_col

    ts_col = schema.time_index.name
    n = len(columns[SEQ])
    file_id = uuid.uuid4().hex
    path = f"{sst_dir}/{file_id}.parquet"

    target = _arrow_schema(schema)
    arrays = []
    for f in target:
        col = columns[f.name]
        if pa.types.is_dictionary(f.type):
            codes = columns.get(tagcode_col(f.name))
            vocab = (tag_dicts or {}).get(f.name)
            if codes is not None and vocab is not None:
                # SST-local dictionary: remap region codes to this file's
                # distinct values — embedding the region-lifetime vocab
                # would bloat every SST of a long-lived churning region
                uniq_codes = np.unique(codes)
                local = np.searchsorted(uniq_codes, codes).astype(np.int32)
                arrays.append(pa.DictionaryArray.from_arrays(
                    pa.array(local, type=pa.int32()),
                    pa.array([vocab[int(c)] for c in uniq_codes],
                             type=pa.utf8()),
                ))
            else:
                arrays.append(
                    pa.array(col.astype(object), type=pa.utf8())
                    .dictionary_encode()
                )
        else:
            arrays.append(pa.array(col, type=f.type))
    table = pa.Table.from_arrays(arrays, schema=target)

    sink = io.BytesIO()
    pq.write_table(
        table,
        sink,
        row_group_size=row_group_size,
        compression="zstd",
        compression_level=1,
        use_dictionary=True,
        write_statistics=True,
    )
    data = sink.getvalue()
    store.write(path, data)
    ts = columns[ts_col]
    seq = columns[SEQ]
    return SstMeta(
        file_id=file_id,
        path=path,
        num_rows=n,
        ts_min=int(ts.min()),
        ts_max=int(ts.max()),
        seq_min=int(seq.min()),
        seq_max=int(seq.max()),
        size_bytes=len(data),
        level=level,
        columns=tuple(f.name for f in target),
    )


def read_sst(
    store: ObjectStore,
    meta: SstMeta,
    schema: Schema,
    ts_range: tuple[int | None, int | None] = (None, None),
    columns: list[str] | None = None,
    tag_filters: dict[str, set] | None = None,
) -> dict[str, np.ndarray]:
    """Read an SST back into numpy columns, pruning row groups by time and
    (when ``tag_filters`` equality/IN sets are given) by tag values via
    Parquet dictionary/statistics filtering — the row-group-level
    counterpart of the file-level bloom skipping index.

    Tag dictionary columns come back as raw values (object arrays);
    re-encoding to region codes happens in the cache layer against the
    region dictionaries.
    """
    ts_idx = schema.time_index
    ts_col = ts_idx.name
    ts_type = pa.timestamp(ts_idx.dtype.time_unit.value)
    conj = []
    lo, hi = ts_range
    if lo is not None:
        conj.append((ts_col, ">=", pa.scalar(int(lo), type=ts_type)))
    if hi is not None:
        conj.append((ts_col, "<", pa.scalar(int(hi), type=ts_type)))
    tag_names = {c.name for c in schema.tag_columns}
    for col, values in (tag_filters or {}).items():
        if col in tag_names and values:
            conj.append((col, "in", [str(v) for v in values]))
    filters = conj or None

    local = store.local_path(meta.path)
    src = local if local else io.BytesIO(store.read(meta.path))
    internal = (TSID, SEQ, OP)
    schema_cols = {c.name for c in schema}
    if meta.columns is not None:
        present = set(meta.columns)
    else:  # legacy meta: one footer read to learn the file's columns
        present = set(pq.read_schema(src).names)
        if isinstance(src, io.BytesIO):
            src.seek(0)
    want = columns if columns is not None else (list(schema_cols) + list(internal))
    want = list(dict.fromkeys(want))
    read_cols = [c for c in want if c in present]
    table = pq.read_table(src, columns=read_cols, filters=filters)

    out: dict[str, np.ndarray] = {}
    for name in table.column_names:
        if name not in schema_cols and name not in internal:
            continue  # dropped by ALTER; dead weight in old SSTs
        arr = table.column(name).combine_chunks()
        if pa.types.is_dictionary(arr.type):
            # decode via the (small) dictionary, not per-row python objects
            dict_vals = np.asarray(arr.dictionary.to_pylist(), dtype=object)
            indices = arr.indices.to_numpy(zero_copy_only=False)
            out[name] = dict_vals[indices]
        elif pa.types.is_string(arr.type) or pa.types.is_binary(arr.type):
            out[name] = np.asarray(arr.to_pylist(), dtype=object)
        elif pa.types.is_timestamp(arr.type):
            out[name] = arr.to_numpy(zero_copy_only=False).astype("int64")
        else:
            out[name] = arr.to_numpy(zero_copy_only=False)
    # schema evolution: backfill columns added after this SST was written
    n = len(out[SEQ]) if SEQ in out else (table.num_rows)
    for c in schema:
        if c.name in want and c.name not in out:
            out[c.name] = default_fill_array(c, n)
    return out
