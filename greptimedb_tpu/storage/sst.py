"""SST layer: sorted Parquet files with pruning stats.

Equivalent of the reference's flat SST format
(src/mito2/src/sst/parquet/flat_format.rs: raw key columns + __primary_key/
__sequence/__op_type internal columns): each SST stores the table's columns
(tags dictionary-encoded by Parquet itself) plus __tsid__/__seq__/__op__,
sorted by (tsid, ts, seq). File-level stats (time range, row count, seq
range) live in the manifest for pruning; row-group stats inside the Parquet
footer give a second pruning level (reference reader.rs row-group pruning).
"""

from __future__ import annotations

import io
import threading
import uuid
from dataclasses import dataclass, field

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from greptimedb_tpu.datatypes.schema import Schema, default_fill_array
from greptimedb_tpu.storage.durability import M_CORRUPTION, SstCorruption
from greptimedb_tpu.storage.memtable import OP, SEQ, TSID, tagcode_col
from greptimedb_tpu.storage.object_store import ObjectStore
from greptimedb_tpu.utils.chaos import CHAOS
from greptimedb_tpu.utils.telemetry import REGISTRY

# per-row python-object materializations for dictionary-encoded string
# columns.  The code-path scan (``tag_encoders`` + ``decode_tags=False``)
# keeps dictionary indices as region codes instead; a tier-1 guard pins
# that the hot scan path (device-cache builds) never grows this counter.
M_OBJECT_DECODE_ROWS = REGISTRY.counter(
    "greptime_scan_object_decode_rows_total",
    "Rows decoded into per-row python objects from dictionary-encoded "
    "columns (0 on the code-path scan)",
)

# the rare legacy fallback in _dict_to_codes mutates a region encoder from
# a decode thread; serialize those inserts (scans may decode in parallel)
_ENCODER_FALLBACK_LOCK = threading.Lock()


@dataclass(frozen=True)
class SstMeta:
    file_id: str
    path: str
    num_rows: int
    ts_min: int
    ts_max: int
    seq_min: int
    seq_max: int
    size_bytes: int
    level: int = 0
    # column names present in the file (schema evolution: old SSTs may lack
    # later-added columns); None only for metas persisted before this field
    columns: tuple[str, ...] | None = None

    def to_dict(self) -> dict:
        d = dict(self.__dict__)
        d["columns"] = list(self.columns) if self.columns is not None else None
        return d

    @staticmethod
    def from_dict(d: dict) -> "SstMeta":
        cols = d.get("columns")
        d = dict(d)
        d["columns"] = tuple(cols) if cols is not None else None
        return SstMeta(**d)

    def overlaps(self, ts_start: int | None, ts_end: int | None) -> bool:
        """Half-open [ts_start, ts_end) vs this file's closed [min,max]."""
        if ts_start is not None and self.ts_max < ts_start:
            return False
        if ts_end is not None and self.ts_min >= ts_end:
            return False
        return True


def _arrow_schema(schema: Schema) -> pa.Schema:
    fields = []
    for c in schema:
        f = c.to_arrow()
        if c.is_tag and pa.types.is_string(f.type):
            f = pa.field(f.name, pa.dictionary(pa.int32(), pa.utf8()), nullable=f.nullable)
        fields.append(f)
    fields.append(pa.field(TSID, pa.int64(), nullable=False))
    fields.append(pa.field(SEQ, pa.int64(), nullable=False))
    fields.append(pa.field(OP, pa.int8(), nullable=False))
    return pa.schema(fields)


def write_sst(
    store: ObjectStore,
    sst_dir: str,
    schema: Schema,
    columns: dict[str, np.ndarray],
    level: int = 0,
    row_group_size: int = 256 * 1024,
    tag_dicts: dict[str, list] | None = None,
) -> SstMeta:
    """Write one sorted SST; caller guarantees (tsid, ts, seq) order.

    ``tag_dicts`` + ``__tagcode_<name>__`` companion columns build the
    Parquet dictionary pages directly from region codes — no per-row
    string hashing.  Both flush and compaction supply codes (compaction
    reads its inputs on the code path); the hash-encode fallback only
    covers callers with raw values and no companions."""
    from greptimedb_tpu.storage.memtable import tagcode_col

    ts_col = schema.time_index.name
    n = len(columns[SEQ])
    file_id = uuid.uuid4().hex
    path = f"{sst_dir}/{file_id}.parquet"

    target = _arrow_schema(schema)
    arrays = []
    for f in target:
        if pa.types.is_dictionary(f.type):
            # codes-first: a code-path scan (compaction over coded parts)
            # may carry ONLY ``__tagcode_*__`` companions, no raw values
            codes = columns.get(tagcode_col(f.name))
            vocab = (tag_dicts or {}).get(f.name)
            if codes is not None and vocab is not None:
                # SST-local dictionary: remap region codes to this file's
                # distinct values — embedding the region-lifetime vocab
                # would bloat every SST of a long-lived churning region
                uniq_codes = np.unique(codes)
                local = np.searchsorted(uniq_codes, codes).astype(np.int32)
                arrays.append(pa.DictionaryArray.from_arrays(
                    pa.array(local, type=pa.int32()),
                    pa.array([vocab[int(c)] for c in uniq_codes],
                             type=pa.utf8()),
                ))
            else:
                arrays.append(
                    pa.array(columns[f.name].astype(object), type=pa.utf8())
                    .dictionary_encode()
                )
        else:
            arrays.append(pa.array(columns[f.name], type=f.type))
    table = pa.Table.from_arrays(arrays, schema=target)

    sink = io.BytesIO()
    pq.write_table(
        table,
        sink,
        row_group_size=row_group_size,
        compression="zstd",
        compression_level=1,
        use_dictionary=True,
        write_statistics=True,
        # page-level CRCs (ISSUE 9): every scan/compaction read verifies
        # them, so silent bit rot is detected instead of served
        write_page_checksum=True,
    )
    data = sink.getvalue()
    after = None
    if CHAOS.enabled:  # durability-boundary crash point + data faults
        data, after = CHAOS.filter_io("sst.write", data)
    store.write(path, data)
    if after is not None:
        raise after
    ts = columns[ts_col]
    seq = columns[SEQ]
    return SstMeta(
        file_id=file_id,
        path=path,
        num_rows=n,
        ts_min=int(ts.min()),
        ts_max=int(ts.max()),
        seq_min=int(seq.min()),
        seq_max=int(seq.max()),
        size_bytes=len(data),
        level=level,
        columns=tuple(f.name for f in target),
    )


def _dict_to_codes(arr, enc) -> np.ndarray:
    """Dictionary array → region tag codes: map the file's (small)
    dictionary through the region encoder ONCE, vectorized over the
    int32 indices — the per-row cost is a single numpy gather, never a
    python-object materialization.  A null dictionary entry maps like
    the write path's NULL convention (empty string)."""
    dict_vals = ["" if v is None else v for v in arr.dictionary.to_pylist()]
    mapping = np.fromiter(
        (enc.get(v) for v in dict_vals), dtype=np.int32,
        count=len(dict_vals),
    )
    if bool((mapping < 0).any()):
        # legacy file carrying a value the region dicts never saw (e.g.
        # pre-manifest data): register it, serialized against concurrent
        # decode threads — codes are append-only so readers stay valid
        with _ENCODER_FALLBACK_LOCK:
            mapping = np.fromiter(
                (enc.get_or_insert(v) for v in dict_vals), dtype=np.int32,
                count=len(dict_vals),
            )
    indices = arr.indices.to_numpy(zero_copy_only=False)
    return mapping[indices.astype(np.int64, copy=False)]


def read_sst(
    store: ObjectStore,
    meta: SstMeta,
    schema: Schema,
    ts_range: tuple[int | None, int | None] = (None, None),
    columns: list[str] | None = None,
    tag_filters: dict[str, set] | None = None,
    tag_encoders: dict | None = None,
    decode_tags: bool = True,
) -> dict[str, np.ndarray]:
    """Read an SST back into numpy columns, pruning row groups by time and
    (when ``tag_filters`` equality/IN sets are given) by tag values via
    Parquet dictionary/statistics filtering — the row-group-level
    counterpart of the file-level bloom skipping index.

    Tag transfer is two-mode.  Default (``tag_encoders=None``): dictionary
    columns come back as raw values (object arrays) and re-encoding
    happens downstream.  Code path (``tag_encoders`` = the region's
    DictionaryEncoders): each dictionary column additionally yields a
    ``__tagcode_<name>__`` int32 companion in REGION code space — the
    file's dictionary is mapped once, vectorized — and with
    ``decode_tags=False`` the per-row object array is never materialized
    at all, so the cache layer consumes codes directly without re-hashing
    a single string.
    """
    ts_idx = schema.time_index
    ts_col = ts_idx.name
    ts_type = pa.timestamp(ts_idx.dtype.time_unit.value)
    conj = []
    lo, hi = ts_range
    if lo is not None:
        conj.append((ts_col, ">=", pa.scalar(int(lo), type=ts_type)))
    if hi is not None:
        conj.append((ts_col, "<", pa.scalar(int(hi), type=ts_type)))
    tag_names = {c.name for c in schema.tag_columns}
    for col, values in (tag_filters or {}).items():
        if col in tag_names and values:
            conj.append((col, "in", [str(v) for v in values]))
    filters = conj or None

    from greptimedb_tpu.storage.scan import M_SCAN_BYTES, M_SCAN_FILES

    M_SCAN_FILES.labels("read").inc()
    # bytes DECODED, not file size: scale by the ts overlap fraction so
    # row-group-pruned reads (grid catch-up tails) don't overstate the
    # metric by the whole file
    span = max(1, meta.ts_max - meta.ts_min + 1)
    eff_lo = meta.ts_min if lo is None else max(meta.ts_min, int(lo))
    eff_hi = meta.ts_max + 1 if hi is None else min(meta.ts_max + 1, int(hi))
    M_SCAN_BYTES.inc(
        meta.size_bytes * min(1.0, max(0.0, (eff_hi - eff_lo) / span)))
    local = store.local_path(meta.path)
    if CHAOS.enabled and local is not None:
        # disk fault injection on the SST read path: route the mmap-able
        # local file through a byte read so bitflip faults apply
        data, _ = CHAOS.filter_io("sst.read", store.read(meta.path))
        local, src = None, io.BytesIO(data)
    else:
        src = local if local else io.BytesIO(store.read(meta.path))
    internal = (TSID, SEQ, OP)
    schema_cols = {c.name for c in schema}
    try:
        if meta.columns is not None:
            present = set(meta.columns)
        else:  # legacy meta: one footer read to learn the file's columns
            present = set(pq.read_schema(src).names)
            if isinstance(src, io.BytesIO):
                src.seek(0)
        want = (columns if columns is not None
                else (list(schema_cols) + list(internal)))
        want = list(dict.fromkeys(want))
        read_cols = [c for c in want if c in present]
        # page_checksum_verification: decode fails loudly on bit rot —
        # the scan layer quarantines the file and repairs/serves around
        # it instead of returning corrupt rows
        table = pq.read_table(src, columns=read_cols, filters=filters,
                              page_checksum_verification=True)
    except (OSError, ValueError, KeyError, pa.ArrowException) as e:
        M_CORRUPTION.labels("sst", "read").inc()
        raise SstCorruption(meta, e) from e

    out: dict[str, np.ndarray] = {}
    for name in table.column_names:
        if name not in schema_cols and name not in internal:
            continue  # dropped by ALTER; dead weight in old SSTs
        arr = table.column(name).combine_chunks()
        if pa.types.is_dictionary(arr.type):
            enc = (tag_encoders or {}).get(name)
            if enc is not None:
                if arr.null_count == 0:
                    out[tagcode_col(name)] = _dict_to_codes(arr, enc)
                    if not decode_tags:
                        continue  # codes ARE the column; no object array
                else:
                    # anomalous row-level nulls (never written by this
                    # engine): decode and re-encode so the code companion
                    # invariant still holds for every part of a scan
                    vals = np.asarray(arr.to_pylist(), dtype=object)
                    M_OBJECT_DECODE_ROWS.inc(len(vals))
                    with _ENCODER_FALLBACK_LOCK:
                        out[tagcode_col(name)] = np.fromiter(
                            (enc.get_or_insert("" if v is None else v)
                             for v in vals),
                            dtype=np.int32, count=len(vals),
                        )
                    if decode_tags:
                        out[name] = vals
                    continue
            # decode via the (small) dictionary, not per-row to_pylist —
            # still a per-row object-pointer array, which the hot scan
            # path avoids entirely (tier-1 pins the counter at 0 there)
            dict_vals = np.asarray(arr.dictionary.to_pylist(), dtype=object)
            indices = arr.indices.to_numpy(zero_copy_only=False)
            M_OBJECT_DECODE_ROWS.inc(len(indices))
            out[name] = dict_vals[indices]
        elif pa.types.is_string(arr.type) or pa.types.is_binary(arr.type):
            out[name] = np.asarray(arr.to_pylist(), dtype=object)
        elif pa.types.is_timestamp(arr.type):
            out[name] = arr.to_numpy(zero_copy_only=False).astype("int64")
        else:
            out[name] = arr.to_numpy(zero_copy_only=False)
    # schema evolution: backfill columns added after this SST was written
    n = len(out[SEQ]) if SEQ in out else table.num_rows
    for c in schema:
        if c.name in want and c.name not in out:
            enc = ((tag_encoders or {}).get(c.name)
                   if c.is_tag and c.dtype.is_string_like else None)
            if enc is not None:
                if tagcode_col(c.name) not in out:
                    fill = default_fill_array(c, 1)[0]
                    code = enc.get(fill)
                    if code < 0:
                        with _ENCODER_FALLBACK_LOCK:
                            code = enc.get_or_insert(fill)
                    out[tagcode_col(c.name)] = np.full(n, code,
                                                       dtype=np.int32)
                if not decode_tags:
                    continue  # the code companion IS the column
            out[c.name] = default_fill_array(c, n)
    return out


def iter_verify_sst_bytes(data: bytes):
    """Row-group-granular checksummed verify: yields one bool per row
    group (True = the group decoded clean with page checksums, False =
    corrupt — iteration stops at the first False).  An unreadable
    footer/metadata yields a single False.  The background scrubber
    drains this generator between idle-preemption checks, so verifying
    a multi-group SST never pins an idle worker for the whole decode
    (ISSUE 18 satellite); ``verify_sst_bytes`` drains it in one go."""
    try:
        pf = pq.ParquetFile(io.BytesIO(data),
                            page_checksum_verification=True)
        n = pf.metadata.num_row_groups
    except (OSError, ValueError, KeyError, pa.ArrowException):
        yield False
        return
    for i in range(n):
        try:
            pf.read_row_group(i)
        except (OSError, ValueError, KeyError, pa.ArrowException):
            yield False
            return
        yield True


def verify_sst_bytes(data: bytes) -> bool:
    """Full checksummed decode of candidate SST bytes — repair validation:
    a replica's copy must prove readable (page checksums included) before
    it replaces a quarantined file."""
    return all(iter_verify_sst_bytes(data))
