"""Skipping indexes: per-SST bloom filters over tag columns.

Reference: src/index/src/bloom_filter/ + the puffin blob container
(SURVEY.md §2.5) — indexes are built at flush/compaction time and prune
SSTs (and eventually row groups) before any Parquet IO. Here each SST gets
one sidecar blob (``<file_id>.idx``) holding a bloom filter per tag
column; ``Region.scan_host`` consults them for equality/IN predicates.

Read-path consumers: cold scans that bypass the HBM-resident cache
(exports, range-restricted scans over beyond-HBM tables). The resident
query path deliberately loads whole regions once and filters on device, so
it does not pass tag_filters; wiring planner-extracted filters into
range-restricted scans lands with the beyond-HBM work.

Bloom layout: double hashing with two crc32-derived hashes (Kirsch-
Mitzenmacher), bit array in numpy uint64 words, target ~1% false positives
(10 bits/key, 7 hashes).
"""

from __future__ import annotations

import json
import struct
import zlib

import numpy as np

BITS_PER_KEY = 10
NUM_HASHES = 7
_MAGIC = b"GTIX1\n"


class BloomFilter:
    def __init__(self, num_bits: int, bits: np.ndarray | None = None):
        self.num_bits = max(int(num_bits), 64)
        words = (self.num_bits + 63) // 64
        self.bits = (
            bits if bits is not None else np.zeros(words, dtype=np.uint64)
        )

    @staticmethod
    def for_keys(n: int) -> "BloomFilter":
        return BloomFilter(max(n, 1) * BITS_PER_KEY)

    def _hashes(self, value: str) -> tuple[int, int]:
        data = value.encode("utf-8") if isinstance(value, str) else bytes(value)
        h1 = zlib.crc32(data)
        h2 = zlib.crc32(data, 0x9E3779B9) | 1  # odd => full period
        return h1, h2

    def add(self, value) -> None:
        h1, h2 = self._hashes(str(value))
        for i in range(NUM_HASHES):
            bit = (h1 + i * h2) % self.num_bits
            self.bits[bit >> 6] |= np.uint64(1 << (bit & 63))

    def might_contain(self, value) -> bool:
        h1, h2 = self._hashes(str(value))
        for i in range(NUM_HASHES):
            bit = (h1 + i * h2) % self.num_bits
            if not (int(self.bits[bit >> 6]) >> (bit & 63)) & 1:
                return False
        return True

    def to_bytes(self) -> bytes:
        return struct.pack("<I", self.num_bits) + self.bits.tobytes()

    @staticmethod
    def from_bytes(raw: bytes) -> "BloomFilter":
        (num_bits,) = struct.unpack_from("<I", raw, 0)
        bits = np.frombuffer(raw[4:], dtype=np.uint64).copy()
        return BloomFilter(num_bits, bits)


# Term dictionaries above this cardinality are dropped from the sidecar
# (the bloom still covers equality); bounds sidecar size on high-churn tags.
VOCAB_LIMIT = 4096
# distinct TOKENS per string-FIELD column kept for full-text pruning
TOKEN_LIMIT = 65536
_MAGIC2 = b"GTIX2\n"
# bump when tokenize() changes: stale token sets in old sidecars must be
# IGNORED (no pruning), never consulted — they would over-prune queries
# whose tokens the old analyzer never produced (e.g. CJK bigrams)
_TOKENIZER_VERSION = 2

_TOKEN_RE = None


def tokenize(text: str) -> list[str]:
    """Lowercase word tokens + CJK bigrams.

    Latin/digit runs split on non-alnum and lowercase (the reference's
    fulltext default analyzer — tantivy's simple tokenizer).  CJK runs
    emit character BIGRAMS (single char when the run is length 1): the
    dictionary-free analog of the reference's tantivy-jieba Chinese
    tokenizer (src/index/Cargo.toml:43-44) — bigram indexing is the
    standard CJK fallback when no segmentation dictionary ships."""
    global _TOKEN_RE
    if _TOKEN_RE is None:
        import re

        _TOKEN_RE = re.compile(
            r"[A-Za-z0-9_]+|[\u3040-\u30ff\u3400-\u4dbf\u4e00-\u9fff"
            r"\uf900-\ufaff\uac00-\ud7af]+"
        )
    out: list[str] = []
    for run in _TOKEN_RE.findall(text):
        if run[0].isascii():
            out.append(run.lower())
        elif len(run) == 1:
            out.append(run)
        else:
            out.extend(run[i:i + 2] for i in range(len(run) - 1))
    return out


class ColumnIndex:
    """Per-column SST index: bloom (always) + exact term dictionary (when
    the column's distinct count fits VOCAB_LIMIT).  The term dictionary is
    the file-level analog of the reference's FST term dict
    (src/index/src/inverted_index/): it makes equality pruning exact and
    lets ARBITRARY predicates (regex matchers) prune whole files."""

    def __init__(self, bloom: BloomFilter, vocab: list[str] | None = None):
        self.bloom = bloom
        self.vocab = vocab
        self._vset = set(vocab) if vocab is not None else None
        self.tokens: set[str] | None = None  # fulltext token set
        self.has_tombstones = False  # file holds delete rows

    def may_contain(self, value) -> bool:
        if self._vset is not None:
            return str(value) in self._vset
        return self.bloom.might_contain(value)

    def any_term_matches(self, pred) -> bool:
        """False only when the exact vocabulary proves no term satisfies
        pred; True when unknown (no vocabulary stored)."""
        if self.vocab is None:
            return True
        return any(pred(t) for t in self.vocab)


def build_sst_index(columns: dict[str, np.ndarray], tag_names: list[str],
                    fulltext_columns: list[str] | None = None,
                    has_tombstones: bool = False,
                    tag_uniques: dict[str, list] | None = None) -> bytes:
    """Serialize per-tag-column blooms + term dicts, plus per-fulltext-
    column token sets, for one SST (the puffin blob, reference
    src/puffin/; fulltext backend = the reference's bloom-based variant,
    src/index/src/fulltext_index/).  ``tag_uniques`` (precomputed distinct
    values, e.g. from dictionary codes) skips the per-row unique pass."""
    blobs: dict[str, bytes] = {}
    vocabs: dict[str, list[str]] = {}
    tokens: dict[str, list[str]] = {}
    for name in tag_names:
        pre = (tag_uniques or {}).get(name)
        if pre is not None:
            uniq = np.asarray(sorted(str(v) for v in pre), dtype=object)
        elif name in columns:
            uniq = np.unique(columns[name].astype(object))
        else:
            continue
        bf = BloomFilter.for_keys(len(uniq))
        for v in uniq:
            bf.add(v)
        blobs[name] = bf.to_bytes()
        if len(uniq) <= VOCAB_LIMIT:
            vocabs[name] = [str(v) for v in uniq]
    for name in fulltext_columns or ():
        if name not in columns:
            continue
        toks: set[str] = set()
        for v in columns[name]:
            if v is None:
                continue
            toks.update(tokenize(str(v)))
            if len(toks) > TOKEN_LIMIT:
                break
        if len(toks) <= TOKEN_LIMIT:
            tokens[name] = sorted(toks)
    header = json.dumps({
        "blooms": {name: len(b) for name, b in blobs.items()},
        "vocabs": vocabs,
        "tokens": tokens,
        "tokv": _TOKENIZER_VERSION,
        "tombstones": bool(has_tombstones),
    }).encode("utf-8")
    out = _MAGIC2 + struct.pack("<I", len(header)) + header
    for name in sorted(blobs):
        out += blobs[name]
    return out


def load_sst_index(raw: bytes) -> dict[str, ColumnIndex]:
    if raw.startswith(_MAGIC2):
        (hlen,) = struct.unpack_from("<I", raw, len(_MAGIC2))
        off = len(_MAGIC2) + 4
        header = json.loads(raw[off:off + hlen])
        off += hlen
        out = {}
        for name in sorted(header["blooms"]):
            ln = header["blooms"][name]
            out[name] = ColumnIndex(
                BloomFilter.from_bytes(raw[off:off + ln]),
                header["vocabs"].get(name),
            )
            off += ln
        if header.get("tokv") == _TOKENIZER_VERSION:
            # token sets from a different analyzer version are DROPPED:
            # pruning against them would hide rows whose tokens the old
            # analyzer never produced (no tokens = no pruning = correct)
            for name, toks in header.get("tokens", {}).items():
                ci = out.get(name)
                if ci is None:
                    ci = out[name] = ColumnIndex(BloomFilter(64))
                ci.tokens = set(toks)
        if header.get("tombstones"):
            for ci in out.values():
                ci.has_tombstones = True
        return out
    if not raw.startswith(_MAGIC):
        raise ValueError("bad index blob magic")
    # v1 (bloom-only) sidecars written by earlier builds
    (hlen,) = struct.unpack_from("<I", raw, len(_MAGIC))
    off = len(_MAGIC) + 4
    header = json.loads(raw[off:off + hlen])
    off += hlen
    out = {}
    for name in sorted(header):
        ln = header[name]
        out[name] = ColumnIndex(BloomFilter.from_bytes(raw[off:off + ln]))
        off += ln
    return out


def sst_may_match(
    index: dict[str, ColumnIndex], tag_filters: dict[str, set]
) -> bool:
    """False only when some filtered column's index excludes EVERY value
    (exact when the term dictionary is present, probabilistic via bloom
    otherwise)."""
    for col, values in tag_filters.items():
        ci = index.get(col)
        if ci is None or not values:
            continue
        if not any(ci.may_contain(v) for v in values):
            return False
    return True


def sst_pred_may_match(
    index: dict[str, ColumnIndex], column: str, pred
) -> bool:
    """File-level pruning for arbitrary term predicates (regex matchers):
    False only when the stored vocabulary proves no term matches."""
    ci = index.get(column)
    if ci is None:
        return True
    return ci.any_term_matches(pred)


def ft_predicate(name: str, query: str):
    """matches = AND of query tokens; matches_term = the query's token
    SEQUENCE appears consecutively (exact-term semantics for terms with
    non-alnum separators like 'v1.0').  Empty-token queries match NOTHING
    — a filter must never silently select everything.  The ONE definition
    of full-text semantics (SQL functions, log-query DSL, pruning)."""
    qtokens = tokenize(query)
    if not qtokens:
        return lambda text: False
    if name == "matches_term":
        k = len(qtokens)

        def term_pred(text: str) -> bool:
            toks = tokenize(text)
            return any(
                toks[i:i + k] == qtokens for i in range(len(toks) - k + 1)
            )

        return term_pred

    qset = set(qtokens)

    def pred(text: str) -> bool:
        return qset.issubset(tokenize(text))

    return pred


def ft_score(query: str):
    """TF-IDF-shaped relevance scoring: returns (query_tokens, tf_vector)
    where tf_vector(text) gives per-query-token saturated term
    frequencies; the caller applies IDF over whatever corpus it scans
    (the table dictionary on the device path, the batch's distinct
    values on the host path — scores are a per-query ranking heuristic,
    not comparable across paths).  The reference's ranking comes from
    tantivy's BM25 (src/index/src/fulltext_index/); this is the same
    shape without per-SST global statistics: tf saturation (BM25 k1=1.2)
    x corpus IDF.  Score 0.0 = no overlap (use `matches` to filter)."""
    qtokens = list(dict.fromkeys(tokenize(query)))  # uniq, stable order

    def tf_vector(text: str) -> list[float]:
        toks = tokenize(text)
        out = []
        for q in qtokens:
            tf = toks.count(q)
            out.append((tf * 2.2) / (tf + 1.2) if tf else 0.0)
        return out

    return qtokens, tf_vector


def ft_score_corpus(query: str, corpus) -> "np.ndarray":
    """Score every text in ``corpus`` against ``query`` — the ONE
    TF-IDF computation, shared by the device (dictionary vocabulary) and
    host (batch distinct values) paths so the BM25 constants can never
    diverge between them."""
    import math

    import numpy as np

    qtokens, tf_vector = ft_score(query)
    tfs = [tf_vector(str(t)) for t in corpus]
    n_docs = max(len(tfs), 1)
    dfs = [sum(1 for v in tfs if v[j]) for j in range(len(qtokens))]
    idf = [math.log(1.0 + (n_docs - df + 0.5) / (df + 0.5)) for df in dfs]
    if not tfs:
        return np.zeros(1, dtype=np.float64)
    return np.asarray(
        [sum(w * i for w, i in zip(v, idf)) for v in tfs],
        dtype=np.float64,
    )


def sst_tokens_may_match(
    index: dict[str, ColumnIndex], column: str, query_tokens: list[str]
) -> bool:
    """Full-text file pruning: False only when the token set proves some
    query token appears NOWHERE in the column (AND semantics).  Files
    containing tombstones are NEVER pruned: a delete row's fields are
    null, so its tokens are absent, yet the merge must see it or deleted
    rows resurrect."""
    ci = index.get(column)
    if ci is None or ci.tokens is None or ci.has_tombstones:
        return True
    return all(t in ci.tokens for t in query_tokens)
