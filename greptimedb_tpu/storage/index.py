"""Skipping indexes: per-SST bloom filters over tag columns.

Reference: src/index/src/bloom_filter/ + the puffin blob container
(SURVEY.md §2.5) — indexes are built at flush/compaction time and prune
SSTs (and eventually row groups) before any Parquet IO. Here each SST gets
one sidecar blob (``<file_id>.idx``) holding a bloom filter per tag
column; ``Region.scan_host`` consults them for equality/IN predicates.

Read-path consumers: cold scans that bypass the HBM-resident cache
(exports, range-restricted scans over beyond-HBM tables). The resident
query path deliberately loads whole regions once and filters on device, so
it does not pass tag_filters; wiring planner-extracted filters into
range-restricted scans lands with the beyond-HBM work.

Bloom layout: double hashing with two crc32-derived hashes (Kirsch-
Mitzenmacher), bit array in numpy uint64 words, target ~1% false positives
(10 bits/key, 7 hashes).
"""

from __future__ import annotations

import json
import struct
import zlib

import numpy as np

BITS_PER_KEY = 10
NUM_HASHES = 7
_MAGIC = b"GTIX1\n"


class BloomFilter:
    def __init__(self, num_bits: int, bits: np.ndarray | None = None):
        self.num_bits = max(int(num_bits), 64)
        words = (self.num_bits + 63) // 64
        self.bits = (
            bits if bits is not None else np.zeros(words, dtype=np.uint64)
        )

    @staticmethod
    def for_keys(n: int) -> "BloomFilter":
        return BloomFilter(max(n, 1) * BITS_PER_KEY)

    def _hashes(self, value: str) -> tuple[int, int]:
        data = value.encode("utf-8") if isinstance(value, str) else bytes(value)
        h1 = zlib.crc32(data)
        h2 = zlib.crc32(data, 0x9E3779B9) | 1  # odd => full period
        return h1, h2

    def add(self, value) -> None:
        h1, h2 = self._hashes(str(value))
        for i in range(NUM_HASHES):
            bit = (h1 + i * h2) % self.num_bits
            self.bits[bit >> 6] |= np.uint64(1 << (bit & 63))

    def might_contain(self, value) -> bool:
        h1, h2 = self._hashes(str(value))
        for i in range(NUM_HASHES):
            bit = (h1 + i * h2) % self.num_bits
            if not (int(self.bits[bit >> 6]) >> (bit & 63)) & 1:
                return False
        return True

    def to_bytes(self) -> bytes:
        return struct.pack("<I", self.num_bits) + self.bits.tobytes()

    @staticmethod
    def from_bytes(raw: bytes) -> "BloomFilter":
        (num_bits,) = struct.unpack_from("<I", raw, 0)
        bits = np.frombuffer(raw[4:], dtype=np.uint64).copy()
        return BloomFilter(num_bits, bits)


def build_sst_index(columns: dict[str, np.ndarray], tag_names: list[str]) -> bytes:
    """Serialize per-tag-column blooms for one SST (the puffin blob)."""
    blobs: dict[str, bytes] = {}
    for name in tag_names:
        if name not in columns:
            continue
        uniq = np.unique(columns[name].astype(object))
        bf = BloomFilter.for_keys(len(uniq))
        for v in uniq:
            bf.add(v)
        blobs[name] = bf.to_bytes()
    header = json.dumps(
        {name: len(b) for name, b in blobs.items()}
    ).encode("utf-8")
    out = _MAGIC + struct.pack("<I", len(header)) + header
    for name in sorted(blobs):
        out += blobs[name]
    return out


def load_sst_index(raw: bytes) -> dict[str, BloomFilter]:
    if not raw.startswith(_MAGIC):
        raise ValueError("bad index blob magic")
    (hlen,) = struct.unpack_from("<I", raw, len(_MAGIC))
    off = len(_MAGIC) + 4
    header = json.loads(raw[off:off + hlen])
    off += hlen
    out = {}
    for name in sorted(header):
        ln = header[name]
        out[name] = BloomFilter.from_bytes(raw[off:off + ln])
        off += ln
    return out


def sst_may_match(
    index: dict[str, BloomFilter], tag_filters: dict[str, set]
) -> bool:
    """False only when some filtered column's bloom excludes EVERY value."""
    for col, values in tag_filters.items():
        bf = index.get(col)
        if bf is None or not values:
            continue
        if not any(bf.might_contain(v) for v in values):
            return False
    return True
