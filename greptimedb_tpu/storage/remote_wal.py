"""Remote (shared) WAL: a Kafka-style replicated log decoupled from any
datanode's local state.

Reference: the Kafka remote WAL (src/log-store/src/kafka/ — per-topic
producers/consumers, high-watermark index; topic allocation in
src/common/wal; pruning procedure src/meta-srv/src/procedure/wal_prune/;
RFC docs/rfcs/2023-03-08-region-fault-tolerance.md).  The point of the
design is FAST FAILOVER: datanodes become (nearly) stateless because the
write-ahead log lives on shared infrastructure — when a node dies, its
regions open elsewhere and replay from the shared log; nothing on the
dead machine is needed.

``SharedLogBroker`` stands in for the Kafka cluster: one segmented
CRC-checked log per topic (reusing the FileLogStore format), with
per-region low watermarks driving whole-segment pruning.  Entries are
envelopes of (region_id, region_sequence, payload) so multiple regions
can multiplex one topic (the reference's WalEntryDistributor demux,
src/mito2/src/wal/).  ``RemoteLogStore`` adapts one (broker, topic,
region) to the LogStore interface Region already consumes — switching a
region between local and remote WAL is a construction-time choice.

Broker-side replication (ISSUE 15, the Kafka replication-factor analog):
``GREPTIME_WAL_REPLICAS=N`` (or the ``replicas`` argument) keeps N
copies of every topic — replica 0 in the legacy layout, replicas 1..N-1
under ``.replica<i>/`` — with **acked-quorum appends** (a record is
durable once ⌈(N+1)/2⌉ replicas fsynced it; a minority of failures is
counted, not fatal) and **read-repair** on replay (a replica missing
offsets the others hold — an earlier failed append, or interior CRC
damage triaged by the segment scanner — is backfilled from a healthy
donor and its damaged spans healed).  Losing or corrupting any single
copy therefore never loses an acked record: ``RemoteLogStore.replay``
serves the union of the surviving quorum.

Single-writer discipline: a topic's append side is the region leader
(regions default to one topic each); follower readers always replay
with repair=False (no truncation, no read-repair — only the append
owner mutates broker state).  **Epoch fencing**: the append owner may
arm a leader epoch (``RemoteLogStore.set_fence``, minted by Metasrv at
open/failover/upgrade); appends and watermark advances carrying an
epoch older than the recorded claim raise FencedError — a fenced-out
zombie's write is REFUSED (its client sees the failure) instead of
silently acked into a forked history.  A real multi-broker deployment
would replace this class with a networked client — the interface is
the contract.
"""

from __future__ import annotations

import json
import os
import struct
import threading

from greptimedb_tpu.errors import FencedError, StorageError
from greptimedb_tpu.storage.durability import M_FENCE_REJECTED
from greptimedb_tpu.storage.object_store import _fsync_dir
from greptimedb_tpu.storage.wal import FileLogStore, LogStore
from greptimedb_tpu.utils.telemetry import REGISTRY

_ENV = struct.Struct("<QQ")  # region_id, region sequence

M_BROKER_APPEND = REGISTRY.counter(
    "greptime_broker_replica_append_total",
    "Per-replica broker append outcomes (quorum ack tolerates a "
    "minority of failures)",
    labels=("outcome",),
)
M_BROKER_QUORUM_FAIL = REGISTRY.counter(
    "greptime_broker_quorum_failures_total",
    "Broker appends that failed to reach a durable quorum (surfaced to "
    "the writer, nothing acked)",
)
M_BROKER_READ_REPAIR = REGISTRY.counter(
    "greptime_broker_read_repair_total",
    "Records backfilled into a lagging/corrupt broker replica from a "
    "healthy donor during owner replay",
)


def default_replicas() -> int:
    """GREPTIME_WAL_REPLICAS (default 1 = the unreplicated legacy
    layout; 3 = Kafka-style majority-quorum replication)."""
    try:
        return max(1, int(os.environ.get("GREPTIME_WAL_REPLICAS", "1")))
    except ValueError:
        return 1


class SharedLogBroker:
    """File-backed shared log service (the 'Kafka cluster')."""

    def __init__(self, root_dir: str, topics_per_node: int | None = None,
                 replicas: int | None = None):
        self.root = root_dir
        os.makedirs(root_dir, exist_ok=True)
        # None → one topic per region (safe for multi-process writers);
        # an int enables shared-topic multiplexing (single process)
        self.topics_per_node = topics_per_node
        self.replicas = default_replicas() if replicas is None else max(
            1, int(replicas))
        self.quorum = self.replicas // 2 + 1
        self._logs: dict[str, list[FileLogStore | None]] = {}
        self._offsets: dict[str, int] = {}
        self._lock = threading.Lock()
        # fencing state per topic: {"<rid>": epoch} mirror of the
        # watermark marker's "_epoch" record, plus the marker mtime it
        # was read at (cross-process claims re-read on mtime change)
        self._epochs: dict[str, dict] = {}
        self._epochs_mtime: dict[str, float] = {}

    # ---- topology ------------------------------------------------------
    def topic_for(self, region_id: int) -> str:
        if self.topics_per_node is None:
            return f"region_{region_id}"
        return f"shared_{region_id % self.topics_per_node}"

    def _replica_dir(self, topic: str, i: int) -> str:
        # replica 0 keeps the legacy single-copy layout, so raising the
        # replication factor on an existing broker adopts the old data
        # as replica 0 and read-repair backfills the new copies
        if i == 0:
            return os.path.join(self.root, topic)
        return os.path.join(self.root, f".replica{i}", topic)

    def _logs_for(self, topic: str) -> list[FileLogStore | None]:
        logs = self._logs.get(topic)
        if logs is None:
            logs = []
            last = self._floor(topic)
            for i in range(self.replicas):
                try:
                    log = FileLogStore(self._replica_dir(topic, i))
                    # append-side owner: REPAIR torn tails per replica (a
                    # SIGKILLed leader can leave a half-written record;
                    # appending after it would hide every later entry
                    # from replay forever)
                    for off, _payload in log.replay(last, repair=True):
                        last = max(last, off)
                except OSError:
                    M_BROKER_APPEND.labels("open_failed").inc()
                    log = None
                logs.append(log)
            if not any(l is not None for l in logs):
                raise StorageError(
                    f"broker topic {topic}: no readable replica")
            self._logs[topic] = logs
            # the append offset resumes past the NEWEST record across
            # replicas — a lagging replica must not rewind the topic
            self._offsets[topic] = last
        return logs

    def acquire(self, topic: str) -> None:
        """(Re)take append ownership of a topic: drop any cached handles
        and offset so state re-reads from shared storage.  Called
        whenever a region (re)opens — leadership may have bounced
        through another broker instance that appended and pruned in the
        meantime."""
        with self._lock:
            for log in self._logs.pop(topic, []) or []:
                if log is not None:
                    log.close()
            self._offsets.pop(topic, None)
            self._epochs.pop(topic, None)
            self._epochs_mtime.pop(topic, None)

    # ---- epoch fencing -------------------------------------------------
    # Claims are EMPTY FILES named ``.epochs/<topic>.<region>.<epoch>``,
    # created O_CREAT|O_EXCL and never overwritten: creation is atomic
    # ACROSS PROCESSES and the recorded epoch is the max over existing
    # claim files, so claiming is monotone by construction — a zombie's
    # lower claim can never clobber a newer leader's (a check-then-write
    # marker field would race exactly there).  The per-append check is
    # one dir-mtime stat + a cached scan.
    def _epoch_dir(self) -> str:
        return os.path.join(self.root, ".epochs")

    def _topic_epochs(self, topic: str) -> dict:
        """Per-region claimed epochs for ``topic``, re-scanned whenever
        the claim dir's mtime moved (another broker instance — the new
        leader's process — may have claimed since)."""
        d = self._epoch_dir()
        try:
            mtime = os.path.getmtime(d)
        except OSError:
            mtime = -1.0
        if (topic not in self._epochs
                or self._epochs_mtime.get(topic) != mtime):
            claims: dict[str, int] = {}
            prefix = f"{topic}."
            try:
                names = os.listdir(d)
            except OSError:
                names = []
            for fn in names:
                if not fn.startswith(prefix):
                    continue
                try:
                    rid, ep = fn[len(prefix):].split(".")
                    claims[rid] = max(int(claims.get(rid, 0)), int(ep))
                except ValueError:
                    continue
            self._epochs[topic] = claims
            self._epochs_mtime[topic] = mtime
        return self._epochs[topic]

    def claim_epoch(self, topic: str, region_id: int, epoch: int) -> None:
        """Record a leader epoch for (topic, region): later appends or
        watermark advances carrying an older epoch are refused.  Claims
        are monotone — a stale claim (zombie re-opening) raises here."""
        epoch = int(epoch)
        with self._lock:
            cur = int(self._topic_epochs(topic).get(str(region_id), 0))
            if cur > epoch:
                M_FENCE_REJECTED.labels("broker_claim").inc()
                raise FencedError(
                    f"broker topic {topic} region {region_id}: epoch "
                    f"{epoch} superseded by {cur}")
            if cur == epoch:
                return
            d = self._epoch_dir()
            os.makedirs(d, exist_ok=True)
            path = os.path.join(d, f"{topic}.{region_id}.{epoch}")
            try:
                os.close(os.open(path, os.O_CREAT | os.O_EXCL))
            except FileExistsError:
                pass  # our own claim from a crashed earlier attempt
            # the claim must survive power loss, or a fenced zombie
            # could append after a restart forgot the directory entry
            _fsync_dir(d)  # gl: allow[GL-L002] -- claims are once-per-leadership-grant, and _lock IS their serialization (same discipline as the watermark marker write)
            self._epochs.setdefault(topic, {})[str(region_id)] = epoch
            self._epochs_mtime.pop(topic, None)  # re-stat next check

    def _check_epoch(self, topic: str, region_id: int,
                     epoch: int | None, surface: str) -> None:
        # an epoch-less writer (epoch None → 0) is fenced by ANY
        # recorded claim: a pre-fencing zombie whose region opened
        # before epochs were minted must not bypass the new leader's
        # fence.  Unfenced standalone brokers record no claims, so the
        # epoch-less path stays open for them.
        cur = int(self._topic_epochs(topic).get(str(region_id), 0))
        if cur > (0 if epoch is None else int(epoch)):
            M_FENCE_REJECTED.labels(surface).inc()
            raise FencedError(
                f"broker topic {topic} region {region_id}: {surface} "
                f"with epoch {epoch} fenced out by {cur}")

    # ---- data plane ----------------------------------------------------
    def append(self, topic: str, region_id: int, sequence: int,
               payload: bytes, epoch: int | None = None) -> int:
        """Durable quorum append; returns the topic offset.  Offset
        assignment and record enqueue happen atomically under the broker
        lock, but the durability wait runs OUTSIDE it — concurrent
        appenders (many regions, many topics) enqueue back-to-back and
        each replica log's group committer flushes the whole batch with
        one write + fsync, acking every waiter at once (the Kafka
        produce-batching analog).  The append succeeds once a MAJORITY
        of replicas is durable; a fenced epoch refuses before any byte
        is written."""
        from greptimedb_tpu.utils.chaos import CHAOS

        CHAOS.inject("wal.append")  # broker stall/failure (chaos tier)
        rec = _ENV.pack(region_id, sequence) + payload
        with self._lock:
            self._check_epoch(topic, region_id, epoch, "broker_append")
            logs = self._logs_for(topic)
            offset = self._offsets[topic] + 1
            self._offsets[topic] = offset
            waits = []
            failed = 0
            for log in logs:
                if log is None:
                    failed += 1
                    continue
                try:
                    if CHAOS.enabled:
                        # per-replica fault point: error/kill/stall one
                        # copy's append boundary (the kill-a-replica
                        # chaos coverage) — quorum must still ack
                        CHAOS.inject("broker.replica")
                    waits.append(log.append_async(offset, rec))
                except BaseException:  # noqa: BLE001 — one replica down
                    failed += 1       # is a counted, survivable event
        ok = 0
        for wait in waits:
            try:
                wait()
                ok += 1
            except BaseException:  # noqa: BLE001
                failed += 1
        if ok:
            M_BROKER_APPEND.labels("ok").inc(ok)
        if failed:
            M_BROKER_APPEND.labels("failed").inc(failed)
        if ok < self.quorum:
            M_BROKER_QUORUM_FAIL.inc()
            # indeterminate, like any distributed write timeout: the
            # record may live on a minority replica and surface after
            # read-repair (a torn-tail-survivor analog); the caller's
            # retry burns a fresh region sequence, so no seq ever
            # replays twice
            raise StorageError(
                f"broker topic {topic}: append reached {ok}/"
                f"{self.replicas} replicas (quorum {self.quorum}) — "
                "not acked; durability indeterminate")
        return offset

    def read(self, topic: str, from_offset: int | None = None,
             repair: bool = False):
        """Yield (offset, region_id, sequence, payload) merged across
        replicas: an offset present on ANY valid replica is served, so
        replay survives the loss or corruption of a minority of copies.

        The read-only path (followers, pruning scans) is a STREAMING
        k-way merge over the per-replica record iterators — sound
        because replica files are offset-ordered by construction
        (appends enqueue under the broker lock in offset order, and
        read-repair rebuilds a repaired replica in offset order).
        ``repair=True`` (append owner only) additionally READ-REPAIRS:
        replicas missing offsets a donor holds, or carrying CRC-damaged
        spans, are sidecar-preserved and rebuilt from the merged view —
        follower reads never mutate.

        A record that reached only a MINORITY (a below-quorum append —
        the writer saw an error, the outcome is INDETERMINATE like any
        distributed write timeout) survives into the merged view:
        durable-but-unacked records may surface after repair, exactly
        like a torn-tail survivor in a local WAL; region sequences are
        never reused (failed appends burn them), so no seq replays
        twice."""
        if from_offset is None:
            from_offset = self._floor(topic)
        logs = self._logs_for(topic)
        if self.replicas == 1:
            log = logs[0]
            for offset, data in log.replay(from_offset, repair=False):
                rid, seq = _ENV.unpack_from(data, 0)
                yield offset, rid, seq, data[_ENV.size:]
            return
        if not repair:
            # streaming union: no materialization — a failover replay
            # over a large unpruned topic must not hold N copies of it
            import heapq

            iters = [log.replay(from_offset, repair=False)
                     for log in logs if log is not None]
            last = None
            for off, data in heapq.merge(*iters, key=lambda t: t[0]):
                if off == last:
                    continue  # the other replicas' copy of one record
                last = off
                rid, seq = _ENV.unpack_from(data, 0)
                yield off, rid, seq, data[_ENV.size:]
            return
        per_replica: list[dict[int, bytes] | None] = []
        merged: dict[int, bytes] = {}
        damaged: list[int] = []
        for i, log in enumerate(logs):
            if log is None:
                per_replica.append(None)
                continue
            recs: dict[int, bytes] = {}
            for offset, data in log.replay(from_offset, repair=False):
                recs[offset] = data
            if any(d.kind == "interior" for d in log.last_triage):
                damaged.append(i)
            per_replica.append(recs)
            for off, data in recs.items():
                merged.setdefault(off, data)
        self._read_repair(topic, logs, per_replica, merged, damaged)
        for off in sorted(merged):
            data = merged[off]
            rid, seq = _ENV.unpack_from(data, 0)
            yield off, rid, seq, data[_ENV.size:]

    def _read_repair(self, topic, logs, per_replica, merged,
                     damaged) -> None:
        """Backfill lagging replicas and heal CRC-damaged ones from the
        merged view (the donor the interior-corruption story was
        missing: any healthy sibling).  Damaged bytes are preserved in
        ``.quarantine`` sidecars FIRST (the PR-9 discipline — this scan
        ran repair=False, so replay wrote none), then the replica is
        rebuilt from the merged view IN OFFSET ORDER — repaired
        replicas must stay offset-sorted on disk, the streaming merged
        read depends on it."""
        for i, recs in enumerate(per_replica):
            log = logs[i]
            if log is None or recs is None:
                continue
            missing = [off for off in merged if off not in recs]
            if not missing and i not in damaged:
                continue
            for d in log.last_triage:
                if d.kind != "interior":
                    continue
                try:
                    with open(d.path, "rb") as f:
                        seg = f.read()
                    log._write_sidecar(d.path, d.start, seg[d.start:d.end])
                except OSError:
                    pass  # segment vanished: nothing left to preserve
            # rebuild: drop the replica's segments (sidecars are kept —
            # they are .quarantine files, not .wal) and re-append the
            # merged view; a crash mid-rebuild leaves a partial replica
            # the quorum covers and the next owner replay re-repairs
            log.close()
            d = self._replica_dir(topic, i)
            try:
                for fn in os.listdir(d):
                    if fn.endswith(".wal"):
                        os.unlink(os.path.join(d, fn))
            except OSError:
                pass
            new_log = FileLogStore(d)
            for off in sorted(merged):
                new_log.append(off, merged[off])
            logs[i] = new_log
            M_BROKER_READ_REPAIR.inc(max(len(missing), 1))

    # ---- pruning (reference wal_prune procedure) -----------------------
    def _wm_path(self, topic: str) -> str:
        return os.path.join(self.root, f"{topic}.watermarks.json")

    def _load_wm(self, topic: str) -> dict:
        path = self._wm_path(topic)
        if os.path.exists(path):
            try:
                with open(path) as f:
                    return json.load(f)
            except (json.JSONDecodeError, OSError):
                return {}  # corrupt marker: conservatively prune nothing
        return {}

    def _floor(self, topic: str) -> int:
        """Offset below which everything has been pruned (scan start)."""
        return int(self._load_wm(topic).get("_floor", 0))

    def set_low_watermark(self, topic: str, region_id: int,
                          sequence: int, epoch: int | None = None) -> None:
        """Region has flushed everything below ``sequence``; entries older
        than every region's watermark become prunable.  A fenced epoch
        (older than the recorded claim) is refused — a zombie's stale
        watermark must not prune records the new leader still needs."""
        with self._lock:
            self._check_epoch(topic, region_id, epoch, "broker_watermark")
            wm = self._load_wm(topic)
            wm[str(region_id)] = max(int(wm.get(str(region_id), 0)), sequence)
            self._prune(topic, wm)
            self._persist_watermarks(topic, wm)

    def _persist_watermarks(self, topic: str, wm: dict) -> None:
        """THE watermark-marker write path (lint GL-D003 owner; called
        under self._lock).  Atomic replace + fsync: a crash mid-write
        must never corrupt the marker (a broken marker would wedge
        flush/prune forever), and the rename must be durable before
        pruning relies on it."""
        path = self._wm_path(topic)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(wm, f)
            f.flush()  # gl: allow[GL-L002] -- _lock IS the watermark-write serialization: a torn interleaving of two markers would wedge flush/prune
            os.fsync(f.fileno())  # gl: allow[GL-L002] -- same: durability before the prune relies on it
        os.replace(tmp, path)
        # rename durability: prune already dropped segments this marker
        # accounts for — losing the directory entry at power loss would
        # replay from a floor below the pruned data
        _fsync_dir(self.root)  # gl: allow[GL-L002] -- same serialization as the marker write above

    def _prune(self, topic: str, wm: dict) -> None:
        """Drop whole segments whose every entry is below its region's
        watermark (the reference prunes Kafka up to the min high
        watermark across regions on the topic).  Scans start at the
        stored floor, not offset 0, so flush cost tracks the UNPRUNED
        suffix only.  Per-replica streaming with early break (NOT the
        merged read — this runs on every flush, and materializing the
        whole unpruned suffix × replicas per flush would tax ingest):
        the cut is the MIN first-kept offset across replicas, so a
        record any copy still needs is never pruned anywhere."""
        logs = self._logs_for(topic)
        floor = self._floor(topic)
        keep_from: int | None = None
        for log in logs:
            if log is None:
                continue
            for offset, data in log.replay(floor, repair=False):
                rid, seq = _ENV.unpack_from(data, 0)
                if seq >= int(wm.get(str(rid), 0)):
                    keep_from = (offset if keep_from is None
                                 else min(keep_from, offset))
                    break
        cut = keep_from if keep_from is not None else (
            self._offsets.get(topic, 0) + 1)
        for log in logs:
            if log is not None:
                log.truncate(cut)
        wm["_floor"] = cut

    def close(self) -> None:
        for logs in self._logs.values():
            for log in logs:
                if log is not None:
                    log.close()
        self._logs.clear()


class RemoteLogStore(LogStore):
    """One region's view of the shared log (LogStore interface)."""

    def __init__(self, broker: SharedLogBroker, region_id: int):
        self.broker = broker
        self.region_id = region_id
        self.topic = broker.topic_for(region_id)
        # leader epoch this store appends under (None = unfenced);
        # armed via set_fence at leadership grant
        self.fence_epoch: int | None = None
        # re-take ownership: leadership may have bounced through another
        # broker instance (other process) that appended/pruned meanwhile
        broker.acquire(self.topic)
        # change-detection hook for Region.storage_fingerprint (follower
        # no-op sync skipping): the topic's segment files
        self.dir = os.path.join(broker.root, self.topic)

    def acquire_ownership(self) -> None:
        """Re-take append ownership at leader promotion (Region.catch_up
        with take_ownership): a follower's broker handle cached the topic
        end-offset at OPEN time, and the old leader has appended since —
        appending through the stale handle would mint colliding offsets
        and corrupt the pruning floor."""
        self.broker.acquire(self.topic)

    def set_fence(self, epoch: int) -> None:
        """Arm epoch fencing for this region's broker writes: the claim
        is recorded broker-side, so a fenced-out zombie's append or
        watermark advance FAILS (its client sees the error) instead of
        being acked into a forked history."""
        self.broker.claim_epoch(self.topic, self.region_id, epoch)
        self.fence_epoch = int(epoch)

    def append(self, sequence: int, payload: bytes) -> None:
        self.broker.append(self.topic, self.region_id, sequence, payload,
                           epoch=self.fence_epoch)

    def replay(self, from_sequence: int = 0, repair: bool = True):
        # repair here means broker read-repair (owner only): followers
        # replay the merged replica view read-only; the broker owns its
        # own tail integrity either way
        for _off, rid, seq, payload in self.broker.read(
                self.topic, repair=repair):
            if rid == self.region_id and seq >= from_sequence:
                yield seq, payload

    def truncate(self, up_to_sequence: int) -> None:
        self.broker.set_low_watermark(self.topic, self.region_id,
                                      up_to_sequence,
                                      epoch=self.fence_epoch)

    def close(self) -> None:
        pass  # broker lifecycle is owned by the node/deployment
