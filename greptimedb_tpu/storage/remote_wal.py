"""Remote (shared) WAL: a Kafka-style replicated log decoupled from any
datanode's local state.

Reference: the Kafka remote WAL (src/log-store/src/kafka/ — per-topic
producers/consumers, high-watermark index; topic allocation in
src/common/wal; pruning procedure src/meta-srv/src/procedure/wal_prune/;
RFC docs/rfcs/2023-03-08-region-fault-tolerance.md).  The point of the
design is FAST FAILOVER: datanodes become (nearly) stateless because the
write-ahead log lives on shared infrastructure — when a node dies, its
regions open elsewhere and replay from the shared log; nothing on the
dead machine is needed.

``SharedLogBroker`` stands in for the Kafka cluster: a directory on
shared storage holding one segmented CRC-checked log per topic (reusing
the FileLogStore format), with per-region low watermarks driving
whole-segment pruning.  Entries are envelopes of
(region_id, region_sequence, payload) so multiple regions can multiplex
one topic (the reference's WalEntryDistributor demux,
src/mito2/src/wal/).  ``RemoteLogStore`` adapts one (broker, topic,
region) to the LogStore interface Region already consumes — switching a
region between local and remote WAL is a construction-time choice.

Single-writer discipline: a topic's append side is the region leader
(regions default to one topic each); readers always replay with
repair=False.  A real multi-broker deployment would replace this class
with a networked client — the interface is the contract.
"""

from __future__ import annotations

import json
import os
import struct
import threading

from greptimedb_tpu.storage.object_store import _fsync_dir
from greptimedb_tpu.storage.wal import FileLogStore, LogStore

_ENV = struct.Struct("<QQ")  # region_id, region sequence


class SharedLogBroker:
    """File-backed shared log service (the 'Kafka cluster')."""

    def __init__(self, root_dir: str, topics_per_node: int | None = None):
        self.root = root_dir
        os.makedirs(root_dir, exist_ok=True)
        # None → one topic per region (safe for multi-process writers);
        # an int enables shared-topic multiplexing (single process)
        self.topics_per_node = topics_per_node
        self._logs: dict[str, FileLogStore] = {}
        self._offsets: dict[str, int] = {}
        self._lock = threading.Lock()

    # ---- topology ------------------------------------------------------
    def topic_for(self, region_id: int) -> str:
        if self.topics_per_node is None:
            return f"region_{region_id}"
        return f"shared_{region_id % self.topics_per_node}"

    def _log(self, topic: str) -> FileLogStore:
        log = self._logs.get(topic)
        if log is None:
            log = FileLogStore(os.path.join(self.root, topic))
            self._logs[topic] = log
            last = self._floor(topic)
            # append-side owner: REPAIR torn tails here (a SIGKILLed
            # leader can leave a half-written record; appending after it
            # would hide every later entry from replay forever)
            for off, _payload in log.replay(last, repair=True):
                last = off
            self._offsets[topic] = last
        return log

    def acquire(self, topic: str) -> None:
        """(Re)take append ownership of a topic: drop any cached handle and
        offset so state re-reads from shared storage.  Called whenever a
        region (re)opens — leadership may have bounced through another
        broker instance that appended and pruned in the meantime."""
        with self._lock:
            log = self._logs.pop(topic, None)
            if log is not None:
                log.close()
            self._offsets.pop(topic, None)

    # ---- data plane ----------------------------------------------------
    def append(self, topic: str, region_id: int, sequence: int,
               payload: bytes) -> int:
        """Durable append; returns the topic offset.  Offset assignment
        and record enqueue happen atomically under the broker lock, but
        the durability wait runs OUTSIDE it — concurrent appenders (many
        regions, many topics) enqueue back-to-back and the log's group
        committer flushes the whole batch with one write + fsync, acking
        every waiter at once (the Kafka produce-batching analog)."""
        from greptimedb_tpu.utils.chaos import CHAOS

        CHAOS.inject("wal.append")  # broker stall/failure (chaos tier)
        with self._lock:
            log = self._log(topic)
            offset = self._offsets[topic] + 1
            self._offsets[topic] = offset
            wait = log.append_async(
                offset, _ENV.pack(region_id, sequence) + payload)
        wait()
        return offset

    def read(self, topic: str, from_offset: int | None = None):
        """Yield (offset, region_id, sequence, payload); read-only (never
        repairs — only the append owner may truncate tails)."""
        log = self._log(topic)
        if from_offset is None:
            from_offset = self._floor(topic)
        for offset, data in log.replay(from_offset, repair=False):
            rid, seq = _ENV.unpack_from(data, 0)
            yield offset, rid, seq, data[_ENV.size:]

    # ---- pruning (reference wal_prune procedure) -----------------------
    def _wm_path(self, topic: str) -> str:
        return os.path.join(self.root, f"{topic}.watermarks.json")

    def _load_wm(self, topic: str) -> dict:
        path = self._wm_path(topic)
        if os.path.exists(path):
            try:
                with open(path) as f:
                    return json.load(f)
            except (json.JSONDecodeError, OSError):
                return {}  # corrupt marker: conservatively prune nothing
        return {}

    def _floor(self, topic: str) -> int:
        """Offset below which everything has been pruned (scan start)."""
        return int(self._load_wm(topic).get("_floor", 0))

    def set_low_watermark(self, topic: str, region_id: int,
                          sequence: int) -> None:
        """Region has flushed everything below ``sequence``; entries older
        than every region's watermark become prunable."""
        with self._lock:
            wm = self._load_wm(topic)
            wm[str(region_id)] = max(int(wm.get(str(region_id), 0)), sequence)
            self._prune(topic, wm)
            # atomic replace + fsync: a crash mid-write must never corrupt
            # the marker (a broken marker would wedge flush/prune forever),
            # and the rename must be durable before pruning relies on it
            path = self._wm_path(topic)
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(wm, f)
                f.flush()  # gl: allow[GL-L002] -- _lock IS the watermark-write serialization: a torn interleaving of two markers would wedge flush/prune
                os.fsync(f.fileno())  # gl: allow[GL-L002] -- same: durability before the prune below relies on it
            os.replace(tmp, path)
            # rename durability: prune (above) already dropped segments
            # this marker accounts for — losing the directory entry at
            # power loss would replay from a floor below the pruned data
            _fsync_dir(self.root)  # gl: allow[GL-L002] -- same serialization as the marker write above

    def _prune(self, topic: str, wm: dict) -> None:
        """Drop whole segments whose every entry is below its region's
        watermark (the reference prunes Kafka up to the min high
        watermark across regions on the topic).  Scans start at the
        stored floor, not offset 0, so flush cost tracks the UNPRUNED
        suffix only."""
        log = self._log(topic)
        keep_from: int | None = None
        for offset, rid, seq, _payload in self.read(topic):
            if seq >= int(wm.get(str(rid), 0)):
                keep_from = offset
                break
        if keep_from is not None:
            log.truncate(keep_from)
            wm["_floor"] = keep_from
        else:
            # everything flushed: drop all closed segments
            end = self._offsets.get(topic, 0) + 1
            log.truncate(end)
            wm["_floor"] = end

    def close(self) -> None:
        for log in self._logs.values():
            log.close()
        self._logs.clear()


class RemoteLogStore(LogStore):
    """One region's view of the shared log (LogStore interface)."""

    def __init__(self, broker: SharedLogBroker, region_id: int):
        self.broker = broker
        self.region_id = region_id
        self.topic = broker.topic_for(region_id)
        # re-take ownership: leadership may have bounced through another
        # broker instance (other process) that appended/pruned meanwhile
        broker.acquire(self.topic)
        # change-detection hook for Region.storage_fingerprint (follower
        # no-op sync skipping): the topic's segment files
        self.dir = os.path.join(broker.root, self.topic)

    def acquire_ownership(self) -> None:
        """Re-take append ownership at leader promotion (Region.catch_up
        with take_ownership): a follower's broker handle cached the topic
        end-offset at OPEN time, and the old leader has appended since —
        appending through the stale handle would mint colliding offsets
        and corrupt the pruning floor."""
        self.broker.acquire(self.topic)

    def append(self, sequence: int, payload: bytes) -> None:
        self.broker.append(self.topic, self.region_id, sequence, payload)

    def replay(self, from_sequence: int = 0, repair: bool = True):
        # repair is meaningless here: the shared log is never truncated by
        # readers (the broker owns its own tail integrity)
        for _off, rid, seq, payload in self.broker.read(self.topic):
            if rid == self.region_id and seq >= from_sequence:
                yield seq, payload

    def truncate(self, up_to_sequence: int) -> None:
        self.broker.set_low_watermark(self.topic, self.region_id,
                                      up_to_sequence)

    def close(self) -> None:
        pass  # broker lifecycle is owned by the node/deployment
