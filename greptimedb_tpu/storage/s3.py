"""S3-compatible object store backend.

Reference: src/object-store (OpenDAL s3/oss/azblob/gcs services,
src/object-store/src/config.rs:31) with the retry layer
(src/object-store/src/layers/) and mito's write-through cache
(src/mito2/src/cache/write_cache.rs): uploads also land in a local disk
cache, and reads are served from (and populate) that cache so Parquet
scans can mmap local files.

No AWS SDK is available in this environment, so this implements the
documented S3 REST protocol directly: AWS Signature Version 4 signing
(stdlib hmac/hashlib), PUT/GET/HEAD/DELETE object and ListObjectsV2 over
urllib, path-style addressing (MinIO-compatible).  ``MockS3Server`` is
an in-process protocol mock for tests.

Shared-storage coherence (ISSUE 15):

- **Conditional put** (``write_if``): ``If-Match``/``If-None-Match``
  headers on PUT — the fenced write surface manifest deltas/checkpoints
  ride so two split-brain leaders cannot interleave histories (a lost
  CAS is HTTP 412 → FencedError, never retried into a plain write).
  The ``s3.cas`` chaos point fires between the CAS landing remotely and
  the local cache fill, the crash window recovery must handle.
- **Cache revalidation**: the per-node write-through cache is safe for
  immutable objects (SSTs — uuid-named, never rewritten) but NOT for
  manifest-prefix paths another node may replace or delete remotely.
  ``read``/``exists`` on those paths revalidate against a remote HEAD
  (ETag/length) instead of trusting a stale local hit.
"""

from __future__ import annotations

import datetime
import hashlib
import hmac
import os
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
import xml.etree.ElementTree as ET

from greptimedb_tpu.errors import FencedError, StorageError
from greptimedb_tpu.storage.object_store import ObjectStore, content_etag
from greptimedb_tpu.utils.chaos import CHAOS, ChaosError, M_REMOTE_RETRY


def _sign(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def sigv4_headers(
    method: str,
    host: str,
    canonical_uri: str,
    query: str,
    region: str,
    access_key: str,
    secret_key: str,
    payload: bytes,
    service: str = "s3",
) -> dict[str, str]:
    """AWS Signature Version 4 (the documented algorithm, applied to
    path-style S3 requests)."""
    now = datetime.datetime.now(datetime.timezone.utc)
    amz_date = now.strftime("%Y%m%dT%H%M%SZ")
    datestamp = now.strftime("%Y%m%d")
    payload_hash = hashlib.sha256(payload).hexdigest()
    canonical_headers = (
        f"host:{host}\nx-amz-content-sha256:{payload_hash}\n"
        f"x-amz-date:{amz_date}\n"
    )
    signed_headers = "host;x-amz-content-sha256;x-amz-date"
    canonical_request = "\n".join([
        method, canonical_uri, query, canonical_headers, signed_headers,
        payload_hash,
    ])
    scope = f"{datestamp}/{region}/{service}/aws4_request"
    string_to_sign = "\n".join([
        "AWS4-HMAC-SHA256", amz_date, scope,
        hashlib.sha256(canonical_request.encode()).hexdigest(),
    ])
    k = _sign(("AWS4" + secret_key).encode(), datestamp)
    k = _sign(k, region)
    k = _sign(k, service)
    k = _sign(k, "aws4_request")
    signature = hmac.new(k, string_to_sign.encode(), hashlib.sha256).hexdigest()
    return {
        "x-amz-date": amz_date,
        "x-amz-content-sha256": payload_hash,
        "Authorization": (
            f"AWS4-HMAC-SHA256 Credential={access_key}/{scope}, "
            f"SignedHeaders={signed_headers}, Signature={signature}"
        ),
    }


class S3ObjectStore(ObjectStore):
    """Path-style S3 client with retries and a write-through local cache."""

    def __init__(
        self,
        endpoint: str,
        bucket: str,
        *,
        prefix: str = "",
        region: str = "us-east-1",
        access_key: str | None = None,
        secret_key: str | None = None,
        cache_dir: str | None = None,
        max_retries: int = 3,
    ):
        self.endpoint = endpoint.rstrip("/")
        self.bucket = bucket
        self.prefix = prefix.strip("/")
        self.region = region
        self.access_key = access_key or os.environ.get(
            "AWS_ACCESS_KEY_ID", "anonymous")
        self.secret_key = secret_key or os.environ.get(
            "AWS_SECRET_ACCESS_KEY", "anonymous")
        self.max_retries = max_retries
        self.cache_dir = cache_dir
        if cache_dir:
            os.makedirs(cache_dir, exist_ok=True)
        parsed = urllib.parse.urlparse(self.endpoint)
        self.host = parsed.netloc
        # scan-driven readahead state (see prefetch): daemon workers are
        # started lazily on the first prefetch; the in-flight map lets the
        # read path WAIT for a fetch already on the wire instead of
        # downloading the same object twice
        self._prefetch_lock = threading.Lock()
        self._inflight: dict[str, threading.Event] = {}
        self._prefetch_q = None
        # default matches the widest decode pool (storage/scan.py): the
        # read path JOINS in-flight prefetches, so fewer fetchers than
        # decode threads would serialize a fetch-bound cold scan
        self._prefetch_threads = max(
            1, int(os.environ.get("GREPTIME_PREFETCH_THREADS", "8")))

    # ---- plumbing ------------------------------------------------------
    def _key(self, path: str) -> str:
        path = path.lstrip("/")
        return f"{self.prefix}/{path}" if self.prefix else path

    def _request(self, method: str, key: str = "", query: str = "",
                 payload: bytes = b"",
                 extra_headers: dict[str, str] | None = None,
                 ) -> tuple[int, bytes, dict]:
        uri = "/" + urllib.parse.quote(f"{self.bucket}/{key}".rstrip("/"))
        url = f"{self.endpoint}{uri}" + (f"?{query}" if query else "")
        headers = sigv4_headers(method, self.host, uri, query, self.region,
                                self.access_key, self.secret_key, payload)
        if extra_headers:
            # conditional headers (If-Match/If-None-Match) ride unsigned:
            # sigv4 signs only host/content-sha256/date above
            headers = {**headers, **extra_headers}
        last_err: Exception | None = None
        for attempt in range(self.max_retries + 1):
            req = urllib.request.Request(url, data=payload or None,
                                         method=method, headers=headers)
            try:
                if method == "GET":
                    CHAOS.inject("s3.read")  # injected object-store fault
                with urllib.request.urlopen(req) as resp:
                    body = resp.read()
                    if method == "GET" and CHAOS.enabled:
                        # silent-bit-rot shape: the read "succeeds" with
                        # one corrupt byte — downstream verification
                        # (parquet page checksums, manifest CRCs) must
                        # catch it, not this layer
                        body, _ = CHAOS.filter_io("s3.read.payload", body)
                    return resp.status, body, dict(resp.headers)
            except urllib.error.HTTPError as e:
                if e.code == 404:
                    return 404, b"", dict(e.headers or {})
                if e.code == 412:
                    # precondition failed: the conditional write lost its
                    # CAS — a FENCING event, never a transient to retry
                    raise FencedError(
                        f"s3 {method} {key}: precondition failed "
                        "(If-Match/If-None-Match lost)") from None
                if e.code < 500:
                    raise StorageError(
                        f"s3 {method} {key}: HTTP {e.code}"
                    ) from None
                last_err = e  # 5xx: retry (reference retry layer)
            except urllib.error.URLError as e:
                last_err = e
            except ChaosError as e:
                last_err = e  # survived like any transient network fault
            if attempt < self.max_retries:  # a retry will actually follow
                # shared fault-pressure counter (same as the flight path)
                M_REMOTE_RETRY.labels("s3", type(last_err).__name__).inc()
            time.sleep(min(0.05 * (2 ** attempt), 1.0))
        raise StorageError(f"s3 {method} {key}: {last_err}")

    def _cache_path(self, path: str) -> str | None:
        if not self.cache_dir:
            return None
        root = os.path.abspath(self.cache_dir)
        p = os.path.abspath(os.path.join(root, path.lstrip("/")))
        # commonpath guard: startswith alone would admit ../cacheA2 given
        # root /x/cacheA, and a relative cache_dir would reject everything
        if os.path.commonpath([p, root]) != root:
            raise ValueError(f"path escapes cache root: {path}")
        return p

    @staticmethod
    def _cache_fill(cp: str, data: bytes) -> None:
        """Atomic cache install: unique temp + rename (concurrent fills of
        one object must never interleave into a corrupt cache file)."""
        import tempfile

        os.makedirs(os.path.dirname(cp), exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(cp))
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(data)
            # gl: allow[GL-D002] -- read cache only: a lost directory entry re-fetches from S3; fsync here would tax every cold GET
            os.replace(tmp, cp)
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise

    # ---- scan-driven readahead ----------------------------------------
    def _ensure_prefetch_workers(self) -> None:
        with self._prefetch_lock:
            if self._prefetch_q is not None:
                return
            import queue

            self._prefetch_q = queue.Queue()
            for i in range(self._prefetch_threads):
                t = threading.Thread(
                    target=self._prefetch_worker, daemon=True,
                    name=f"s3-prefetch-{i}",
                )
                t.start()

    def _prefetch_worker(self) -> None:
        while True:
            path = self._prefetch_q.get()
            try:
                status, body, _h = self._request("GET", self._key(path))
                if status != 404:
                    cp = self._cache_path(path)
                    if cp:
                        self._cache_fill(cp, body)
            except Exception:  # noqa: BLE001 — readahead is best-effort;
                pass  # the read path re-fetches on demand
            finally:
                with self._prefetch_lock:
                    ev = self._inflight.pop(path, None)
                if ev is not None:
                    ev.set()

    def prefetch(self, paths: list[str]) -> int:
        """Queue background read-through fills for not-yet-local objects
        (the scan pipeline calls this with the selected SSTs before the
        decode pool reaches them).  Returns the number queued; objects
        already cached or already in flight are skipped."""
        if not self.cache_dir:
            return 0
        queued = 0
        for path in paths:
            cp = self._cache_path(path)
            if cp and os.path.exists(cp):
                continue
            with self._prefetch_lock:
                if path in self._inflight:
                    continue
                self._inflight[path] = threading.Event()
            self._ensure_prefetch_workers()
            self._prefetch_q.put(path)
            queued += 1
        return queued

    def _wait_inflight(self, path: str) -> None:
        """Block (bounded) on an in-flight prefetch of ``path`` so the
        read path joins the existing download instead of duplicating it;
        a wedged fetch degrades to the caller's own fetch after the
        timeout."""
        with self._prefetch_lock:
            ev = self._inflight.get(path)
        if ev is not None:
            ev.wait(timeout=60.0)

    # ---- cache-coherence policy ---------------------------------------
    @staticmethod
    def _must_revalidate(path: str) -> bool:
        """Paths whose objects are REWRITTEN or deleted in place by other
        nodes (manifest deltas/checkpoints, epoch markers, watermark
        markers): a local cache hit must be HEAD/ETag-revalidated, never
        trusted.  Immutable uuid-named SSTs keep the zero-round-trip
        cache hit."""
        p = "/" + path.lstrip("/")
        return "/manifest/" in p or p.endswith(".watermarks.json")

    @staticmethod
    def _etag_matches(etag: str, data: bytes) -> bool:
        """Remote ETag vs local bytes.  Single-part ETags are the content
        md5; multipart ETags (``...-N``) are not — those degrade to the
        caller's length check."""
        etag = etag.strip('"')
        if not etag or "-" in etag:
            return True  # unverifiable by content hash alone
        return etag == content_etag(data)

    # ---- ObjectStore ---------------------------------------------------
    def write(self, path: str, data: bytes) -> None:
        status, _body, _h = self._request("PUT", self._key(path),
                                          payload=data)
        if status not in (200, 201, 204):
            raise StorageError(f"s3 PUT {path}: HTTP {status}")
        cp = self._cache_path(path)
        if cp:  # write-through: subsequent reads are local
            self._cache_fill(cp, data)

    def write_if(self, path: str, data: bytes, *,
                 if_match: str | None = None,
                 if_none_match: bool = False) -> None:
        """Conditional PUT (the epoch-fencing surface): exactly one of
        ``if_none_match`` (create-only) / ``if_match`` (etag CAS).  A
        lost precondition raises FencedError (HTTP 412, not retried)."""
        if if_none_match == (if_match is not None):
            raise ValueError("write_if needs exactly one of "
                             "if_match / if_none_match")
        hdrs = ({"If-None-Match": "*"} if if_none_match
                else {"If-Match": f'"{if_match}"'})
        status, _body, _h = self._request("PUT", self._key(path),
                                          payload=data, extra_headers=hdrs)
        if status not in (200, 201, 204):
            raise StorageError(f"s3 conditional PUT {path}: HTTP {status}")
        # crash window between the CAS landing remotely and the local
        # cache fill: the chaos tier kills here; recovery must classify
        # "failed but actually landed" correctly (manifest readback)
        CHAOS.inject("s3.cas")
        cp = self._cache_path(path)
        if cp:
            self._cache_fill(cp, data)

    def head(self, path: str) -> dict | None:
        status, _body, hdrs = self._request("HEAD", self._key(path))
        if status != 200:
            return None
        try:
            length = int(hdrs.get("Content-Length") or 0)
        except ValueError:
            length = 0
        return {"etag": (hdrs.get("ETag") or "").strip('"'),
                "length": length}

    def read(self, path: str) -> bytes:
        self._wait_inflight(path)
        cp = self._cache_path(path)
        if cp and os.path.exists(cp):
            with open(cp, "rb") as f:
                cached = f.read()
            if not self._must_revalidate(path):
                return cached
            h = self.head(path)
            if h is None:
                # another node deleted the object: the stale hit must
                # not resurrect it
                try:
                    os.unlink(cp)
                except OSError:
                    pass
                raise StorageError(f"s3 object not found: {path}")
            if (h["length"] == len(cached)
                    and self._etag_matches(h["etag"], cached)):
                return cached
            # replaced remotely: fall through to a fresh GET + refill
        status, body, _h = self._request("GET", self._key(path))
        if status == 404:
            raise StorageError(f"s3 object not found: {path}")
        if cp:  # read-through fill
            self._cache_fill(cp, body)
        return body

    def exists(self, path: str) -> bool:
        cp = self._cache_path(path)
        if cp and os.path.exists(cp) and not self._must_revalidate(path):
            return True
        h = self.head(path)
        if h is None and cp and os.path.exists(cp):
            try:  # remote delete: drop the stale cache entry too
                os.unlink(cp)
            except OSError:
                pass
        return h is not None

    def list(self, prefix: str) -> list[str]:
        key_prefix = self._key(prefix)
        q = urllib.parse.urlencode(
            {"list-type": "2", "prefix": key_prefix}
        )
        ns = "{http://s3.amazonaws.com/doc/2006-03-01/}"
        strip = (self.prefix + "/") if self.prefix else ""
        out: list[str] = []
        token = None
        while True:  # ListObjectsV2 pagination (1000 keys/page on real S3)
            qq = q if token is None else (
                q + "&" + urllib.parse.urlencode(
                    {"continuation-token": token})
            )
            status, body, _h = self._request("GET", "", query=qq)
            if status != 200:
                raise StorageError(f"s3 LIST {prefix}: HTTP {status}")
            root = ET.fromstring(body)
            keys = [c.text or "" for c in root.iter(f"{ns}Key")]
            if not keys:  # mocks without the namespace
                keys = [c.text or "" for c in root.iter("Key")]
            out.extend(
                k[len(strip):] if k.startswith(strip) else k for k in keys
            )
            trunc = root.find(f"{ns}IsTruncated")
            if trunc is None:
                trunc = root.find("IsTruncated")
            if trunc is None or (trunc.text or "").lower() != "true":
                break
            tok = root.find(f"{ns}NextContinuationToken")
            if tok is None:
                tok = root.find("NextContinuationToken")
            if tok is None or not tok.text:
                break
            token = tok.text
        return sorted(out)

    def delete(self, path: str) -> None:
        self._request("DELETE", self._key(path))
        cp = self._cache_path(path)
        if cp and os.path.exists(cp):
            os.unlink(cp)

    def delete_if(self, path: str, *, if_match: str) -> None:
        """Conditional DELETE (checkpoint-GC fencing): the object dies
        only while its etag still matches — a 412 surfaces as
        FencedError via _request.  The local cache copy dies with it."""
        status, _body, _h = self._request(
            "DELETE", self._key(path),
            extra_headers={"If-Match": f'"{if_match}"'})
        if status not in (200, 202, 204):
            raise StorageError(f"s3 conditional DELETE {path}: "
                               f"HTTP {status}")
        cp = self._cache_path(path)
        if cp and os.path.exists(cp):
            os.unlink(cp)

    def local_path(self, path: str) -> str | None:
        """Serve Parquet mmap reads from the write-through cache,
        fetching on demand (the reference file cache's read path)."""
        cp = self._cache_path(path)
        if cp is None:
            return None
        if not os.path.exists(cp):
            self._wait_inflight(path)  # join a prefetch already in flight
        if not os.path.exists(cp):
            try:
                self.read(path)  # read-through populates the cache
            except StorageError:
                return None
        return cp if os.path.exists(cp) else None


class MockS3Server:
    """In-process S3 protocol mock (PUT/GET/HEAD/DELETE + ListObjectsV2,
    path-style) for tests — the role MinIO plays in the reference's CI.

    Implements the conditional-PUT subset (``If-Match``/``If-None-Match``
    → 412 on a lost precondition, like real S3 since 2024-11) and serves
    content-md5 ETags on PUT/GET/HEAD, so the fencing and cache-
    revalidation paths exercise the same wire semantics in tests."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 require_auth: bool = True):
        import http.server

        store: dict[str, bytes] = {}
        cas_lock = threading.Lock()
        mock = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, *a):  # noqa: D102
                pass

            def _key(self):
                parsed = urllib.parse.urlparse(self.path)
                return urllib.parse.unquote(parsed.path.lstrip("/")), (
                    urllib.parse.parse_qs(parsed.query)
                )

            def _check_auth(self) -> bool:
                if not require_auth:
                    return True
                auth = self.headers.get("Authorization", "")
                ok = auth.startswith("AWS4-HMAC-SHA256 Credential=")
                if not ok:
                    self.send_response(403)
                    self.end_headers()
                return ok

            def do_PUT(self):
                if not self._check_auth():
                    return
                key, _q = self._key()
                n = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(n)
                if_match = self.headers.get("If-Match")
                if_none = self.headers.get("If-None-Match")
                with cas_lock:  # CAS decisions + install are atomic
                    cur = store.get(key)
                    if if_none is not None and cur is not None:
                        self.send_response(412)
                        self.end_headers()
                        return
                    if if_match is not None:
                        want = if_match.strip('"')
                        if cur is None or content_etag(cur) != want:
                            self.send_response(412)
                            self.end_headers()
                            return
                    store[key] = body
                self.send_response(200)
                self.send_header("ETag", f'"{content_etag(body)}"')
                self.end_headers()

            def do_GET(self):
                if not self._check_auth():
                    return
                key, q = self._key()
                if "list-type" in q:
                    prefix = q.get("prefix", [""])[0]
                    bucket = key.split("/")[0]
                    keys = sorted(
                        k.split("/", 1)[1] for k in store
                        if k.startswith(f"{bucket}/")
                        and k.split("/", 1)[1].startswith(prefix)
                    )
                    body = "<ListBucketResult>" + "".join(
                        f"<Contents><Key>{k}</Key></Contents>" for k in keys
                    ) + "</ListBucketResult>"
                    self.send_response(200)
                    self.send_header("Content-Type", "application/xml")
                    self.end_headers()
                    self.wfile.write(body.encode())
                    return
                if key in store:
                    self.send_response(200)
                    self.send_header("Content-Length", str(len(store[key])))
                    self.send_header("ETag",
                                     f'"{content_etag(store[key])}"')
                    self.end_headers()
                    self.wfile.write(store[key])
                else:
                    self.send_response(404)
                    self.end_headers()

            def do_HEAD(self):
                if not self._check_auth():
                    return
                key, _q = self._key()
                if key in store:
                    self.send_response(200)
                    self.send_header("Content-Length", str(len(store[key])))
                    self.send_header("ETag",
                                     f'"{content_etag(store[key])}"')
                else:
                    self.send_response(404)
                self.end_headers()

            def do_DELETE(self):
                if not self._check_auth():
                    return
                key, _q = self._key()
                if_match = self.headers.get("If-Match")
                with cas_lock:  # conditional check + pop are atomic
                    if if_match is not None:
                        cur = store.get(key)
                        want = if_match.strip('"')
                        if cur is None or content_etag(cur) != want:
                            self.send_response(412)
                            self.end_headers()
                            return
                    store.pop(key, None)
                self.send_response(204)
                self.end_headers()

        self.store = store
        self._httpd = http.server.ThreadingHTTPServer((host, port), Handler)
        self.endpoint = f"http://{host}:{self._httpd.server_port}"
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
