"""Memtable: the mutable in-memory head of a region.

The reference offers per-series BTree memtables and an Arrow-native bulk
memtable (src/mito2/src/memtable/{time_series.rs,bulk.rs}). On the TPU path
all queries consume dense columnar tensors, so the bulk shape is the only
one that makes sense: appended row groups stay as numpy column chunks
(zero re-organization at ingest — that's what keeps ingest fast in Python),
and sorting/dedup happen once at freeze (flush) time.
"""

from __future__ import annotations

import numpy as np

from greptimedb_tpu.datatypes.schema import Schema

TSID = "__tsid__"
SEQ = "__seq__"
OP = "__op__"  # 0 = put, 1 = delete tombstone

OP_PUT = 0
OP_DELETE = 1

# per-tag dictionary-code companion columns carried through memtable
# chunks so flush/index/cache paths never re-hash raw tag strings
TAGCODE_PREFIX = "__tagcode_"


def tagcode_col(tag_name: str) -> str:
    return f"{TAGCODE_PREFIX}{tag_name}__"


class Memtable:
    def __init__(self, schema: Schema):
        self.schema = schema
        self._chunks: list[dict[str, np.ndarray]] = []
        self.num_rows = 0
        self.bytes = 0
        self.ts_min: int | None = None
        self.ts_max: int | None = None
        self.min_seq: int | None = None
        self.max_seq: int | None = None

    def append(self, chunk: dict[str, np.ndarray],
               ts_bounds: tuple[int, int] | None = None,
               seq: int | None = None) -> None:
        """Append a pre-encoded columnar slab: schema columns (tags
        already as raw values, ts as int64, fields numeric) +
        __tsid__/__seq__/__op__.  The slab is stored as-is — zero
        reorganization at ingest; sorting/dedup happen once at freeze.

        ``ts_bounds`` and ``seq``, when the caller already knows them
        (Region.write computes the ts extremes for its append
        classification and stamps one sequence per batch), skip the
        per-column min/max reductions on the hot path."""
        n = len(chunk[SEQ])
        if n == 0:
            return
        self._chunks.append(chunk)
        self.num_rows += n
        self.bytes += sum(
            a.nbytes if isinstance(a, np.ndarray) else 64 * n for a in chunk.values()
        )
        if ts_bounds is not None:
            lo, hi = int(ts_bounds[0]), int(ts_bounds[1])
        else:
            ts = chunk[self.schema.time_index.name]
            lo, hi = int(ts.min()), int(ts.max())
        self.ts_min = lo if self.ts_min is None else min(self.ts_min, lo)
        self.ts_max = hi if self.ts_max is None else max(self.ts_max, hi)
        if seq is not None:
            slo = shi = int(seq)
        else:
            sc = chunk[SEQ]
            slo, shi = int(sc.min()), int(sc.max())
        self.min_seq = slo if self.min_seq is None else min(self.min_seq, slo)
        self.max_seq = shi if self.max_seq is None else max(self.max_seq, shi)

    @property
    def is_empty(self) -> bool:
        return self.num_rows == 0

    def freeze(self, dedup: bool = True) -> dict[str, np.ndarray]:
        """Concatenate, sort by (tsid, ts, seq)[, dedup keep-last].

        Matches mito2 flush semantics (handle_write + flush.rs): the SST is
        sorted on the primary key and contains one row per (series, ts) with
        the highest sequence; delete tombstones survive dedup so they can
        shadow older SSTs until compaction drops them.  ``dedup=False`` is
        append mode: every row survives (the log/trace data model).
        """
        if not self._chunks:
            return {}
        names = list(self._chunks[0].keys())
        merged = {
            k: np.concatenate([c[k] for c in self._chunks]) for k in names
        }
        ts_col = self.schema.time_index.name
        order = np.lexsort((merged[SEQ], merged[ts_col], merged[TSID]))
        merged = {k: v[order] for k, v in merged.items()}
        if not dedup:
            return merged
        # keep-last within (tsid, ts): last in sorted order has max seq
        tsid, ts = merged[TSID], merged[ts_col]
        is_last = np.ones(len(tsid), dtype=bool)
        if len(tsid) > 1:
            same = (tsid[1:] == tsid[:-1]) & (ts[1:] == ts[:-1])
            is_last[:-1] = ~same
        return {k: v[is_last] for k, v in merged.items()}

    def snapshot_chunks(self) -> list[dict[str, np.ndarray]]:
        """Raw (unsorted, possibly duplicated) chunks for scan-time merge."""
        return list(self._chunks)
