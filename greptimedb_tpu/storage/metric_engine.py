"""Metric engine: many logical metric tables on one physical region.

Reference: src/metric-engine (SURVEY.md §2.4, RFC 2023-07-10-metric-engine)
— Prometheus workloads create one table per metric name; at 10k+ metrics,
one region/WAL/manifest per table drowns the system in per-table overhead.
The metric engine multiplexes all of them onto a single physical region by
injecting a ``__metric__`` tag (the reference injects __table_id/__tsid via
its row modifiers) and evolving one shared label-column superset online.

TPU significance: a SINGLE resident DeviceTable holds every metric's
samples, so cross-metric PromQL evaluation shares one (tsid, ts)-sorted
tensor and one kernel cache — the 10M-series design from SURVEY §5.7.

Logical tables are catalog entries with engine="metric" pointing at the
physical table; reads go through LogicalMetricView, which duck-types the
Region surface the planners consume while hiding ``__metric__`` and
restricting the series registry to the metric's own series.
"""

from __future__ import annotations

import numpy as np

from greptimedb_tpu.datatypes.batch import DictionaryEncoder
from greptimedb_tpu.datatypes.schema import ColumnSchema, Schema
from greptimedb_tpu.datatypes.types import ConcreteDataType, SemanticType
from greptimedb_tpu.errors import InvalidArguments

METRIC_COLUMN = "__metric__"
PHYSICAL_TABLE = "greptime_physical_table"


def physical_schema() -> Schema:
    return Schema((
        ColumnSchema(METRIC_COLUMN, ConcreteDataType.STRING, SemanticType.TAG,
                     nullable=False),
        ColumnSchema("ts", ConcreteDataType.TIMESTAMP_MILLISECOND,
                     SemanticType.TIMESTAMP, nullable=False),
        ColumnSchema("val", ConcreteDataType.FLOAT64, SemanticType.FIELD),
    ))


class MetricEngine:
    """Facade owned by the standalone instance."""

    def __init__(self, db):
        self.db = db

    # ---- physical table ------------------------------------------------
    def physical_region(self, dbname: str | None = None):
        dbname = dbname or self.db.current_db
        if not self.db.catalog.table_exists(dbname, PHYSICAL_TABLE):
            info = self.db.catalog.create_table(
                dbname, PHYSICAL_TABLE, physical_schema(),
                engine="metric_physical", if_not_exists=True,
            )
            if info is not None:
                self.db.regions.create_region(info.region_ids[0],
                                              physical_schema())
        info = self.db.catalog.get_table(dbname, PHYSICAL_TABLE)
        return self.db._open_or_create(info.region_ids[0], info.schema)

    # ---- logical tables ------------------------------------------------
    def ensure_logical(self, metric: str, tag_names: list[str],
                       dbname: str | None = None) -> None:
        """Register/extend a logical table and grow the physical label set."""
        region = self.physical_region(dbname)
        for t in tag_names:
            if t == METRIC_COLUMN:
                raise InvalidArguments(f"{METRIC_COLUMN} is reserved")
            if not region.schema.has_column(t):
                region.add_tag_column(t)
        dbname = dbname or self.db.current_db
        cols = [ColumnSchema(t, ConcreteDataType.STRING, SemanticType.TAG)
                for t in tag_names]
        cols.append(ColumnSchema("ts", ConcreteDataType.TIMESTAMP_MILLISECOND,
                                 SemanticType.TIMESTAMP, nullable=False))
        cols.append(ColumnSchema("val", ConcreteDataType.FLOAT64,
                                 SemanticType.FIELD))
        schema = Schema(tuple(cols))
        if not self.db.catalog.table_exists(dbname, metric):
            info = self.db.catalog.create_table(
                dbname, metric, schema, engine="metric", if_not_exists=True,
            )
            # logical tables share the physical region
            if info is not None:
                phys = self.db.catalog.get_table(dbname, PHYSICAL_TABLE)
                info.region_ids = list(phys.region_ids)
                self.db.catalog.update_table(info)
        else:
            info = self.db.catalog.get_table(dbname, metric)
            if info.engine != "metric":
                raise InvalidArguments(
                    f"table {metric} exists with engine {info.engine}"
                )
            known = {c.name for c in info.schema}
            grown = False
            for t in tag_names:
                if t not in known:
                    info.schema = Schema(
                        (info.schema.columns[0:0]
                         + tuple(c for c in info.schema if c.is_tag)
                         + (ColumnSchema(t, ConcreteDataType.STRING,
                                         SemanticType.TAG),)
                         + tuple(c for c in info.schema if not c.is_tag)),
                        version=info.schema.version + 1,
                    )
                    grown = True
            if grown:
                self.db.catalog.update_table(info)

    def write(self, metric: str, cols: dict,
              dbname: str | None = None, ensure: bool = True) -> int:
        """Route one metric's batch into the physical region.  The
        injected ``__metric__`` column is a single-entry dictionary
        column (codes are one memset), not ``[metric] * n`` — per-row
        object lists would undo the vectorized wire parse.

        ``ensure=False`` skips the logical-table/label probe: callers
        that already ran ``ensure_logical`` under their DDL lock (the
        remote-write ingest pool) append without re-entering it, so the
        lock never spans the physical region's WAL flush."""
        from greptimedb_tpu.datatypes.batch import DictColumn

        tag_names = list(cols.get("__tags__") or [])
        if ensure:
            self.ensure_logical(metric, tag_names, dbname)
        region = self.physical_region(dbname)
        n = len(cols["ts"])
        data = {
            METRIC_COLUMN: DictColumn(
                np.asarray([metric], dtype=object),
                np.zeros(n, dtype=np.int32)),
            "ts": cols["ts"], "val": cols["val"],
        }
        for t in tag_names:
            data[t] = cols[t]
        region.write(data)
        return n

    def is_logical(self, dbname: str, table: str) -> bool:
        try:
            return self.db.catalog.get_table(dbname, table).engine == "metric"
        except Exception:  # noqa: BLE001
            return False

    def view(self, dbname: str, metric: str) -> "LogicalMetricView":
        info = self.db.catalog.get_table(dbname, metric)
        cache = getattr(self.db, "_metric_views", None)
        if cache is None:
            cache = self.db._metric_views = {}
        key = (dbname, metric)
        v = cache.get(key)
        if v is None or v.schema.version != info.schema.version:
            v = LogicalMetricView(self, metric, info.schema, info.table_id)
            cache[key] = v
        return v


class LogicalMetricView:
    """One metric's slice of the physical region, duck-typing Region for
    the planners (schema/encoders/_series/num_series/generation/scan_host/
    region_id/tag_names)."""

    def __init__(self, engine: MetricEngine, metric: str, schema: Schema,
                 table_id: int):
        self.engine = engine
        self.metric = metric
        self.schema = schema
        self.physical = engine.physical_region()
        # table_id-derived: collision-free, disjoint from real region ids
        # (positive) and CombinedRegionView ids (-(hash%2^40)-1)
        self.region_id = -(1 << 50) - table_id
        self._built_for: int | None = None
        self.encoders: dict[str, DictionaryEncoder] = {}
        self._series: dict[tuple, int] = {}
        self._refresh()

    @property
    def generation(self) -> int:
        return self.physical.generation

    @property
    def tag_names(self) -> list[str]:
        return [c.name for c in self.schema.tag_columns]

    @property
    def num_series(self) -> int:
        self._refresh()
        return len(self._series)

    def ts_bounds(self):
        # physical-wide bounds: per-metric bounds would need per-series time
        # stats; queries with WHERE time ranges override these anyway
        return self.physical.ts_bounds()

    def _refresh(self) -> None:
        """Project the physical series registry onto this metric's tags.

        Logical tsids must be stable under physical growth: they are
        assigned in physical-tsid order and only appended.
        """
        gen = self.physical.generation
        if self._built_for == gen:
            return
        phys = self.physical
        metric_enc = phys.encoders[METRIC_COLUMN]
        my_code = metric_enc.get(self.metric)
        phys_tags = phys.tag_names
        col_pos = {name: i for i, name in enumerate(phys_tags)}
        metric_pos = col_pos[METRIC_COLUMN]
        self.encoders = {
            name: phys.encoders[name] for name in self.tag_names
        }
        mine = self._series
        self._tsid_map: dict[int, int] = getattr(self, "_tsid_map", {})
        for key, ptsid in sorted(phys._series.items(), key=lambda kv: kv[1]):
            if my_code < 0 or key[metric_pos] != my_code:
                continue
            if ptsid in self._tsid_map:
                continue
            lkey = tuple(key[col_pos[t]] for t in self.tag_names)
            if lkey not in mine:
                mine[lkey] = len(mine)
            self._tsid_map[ptsid] = mine[lkey]
        self._built_for = gen

    def scan_host(self, ts_range=(None, None), columns=None, tag_filters=None,
                  tag_preds=None, ft_tokens=None):
        self._refresh()
        filters = dict(tag_filters or {})
        filters[METRIC_COLUMN] = {self.metric}
        want = None
        if columns is not None:
            want = list(dict.fromkeys(list(columns) + [METRIC_COLUMN]))
        host = self.physical.scan_host(ts_range, want, filters, tag_preds,
                                       ft_tokens)
        sel = host[METRIC_COLUMN] == self.metric  # vectorized object-eq
        from greptimedb_tpu.storage.memtable import TSID

        out = {}
        for k, v in host.items():
            if k == METRIC_COLUMN:
                continue
            if columns is not None and k not in want and not k.startswith("__"):
                continue
            out[k] = v[sel]
        # physical tsid -> logical tsid via a dense lookup table
        tmap = self._tsid_map
        max_p = max(tmap) if tmap else -1
        lookup = np.full(max_p + 2, -1, dtype=np.int64)
        for p, l in tmap.items():
            lookup[p] = l
        ptsid = np.clip(out[TSID].astype(np.int64), 0, max_p + 1)
        out[TSID] = lookup[ptsid]
        keep = out[TSID] >= 0
        if not keep.all():
            out = {k: v[keep] for k, v in out.items()}
        return out
