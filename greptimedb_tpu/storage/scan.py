"""Streaming cold-scan pipeline: parallel SST decode + sorted-run merge +
overlapped HBM upload.

The resident caches (storage/cache.py, storage/grid.py) made warm queries
fast, but every cold query and cache (re)build still paid a strictly
sequential read → decode → global-lexsort → upload chain.  This module is
the shared machinery that turns that chain into a pipeline, the
tensor-runtime input-pipeline shape (prefetch + double buffering) of
Theseus (arXiv:2508.05029) applied to the scan path:

- ``read_parts``: fetch+decode SSTs concurrently on a bounded
  ThreadPoolExecutor.  pyarrow's Parquet decode releases the GIL, so
  decode threads scale on real cores; ``GREPTIME_SCAN_THREADS`` caps the
  pool (default ``min(8, files, cores)``).  Staging memory is admitted
  through
  the optional WorkloadMemoryManager (workload ``"scan"``) with
  reject-to-SEQUENTIAL fallback — an over-quota scan degrades to the old
  one-file-at-a-time loop instead of failing.
- ``merge_parts``: SSTs are written sorted by ``(tsid, ts, seq)``, so the
  global ``np.lexsort`` over the concatenated scan is redundant work.
  Single-source scans skip sorting entirely; pre-sorted runs whose key
  ranges don't interleave (TWCS windows of a single series, sequential
  flushes of growing series sets) reduce to an ordered concat;
  time-disjoint runs merge with one narrow tsid-key radix argsort; the
  general case takes one packed-key radix argsort — numpy's stable
  integer sort — instead of a 3-key comparison lexsort.  Output is
  bit-exact with the lexsort path (``GREPTIME_SCAN_FORCE_LEXSORT=1``
  forces the old path for A/B and parity tests).
- ``stream_to_device``: chunked host→device upload with DOUBLE BUFFERING —
  the next chunk's ``device_put`` dispatches while the previous one is
  still in flight (bounded at 2 outstanding chunks, so the relay-safety
  property of bounded in-flight bytes is preserved), overlapping host
  staging with the PCIe/ICI transfer.

Telemetry: every phase lands in ``greptime_scan_*`` registry metrics and
(tracer on) ``scan``/``scan_decode``/``scan_merge`` spans nested under the
query's execute stage, so EXPLAIN ANALYZE and slow_queries show where cold
time goes.
"""

from __future__ import annotations

import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from greptimedb_tpu.utils.telemetry import REGISTRY

M_SCAN_FILES = REGISTRY.counter(
    "greptime_scan_files_total",
    "SST files seen by the scan pipeline, by outcome "
    "(read/pruned/prefetched)",
    labels=("event",),
)
M_SCAN_BYTES = REGISTRY.counter(
    "greptime_scan_bytes_total",
    "Compressed SST bytes decoded by the scan pipeline",
)
M_SCAN_ROWS = REGISTRY.counter(
    "greptime_scan_rows_total",
    "Rows produced by scan-pipeline merges",
)
M_SCAN_PHASE = REGISTRY.histogram(
    "greptime_scan_phase_seconds",
    "Cold-scan phase wall time (decode/merge/upload)",
    labels=("phase",),
)
M_SCAN_MERGE = REGISTRY.counter(
    "greptime_scan_merge_total",
    "Merge strategy taken by scan merges "
    "(presorted/concat/merge/packed_sort/lexsort/empty)",
    labels=("path",),
)
M_SCAN_FALLBACK = REGISTRY.counter(
    "greptime_scan_sequential_fallbacks_total",
    "Parallel scans degraded to sequential decode, by reason",
    labels=("reason",),
)

# last strategy merge_parts took (test/debug observability; the registry
# counter is the aggregate view, this is the "what did MY scan just do")
LAST_MERGE_PATH: str = ""
# last completed scan's phase summary, for the query engines' metrics
# sink (EXPLAIN ANALYZE cold row, slow_queries stages): "seq" bumps once
# per read_parts so a consumer can tell a FRESH cold scan from stale
# state.  THREAD-LOCAL: scans run concurrently from scheduler workers,
# the ingest pool (compaction) and flush paths — a process-global dict
# cross-attributed one thread's decode/merge phases to another thread's
# EXPLAIN ANALYZE/slow_queries row (and a compaction landing mid-query
# overwrote the query's numbers entirely).
_SCAN_STATS_TLS = threading.local()


def scan_stats() -> dict:
    """This thread's last scan phase summary (mutable — read_parts and
    merge_parts write into it)."""
    d = getattr(_SCAN_STATS_TLS, "stats", None)
    if d is None:
        d = _SCAN_STATS_TLS.stats = {"seq": 0}
    return d

# mirrors cache.py's relay-safety bound: one multi-hundred-MB device_put
# RPC can break the TPU relay tunnel, so uploads stream in bounded pieces
_UPLOAD_CHUNK_BYTES = 64 << 20
# double buffer: chunks in flight before blocking on the oldest.  2 keeps
# host staging overlapped with the transfer while bounding outstanding
# relay bytes at 2 chunks (the serialized predecessor allowed 1).
_UPLOAD_DEPTH = 2


# Scan-pool preemption hook (serving/scheduler.py installs it when the
# scheduler is enabled): returns True when the CALLING thread is running
# background-priority work while interactive queries wait — the decode
# pool then narrows to one thread so a cold scan/compaction pass stops
# monopolizing cores under interactive load.  None (scheduler off) costs
# the warm path nothing.
background_yield_hook = None


def scan_threads(num_files: int) -> int:
    """Decode-pool width: ``GREPTIME_SCAN_THREADS`` wins, else
    ``min(8, files, cores)`` narrowed to 1 while the serving scheduler
    reports this thread should yield — more threads than files is pure
    overhead, more than the core count just contends the GIL-held decode
    segments, and more than 8 saturates memory bandwidth before it
    saturates cores."""
    env = os.environ.get("GREPTIME_SCAN_THREADS")
    if env:
        try:
            return max(1, int(env))
        except ValueError:
            pass
    hook = background_yield_hook
    if hook is not None:
        try:
            if hook():
                return 1
        except Exception:  # noqa: BLE001 — preemption is best-effort
            pass
    return max(1, min(8, num_files, os.cpu_count() or 1))


class _Staging:
    """Live bytes held by in-flight parallel decodes — the pull-based
    usage source for the ``"scan"`` memory workload (utils/memory.py)."""

    def __init__(self):
        self._bytes = 0
        self._lock = threading.Lock()

    def add(self, n: int) -> None:
        with self._lock:
            self._bytes += n

    @property
    def bytes(self) -> int:
        return self._bytes


STAGING = _Staging()


def staging_bytes() -> int:
    """Usage hook for WorkloadMemoryManager.register("scan", ...)."""
    return STAGING.bytes


def estimate_staging_bytes(metas, ncols: int,
                           ts_range=(None, None)) -> int:
    """Decoded-bytes estimate for quota admission: ~8 bytes/cell over the
    rows a ``ts_range``-restricted read can actually return.  Scaling each
    file by its time-overlap fraction matters on the catch-up path, where
    whole files prune down to a near-empty tail — a full-file estimate
    there would trip reject-to-sequential exactly when real staging is
    smallest."""
    lo, hi = ts_range
    rows = 0.0
    for m in metas:
        span = max(1, int(m.ts_max) - int(m.ts_min) + 1)
        eff_lo = int(m.ts_min) if lo is None else max(int(m.ts_min), int(lo))
        eff_hi = (int(m.ts_max) + 1 if hi is None
                  else min(int(m.ts_max) + 1, int(hi)))
        frac = min(1.0, max(0.0, (eff_hi - eff_lo) / span))
        rows += m.num_rows * frac
    return int(rows * 8 * max(1, ncols))


def read_parts(tasks, memory=None, est_bytes: int = 0):
    """Run decode ``tasks`` (zero-arg callables returning column dicts),
    order-preserving.  Decodes concurrently on a bounded pool unless the
    thread knob says 1, there is nothing to parallelize, or the staging
    estimate is rejected by the ``"scan"`` memory workload — in which
    case it falls back to the sequential loop (identical output)."""
    n = len(tasks)
    stats = scan_stats()
    seq = stats.get("seq", 0) + 1
    stats.clear()
    stats["seq"] = seq
    if n == 0:
        return []
    threads = min(scan_threads(n), n)
    admitted = 0
    if threads > 1 and memory is not None and est_bytes > 0:
        if memory.try_admit("scan", est_bytes):
            admitted = est_bytes
        else:
            M_SCAN_FALLBACK.labels("quota").inc()
            threads = 1
    t0 = time.perf_counter()
    try:
        if threads <= 1:
            out = [t() for t in tasks]
        else:
            STAGING.add(admitted)
            try:
                with ThreadPoolExecutor(
                    max_workers=threads, thread_name_prefix="scan-decode"
                ) as pool:
                    out = list(pool.map(lambda t: t(), tasks))
            finally:
                STAGING.add(-admitted)
    finally:
        dt = time.perf_counter() - t0
        M_SCAN_PHASE.labels("decode").observe(dt)
        stats["files"] = n
        stats["threads"] = threads
        stats["decode_ms"] = round(dt * 1000, 3)
    return out


# ---------------------------------------------------------------------------
# Sorted-run merge
# ---------------------------------------------------------------------------


def _pack_keys(parts, ts_name: str, tsid_name: str, seq_name: str):
    """Per-part 1-D int64 keys order-equivalent to lexicographic
    (tsid, ts, seq), or None when the combined bit width cannot fit 62
    bits (caller falls back to np.lexsort).  Values are biased to their
    global minima so pre-epoch timestamps and large sequences pack."""
    live = [p for p in parts if len(p[ts_name])]
    if not live:
        return []
    ts_min = min(int(p[ts_name].min()) for p in live)
    ts_max = max(int(p[ts_name].max()) for p in live)
    seq_min = min(int(p[seq_name].min()) for p in live)
    seq_max = max(int(p[seq_name].max()) for p in live)
    tsid_max = max(int(p[tsid_name].max()) for p in live)
    if min(int(p[tsid_name].min()) for p in live) < 0:
        return None  # poison codes: refuse, lexsort handles anything
    w_ts = max(1, int(ts_max - ts_min).bit_length())
    w_seq = max(1, int(seq_max - seq_min).bit_length())
    w_tsid = max(1, int(tsid_max).bit_length())
    if w_tsid + w_ts + w_seq > 62:
        return None
    keys = []
    for p in parts:
        tsid = p[tsid_name].astype(np.int64, copy=False)
        rel_ts = p[ts_name].astype(np.int64, copy=False) - ts_min
        rel_seq = p[seq_name].astype(np.int64, copy=False) - seq_min
        keys.append((tsid << np.int64(w_ts + w_seq))
                    | (rel_ts << np.int64(w_seq)) | rel_seq)
    return keys


def merge_parts(parts, ts_name: str, tsid_name: str, seq_name: str):
    """Merge scan parts into global (tsid, ts, seq) order; returns
    ``(merged_columns, path)``.

    Bit-exact with ``np.lexsort((seq, ts, tsid))`` over the concatenation
    on every path (stable reductions of stably-sorted runs ≡ a stable
    global sort).  Strategy tiers, cheapest first:

    - ``presorted``: one already-sorted source — no sort, no copy;
    - ``concat``: sorted runs whose key ranges don't interleave in part
      order — a plain concatenate;
    - ``merge``: sorted runs with pairwise-DISJOINT time ranges (the
      TWCS-common case): concat in time order, then one stable argsort
      on the tsid column alone — numpy's stable integer sort is a radix
      sort, and the narrow tsid key needs a fraction of the passes a
      3-key comparison lexsort burns; within a tsid, time order equals
      run order, so the result is exact;
    - ``packed_sort``: interleaving/unsorted sources — one stable radix
      argsort over the packed 1-D keys (still ~4x under lexsort);
    - ``lexsort``: key space too wide to pack, or forced via
      ``GREPTIME_SCAN_FORCE_LEXSORT=1`` (the A/B reference path).
    """
    global LAST_MERGE_PATH
    t0 = time.perf_counter()
    merged, path = _merge_parts(parts, ts_name, tsid_name, seq_name)
    dt = time.perf_counter() - t0
    M_SCAN_PHASE.labels("merge").observe(dt)
    M_SCAN_MERGE.labels(path).inc()
    M_SCAN_ROWS.inc(len(merged[ts_name]))
    LAST_MERGE_PATH = path
    stats = scan_stats()
    stats["path"] = path
    stats["rows"] = len(merged[ts_name])
    stats["merge_ms"] = round(dt * 1000, 3)
    return merged, path


def _concat(parts, names):
    return {k: np.concatenate([p[k] for p in parts]) for k in names}


def _merge_parts(parts, ts_name, tsid_name, seq_name):
    names = list(parts[0].keys())
    live = [p for p in parts if len(p[ts_name])]
    if not live:
        return _concat(parts, names), "empty"

    def lexsorted():
        merged = _concat(parts, names)
        order = np.lexsort(
            (merged[seq_name], merged[ts_name], merged[tsid_name]))
        return {k: v[order] for k, v in merged.items()}, "lexsort"

    if os.environ.get("GREPTIME_SCAN_FORCE_LEXSORT") == "1":
        return lexsorted()
    keys = _pack_keys(live, ts_name, tsid_name, seq_name)
    if keys is None:
        return lexsorted()
    # packed order == (tsid, ts, seq) order by construction, so run
    # sortedness is one vectorized diff per part
    sorted_flags = [
        len(k) <= 1 or not bool((np.diff(k) < 0).any()) for k in keys
    ]
    if len(live) == 1:
        if sorted_flags[0]:
            return dict(live[0]), "presorted"
        o = np.argsort(keys[0], kind="stable")
        return {k: v[o] for k, v in live[0].items()}, "packed_sort"
    if all(sorted_flags):
        # ordered concat: consecutive runs' key ranges don't interleave —
        # single-series TWCS windows, flushes of monotonically growing
        # series sets.  Non-strict boundaries are safe in part order:
        # equal keys keep concat order, exactly what a stable sort does.
        if all(int(keys[i][-1]) <= int(keys[i + 1][0])
               for i in range(len(keys) - 1)):
            return _concat(live, names), "concat"
        # sorted-run merge, disjoint-time tier: order runs by time; when
        # strictly disjoint, within any tsid the run order IS the time
        # order, so one stable radix argsort on the narrow tsid key
        # restores the full (tsid, ts, seq) order.  Strictness makes
        # cross-run key ties impossible — bit-exact with lexsort.
        bounds = [
            (int(p[ts_name].min()), int(p[ts_name].max())) for p in live
        ]
        time_order = sorted(range(len(live)), key=lambda i: bounds[i][0])
        if all(bounds[time_order[j]][1] < bounds[time_order[j + 1]][0]
               for j in range(len(time_order) - 1)):
            runs = [live[i] for i in time_order]
            cat_tsid = np.concatenate([p[tsid_name] for p in runs])
            o = np.argsort(cat_tsid, kind="stable")
            merged = _concat(runs, names)
            return {k: v[o] for k, v in merged.items()}, "merge"
    # interleaving or unsorted runs: one stable radix argsort over the
    # packed keys of the concatenation (original part order — stability
    # then matches the lexsort reference exactly)
    o = np.argsort(np.concatenate(keys), kind="stable")
    merged = _concat(live, names)
    return {k: v[o] for k, v in merged.items()}, "packed_sort"


# ---------------------------------------------------------------------------
# Overlapped host→device upload
# ---------------------------------------------------------------------------


def stream_to_device(arr: np.ndarray, sharding=None):
    """Host→device upload: small arrays in one hop; large ones flattened
    and streamed in bounded chunks with ``_UPLOAD_DEPTH`` dispatches in
    flight, so the host-side slice staging of chunk i+1 overlaps chunk
    i's transfer (the double-buffered handoff).  With a sharding, the
    array lands distributed in one placement — multi-chip meshes have
    per-chip links, not the single-relay bottleneck the chunking guards."""
    import jax
    import jax.numpy as jnp

    t0 = time.perf_counter()
    try:
        if sharding is not None:
            return jax.device_put(arr, sharding)
        if arr.nbytes <= _UPLOAD_CHUNK_BYTES:
            return jnp.asarray(arr)
        flat = np.ascontiguousarray(arr).reshape(-1)
        per = max(1, _UPLOAD_CHUNK_BYTES // max(1, arr.dtype.itemsize))
        parts = []
        inflight: list = []
        for i in range(0, flat.shape[0], per):
            p = jax.device_put(flat[i:i + per])
            inflight.append(p)
            parts.append(p)
            if len(inflight) >= _UPLOAD_DEPTH:
                inflight.pop(0).block_until_ready()
        for p in inflight:
            p.block_until_ready()
        out = jnp.concatenate(parts).reshape(arr.shape)
        out.block_until_ready()
        return out
    finally:
        M_SCAN_PHASE.labels("upload").observe(time.perf_counter() - t0)


def prefetch_store(store, metas) -> int:
    """Scan-driven readahead: ask the object store to start pulling the
    selected-but-not-yet-local SSTs before the decode pool reaches them.
    No-op for stores without a prefetcher (local fs, memory)."""
    fetch = getattr(store, "prefetch", None)
    if fetch is None or not metas:
        return 0
    queued = int(fetch([m.path for m in metas]))
    if queued:
        M_SCAN_FILES.labels("prefetched").inc(queued)
    return queued
