"""Write-ahead log: segmented, CRC-checked, per-region append log.

Equivalent of the reference's raft-engine local WAL
(src/log-store/src/raft_engine/) behind the LogStore trait
(src/store-api/src/logstore.rs:51): entries are (region, sequence, payload)
appended durably before memtable writes; region open replays entries past
the flushed sequence (SURVEY.md §5.4 mechanism 1). A Kafka-style remote WAL
can implement the same LogStore interface later.

Record format (little-endian): [u32 len][u32 crc32(payload)][u64 sequence]
[payload]. Torn tails (crash mid-append) are detected by length/CRC and
truncated on replay. Payloads are columnar row groups serialized with
Arrow IPC — portable and fast, no pickle.

Group commit (``GREPTIME_WAL_GROUP_COMMIT``, default on): concurrent
appenders hand their encoded records to a per-log committer; one of them
becomes the flush leader and writes EVERY buffered record with a single
buffered write + flush (+ one fsync when ``sync``), while followers block
until their record is durable — the classic leader/follower group commit
(InnoDB redo, Kafka producer batching).  A lone writer never waits: the
leader flushes immediately and arrivals during its write accumulate for
the NEXT leader.  ``GREPTIME_WAL_LINGER_MS`` optionally makes a leader
hold the batch open for that long when the PREVIOUS flush was contended
(batch > 1) — deeper batches per fsync on slow devices, no added latency
when traffic is serial.  Each writer is acked only after the flush (and
fsync, when enabled) covering its record returns.
"""

from __future__ import annotations

import os
import io
import struct
import threading
import time
import zlib

import pyarrow as pa
import pyarrow.ipc

from greptimedb_tpu.utils import telemetry

_HDR = struct.Struct("<IIQ")
_SEGMENT_TARGET = 64 * 1024 * 1024

# CRC of record payloads: the C++ helper (same polynomial, sliced table)
# is ~2x zlib on the MB-sized payloads group commit produces and runs
# GIL-free through ctypes, letting concurrent appenders' checksums
# overlap; zlib is the always-present fallback and reads identically on
# replay (identical CRC-32)
try:
    from greptimedb_tpu import native as _native

    _crc32 = _native.crc32 if _native.lib() is not None else None
except Exception:  # pragma: no cover — native build is best-effort
    _crc32 = None


def _payload_crc(payload: bytes) -> int:
    if _crc32 is not None and len(payload) >= 1 << 16:
        return _crc32(payload)
    return zlib.crc32(payload)

M_WAL_BATCH = telemetry.REGISTRY.histogram(
    "greptime_ingest_wal_batch_size",
    "records per WAL group-commit flush",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128))
M_WAL_FSYNCS = telemetry.REGISTRY.counter(
    "greptime_ingest_wal_fsyncs_total", "WAL fsync calls")


def group_commit_enabled() -> bool:
    return os.environ.get("GREPTIME_WAL_GROUP_COMMIT", "on").lower() not in (
        "off", "0", "false")


def _linger_s() -> float:
    try:
        return float(os.environ.get("GREPTIME_WAL_LINGER_MS", "0")) / 1000.0
    except ValueError:
        return 0.0


class _GroupCommitter:
    """Leader/follower flush protocol for one log's appenders.

    ``enqueue`` assigns a monotonically increasing ticket under the lock
    (so record order in the file equals enqueue order); ``wait`` blocks
    until a flush covering the ticket has completed, electing the caller
    leader when no flush is in flight.  The leader swaps the buffer out,
    writes it OUTSIDE the lock (followers keep enqueueing into the fresh
    buffer meanwhile), then publishes progress and wakes everyone."""

    def __init__(self, store: "FileLogStore"):
        self._store = store
        self._cond = threading.Condition()
        self._buf: list[bytes] = []
        self._enqueued = 0
        self._flushed = 0
        self._flushing = False
        self._last_batch = 1
        self._error: BaseException | None = None
        self._error_upto = 0

    def enqueue(self, rec: bytes) -> int:
        with self._cond:
            self._buf.append(rec)
            self._enqueued += 1
            ticket = self._enqueued
            self._cond.notify_all()  # wake a lingering leader
            return ticket

    def wait(self, ticket: int) -> None:
        with self._cond:
            while self._flushed < ticket:
                if self._flushing:
                    self._cond.wait()
                    continue
                self._lead()
            if self._error is not None and ticket <= self._error_upto:
                raise self._error

    def _lead(self) -> None:
        """Called under the lock with no flush in flight: flush the
        current buffer as its leader."""
        self._flushing = True
        linger = _linger_s()
        if linger > 0 and self._last_batch > 1:
            # saturation signal: the previous flush was contended — hold
            # the batch open briefly so concurrent appenders join it
            deadline = time.monotonic() + linger
            while True:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or len(self._buf) >= 128:
                    break
                self._cond.wait(timeout=remaining)
        take = self._buf
        self._buf = []
        upto = self._enqueued
        self._cond.release()
        err: BaseException | None = None
        try:
            self._store._flush_records(b"".join(take), len(take))
        except BaseException as e:  # noqa: BLE001 — delivered to waiters
            err = e
        finally:
            self._cond.acquire()
            self._flushed = upto
            self._last_batch = max(1, len(take))
            self._flushing = False
            if err is not None:
                self._error = err
                self._error_upto = upto
            self._cond.notify_all()


class LogStore:
    """Interface (reference store-api logstore.rs:51)."""

    # False = appends are dropped (Noop): writers may skip payload
    # serialization entirely — the encode cost is pure waste
    durable = True

    def append(self, sequence: int, payload: bytes) -> None:
        raise NotImplementedError

    def replay(self, from_sequence: int, repair: bool = True):
        raise NotImplementedError

    def truncate(self, up_to_sequence: int) -> None:
        raise NotImplementedError


class FileLogStore(LogStore):
    """One directory of numbered segment files per region."""

    def __init__(self, wal_dir: str, sync: bool = False,
                 group_commit: bool | None = None):
        self.dir = wal_dir
        self.sync = sync
        os.makedirs(wal_dir, exist_ok=True)
        segs = self._segments()
        self._current_id = segs[-1] if segs else 0
        self._fh = open(self._seg_path(self._current_id), "ab")
        if group_commit is None:
            group_commit = group_commit_enabled()
        self._gc = _GroupCommitter(self) if group_commit else None

    def _seg_path(self, seg_id: int) -> str:
        return os.path.join(self.dir, f"{seg_id:020d}.wal")

    def _segments(self) -> list[int]:
        out = []
        for fn in os.listdir(self.dir):
            if fn.endswith(".wal"):
                out.append(int(fn[:-4]))
        return sorted(out)

    def _flush_records(self, data: bytes, count: int) -> None:
        """One buffered write + flush (+ fsync) for ``count`` records —
        the single IO round-trip a whole commit group shares."""
        self._fh.write(data)
        self._fh.flush()
        if self.sync:
            os.fsync(self._fh.fileno())
            M_WAL_FSYNCS.inc()
        M_WAL_BATCH.observe(count)
        if self._fh.tell() >= _SEGMENT_TARGET:
            self._roll()

    def append(self, sequence: int, payload: bytes) -> None:
        rec = _HDR.pack(len(payload), _payload_crc(payload), sequence) + payload
        if self._gc is not None:
            self._gc.wait(self._gc.enqueue(rec))
            return
        # single durability path — group-commit off writes a batch of one
        # through the same helper, so metrics (fsyncs, batch sizes) and
        # any future durability change stay consistent across modes
        self._flush_records(rec, 1)

    def append_async(self, sequence: int, payload: bytes):
        """Enqueue a record for the next commit group and return a
        ``wait()`` callable that blocks until it is durable.  Lets callers
        that serialize sequence assignment under their own lock (the
        shared-log broker) enqueue inside it and wait OUTSIDE it — the
        group commit then merges appends from many topics/regions into
        one fsync."""
        rec = _HDR.pack(len(payload), _payload_crc(payload), sequence) + payload
        if self._gc is None:
            # synchronous path: write now, nothing to wait for
            self._flush_records(rec, 1)
            return lambda: None
        ticket = self._gc.enqueue(rec)
        return lambda: self._gc.wait(ticket)

    def _roll(self) -> None:
        self._fh.close()
        self._current_id += 1
        self._fh = open(self._seg_path(self._current_id), "ab")

    def replay(self, from_sequence: int = 0, repair: bool = True):
        """Yield (sequence, payload) for entries with sequence >= from_sequence.
        Stops at the first torn/corrupt record; with ``repair`` (write
        ownership — leader open/recovery) the torn tail is truncated so
        future appends start clean.  Followers replaying a WAL directory
        shared with a live leader MUST pass repair=False: a partially
        flushed leader append would otherwise be destroyed mid-write."""
        try:
            from greptimedb_tpu import native
        except ImportError:
            native = None
        for seg in self._segments():
            path = self._seg_path(seg)
            with open(path, "rb") as f:
                data = f.read()
            good_end = 0
            scanned = native.wal_scan(data, from_sequence) if native else None
            if scanned is not None:
                spans, good_end = scanned
                for seq, off, ln in spans:
                    yield seq, data[off:off + ln]
            else:
                off = 0
                while off + _HDR.size <= len(data):
                    ln, crc, seq = _HDR.unpack_from(data, off)
                    end = off + _HDR.size + ln
                    if end > len(data):
                        break
                    payload = data[off + _HDR.size : end]
                    if zlib.crc32(payload) != crc:
                        break
                    good_end = end
                    off = end
                    if seq >= from_sequence:
                        yield seq, payload
            if good_end < len(data):
                if repair:
                    # torn tail: truncate so future appends start clean
                    with open(path, "r+b") as f:
                        f.truncate(good_end)
                    if seg == self._current_id:
                        self._fh.close()
                        self._fh = open(path, "ab")
                break

    def truncate(self, up_to_sequence: int) -> None:
        """Drop whole segments whose every entry is below up_to_sequence."""
        for seg in self._segments()[:-1]:  # never drop the active segment
            path = self._seg_path(seg)
            keep = False
            with open(path, "rb") as f:
                data = f.read()
            off = 0
            while off + _HDR.size <= len(data):
                ln, _crc, seq = _HDR.unpack_from(data, off)
                if seq >= up_to_sequence:
                    keep = True
                    break
                off += _HDR.size + ln
            if not keep:
                os.unlink(path)

    def close(self) -> None:
        self._fh.close()


class NoopLogStore(LogStore):
    """WAL-less mode for benchmarks (reference src/log-store/src/noop/)."""

    durable = False

    def append(self, sequence: int, payload: bytes) -> None:
        pass

    def replay(self, from_sequence: int = 0, repair: bool = True):
        return iter(())

    def truncate(self, up_to_sequence: int) -> None:
        pass

    def close(self) -> None:
        pass


# ---- payload codec: Arrow IPC over the write columns -----------------------

_OP_META = b"greptime.op"


def encode_write(columns: dict, op: int = 0) -> bytes:
    """Serialize one write batch.  Only the schema columns belong in the
    payload: per-row ``__tsid__``/``__seq__``/``__op__`` are derivable at
    replay (tsids recompute deterministically, the sequence rides the
    record header, and a batch has ONE op) — logging them would grow
    every record ~15% for bytes replay throws away.  ``op`` lands in the
    stream's schema metadata instead."""
    table = pa.table(columns)
    if op:
        table = table.replace_schema_metadata({_OP_META: str(op)})
    sink = io.BytesIO()
    with pa.ipc.new_stream(sink, table.schema) as w:
        w.write_table(table)
    return sink.getvalue()


def decode_write(payload: bytes) -> dict:
    return decode_write_full(payload)[0]


def decode_write_full(payload: bytes) -> tuple[dict, int]:
    """(columns, op) — accepts both the slim format and older payloads
    that carried __seq__/__op__ columns (replay prefers the columns when
    present, so logs written before the slimming replay identically)."""
    with pa.ipc.open_stream(io.BytesIO(payload)) as r:
        table = r.read_all()
    meta = table.schema.metadata or {}
    op = int(meta.get(_OP_META, b"0"))
    cols = {name: table.column(name).combine_chunks()
            for name in table.column_names}
    return cols, op
