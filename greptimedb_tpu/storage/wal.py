"""Write-ahead log: segmented, CRC-checked, per-region append log.

Equivalent of the reference's raft-engine local WAL
(src/log-store/src/raft_engine/) behind the LogStore trait
(src/store-api/src/logstore.rs:51): entries are (region, sequence, payload)
appended durably before memtable writes; region open replays entries past
the flushed sequence (SURVEY.md §5.4 mechanism 1). A Kafka-style remote WAL
can implement the same LogStore interface later.

Record format (little-endian): [u32 len][u32 crc32(payload)][u64 sequence]
[u32 crc32(header)][payload]. The header CRC covers the 16-byte
(len, payload-crc, sequence) prefix so a bit flip ANYWHERE in a record —
including the sequence field — is detected; a payload-only checksum
would let a flipped sequence replay as a wrong-but-valid record.
Payloads are columnar row groups serialized with Arrow IPC — portable
and fast, no pickle.

Corruption triage (ISSUE 9, the raft-engine recovery-modes analog):
replay distinguishes a **torn tail** (crash debris at the end of the
active segment — truncated, today's behavior, correct) from **interior
corruption** (bit rot inside acked records): on a bad record it scans
forward for the next valid record boundary, counts the event in
``greptime_durability_corruption_total{store="wal",kind=...}``, copies
the damaged bytes to a ``.quarantine`` sidecar (originals preserved,
never deleted), keeps replaying past the hole, and reports the lost
sequence range in ``last_triage`` so the region can resync it from the
remote WAL or a follower replica before declaring data loss
(``heal()`` then compacts the damaged span out of the segment).

Group commit (``GREPTIME_WAL_GROUP_COMMIT``, default on): concurrent
appenders hand their encoded records to a per-log committer; one of them
becomes the flush leader and writes EVERY buffered record with a single
buffered write + flush (+ one fsync when ``sync``), while followers block
until their record is durable — the classic leader/follower group commit
(InnoDB redo, Kafka producer batching).  A lone writer never waits: the
leader flushes immediately and arrivals during its write accumulate for
the NEXT leader.  ``GREPTIME_WAL_LINGER_MS`` optionally makes a leader
hold the batch open for that long when the PREVIOUS flush was contended
(batch > 1) — deeper batches per fsync on slow devices, no added latency
when traffic is serial.  Each writer is acked only after the flush (and
fsync, when enabled) covering its record returns.
"""

from __future__ import annotations

import os
import io
import struct
import threading
import time
import zlib
from dataclasses import dataclass

import pyarrow as pa
import pyarrow.ipc

from greptimedb_tpu.storage.durability import M_CORRUPTION, M_QUARANTINED
from greptimedb_tpu.storage.object_store import _fsync_dir
from greptimedb_tpu.utils import telemetry
from greptimedb_tpu.utils.chaos import CHAOS

# record header: [u32 len][u32 crc32(payload)][u64 seq] + [u32 crc32(hdr)]
_HDR = struct.Struct("<IIQ")
_HCRC = struct.Struct("<I")
_REC_HDR = _HDR.size + _HCRC.size  # 20 bytes
_SEGMENT_TARGET = 64 * 1024 * 1024

# CRC of record payloads: the C++ helper (same polynomial, sliced table)
# is ~2x zlib on the MB-sized payloads group commit produces and runs
# GIL-free through ctypes, letting concurrent appenders' checksums
# overlap; zlib is the always-present fallback and reads identically on
# replay (identical CRC-32)
try:
    from greptimedb_tpu import native as _native

    _crc32 = _native.crc32 if _native.lib() is not None else None
except Exception:  # pragma: no cover — native build is best-effort
    _crc32 = None


def _payload_crc(payload: bytes) -> int:
    if _crc32 is not None and len(payload) >= 1 << 16:
        return _crc32(payload)
    return zlib.crc32(payload)


def _pack_record(sequence: int, payload: bytes) -> bytes:
    hdr = _HDR.pack(len(payload), _payload_crc(payload), sequence)
    return hdr + _HCRC.pack(zlib.crc32(hdr)) + payload


def _native():
    try:
        from greptimedb_tpu import native
    except ImportError:
        return None
    return native


def _scan(data: bytes, off: int, native_mod):
    """Scan valid records from ``off``; returns ``(spans, end)`` where
    spans are (seq, payload_off, payload_len) and ``end`` is the offset
    after the last valid record (== len(data) on a clean scan)."""
    if native_mod is not None:
        view = data if off == 0 else data[off:]
        scanned = native_mod.wal_scan(view, 0)
        if scanned is not None:
            spans, end = scanned
            if off:
                spans = [(s, o + off, ln) for s, o, ln in spans]
                end += off
            return spans, end
    spans = []
    n = len(data)
    while off + _REC_HDR <= n:
        ln, crc, seq = _HDR.unpack_from(data, off)
        (hcrc,) = _HCRC.unpack_from(data, off + _HDR.size)
        if zlib.crc32(data[off:off + _HDR.size]) != hcrc:
            break
        end = off + _REC_HDR + ln
        if end > n:
            break
        if zlib.crc32(data[off + _REC_HDR:end]) != crc:
            break
        spans.append((seq, off + _REC_HDR, ln))
        off = end
    return spans, off


def _parse_v1(data: bytes, off: int):
    """Legacy 16-byte-header record ([len][crc(payload)][seq], no header
    CRC) at ``off`` — read compatibility for data homes written before
    the v2 format (tests/compat fixtures).  Returns (seq, payload_off,
    payload_len) or None.  Only consulted where a v2 parse failed, so a
    v2 record never misreads as v1."""
    if off + _HDR.size > len(data):
        return None
    ln, crc, seq = _HDR.unpack_from(data, off)
    end = off + _HDR.size + ln
    if end > len(data):
        return None
    if zlib.crc32(data[off + _HDR.size:end]) != crc:
        return None
    return seq, off + _HDR.size, ln


def _walk(data: bytes, native_mod):
    """Classify a segment byte-exactly into record and damage spans:
    yields ``("rec", seq, payload_off, payload_len, rec_start, rec_end)``
    for every valid record (v2, or legacy v1 where v2 fails) and
    ``("gap", start, end)`` for invalid spans (``end == len(data)``:
    damage reaches EOF).  Shared by replay, heal and truncate so all
    three agree on what a segment contains."""
    off = 0
    n = len(data)
    while off < n:
        spans, end = _scan(data, off, native_mod)
        for seq, poff, ln in spans:
            yield ("rec", seq, poff, ln, poff - _REC_HDR, poff + ln)
        if end >= n:
            return
        v1 = _parse_v1(data, end)
        if v1 is not None:
            # consume the whole legacy run inline: re-entering the v2
            # scanner (which slices data[off:] for the native library)
            # per record would make a long v1 segment O(n^2) in copies
            off = end
            while v1 is not None:
                seq, poff, ln = v1
                yield ("rec", seq, poff, ln, off, poff + ln)
                off = poff + ln
                v1 = _parse_v1(data, off)
            continue
        nxt = _next_boundary(data, end + 1, native_mod)
        yield ("gap", end, nxt if nxt is not None else n)
        if nxt is None:
            return
        off = nxt


def _next_boundary(data: bytes, start: int,
                   native_mod=None) -> int | None:
    """Byte-scan forward for the next offset holding a fully valid record
    (header CRC + bounds + payload CRC) — the interior-corruption resync
    point.  None = no valid record follows (damage reaches EOF)."""
    if native_mod is not None:
        l = native_mod.lib()
        if l is not None and not getattr(l, "_gt_no_wal", False):
            return native_mod.wal_find_boundary(data, start)
    n = len(data)
    for off in range(max(0, start), n - _REC_HDR + 1):
        ln, crc, _seq = _HDR.unpack_from(data, off)
        (hcrc,) = _HCRC.unpack_from(data, off + _HDR.size)
        if zlib.crc32(data[off:off + _HDR.size]) != hcrc:
            continue
        end = off + _REC_HDR + ln
        if end > n:
            continue
        if zlib.crc32(data[off + _REC_HDR:end]) != crc:
            continue
        return off
    return None


@dataclass
class WalDamage:
    """One triaged corruption event from a replay pass."""

    path: str          # segment file
    kind: str          # "torn_tail" | "interior"
    start: int         # damaged byte span [start, end) within the segment
    end: int
    prev_seq: int | None  # last valid sequence before the damage
    next_seq: int | None  # first valid sequence after (None: none found)

    def lost_range(self) -> tuple[int, int | None] | None:
        """Inclusive sequence range the damage may have destroyed, or
        None when nothing can be missing (pure garbage between two
        consecutive sequences).  ``(lo, None)`` = open-ended."""
        lo = (self.prev_seq + 1) if self.prev_seq is not None else 1
        if self.next_seq is None:
            return None if self.kind == "torn_tail" else (lo, None)
        hi = self.next_seq - 1
        return None if hi < lo else (lo, hi)

M_WAL_BATCH = telemetry.REGISTRY.histogram(
    "greptime_ingest_wal_batch_size",
    "records per WAL group-commit flush",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128))
M_WAL_FSYNCS = telemetry.REGISTRY.counter(
    "greptime_ingest_wal_fsyncs_total", "WAL fsync calls")


def group_commit_enabled() -> bool:
    return os.environ.get("GREPTIME_WAL_GROUP_COMMIT", "on").lower() not in (
        "off", "0", "false")


def _linger_s() -> float:
    try:
        return float(os.environ.get("GREPTIME_WAL_LINGER_MS", "0")) / 1000.0
    except ValueError:
        return 0.0


class _GroupCommitter:
    """Leader/follower flush protocol for one log's appenders.

    ``enqueue`` assigns a monotonically increasing ticket under the lock
    (so record order in the file equals enqueue order); ``wait`` blocks
    until a flush covering the ticket has completed, electing the caller
    leader when no flush is in flight.  The leader swaps the buffer out,
    writes it OUTSIDE the lock (followers keep enqueueing into the fresh
    buffer meanwhile), then publishes progress and wakes everyone."""

    def __init__(self, store: "FileLogStore"):
        self._store = store
        self._cond = threading.Condition()
        self._buf: list[bytes] = []
        self._enqueued = 0
        self._flushed = 0
        self._flushing = False
        self._last_batch = 1
        self._error: BaseException | None = None
        self._error_upto = 0

    def enqueue(self, rec: bytes) -> int:
        with self._cond:
            self._buf.append(rec)
            self._enqueued += 1
            ticket = self._enqueued
            self._cond.notify_all()  # wake a lingering leader
            return ticket

    def wait(self, ticket: int) -> None:
        with self._cond:
            while self._flushed < ticket:
                if self._flushing:
                    self._cond.wait()
                    continue
                self._lead()
            if self._error is not None and ticket <= self._error_upto:
                raise self._error

    def _lead(self) -> None:
        """Called under the lock with no flush in flight: flush the
        current buffer as its leader."""
        self._flushing = True
        linger = _linger_s()
        if linger > 0 and self._last_batch > 1:
            # saturation signal: the previous flush was contended — hold
            # the batch open briefly so concurrent appenders join it
            deadline = time.monotonic() + linger
            while True:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or len(self._buf) >= 128:
                    break
                self._cond.wait(timeout=remaining)
        take = self._buf
        self._buf = []
        upto = self._enqueued
        self._cond.release()
        err: BaseException | None = None
        try:
            self._store._flush_records(b"".join(take), len(take))
        except BaseException as e:  # noqa: BLE001 — delivered to waiters
            err = e
        finally:
            self._cond.acquire()
            self._flushed = upto
            self._last_batch = max(1, len(take))
            self._flushing = False
            if err is not None:
                self._error = err
                self._error_upto = upto
            self._cond.notify_all()


class LogStore:
    """Interface (reference store-api logstore.rs:51)."""

    # False = appends are dropped (Noop): writers may skip payload
    # serialization entirely — the encode cost is pure waste
    durable = True

    def append(self, sequence: int, payload: bytes) -> None:
        raise NotImplementedError

    def replay(self, from_sequence: int, repair: bool = True):
        raise NotImplementedError

    def truncate(self, up_to_sequence: int) -> None:
        raise NotImplementedError


class FileLogStore(LogStore):
    """One directory of numbered segment files per region."""

    def __init__(self, wal_dir: str, sync: bool = False,
                 group_commit: bool | None = None):
        self.dir = wal_dir
        self.sync = sync
        os.makedirs(wal_dir, exist_ok=True)
        segs = self._segments()
        self._current_id = segs[-1] if segs else 0
        self._fh = open(self._seg_path(self._current_id), "ab")
        if group_commit is None:
            group_commit = group_commit_enabled()
        self._gc = _GroupCommitter(self) if group_commit else None
        # corruption triage report of the most recent replay() pass
        self.last_triage: list[WalDamage] = []

    def _seg_path(self, seg_id: int) -> str:
        return os.path.join(self.dir, f"{seg_id:020d}.wal")

    def _segments(self) -> list[int]:
        out = []
        for fn in os.listdir(self.dir):
            if fn.endswith(".wal"):
                out.append(int(fn[:-4]))
        return sorted(out)

    def _flush_records(self, data: bytes, count: int) -> None:
        """One buffered write + flush (+ fsync) for ``count`` records —
        the single IO round-trip a whole commit group shares.

        A failed/torn flush rolls the file back to the pre-flush offset:
        a survivable write error is surfaced to the appenders (nothing
        acked) and must not leave half-records that later appends would
        bury as interior corruption — only a real crash leaves a torn
        tail, and replay truncates that."""
        after = None
        if CHAOS.enabled:  # disk fault injection: torn/bitflip/error/kill
            data, after = CHAOS.filter_io("wal.flush", data)
        pos = self._fh.tell()
        try:
            self._fh.write(data)
            self._fh.flush()
            if after is not None:
                raise after  # torn write: prefix persisted, then fail
            if self.sync:
                os.fsync(self._fh.fileno())
                M_WAL_FSYNCS.inc()
        except BaseException:
            try:
                self._fh.truncate(pos)
            except OSError:
                pass  # rollback is best-effort; replay triage covers it
            raise
        M_WAL_BATCH.observe(count)
        if self._fh.tell() >= _SEGMENT_TARGET:
            self._roll()

    def append(self, sequence: int, payload: bytes) -> None:
        rec = _pack_record(sequence, payload)
        if self._gc is not None:
            self._gc.wait(self._gc.enqueue(rec))
            return
        # single durability path — group-commit off writes a batch of one
        # through the same helper, so metrics (fsyncs, batch sizes) and
        # any future durability change stay consistent across modes
        self._flush_records(rec, 1)

    def append_async(self, sequence: int, payload: bytes):
        """Enqueue a record for the next commit group and return a
        ``wait()`` callable that blocks until it is durable.  Lets callers
        that serialize sequence assignment under their own lock (the
        shared-log broker) enqueue inside it and wait OUTSIDE it — the
        group commit then merges appends from many topics/regions into
        one fsync."""
        rec = _pack_record(sequence, payload)
        if self._gc is None:
            # synchronous path: write now, nothing to wait for
            self._flush_records(rec, 1)
            return lambda: None
        ticket = self._gc.enqueue(rec)
        return lambda: self._gc.wait(ticket)

    def _roll(self) -> None:
        self._fh.close()
        self._current_id += 1
        self._fh = open(self._seg_path(self._current_id), "ab")

    def replay(self, from_sequence: int = 0, repair: bool = True):
        """Yield (sequence, payload) for entries with sequence >= from_sequence.

        Corruption triage instead of stop-at-first-error: a **torn tail**
        (damage reaching EOF of the final segment) is truncated under
        ``repair`` — crash debris, today's behavior, correct; **interior**
        damage (a valid record boundary exists beyond it) is counted,
        copied to a ``.quarantine`` sidecar (repair mode), and replay
        CONTINUES from the next boundary — acked records after bit rot
        are never silently discarded.  Every event lands in
        ``self.last_triage`` with the lost sequence range, so the region
        can resync the hole (remote WAL / follower replica) and then
        ``heal()`` the segment.  Followers replaying a WAL directory
        shared with a live leader MUST pass repair=False: a partially
        flushed leader append would otherwise be destroyed mid-write."""
        native = _native()
        self.last_triage = []
        pending: WalDamage | None = None
        # carried ACROSS segments: damage at the head of segment k+1 must
        # bound its lost range from segment k's last record, not from 1
        last_seq: int | None = None
        segs = self._segments()
        for idx, seg in enumerate(segs):
            path = self._seg_path(seg)
            with open(path, "rb") as f:
                data = f.read()
            for ev in _walk(data, native):
                if ev[0] == "rec":
                    _, seq, poff, ln, _rs, _re = ev
                    if pending is not None:
                        # first valid record after a hole
                        pending.next_seq = seq
                        pending = None
                    last_seq = seq
                    if seq >= from_sequence:
                        yield seq, data[poff:poff + ln]
                    continue
                _, start, dmg_end = ev
                if dmg_end >= len(data) and idx == len(segs) - 1:
                    # torn tail of the active segment: expected crash
                    # debris — truncate (write ownership only)
                    M_CORRUPTION.labels("wal", "torn_tail").inc()
                    self.last_triage.append(WalDamage(
                        path, "torn_tail", start, len(data), last_seq,
                        None))
                    if repair:
                        with open(path, "r+b") as f:
                            f.truncate(start)
                        if seg == self._current_id:
                            self._fh.close()
                            self._fh = open(path, "ab")
                    break
                # interior damage: valid records follow (in this segment
                # or a later one) — the next "rec" event patches next_seq
                dmg = WalDamage(path, "interior", start, dmg_end,
                                last_seq, None)
                M_CORRUPTION.labels("wal", "interior").inc()
                self.last_triage.append(dmg)
                pending = dmg
                if repair:
                    self._write_sidecar(path, start, data[start:dmg_end])

    def verify(self) -> list[WalDamage]:
        """Read-only integrity sweep over every segment (the scrubber's
        entry point, ISSUE 15): classify damage byte-exactly like
        replay() — including cross-segment lost-range bounding — and
        preserve the damaged bytes in ``.quarantine`` sidecars, but
        mutate NOTHING else.  Unlike replay, tail damage is *reported*
        (kind "torn_tail"), never truncated: on a LIVE region the tail
        is acked data hit by bit rot, not crash debris, and the caller
        (Region.scrub_wal) decides between resync and flush-cover."""
        native = _native()
        damages: list[WalDamage] = []
        pending: WalDamage | None = None
        last_seq: int | None = None
        segs = self._segments()
        for idx, seg in enumerate(segs):
            path = self._seg_path(seg)
            with open(path, "rb") as f:
                data = f.read()
            for ev in _walk(data, native):
                if ev[0] == "rec":
                    _, seq, _poff, _ln, _rs, _re = ev
                    if pending is not None:
                        pending.next_seq = seq
                        pending = None
                    last_seq = seq
                    continue
                _, start, dmg_end = ev
                tail = dmg_end >= len(data) and idx == len(segs) - 1
                dmg = WalDamage(path, "torn_tail" if tail else "interior",
                                start, dmg_end, last_seq, None)
                M_CORRUPTION.labels(
                    "wal", "scrub_tail" if tail else "scrub_interior").inc()
                damages.append(dmg)
                pending = dmg
                self._write_sidecar(path, start, data[start:dmg_end])
        return damages

    def drop_damage(self, damages: "list[WalDamage]") -> int:
        """Remove verified damage from the segments AFTER its bytes are
        sidecar-preserved and its lost range recovered (resynced or
        flush-covered): interior spans compact out via heal(); tail
        damage truncates the segment to its valid prefix (re-opening the
        active handle).  Returns bytes dropped."""
        interior_paths = {d.path for d in damages if d.kind == "interior"}
        dropped = self.heal(damages)
        for d in damages:
            if d.kind != "torn_tail" or d.path in interior_paths:
                # heal's compaction keeps only valid records, so it
                # already dropped this file's tail span too
                continue
            try:
                size = os.path.getsize(d.path)
            except OSError:
                continue
            if size <= d.start:
                continue  # already compacted/truncated
            dropped += size - d.start
            with open(d.path, "r+b") as f:
                f.truncate(d.start)
            if d.path == self._seg_path(self._current_id):
                self._fh.close()
                self._fh = open(d.path, "ab")
        return dropped

    def _write_sidecar(self, path: str, start: int, blob: bytes) -> None:
        """Preserve damaged bytes beside the segment (never deleted);
        idempotent per (segment, offset) so repeated failed opens don't
        stack duplicates."""
        side = f"{path}.{start}.quarantine"
        if os.path.exists(side):
            return
        tmp = side + ".tmp"
        with open(tmp, "wb") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, side)
        # rename durability: the sidecar is the only surviving copy of
        # the damaged bytes once heal() compacts the segment — a power
        # loss must not be able to forget its directory entry
        _fsync_dir(os.path.dirname(side))
        M_QUARANTINED.labels("wal").inc()

    def heal(self, damages: "list[WalDamage] | None" = None) -> int:
        """Compact interior-damaged segments down to their valid records
        (call AFTER the lost range was resynced and re-appended durably —
        healing first would turn a repairable hole into silent loss).
        Damaged bytes already live in the ``.quarantine`` sidecars.
        Returns the number of bytes dropped."""
        damages = self.last_triage if damages is None else damages
        native = _native()
        dropped = 0
        for path in sorted({d.path for d in damages
                            if d.kind == "interior"}):
            with open(path, "rb") as f:
                data = f.read()
            keep = bytearray()
            for ev in _walk(data, native):
                if ev[0] == "rec":
                    keep += data[ev[4]:ev[5]]
            if len(keep) == len(data):
                continue
            dropped += len(data) - len(keep)
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(bytes(keep))
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            # rename durability: a healed segment that reverts to its
            # damaged pre-compaction bytes at power loss would re-open
            # with interior corruption the triage believes is repaired
            _fsync_dir(os.path.dirname(path))
            if path == self._seg_path(self._current_id):
                self._fh.close()
                self._fh = open(path, "ab")
        return dropped

    def truncate(self, up_to_sequence: int) -> None:
        """Drop whole segments whose every entry is below up_to_sequence.
        Unverifiable bytes (damage) conservatively KEEP the segment — a
        quarantine/resync may still need them.

        Walks headers only (v2 header CRC validates len/seq without
        touching the payload): truncation runs on every flush, and
        payload checksums belong to replay/heal, not this hot path."""
        for seg in self._segments()[:-1]:  # never drop the active segment
            path = self._seg_path(seg)
            keep = False
            with open(path, "rb") as f:
                data = f.read()
            off, n = 0, len(data)
            while off < n:
                if off + _REC_HDR <= n:
                    ln, _crc, seq = _HDR.unpack_from(data, off)
                    (hcrc,) = _HCRC.unpack_from(data, off + _HDR.size)
                    if (zlib.crc32(data[off:off + _HDR.size]) == hcrc
                            and off + _REC_HDR + ln <= n):
                        if seq >= up_to_sequence:
                            keep = True
                            break
                        off += _REC_HDR + ln
                        continue
                v1 = _parse_v1(data, off)  # legacy record (payload CRC)
                if v1 is not None:
                    seq, poff, ln = v1
                    if seq >= up_to_sequence:
                        keep = True
                        break
                    off = poff + ln
                    continue
                keep = True  # damage: never drop unverified bytes
                break
            if not keep:
                os.unlink(path)

    def close(self) -> None:
        self._fh.close()


class NoopLogStore(LogStore):
    """WAL-less mode for benchmarks (reference src/log-store/src/noop/)."""

    durable = False

    def append(self, sequence: int, payload: bytes) -> None:
        pass

    def replay(self, from_sequence: int = 0, repair: bool = True):
        return iter(())

    def truncate(self, up_to_sequence: int) -> None:
        pass

    def close(self) -> None:
        pass


# ---- payload codec: Arrow IPC over the write columns -----------------------

_OP_META = b"greptime.op"


def encode_write(columns: dict, op: int = 0) -> bytes:
    """Serialize one write batch.  Only the schema columns belong in the
    payload: per-row ``__tsid__``/``__seq__``/``__op__`` are derivable at
    replay (tsids recompute deterministically, the sequence rides the
    record header, and a batch has ONE op) — logging them would grow
    every record ~15% for bytes replay throws away.  ``op`` lands in the
    stream's schema metadata instead."""
    table = pa.table(columns)
    if op:
        table = table.replace_schema_metadata({_OP_META: str(op)})
    sink = io.BytesIO()
    with pa.ipc.new_stream(sink, table.schema) as w:
        w.write_table(table)
    return sink.getvalue()


def decode_write(payload: bytes) -> dict:
    return decode_write_full(payload)[0]


def decode_write_full(payload: bytes) -> tuple[dict, int]:
    """(columns, op) — accepts both the slim format and older payloads
    that carried __seq__/__op__ columns (replay prefers the columns when
    present, so logs written before the slimming replay identically)."""
    with pa.ipc.open_stream(io.BytesIO(payload)) as r:
        table = r.read_all()
    meta = table.schema.metadata or {}
    op = int(meta.get(_OP_META, b"0"))
    cols = {name: table.column(name).combine_chunks()
            for name in table.column_names}
    return cols, op
