"""Write-ahead log: segmented, CRC-checked, per-region append log.

Equivalent of the reference's raft-engine local WAL
(src/log-store/src/raft_engine/) behind the LogStore trait
(src/store-api/src/logstore.rs:51): entries are (region, sequence, payload)
appended durably before memtable writes; region open replays entries past
the flushed sequence (SURVEY.md §5.4 mechanism 1). A Kafka-style remote WAL
can implement the same LogStore interface later.

Record format (little-endian): [u32 len][u32 crc32(payload)][u64 sequence]
[payload]. Torn tails (crash mid-append) are detected by length/CRC and
truncated on replay. Payloads are columnar row groups serialized with
Arrow IPC — portable and fast, no pickle.
"""

from __future__ import annotations

import io
import os
import struct
import zlib

import pyarrow as pa
import pyarrow.ipc

_HDR = struct.Struct("<IIQ")
_SEGMENT_TARGET = 64 * 1024 * 1024


class LogStore:
    """Interface (reference store-api logstore.rs:51)."""

    # False = appends are dropped (Noop): writers may skip payload
    # serialization entirely — the encode cost is pure waste
    durable = True

    def append(self, sequence: int, payload: bytes) -> None:
        raise NotImplementedError

    def replay(self, from_sequence: int, repair: bool = True):
        raise NotImplementedError

    def truncate(self, up_to_sequence: int) -> None:
        raise NotImplementedError


class FileLogStore(LogStore):
    """One directory of numbered segment files per region."""

    def __init__(self, wal_dir: str, sync: bool = False):
        self.dir = wal_dir
        self.sync = sync
        os.makedirs(wal_dir, exist_ok=True)
        segs = self._segments()
        self._current_id = segs[-1] if segs else 0
        self._fh = open(self._seg_path(self._current_id), "ab")

    def _seg_path(self, seg_id: int) -> str:
        return os.path.join(self.dir, f"{seg_id:020d}.wal")

    def _segments(self) -> list[int]:
        out = []
        for fn in os.listdir(self.dir):
            if fn.endswith(".wal"):
                out.append(int(fn[:-4]))
        return sorted(out)

    def append(self, sequence: int, payload: bytes) -> None:
        rec = _HDR.pack(len(payload), zlib.crc32(payload), sequence) + payload
        self._fh.write(rec)
        self._fh.flush()
        if self.sync:
            os.fsync(self._fh.fileno())
        if self._fh.tell() >= _SEGMENT_TARGET:
            self._roll()

    def _roll(self) -> None:
        self._fh.close()
        self._current_id += 1
        self._fh = open(self._seg_path(self._current_id), "ab")

    def replay(self, from_sequence: int = 0, repair: bool = True):
        """Yield (sequence, payload) for entries with sequence >= from_sequence.
        Stops at the first torn/corrupt record; with ``repair`` (write
        ownership — leader open/recovery) the torn tail is truncated so
        future appends start clean.  Followers replaying a WAL directory
        shared with a live leader MUST pass repair=False: a partially
        flushed leader append would otherwise be destroyed mid-write."""
        try:
            from greptimedb_tpu import native
        except ImportError:
            native = None
        for seg in self._segments():
            path = self._seg_path(seg)
            with open(path, "rb") as f:
                data = f.read()
            good_end = 0
            scanned = native.wal_scan(data, from_sequence) if native else None
            if scanned is not None:
                spans, good_end = scanned
                for seq, off, ln in spans:
                    yield seq, data[off:off + ln]
            else:
                off = 0
                while off + _HDR.size <= len(data):
                    ln, crc, seq = _HDR.unpack_from(data, off)
                    end = off + _HDR.size + ln
                    if end > len(data):
                        break
                    payload = data[off + _HDR.size : end]
                    if zlib.crc32(payload) != crc:
                        break
                    good_end = end
                    off = end
                    if seq >= from_sequence:
                        yield seq, payload
            if good_end < len(data):
                if repair:
                    # torn tail: truncate so future appends start clean
                    with open(path, "r+b") as f:
                        f.truncate(good_end)
                    if seg == self._current_id:
                        self._fh.close()
                        self._fh = open(path, "ab")
                break

    def truncate(self, up_to_sequence: int) -> None:
        """Drop whole segments whose every entry is below up_to_sequence."""
        for seg in self._segments()[:-1]:  # never drop the active segment
            path = self._seg_path(seg)
            keep = False
            with open(path, "rb") as f:
                data = f.read()
            off = 0
            while off + _HDR.size <= len(data):
                ln, _crc, seq = _HDR.unpack_from(data, off)
                if seq >= up_to_sequence:
                    keep = True
                    break
                off += _HDR.size + ln
            if not keep:
                os.unlink(path)

    def close(self) -> None:
        self._fh.close()


class NoopLogStore(LogStore):
    """WAL-less mode for benchmarks (reference src/log-store/src/noop/)."""

    durable = False

    def append(self, sequence: int, payload: bytes) -> None:
        pass

    def replay(self, from_sequence: int = 0, repair: bool = True):
        return iter(())

    def truncate(self, up_to_sequence: int) -> None:
        pass

    def close(self) -> None:
        pass


# ---- payload codec: Arrow IPC over the write columns -----------------------

def encode_write(columns: dict) -> bytes:
    table = pa.table(columns)
    sink = io.BytesIO()
    with pa.ipc.new_stream(sink, table.schema) as w:
        w.write_table(table)
    return sink.getvalue()


def decode_write(payload: bytes) -> dict:
    with pa.ipc.open_stream(io.BytesIO(payload)) as r:
        table = r.read_all()
    return {name: table.column(name).combine_chunks() for name in table.column_names}
