"""File engine: read-only regions over external CSV/Parquet/JSON files.

Reference: src/file-engine (1,671 LoC) + src/common/datasource —
``CREATE EXTERNAL TABLE t (...) WITH (location='...', format='parquet')``
registers a table whose data lives in user-managed files; scans read the
files on demand (no WAL, no memtable, no flush).  The view duck-types the
Region surface the planners and device cache consume, so external files
flow into the same resident-tensor query path as native tables.
"""

from __future__ import annotations

import glob
import os

import numpy as np

from greptimedb_tpu.datatypes.batch import DictionaryEncoder
from greptimedb_tpu.datatypes.schema import Schema
from greptimedb_tpu.errors import InvalidArguments, StorageError
from greptimedb_tpu.storage.memtable import OP, SEQ, TSID


def _read_file(path: str, fmt: str):
    import pyarrow as pa

    if fmt == "parquet":
        import pyarrow.parquet as pq

        return pq.read_table(path)
    if fmt == "csv":
        import pyarrow.csv as pacsv

        return pacsv.read_csv(path)
    if fmt == "json":
        import pyarrow.json as pajson

        return pajson.read_json(path)
    raise InvalidArguments(f"unsupported external format {fmt!r}")


class FileTableView:
    """One external table; duck-types Region for planner/cache consumers."""

    def __init__(self, name: str, schema: Schema, location: str, fmt: str,
                 region_id: int):
        self.schema = schema
        self.location = location
        self.format = fmt
        # negative id space distinct from combined (-hash) and metric
        # (-(1<<50)-id) views
        self.region_id = -(1 << 55) - region_id
        self.encoders: dict[str, DictionaryEncoder] = {
            c.name: DictionaryEncoder() for c in schema.tag_columns
        }
        self._series: dict[tuple, int] = {}
        self._mtimes: tuple = ()
        self._host: dict[str, np.ndarray] | None = None
        self.generation = 0
        self.base_version = 0  # files change wholesale: full rebuilds only

    @property
    def tag_names(self) -> list[str]:
        return [c.name for c in self.schema.tag_columns]

    @property
    def num_series(self) -> int:
        self._refresh()
        return len(self._series)

    def _files(self) -> list[str]:
        loc = self.location
        if os.path.isdir(loc):
            pats = {"parquet": "*.parquet", "csv": "*.csv", "json": "*.json"}
            return sorted(glob.glob(os.path.join(loc, pats[self.format])))
        if any(ch in loc for ch in "*?["):
            return sorted(glob.glob(loc))
        return [loc]

    def _refresh(self) -> None:
        files = self._files()
        try:
            mtimes = tuple((f, os.path.getmtime(f)) for f in files)
        except OSError as e:
            raise StorageError(f"external table location: {e}") from None
        if self._host is not None and mtimes == self._mtimes:
            return
        from greptimedb_tpu.storage.region import Region

        if not files:
            raise StorageError(
                f"no {self.format} files at {self.location!r}"
            )
        tables = [_read_file(f, self.format) for f in files]
        cols: dict[str, np.ndarray] = {}
        n = sum(t.num_rows for t in tables)
        for c in self.schema:
            parts = []
            for t in tables:
                if c.name not in t.column_names:
                    raise StorageError(
                        f"external file missing column {c.name!r}"
                    )
                col = t.column(c.name)
                if c.dtype.is_string_like:
                    parts.append(np.asarray(col.to_pylist(), dtype=object))
                elif c.dtype.is_timestamp:
                    arr = col.to_numpy(zero_copy_only=False)
                    parts.append(np.asarray(arr).astype("datetime64[ms]")
                                 .astype(np.int64)
                                 if arr.dtype.kind == "M"
                                 else np.asarray(arr).astype(np.int64))
                else:
                    parts.append(
                        col.to_numpy(zero_copy_only=False)
                        .astype(c.dtype.to_numpy())
                    )
            cols[c.name] = np.concatenate(parts) if parts else np.empty(0)
        # derive series registry + internals exactly like a native region.
        # MUTATE the existing dicts: planning contexts capture these object
        # references, so wholesale replacement would strand them
        self.encoders.clear()
        self.encoders.update({
            c.name: DictionaryEncoder() for c in self.schema.tag_columns
        })
        self._series.clear()
        cols[TSID] = Region._encode_tags(self, cols, n)
        cols[SEQ] = np.arange(1, n + 1, dtype=np.int64)
        cols[OP] = np.zeros(n, dtype=np.int8)
        ts_name = self.schema.time_index.name
        order = np.lexsort((cols[ts_name], cols[TSID]))
        self._host = {k: v[order] for k, v in cols.items()}
        self._mtimes = mtimes
        self.generation += 1
        self.base_version += 1

    def ts_bounds(self):
        self._refresh()
        ts = self._host[self.schema.time_index.name]
        if not len(ts):
            return None
        return (int(ts.min()), int(ts.max()))

    def scan_host(self, ts_range=(None, None), columns=None,
                  tag_filters=None, tag_preds=None, ft_tokens=None):
        self._refresh()
        host = self._host
        ts = host[self.schema.time_index.name]
        mask = np.ones(len(ts), dtype=bool)
        lo, hi = ts_range
        if lo is not None:
            mask &= ts >= lo
        if hi is not None:
            mask &= ts < hi
        if tag_filters:
            for col, values in tag_filters.items():
                if col in host:
                    vset = {str(v) for v in values}
                    mask &= np.array(
                        [str(v) in vset for v in host[col]], dtype=bool
                    )
        keep = None
        if columns is not None:
            keep = set(columns) | {TSID, SEQ, OP,
                                   self.schema.time_index.name}
        return {
            k: v[mask] for k, v in host.items()
            if keep is None or k in keep
        }
