"""Region: the unit of storage, replication and parallelism.

Equivalent of a mito2 region (reference src/mito2/src/engine.rs + worker
handlers): one time-series shard owning a WAL, a memtable, SSTs and a
manifest. The reference routes regions to worker-loop threads; here writes
are synchronous per region (Python) with the GIL-free heavy lifting in
numpy/pyarrow, and the parallel axis moves to the TPU mesh (parallel/).

Write encoding: tag values → per-column dictionary codes → a packed series
key → region-wide __tsid__ (series registry); dictionaries live in the
manifest so codes are stable across restarts (the metric-engine __tsid
idea, reference src/metric-engine/src/row_modifier.rs).
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field

import numpy as np

from greptimedb_tpu.datatypes.batch import DictColumn, DictionaryEncoder
from greptimedb_tpu.datatypes.schema import Schema, default_fill_array
from greptimedb_tpu.errors import InvalidArguments, RegionNotFound, StorageError
from greptimedb_tpu.storage.durability import (
    M_QUARANTINED,
    M_REPAIRED,
    ManifestCorruption,
    RegionQuarantined,
    SstCorruption,
    WalHole,
    quarantine_object,
)
from greptimedb_tpu.storage.manifest import Manifest
from greptimedb_tpu.storage.memtable import (
    Memtable, OP, OP_DELETE, OP_PUT, SEQ, TAGCODE_PREFIX, TSID, tagcode_col,
)
from greptimedb_tpu.storage.object_store import FsObjectStore, ObjectStore
from greptimedb_tpu.storage.sst import SstMeta, read_sst, write_sst
from greptimedb_tpu.storage.wal import (
    FileLogStore,
    NoopLogStore,
    decode_write_full,
    encode_write,
)

import pyarrow as pa


# append-log cap: beyond this many unconsumed delta chunks the cache does
# a full rebuild anyway, so stop buffering and force a structure change
MAX_APPEND_CHUNKS = 256


@dataclass
class RegionOptions:
    flush_threshold_bytes: int = 256 * 1024 * 1024
    compaction_window_ms: int = 24 * 3600 * 1000  # TWCS time window
    compaction_trigger_files: int = 8  # files per window before merge
    wal_enabled: bool = True
    wal_sync: bool = False
    # append mode (reference CREATE TABLE WITH (append_mode='true'),
    # mito2 MergeMode): rows with equal (series, ts) keys are ALL kept —
    # the log/trace data model, where many events share a millisecond
    append_mode: bool = False
    # retention (reference WITH (ttl='7d'), src/store-api/src/
    # mito_engine_options.rs): SSTs whose newest row is older than
    # now - ttl are dropped whole at flush/compaction time; None = keep
    # forever
    ttl_ms: int | None = None

    def to_dict(self) -> dict:
        return dict(self.__dict__)


class Region:
    # capability flag for build_device_table: scan_host accepts
    # ``with_tag_codes`` (duck-typed views that wrap scan_host don't)
    scan_supports_codes = True

    def __init__(
        self,
        region_id: int,
        store: ObjectStore,
        schema: Schema,
        manifest: Manifest,
        wal_dir: str | None,
        options: RegionOptions,
        log_store: "LogStore | None" = None,
        memory=None,
    ):
        self.region_id = region_id
        self.store = store
        self.schema = schema
        self.options = options
        self.manifest = manifest
        # optional WorkloadMemoryManager: write() admits incoming batches
        # against the engine-wide ingest (write-buffer) quota
        self.memory = memory
        self._dir = f"region_{region_id}"
        if log_store is not None:
            # injected WAL (remote/shared log — storage/remote_wal.py)
            self.wal = log_store
        elif options.wal_enabled and wal_dir is not None:
            self.wal = FileLogStore(wal_dir, sync=options.wal_sync)
        else:
            self.wal = NoopLogStore()
        self.memtable = Memtable(schema)
        self.next_seq = manifest.state.flushed_seq + 1
        # incremental device-cache protocol: base_version changes only on
        # STRUCTURE changes (flush/compaction/truncate/catch-up/upsert...);
        # pure time-forward appends go to _append_log so the cache layer
        # can extend resident tensors instead of rebuilding (cache.py)
        self.base_version = 0
        self._append_log: list[dict] = []
        # count of chunks trimmed off the log's front: consumer positions
        # are ABSOLUTE (base + list index), so sustained ingest can trim
        # consumed chunks without invalidating up-to-date consumers
        self._append_base = 0
        self._max_ts_seen: int | None = None  # lazy; -2**63 = empty
        # serializes writers of THIS region only: concurrent ingest to
        # different regions proceeds in parallel (the parallel axis of
        # the sharded ingest pipeline) while each region keeps the
        # single-writer discipline its sequence/memtable code assumes
        self._write_lock = threading.RLock()
        # guards (_append_base, _append_log) as a pair: cache consumers
        # read them lock-free of _write_lock, so trim (del + base bump)
        # must be atomic w.r.t. append_chunks_since/append_pos — a torn
        # read would silently skip or duplicate chunks in the resident
        # device tail.  Never held across I/O: list ops only.
        self._append_log_lock = threading.Lock()
        # tag encoders hydrated from the manifest
        self.encoders: dict[str, DictionaryEncoder] = {
            c.name: DictionaryEncoder(manifest.state.dicts.get(c.name, []))
            for c in schema.tag_columns
        }
        self._series: dict[tuple, int] = {
            tuple(codes): i for i, codes in enumerate(manifest.state.series)
        }
        # repeated-writer fast paths (the device flow runtime's sink
        # upserts hit both every fold): per-tag-column DictColumn
        # vocabulary→region-code maps keyed on the vocabulary array's
        # identity (vocabularies are append-only — covered entries are
        # immutable), and the single-tag code→tsid mirror of _series.
        # Cleared wherever _series/encoders are rebuilt.
        self._dictcol_memo: dict[str, tuple] = {}
        self._series_map1: np.ndarray | None = None
        self.generation = 0  # bumped on any data mutation; cache key
        # bumped only on structure changes that can MUTATE row content
        # (upserts/deletes/compaction/ttl/truncate/alter/replay) — flush is
        # content-preserving (rows just move memtable → SST), so a resident
        # grid whose epoch still matches can CATCH UP from the flushed
        # files instead of rebuilding (storage/grid.py catch_up_grid_table)
        self.mutation_epoch = 0
        self._index_cache: dict[str, dict] = {}  # file_id -> column blooms
        # durability repair hooks (ISSUE 9).  ``repair_source``: fetch a
        # replica's copy of an object (path -> bytes | None), e.g.
        # durability.repair_sst_from_peer over the Flight object plane.
        # ``wal_resync``: fetch missing WAL records for a lost sequence
        # range ((lo, hi) -> [(seq, payload)]), e.g.
        # durability.resync_from_log_store / resync_from_peer_wal.
        self.repair_source = None
        self.wal_resync = None
        # leader epoch this region's shared-storage writes are fenced
        # under (ISSUE 15); None = unfenced (standalone / follower)
        self.fence_epoch: int | None = None

    # ------------------------------------------------------------------
    @property
    def tag_names(self) -> list[str]:
        return [c.name for c in self.schema.tag_columns]

    @property
    def ts_name(self) -> str:
        return self.schema.time_index.name

    @property
    def num_series(self) -> int:
        return len(self._series)

    @property
    def series_generation(self) -> tuple:
        """Version of the SERIES REGISTRY (tsid ↔ tag-code mapping) only,
        unlike ``generation`` which bumps on every data write.  The
        registry is append-only between structure changes (every rebuild
        site calls _mark_structure_change), so (base_version, len) is a
        sound invalidation key — PromQL matcher selections, group-id
        vectors and the inverted index depend only on this and survive
        pure data appends of existing series (the steady-scrape case)."""
        return (self.base_version, len(self._series))

    @property
    def sst_files(self) -> list[SstMeta]:
        return list(self.manifest.state.files.values())

    # ---- append-log positions (device-cache incremental protocol) -----
    @property
    def append_pos(self) -> int:
        """Absolute position past the newest append-log chunk.  Consumers
        (storage/cache.py) remember the position they consumed to; pure
        appends between two positions EXTEND resident tensors in place."""
        with self._append_log_lock:
            return self._append_base + len(self._append_log)

    def append_chunks_since(self, pos: int) -> "list[dict] | None":
        """Chunks appended after absolute position ``pos``, or None when
        ``pos`` predates the trimmed window (consumer too stale: rebuild)."""
        with self._append_log_lock:
            i = pos - self._append_base
            if i < 0:
                return None
            return self._append_log[i:]

    # ---- write path ---------------------------------------------------
    def _encode_tags(
        self, columns: dict[str, np.ndarray], n: int,
        out_codes: dict[str, np.ndarray] | None = None,
    ) -> np.ndarray:
        """tags → per-column codes (mutating region dicts) → __tsid__.

        ``out_codes`` (when given) receives the per-column int32 code
        arrays so downstream consumers (SST dictionary pages, bloom index,
        device canonicalization) never re-hash the raw strings."""
        tag_cols = self.tag_names
        if not tag_cols:
            return np.zeros(n, dtype=np.int64)
        import pandas as pd

        code_arrays = []
        for name in tag_cols:
            enc = self.encoders[name]
            col = columns[name]
            if isinstance(col, DictColumn):
                # pre-factorized by the vectorized wire parser: the
                # (codes, vocabulary) pair IS the factorization — skip
                # the per-row hash entirely.
                # Repeated-writer memo: a caller reusing one append-only
                # vocabulary array across writes (the device flow
                # runtime's sink upserts, every fold) resolves through a
                # cached vocab-pos→region-code map — only NEVER-SEEN
                # referenced entries pay the python registration, once
                # ever (covered entries are immutable by the dictionary
                # append-only contract).
                vbase = col.values if col.values.base is None \
                    else col.values.base
                # lazy attrs: region-LIKES (CombinedRegionView, staged
                # providers) borrow this method without Region.__init__
                memo_map = getattr(self, "_dictcol_memo", None)
                if memo_map is None:
                    memo_map = self._dictcol_memo = {}
                memo = memo_map.get(name)
                if memo is not None and memo[0] is vbase:
                    cmap = memo[1]
                    if len(cmap) < len(col.values):
                        cmap = np.concatenate([
                            cmap, np.full(len(col.values) - len(cmap), -1,
                                          np.int64)])
                        memo_map[name] = (vbase, cmap)
                    col_codes = cmap[col.codes]
                    need = col_codes < 0
                    if need.any():
                        for rc in np.unique(col.codes[need]).tolist():
                            v = col.values[rc]
                            if v is None or (isinstance(v, float)
                                             and v != v):
                                v = ""  # NULL tags encode as ""
                            cmap[rc] = enc.get_or_insert(v)
                        col_codes = cmap[col.codes]
                    if out_codes is not None:
                        out_codes[name] = col_codes.astype(np.int32)
                    code_arrays.append(col_codes)
                    continue
                # Compact to REFERENCED vocabulary entries first: a
                # sliced column (DictColumn .take from partition routing /
                # per-measurement splits) keeps the whole-batch
                # vocabulary, and registering unreferenced values would
                # grow this region's dictionary with values that were
                # routed elsewhere, forever
                inv, uniq = col.codes, col.values
                orig_len = len(uniq)
                # referenced-code set via bincount (O(n + vocab)) instead
                # of a sort — codes are small non-negative ints
                used = (np.flatnonzero(np.bincount(inv, minlength=len(uniq)))
                        if inv.size > len(uniq) else np.unique(inv))
                if len(used) < len(uniq):
                    remap = np.full(len(uniq), -1, dtype=inv.dtype)
                    remap[used] = np.arange(len(used), dtype=inv.dtype)
                    inv = remap[inv]
                    uniq = uniq[used]
            else:
                vals = np.asarray(col, dtype=object)
                # hash-factorize (O(n), no object-array sort): tag columns
                # repeat heavily, so python cost is paid per UNIQUE value
                # only
                inv, uniq = pd.factorize(vals, use_na_sentinel=False)
            if any(
                v is None or (isinstance(v, float) and v != v) for v in uniq
            ):
                # NULL tags (None/NaN from factorize) encode as "" — the
                # device dictionary space has no null representation (same
                # rule as add_tag_column backfill); a None in the vocab
                # would wedge every subsequent flush.  Integer-typed tags
                # pass through untouched.
                uniq = np.array(
                    ["" if v is None or (isinstance(v, float) and v != v)
                     else v for v in uniq], dtype=object)
            codes = np.fromiter(
                (enc.get_or_insert(v) for v in uniq), dtype=np.int64,
                count=len(uniq),
            )
            col_codes = codes[inv]
            if isinstance(col, DictColumn):
                # seed the repeated-writer memo (referenced entries only
                # — unreferenced positions stay -1 and register lazily)
                cmap = np.full(orig_len, -1, np.int64)
                cmap[used if len(used) < orig_len
                     else slice(None)] = codes
                vbase = col.values if col.values.base is None \
                    else col.values.base
                memo_map = getattr(self, "_dictcol_memo", None)
                if memo_map is None:
                    memo_map = self._dictcol_memo = {}
                memo_map[name] = (vbase, cmap)
            if out_codes is not None:
                out_codes[name] = col_codes.astype(np.int32)
            code_arrays.append(col_codes)
        # vectorized any-arity series resolution: pack per-column codes
        # into one int64 key when the combined bit width fits (exact,
        # injective), factorize the packed ints, then a python loop over
        # UNIQUE keys only (the metric-engine physical region routinely
        # has many tag columns, so no per-row python fallback is
        # acceptable on the ingest hot path)
        if len(code_arrays) == 1:
            # single-tag tables resolve through a dense code→tsid mirror
            # of _series: one gather per write, python only for codes
            # never seen before (the repeated-writer hot path — flow sink
            # upserts, single-tag metric tables)
            codes1 = code_arrays[0]
            mx = int(codes1.max()) if n else -1
            smap = getattr(self, "_series_map1", None)
            if smap is None or mx >= len(smap):
                grown = np.full(max(16, 2 * (mx + 1)), -1, np.int64)
                if smap is not None:
                    grown[: len(smap)] = smap
                else:
                    for key, tsid in self._series.items():
                        if key[0] < len(grown):
                            grown[key[0]] = tsid
                smap = self._series_map1 = grown
            tsids1 = smap[codes1]
            need = tsids1 < 0
            if need.any():
                # FIRST-OCCURRENCE registration order (pd.factorize's):
                # tsid assignment order is observable via first/last
                # tie-breaks on equal timestamps (PR-8 discipline)
                uniq_new, first_idx = np.unique(codes1[need],
                                                return_index=True)
                for c in uniq_new[np.argsort(first_idx,
                                             kind="stable")].tolist():
                    key = (int(c),)
                    tsid = self._series.get(key)
                    if tsid is None:
                        tsid = len(self._series)
                        self._series[key] = tsid
                    smap[c] = tsid
                tsids1 = smap[codes1]
            return tsids1
        widths = [
            max(int(a.max()) if n else 0, 1).bit_length()
            for a in code_arrays
        ]
        if sum(widths) <= 62:
            packed = code_arrays[0]
            for a, w in zip(code_arrays[1:], widths[1:]):
                packed = (packed << np.int64(w)) | a
        else:  # astronomically wide key space: exact structured unique
            packed = None
        if packed is not None:
            pmax = int(packed.max()) + 1 if n else 0
            if 0 < pmax <= max(1024, 4 * n):
                # dense key space (the common case: few live series):
                # bincount-factorize is O(n + keyspace) with no hash
                # table.  Uniques are then reordered to FIRST-OCCURRENCE
                # order — exactly pd.factorize's — because the order NEW
                # series ids are assigned in is observable downstream
                # (first/last picks on equal timestamps follow the
                # device layout's tsid order)
                uniq_sorted = np.flatnonzero(
                    np.bincount(packed, minlength=pmax))
                remap = np.zeros(pmax, dtype=np.int64)
                remap[uniq_sorted] = np.arange(len(uniq_sorted))
                inv_s = remap[packed]
                first = np.empty(len(uniq_sorted), dtype=np.int64)
                first[inv_s[::-1]] = np.arange(n - 1, -1, -1,
                                               dtype=np.int64)
                order = np.argsort(first, kind="stable")
                rank = np.empty(len(order), dtype=np.int64)
                rank[order] = np.arange(len(order), dtype=np.int64)
                uniq_packed = uniq_sorted[order]
                inv2 = rank[inv_s]
            else:
                inv2, uniq_packed = pd.factorize(packed)
            # first-occurrence row per unique packed key (reversed write:
            # the earliest row wins), to recover the exact code tuple
            first_row = np.empty(len(uniq_packed), dtype=np.int64)
            rev = np.arange(n - 1, -1, -1)
            first_row[inv2[rev]] = rev
            tsids = np.empty(len(uniq_packed), dtype=np.int64)
            for j in range(len(uniq_packed)):
                r = int(first_row[j])
                key = tuple(int(a[r]) for a in code_arrays)
                tsid = self._series.get(key)
                if tsid is None:
                    tsid = len(self._series)
                    self._series[key] = tsid
                tsids[j] = tsid
            return tsids[inv2]
        code_mat = np.stack(code_arrays, axis=1)  # [n, k] int64
        uniq_rows, inv2 = np.unique(code_mat, axis=0, return_inverse=True)
        tsids = np.empty(len(uniq_rows), dtype=np.int64)
        for j in range(len(uniq_rows)):
            key = tuple(int(c) for c in uniq_rows[j])
            tsid = self._series.get(key)
            if tsid is None:
                tsid = len(self._series)
                self._series[key] = tsid
            tsids[j] = tsid
        return tsids[inv2.reshape(-1)]

    def write(self, data: dict[str, list | np.ndarray], op: int = OP_PUT,
              wire_payload: bytes | None = None) -> int:
        """Synchronous write of one row group; returns the sequence.

        Serialized per region by ``_write_lock`` — concurrent ingest to
        DIFFERENT regions runs in parallel (the sharded half of the
        vectorized ingest pipeline), while sequence assignment, tag
        encoding and memtable mutation for one region stay single-writer.
        Tag columns may arrive as ``DictColumn`` (vectorized wire parse):
        codes flow straight into the series registry and the WAL encodes
        them as Arrow dictionary arrays — no per-row string objects until
        the memtable materialization (a C-level vocabulary gather).

        ``wire_payload``: the batch's original wire bytes when they are
        already a valid slim WAL payload (an Arrow IPC stream of exactly
        the columns in ``data``, ts as int64 epoch ms, no nulls — the
        arrow bulk surface).  Logged verbatim instead of re-serializing
        the batch, PROVIDED every schema column arrived structurally
        (checked below); otherwise ignored."""
        with self._write_lock:
            return self._write_locked(data, op, wire_payload)

    def _write_locked(self, data, op: int,
                      wire_payload: bytes | None = None) -> int:
        from greptimedb_tpu.utils.tracing import TRACER

        ts_name = self.ts_name
        n = len(data[ts_name])
        if self.memory is not None:
            # rough batch footprint: ~16B/cell covers the typical mix of
            # f64/int64 values plus object-array overhead for tags
            self.memory.admit("ingest", n * len(data) * 16)
        # wire_payload stays usable only while every schema column turns
        # out to have arrived structurally (typed ndarray / string-typed
        # DictColumn) — exactly the inputs replay_wal re-derives
        # identically from the raw wire stream
        wire_ok = wire_payload is not None and op == OP_PUT
        cols: dict[str, np.ndarray] = {}
        for c in self.schema:
            if c.name not in data:
                if not c.nullable and c.default is None:
                    raise InvalidArguments(f"missing column {c.name}")
                # default-filled here ≠ present in the wire bytes: replay
                # of the raw stream would KeyError on this column
                wire_ok = False
                cols[c.name] = default_fill_array(c, n)
            else:
                v = data[c.name]
                if wire_ok and not (
                    (isinstance(v, DictColumn) and c.dtype.is_string_like)
                    or (isinstance(v, np.ndarray) and v.dtype != object)
                ):
                    wire_ok = False
                if isinstance(v, DictColumn) and c.dtype.is_string_like:
                    cols[c.name] = v  # stays dictionary-coded end to end
                elif isinstance(v, DictColumn):
                    v = v.materialize()
                    cols[c.name] = v.astype(c.dtype.to_numpy())
                elif c.dtype.is_string_like:
                    cols[c.name] = np.asarray(v, dtype=object)
                elif c.dtype.is_timestamp:
                    # copy=False: parser output is never aliased by the
                    # caller afterwards, so an already-int64 ts passes
                    # through untouched
                    cols[c.name] = np.asarray(v).astype(np.int64,
                                                        copy=False)
                elif isinstance(v, np.ndarray) and v.dtype != object:
                    # typed arrays (arrow ingest, staging scans) can't hold
                    # None — keep the single-pass hot path; copy=False
                    # skips the memcpy when the wire dtype already matches
                    cols[c.name] = v.astype(c.dtype.to_numpy(), copy=False)
                else:
                    arr = np.asarray(v, dtype=object)
                    if any(x is None for x in arr):
                        if not c.nullable:
                            raise InvalidArguments(
                                f"column {c.name} is NOT NULL"
                            )
                        # NULL encoding (NOT the declared default — explicit
                        # NULL is not DEFAULT): NaN for floats, 0 for ints,
                        # matching default_fill_array's null branch and the
                        # arrow path's fill_null(0)
                        fill = np.nan if c.dtype.is_float else 0
                        arr = np.array(
                            [fill if x is None else x for x in arr],
                            dtype=object,
                        )
                    try:
                        cols[c.name] = arr.astype(c.dtype.to_numpy())
                    except (TypeError, ValueError) as e:
                        raise InvalidArguments(
                            f"column {c.name}: {e}"
                        ) from None
        seq = self.next_seq
        self.next_seq += 1
        chunk = dict(cols)
        tag_codes: dict[str, np.ndarray] = {}
        chunk[TSID] = self._encode_tags(cols, n, out_codes=tag_codes)
        for tname, tcodes in tag_codes.items():
            chunk[tagcode_col(tname)] = tcodes
        chunk[SEQ] = np.full(n, seq, dtype=np.int64)
        chunk[OP] = np.full(n, op, dtype=np.int8)

        # durability first (reference handle_write.rs: WAL before memtable);
        # non-durable stores (Noop) skip serialization entirely — encoding
        # 10 columns of a million-row batch for /dev/null is pure overhead
        if getattr(self.wal, "durable", True):
            with TRACER.stage("ingest_wal", region=self.region_id, rows=n):
                if wire_ok:
                    # the wire bytes already ARE the slim payload (arrow
                    # bulk: same columns, int64 ms ts, no nulls, op PUT
                    # implied by absent metadata) — log them verbatim,
                    # skipping a full re-serialization of the batch
                    self.wal.append(seq, wire_payload)
                else:
                    wal_cols = {}
                    for k, v in chunk.items():
                        if k.startswith(TAGCODE_PREFIX) or k in (
                                TSID, SEQ, OP):
                            # derivable at replay: codes/tsids recompute,
                            # the sequence rides the record header, op is
                            # one value per batch (schema metadata)
                            continue
                        if isinstance(v, DictColumn):
                            # dictionary-coded tags log as Arrow
                            # dictionary arrays: vocabulary once + int32
                            # codes per row
                            wal_cols[k] = pa.DictionaryArray.from_arrays(
                                pa.array(v.codes),
                                pa.array(v.values.tolist()))
                            continue
                        # object-dtype (string) columns: pa.array over the
                        # python list preserves None as arrow nulls
                        # (astype(str) would corrupt NULL into the literal
                        # 'None' across recovery)
                        wal_cols[k] = pa.array(
                            v.tolist() if v.dtype == object else v)
                    self.wal.append(seq, encode_write(wal_cols, op=op))
        # memtable stores ts as int64 under the schema's ts column name;
        # dictionary-coded tags materialize here — one vocabulary gather
        # per column (rows share the vocabulary's string objects)
        mt_chunk = {
            k: (v.materialize() if isinstance(v, DictColumn) else v)
            for k, v in chunk.items()
        }
        mt_chunk[self.ts_name] = np.asarray(
            mt_chunk[self.ts_name]).astype(np.int64, copy=False)

        # incremental-cache classification: a batch whose timestamps all lie
        # strictly AFTER everything seen is a pure append (no upsert/delete
        # can touch resident rows) — log it for device-side extension
        if self._max_ts_seen is None:
            b = self.ts_bounds()
            self._max_ts_seen = b[1] if b is not None else -(1 << 63)
        ts_i64 = mt_chunk[self.ts_name]
        ts_lo = int(ts_i64.min()) if n else 0
        ts_hi = int(ts_i64.max()) if n else 0
        appendable = op == OP_PUT and n > 0 and ts_lo > self._max_ts_seen
        if appendable and n > 1:
            # within-batch duplicate (series, ts) keys dedup keep-last in
            # the memtable but would append verbatim on the device — not
            # extendable.  Pack (tsid, rel_ts) into one int64 so the
            # uniqueness probe is a 1-D sort, not np.unique(axis=0)'s
            # structured row sort (~6x slower on 1M-row ingest batches);
            # falls back to the row-wise check if the key space overflows.
            tsid_i64 = chunk[TSID].astype(np.int64)
            rel = ts_i64 - ts_lo
            if int(tsid_i64.max()) < (1 << 30) and int(rel.max()) < (1 << 34):
                packed = (tsid_i64 << 34) | rel
                packed.sort()  # fresh array — safe to sort in place
                if bool((packed[1:] == packed[:-1]).any()):
                    appendable = False
            else:
                pairs = np.stack([tsid_i64, ts_i64], axis=1)
                if len(np.unique(pairs, axis=0)) != n:
                    appendable = False
        if n > 0:
            self._max_ts_seen = max(self._max_ts_seen, ts_hi)

        with TRACER.stage("ingest_memtable", region=self.region_id, rows=n):
            self.memtable.append(
                mt_chunk, ts_bounds=(ts_lo, ts_hi) if n else None, seq=seq)
        self.generation += 1
        # consumers like the streaming flow engine need to know whether
        # this batch could have OVERWRITTEN existing rows (upsert) — an
        # incremental aggregate may only fold in pure appends
        self.last_write_appendable = appendable or n == 0
        if appendable:
            with self._append_log_lock:
                self._append_log.append(mt_chunk)
                if len(self._append_log) > MAX_APPEND_CHUNKS:
                    # sustained ingest: trim the consumed front instead
                    # of forcing a structure change — up-to-date
                    # consumers (absolute positions) keep extending
                    # forever; a consumer behind the trimmed window
                    # rebuilds (it was stale anyway)
                    drop = len(self._append_log) - MAX_APPEND_CHUNKS
                    del self._append_log[:drop]
                    self._append_base += drop
        elif n > 0:
            self._mark_structure_change()
        # n == 0: nothing changed; keep resident tables valid
        if self.memtable.bytes >= self.options.flush_threshold_bytes:
            self.flush()
        return seq

    def _mark_structure_change(self, content_preserving: bool = False) -> None:
        """Resident device tables for this region can no longer be extended
        in place — bump the base version so the cache rebuilds.

        ``content_preserving=True`` (flush only: rows move memtable → SST
        byte-identically — dedup/tombstone interactions would have bumped
        the epoch at write time already) keeps ``mutation_epoch`` intact so
        the grid cache may catch up incrementally from the new files."""
        self.base_version += 1
        if not content_preserving:
            self.mutation_epoch += 1
        with self._append_log_lock:
            self._append_base += len(self._append_log)
            self._append_log.clear()
        self._max_ts_seen = None

    def delete(self, data: dict[str, list | np.ndarray]) -> int:
        """Delete by full key (tags + ts): writes tombstones."""
        return self.write(data, op=OP_DELETE)

    def add_tag_column(self, name: str) -> None:
        """Online tag addition (reference alter-on-demand for metric-engine
        labels, src/operator/src/insert.rs + metric engine row_modifier).

        Existing series extend their key with the empty-string code; tsids
        are preserved, so resident caches/devices stay consistent. Flushes
        first so every SST is backfillable by schema evolution.

        Takes the region write lock (reentrant — flush re-acquires) for
        the whole swap: concurrent ingest-pool writers must never observe
        a half-rebuilt series registry or a schema/encoder mismatch.
        """
        from greptimedb_tpu.datatypes.schema import ColumnSchema, Schema
        from greptimedb_tpu.datatypes.types import ConcreteDataType, SemanticType

        with self._write_lock:
            self._add_tag_column_locked(name)

    def _add_tag_column_locked(self, name: str) -> None:
        from greptimedb_tpu.datatypes.schema import ColumnSchema, Schema
        from greptimedb_tpu.datatypes.types import ConcreteDataType, SemanticType

        if self.schema.has_column(name):
            return
        self.flush()
        new_schema = Schema(
            self.schema.columns
            + (ColumnSchema(name, ConcreteDataType.STRING, SemanticType.TAG),),
            version=self.schema.version + 1,
        )
        enc = DictionaryEncoder()
        empty_code = enc.get_or_insert("")
        self.encoders[name] = enc
        # extend every registered series key in place (ids unchanged)
        self._series = {
            key + (empty_code,): tsid for key, tsid in self._series.items()
        }
        self._dictcol_memo.clear()
        self._series_map1 = None
        self.schema = new_schema
        self.memtable.schema = new_schema
        self.manifest.commit({"kind": "schema", "schema": new_schema.to_dict()})
        self.manifest.commit({
            "kind": "reset_dicts",
            "dicts": {k: e.values() for k, e in self.encoders.items()},
            "series": [list(k) for k in sorted(self._series,
                                               key=self._series.get)],
        })
        self.generation += 1
        self._mark_structure_change()

    # ---- flush / replay ------------------------------------------------
    def flush(self) -> SstMeta | None:
        with self._write_lock:
            return self._flush_locked()

    def _flush_locked(self) -> SstMeta | None:
        if self.memtable.is_empty:
            return None
        frozen = self.memtable.freeze(dedup=not self.options.append_mode)
        flushed_seq = self.memtable.max_seq
        # storage keeps ts as int64 epoch in schema unit
        meta = write_sst(
            self.store, f"{self._dir}/sst", self.schema, frozen,
            tag_dicts={k: enc.values() for k, enc in self.encoders.items()},
        )
        self._write_sst_index(meta, frozen)
        self.manifest.commit(
            {
                "kind": "dicts",
                "dicts": {k: enc.values() for k, enc in self.encoders.items()},
                "series": [list(k) for k in sorted(self._series, key=self._series.get)],
            }
        )
        self.manifest.commit(
            {"kind": "edit", "add": [meta.to_dict()], "flushed_seq": flushed_seq}
        )
        self.memtable = Memtable(self.schema)
        self.wal.truncate(flushed_seq + 1)
        self.generation += 1
        self._mark_structure_change(content_preserving=True)
        self._maybe_compact()
        return meta

    def replay_wal(self, repair: bool = True) -> int:
        """Replay entries past flushed_seq into the memtable (region open).

        Tag codes/tsids are RECOMPUTED (not trusted from the log): encoders
        are hydrated from the manifest's flush-time state, and replaying
        writes in original order regrows them deterministically — so the
        series registry stays consistent for post-replay writes.

        ``repair=False`` = read-only replay (followers sharing the leader's
        WAL dir must never truncate its active segment).

        Corruption triage (ISSUE 9): a torn tail is truncated by the log
        store (crash debris, correct); INTERIOR corruption — a lost acked
        sequence range in the middle of the log — is resynced from
        ``self.wal_resync`` (remote WAL / follower replica) and the
        damaged segment healed; without a covering resync source the open
        raises WalHole instead of silently dropping acked writes (the
        damaged bytes stay quarantined in sidecars either way).
        """
        from_seq = self.manifest.state.flushed_seq + 1
        count = 0
        for seq, payload in self.wal.replay(from_seq, repair=repair):
            self._apply_wal_record(seq, payload)
            count += 1
        if repair:
            count += self._resync_wal_holes(from_seq)
        if count:
            self.generation += 1
            self._mark_structure_change()
        return count

    def _decode_wal_chunk(self, seq: int, payload: bytes) -> dict:
        """One WAL record → memtable chunk (codes/tsids recomputed)."""
        cols, op = decode_write_full(payload)
        chunk: dict[str, np.ndarray] = {}
        for c in self.schema:
            arr = cols[c.name]
            if c.dtype.is_string_like:
                chunk[c.name] = np.asarray(arr.to_pylist(), dtype=object)
            else:
                chunk[c.name] = arr.to_numpy(zero_copy_only=False).astype(
                    np.int64 if c.dtype.is_timestamp else c.dtype.to_numpy()
                )
        n = len(chunk[self.ts_name])
        tag_codes: dict[str, np.ndarray] = {}
        chunk[TSID] = self._encode_tags(chunk, n, out_codes=tag_codes)
        for tname, tcodes in tag_codes.items():
            chunk[tagcode_col(tname)] = tcodes
        # slim payloads derive __seq__/__op__ (header sequence +
        # metadata op); pre-slimming records still carry the columns
        # and replay them verbatim
        chunk[SEQ] = (cols[SEQ].to_numpy(zero_copy_only=False)
                      if SEQ in cols else np.full(n, seq, dtype=np.int64))
        chunk[OP] = (cols[OP].to_numpy(zero_copy_only=False)
                     .astype(np.int8)
                     if OP in cols else np.full(n, op, dtype=np.int8))
        return chunk

    def _apply_wal_record(self, seq: int, payload: bytes) -> None:
        self.memtable.append(self._decode_wal_chunk(seq, payload))
        self.next_seq = max(self.next_seq, seq + 1)

    def _resync_wal_holes(self, from_seq: int) -> int:
        """Repair interior WAL corruption found by the last replay pass:
        fetch the lost sequence range from ``wal_resync``, re-log it
        durably, apply it, and heal the damaged segments.  Raises WalHole
        when acked sequences are lost and no source covers them."""
        triage = getattr(self.wal, "last_triage", None)
        if not triage:
            return 0
        holes: list[tuple[int, int | None]] = []
        for d in triage:
            if d.kind != "interior":
                continue
            r = d.lost_range()
            if r is None:
                continue  # pure garbage between consecutive sequences
            lo, hi = r
            lo = max(lo, from_seq)
            if hi is not None and hi < lo:
                continue  # entirely below flushed_seq: already in SSTs
            holes.append((lo, hi))
        if not holes:
            # nothing recoverable was lost; drop the damaged spans (the
            # sidecars keep the original bytes)
            self.wal.heal()
            return 0
        if self.wal_resync is None:
            raise WalHole(self.region_id, holes)
        count = 0
        for lo, hi in holes:
            fetched = sorted(self.wal_resync(
                lo, hi if hi is not None else (1 << 62)))
            # the source is the authority on what existed: sequences it
            # lacks may simply never have been written (failed appends
            # burn sequences) — but a source with NOTHING for the hole
            # is indistinguishable from loss, so declare it loudly
            if not fetched:
                raise WalHole(self.region_id, [(lo, hi)])
            for seq, payload in fetched:
                self.wal.append(seq, payload)  # re-log durably FIRST
                self._apply_wal_record(seq, payload)
                count += 1
            M_REPAIRED.labels("wal", "resync").inc(len(fetched))
        self.wal.heal()
        return count

    # ---- compaction (TWCS-lite) ---------------------------------------
    def _windows(self) -> dict[int, list[SstMeta]]:
        w = self.options.compaction_window_ms
        out: dict[int, list[SstMeta]] = {}
        for m in self.sst_files:
            out.setdefault(m.ts_min // w, []).append(m)
        return out

    def _maybe_compact(self) -> None:
        self.apply_ttl()
        for _win, files in self._windows().items():
            if len(files) >= self.options.compaction_trigger_files:
                try:
                    self.compact_files(files)
                except SstCorruption as e:
                    # corrupt input quarantined (or repaired): skip this
                    # window now; the next flush re-triggers it over the
                    # surviving/repaired file set
                    self._handle_sst_corruption(e)

    @staticmethod
    def _now_ms() -> int:
        import time as _time

        return int(_time.time() * 1000)

    def apply_ttl(self) -> int:
        """Drop SSTs fully past the retention window (reference TWCS
        picker expiration, src/mito2/src/compaction/twcs.rs + ttl in
        src/store-api/src/mito_engine_options.rs).  Whole-file drops
        only — a file with any live row stays until a later sweep.
        Returns the number of files dropped."""
        ttl = self.options.ttl_ms
        if not ttl:
            return 0
        from greptimedb_tpu.datatypes.types import TimeUnit

        # SST ts_max is in the table's native time unit — convert the
        # ms cutoff (a TIMESTAMP(0) table must not compare seconds
        # against milliseconds: that expires everything instantly)
        unit = self.schema.time_index.dtype.time_unit
        cutoff = TimeUnit.MILLISECOND.convert(self._now_ms() - ttl, unit)
        expired = [m for m in self.sst_files if m.ts_max < cutoff]
        if not expired:
            return 0
        self.manifest.commit({
            "kind": "edit", "add": [],
            "remove": [m.file_id for m in expired],
        })
        for m in expired:
            self.store.delete(m.path)
            self.store.delete(self._index_path(m))
            self._index_cache.pop(m.file_id, None)
        self.generation += 1
        self._mark_structure_change()
        return len(expired)

    def compact_files(self, files: list[SstMeta]) -> SstMeta:
        """Merge SSTs: sort, dedup keep-last, drop tombstones fully covered.

        Reference: TWCS picker + merge (src/mito2/src/compaction/twcs.rs).
        Tombstones are dropped only when the merge covers the whole region
        history for that key range — conservatively, when the input includes
        every SST file (full compaction); otherwise they are carried over.
        """
        from greptimedb_tpu.storage.scan import (
            estimate_staging_bytes, merge_parts, prefetch_store, read_parts,
        )

        # parallel decode through the scan pipeline, on the CODE path:
        # tags travel as region-code companions (read_sst maps each
        # file's dictionary once), so the rewrite never re-hashes a raw
        # string, and write_sst below rebuilds dictionary pages straight
        # from the codes.  Inputs are sorted SSTs — the sorted-run merge
        # replaces the global lexsort.
        prefetch_store(self.store, files)
        est = estimate_staging_bytes(files, len(self.schema) + 3)
        parts = read_parts(
            [
                (lambda m=m: read_sst(self.store, m, self.schema,
                                      tag_encoders=self.encoders,
                                      decode_tags=False))
                for m in files
            ],
            memory=self.memory, est_bytes=est,
        )
        merged, _path = merge_parts(parts, self.ts_name, TSID, SEQ)
        if not self.options.append_mode:
            tsid, ts = merged[TSID], merged[self.ts_name]
            keep = np.ones(len(tsid), dtype=bool)
            if len(tsid) > 1:
                same = (tsid[1:] == tsid[:-1]) & (ts[1:] == ts[:-1])
                keep[:-1] = ~same
            merged = {k: v[keep] for k, v in merged.items()}
        full = len(files) == len(self.sst_files) and self.memtable.is_empty
        if full:
            alive = merged[OP] != OP_DELETE
            merged = {k: v[alive] for k, v in merged.items()}
        new_meta = write_sst(
            self.store, f"{self._dir}/sst", self.schema, merged,
            level=max(m.level for m in files) + 1,
            tag_dicts={k: enc.values() for k, enc in self.encoders.items()},
        )
        self._write_sst_index(new_meta, merged)
        self.manifest.commit(
            {
                "kind": "edit",
                "add": [new_meta.to_dict()],
                "remove": [m.file_id for m in files],
            }
        )
        for m in files:
            self.store.delete(m.path)
            self.store.delete(self._index_path(m))
            self._index_cache.pop(m.file_id, None)
        self.generation += 1
        self._mark_structure_change()
        return new_meta

    def compact(self) -> None:
        """Full compaction of all SSTs (admin function, reference
        src/common/function/src/admin.rs compact_region)."""
        if self.memtable.num_rows:
            self.flush()
        self.apply_ttl()
        for _attempt in range(8):
            files = self.sst_files
            if not files:
                return
            try:
                self.compact_files(files)
                return
            except SstCorruption as e:
                # quarantine/repair the bad input, retry over the
                # refreshed live set
                self._handle_sst_corruption(e)

    def truncate(self) -> None:
        for m in self.sst_files:
            self.store.delete(m.path)
            self.store.delete(self._index_path(m))
        self._index_cache.clear()
        self.manifest.commit({"kind": "truncate", "truncated_seq": self.next_seq - 1})
        self.memtable = Memtable(self.schema)
        self.generation += 1
        self._mark_structure_change()

    def install_fence(self, epoch: int) -> None:
        """Arm leader-epoch fencing (ISSUE 15) on every shared-storage
        write surface this region owns: manifest deltas/checkpoints go
        through conditional puts under the epoch claim, and remote-WAL
        appends/watermark advances carry the epoch to the broker.  The
        epoch is minted by Metasrv at open/failover/migration-upgrade;
        a delayed write from a fenced-out predecessor then fails loudly
        (FencedError) instead of forking history.  No-op when
        GREPTIME_S3_FENCING=off."""
        from greptimedb_tpu.storage.manifest import fencing_enabled

        if not fencing_enabled():
            return
        self.manifest.set_fence(epoch)
        set_wal = getattr(self.wal, "set_fence", None)
        if set_wal is not None:
            set_wal(epoch)
        self.fence_epoch = int(epoch)

    # ---- proactive integrity (ISSUE 15, driven by storage/scrubber.py) -
    def scrub_wal(self) -> dict:
        """Verify every WAL segment NOW, while every acked row is still
        recoverable, instead of letting the next crash's replay find the
        rot.  Damage below the flushed floor just drops (rows live in
        SSTs; bytes preserved in sidecars).  A lost acked range above
        the floor resyncs from ``wal_resync`` (remote WAL / follower
        replica) and re-logs durably; with no covering source the region
        FLUSHES instead — the live memtable still holds every acked row,
        so advancing the durable floor past the hole repairs durability
        with zero loss (the option a crash-time replay no longer has)."""
        wal = self.wal
        if not isinstance(wal, FileLogStore):
            return {"damage": 0, "repaired": 0, "flushed": False}
        with self._write_lock:
            damages = wal.verify()
            if not damages:
                return {"damage": 0, "repaired": 0, "flushed": False}
            floor = self.manifest.state.flushed_seq + 1
            acked_hi = self.next_seq - 1
            holes: list[tuple[int, int]] = []
            for d in damages:
                if d.kind == "torn_tail":
                    # on a LIVE region the tail is acked data, never
                    # crash debris: everything up to next_seq-1 was acked
                    lo = (d.prev_seq + 1) if d.prev_seq is not None else 1
                    hi = acked_hi
                else:
                    r = d.lost_range()
                    if r is None:
                        continue  # garbage between consecutive sequences
                    lo, hi = r
                    hi = acked_hi if hi is None else hi
                lo = max(lo, floor)
                if hi < lo:
                    continue  # fully below the floor: already in SSTs
                holes.append((lo, hi))
            fetched: list[tuple[int, bytes]] = []
            covered = bool(holes) and self.wal_resync is not None
            if covered:
                for lo, hi in holes:
                    got = sorted(self.wal_resync(lo, hi))
                    if {s for s, _ in got} != set(range(lo, hi + 1)):
                        covered = False
                        break
                    fetched.extend(got)
            # SECURE the recovery durably FIRST, drop the damage LAST:
            # a crash anywhere in between must leave the corruption
            # loud (triaged at the next open), never a silently-clean
            # log missing acked rows (the _resync_wal_holes ordering)
            repaired = 0
            flushed = False
            if not holes:
                wal.drop_damage(damages)  # sub-floor debris only
            elif covered:
                if any(d.kind == "torn_tail" for d in damages):
                    # re-logging INTO a damaged tail would be destroyed
                    # by the tail truncation below (and truncating first
                    # would silently clean an unrecovered hole): roll to
                    # a fresh segment, so the re-logged records survive
                    # and interim crashes replay the damage as interior
                    # (valid records follow) — still loud, still triaged
                    wal._roll()
                for s, p in fetched:
                    wal.append(s, p)  # re-log durably
                wal.drop_damage(damages)
                repaired = len(fetched)
                M_REPAIRED.labels("wal", "scrub_resync").inc(repaired)
            else:
                # flush advances the durable floor past the hole (the
                # memtable holds every acked row); only then is the
                # damage mere sub-floor debris safe to drop
                self._flush_locked()
                wal.drop_damage(damages)
                flushed = True
                M_REPAIRED.labels("wal", "scrub_flush").inc()
            return {"damage": len(damages), "repaired": repaired,
                    "flushed": flushed}

    def scrub_manifest(self) -> dict:
        """Verify every on-disk manifest file against its CRC envelope.
        A corrupt file is quarantined, and — because the LIVE in-memory
        state supersedes the whole on-disk chain — repaired by forcing a
        fresh read-back-verified checkpoint, whose GC then collapses the
        damaged history.  The restart that would otherwise have tripped
        over the rot (possibly quarantining the region) now opens from
        the clean checkpoint."""
        from greptimedb_tpu.storage.durability import M_CORRUPTION
        from greptimedb_tpu.storage.manifest import (
            _decode_file, _encode_file,
        )

        checked = 0
        with self._write_lock:
            corrupt: list[str] = []
            epoch_bad = False
            for p in self.store.list(self.manifest.dir):
                if "/quarantine/" in p:
                    # moved-aside corpses: already flagged, preserved,
                    # never live — re-scrubbing them would re-quarantine
                    # (a self-rename that DELETES the bytes on rename-
                    # less remote stores) and alert forever
                    continue
                fn = p.rsplit("/", 1)[-1]
                is_epoch = fn == "EPOCH"
                if not (fn.startswith("checkpoint-")
                        or fn.startswith("delta-") or is_epoch):
                    continue
                try:
                    raw = self.store.read(p)
                except Exception:  # noqa: BLE001 — vanished under GC
                    continue
                checked += 1
                if _decode_file(raw) is None:
                    M_CORRUPTION.labels("manifest", "scrub").inc()
                    if is_epoch:
                        epoch_bad = True
                    else:
                        corrupt.append(p)
            if epoch_bad and self.manifest.fence_epoch is not None:
                # rewrite the epoch marker from the armed fence — a
                # rotted marker must not degrade fencing to "unknown"
                # forever.  CAS on the CORRUPT bytes' etag: if another
                # leader (re)claimed between our read and this write,
                # the replace loses instead of rolling its claim back.
                from greptimedb_tpu.errors import FencedError
                from greptimedb_tpu.storage.object_store import (
                    content_etag,
                )

                _ep, raw = self.manifest._read_epoch()
                if raw is not None and _decode_file(raw) is None:
                    try:
                        self.store.write_if(
                            self.manifest._epoch_path,
                            _encode_file(
                                {"epoch": self.manifest.fence_epoch}),
                            if_match=content_etag(raw))
                        M_REPAIRED.labels("manifest", "scrub_epoch").inc()
                    except FencedError:
                        pass  # someone else repaired/reclaimed: theirs wins
            if not corrupt:
                return {"checked": checked, "corrupt": 1 if epoch_bad
                        else 0}
            self.manifest.quarantine_files(corrupt)
            # the live state is the authority: a fresh verified
            # checkpoint re-establishes clean on-disk history and GCs
            # whatever the damaged versions still covered
            self.manifest.checkpoint()
            M_REPAIRED.labels("manifest", "scrub_checkpoint").inc()
            return {"checked": checked,
                    "corrupt": len(corrupt) + (1 if epoch_bad else 0)}

    def catch_up(self, take_ownership: bool = False) -> None:
        """Re-sync this region from shared storage (follower sync, leader
        upgrade after migration — reference handle_catchup.rs): reload the
        manifest, REHYDRATE tag dictionaries and the series registry from it
        (stale encoders would mint colliding tsids against newer SSTs),
        drop memtable state, sync the sequence counter, replay the WAL.

        ``take_ownership=True`` (leader upgrade) additionally repairs torn
        WAL tails; followers replay read-only — the leader may be mid-append
        on the shared segment."""
        from greptimedb_tpu.storage.manifest import Manifest

        try:
            self.manifest = Manifest.open(self.store, f"{self._dir}/manifest")
        except ManifestCorruption as mc:
            # same recovery gate as engine open: proceed on the good
            # prefix only when OUR replayable WAL covers the lost
            # actions.  Only an ownership-taking catch-up (leader
            # upgrade) may move the suspect files aside — followers stay
            # read-only on shared storage.
            floor = None
            for seq, _p in self.wal.replay(0, repair=False):
                floor = seq
                break
            covered = (mc.tail_only and mc.manifest.exists
                       and floor is not None
                       and floor <= mc.manifest.state.flushed_seq + 1)
            if not covered:
                raise
            if take_ownership:
                mc.manifest.quarantine_files(mc.bad_files)
            M_REPAIRED.labels("manifest", "wal_replay").inc()
            self.manifest = mc.manifest
        if self.fence_epoch is not None:
            # the reopened Manifest object starts unfenced: re-arm the
            # claim this region already holds (idempotent re-claim).  A
            # SUPERSEDED claim (this node was demoted and is now being
            # re-promoted under a NEWER minted epoch) must not wedge the
            # promotion: drop the stale arm — the grant handler installs
            # the new epoch right after this catch-up.
            from greptimedb_tpu.errors import FencedError

            try:
                self.manifest.set_fence(self.fence_epoch)
            except FencedError:
                self.fence_epoch = None
        state = self.manifest.state
        # adopt the manifest schema FIRST: the leader may have added tag
        # columns online (add_tag_column), and encoders built from the stale
        # schema would miss them, breaking the next replay/write
        if state.schema is not None:
            self.schema = state.schema
        self.encoders = {
            c.name: DictionaryEncoder(state.dicts.get(c.name, []))
            for c in self.schema.tag_columns
        }
        self._series = {
            tuple(codes): i for i, codes in enumerate(state.series)
        }
        self._dictcol_memo.clear()
        self._series_map1 = None
        self.memtable = Memtable(self.schema)
        self.next_seq = max(self.next_seq, state.flushed_seq + 1)
        if take_ownership:
            # shared-log stores must re-read the topic tail before this
            # promoted region appends (stale cached end-offsets collide)
            acquire = getattr(self.wal, "acquire_ownership", None)
            if acquire is not None:
                acquire()
        self.replay_wal(repair=take_ownership)
        self.generation += 1
        self._mark_structure_change()
        self._index_cache.clear()

    def storage_fingerprint(self) -> tuple:
        """Cheap change detector for no-op sync skipping: manifest file set
        + WAL segment names/sizes."""
        import os as _os

        manifest_files = tuple(self.store.list(f"{self._dir}/manifest"))
        wal_state: tuple = ()
        if hasattr(self.wal, "dir"):
            try:
                wal_state = tuple(
                    (fn, _os.path.getsize(_os.path.join(self.wal.dir, fn)))
                    for fn in sorted(_os.listdir(self.wal.dir))
                )
            except OSError:
                wal_state = ()
        return (manifest_files, wal_state)

    def ts_bounds(self) -> tuple[int, int] | None:
        """Data time bounds across memtable + SSTs; None when empty (an
        empty region must not drag a combined view's bounds to epoch 0)."""
        lo = self.memtable.ts_min
        hi = self.memtable.ts_max
        for m in self.sst_files:
            lo = m.ts_min if lo is None else min(lo, m.ts_min)
            hi = m.ts_max if hi is None else max(hi, m.ts_max)
        if lo is None:
            return None
        return (lo, hi)

    # ---- skipping index -------------------------------------------------
    def _index_path(self, meta) -> str:
        return f"{self._dir}/sst/{meta.file_id}.idx"

    def _write_sst_index(self, meta, columns: dict[str, np.ndarray]) -> None:
        from greptimedb_tpu.storage.index import build_sst_index

        tag_names = self.tag_names
        from greptimedb_tpu.datatypes.types import ConcreteDataType

        # full-text token sets for textual FIELD columns (log lines): the
        # bloom-based fulltext backend's file-pruning tier.  VECTOR/BINARY
        # are string-like in storage but tokenizing them is pure waste.
        ft_cols = [
            c.name for c in self.schema.field_columns
            if c.dtype in (ConcreteDataType.STRING, ConcreteDataType.JSON)
            and c.name in columns
        ]
        if not tag_names and not ft_cols:
            return
        has_tomb = bool((columns[OP] == OP_DELETE).any()) if OP in columns else False
        # distinct values per tag from the dictionary-code companion
        # columns when present: unique over int32 codes beats unique over
        # object strings by an order of magnitude on wide batches
        tag_uniques: dict[str, list] = {}
        for name in tag_names:
            codes = columns.get(tagcode_col(name))
            if codes is None:
                continue
            vocab = self.encoders[name].values()
            tag_uniques[name] = [vocab[int(c)] for c in np.unique(codes)]
        self.store.write(
            self._index_path(meta),
            build_sst_index(columns, tag_names, fulltext_columns=ft_cols,
                            has_tombstones=has_tomb,
                            tag_uniques=tag_uniques or None),
        )

    def _sst_index(self, meta) -> dict | None:
        from greptimedb_tpu.storage.index import load_sst_index

        cached = self._index_cache.get(meta.file_id)
        if cached is not None:
            return cached
        if not self.store.exists(self._index_path(meta)):
            return None  # pre-index SSTs: no pruning
        idx = load_sst_index(self.store.read(self._index_path(meta)))
        self._index_cache[meta.file_id] = idx
        return idx

    # ---- SST corruption: quarantine + repair ---------------------------
    def _handle_sst_corruption(self, exc: SstCorruption) -> str:
        """A verified read failed: move the damaged file aside (bytes
        preserved), then repair from a replica (``repair_source``) or
        re-flush from the WAL when the file's sequence range survived
        truncation; otherwise pull it from the live set via a manifest
        quarantine action so the region keeps serving its remaining
        files.  Returns "repaired" or "quarantined" (both mean: retry the
        read)."""
        meta = exc.meta
        with self._write_lock:
            if meta.file_id not in self.manifest.state.files:
                return "quarantined"  # another thread already handled it
            try:
                quarantine_object(self.store, meta.path)
            except (KeyError, OSError):
                pass  # file vanished entirely: nothing left to preserve
            M_QUARANTINED.labels("sst").inc()
            self._index_cache.pop(meta.file_id, None)
            # 1) replica repair over the Flight object plane
            if self.repair_source is not None:
                from greptimedb_tpu.storage.sst import verify_sst_bytes

                data = self.repair_source(meta.path)
                if data is not None and verify_sst_bytes(data):
                    self.store.write(meta.path, data)
                    M_REPAIRED.labels("sst", "replica").inc()
                    return "repaired"
            # 2) WAL re-flush: a flush-produced file whose sequence range
            # is still fully in the log (truncation crashed or lagged)
            if self._reflush_sst_from_wal(meta):
                M_REPAIRED.labels("sst", "wal").inc()
                self.generation += 1
                self._mark_structure_change()
                return "repaired"
            # 3) serve around it, loudly: the quarantine action pulls the
            # file from the live set and records it in manifest state
            self.manifest.commit(
                {"kind": "quarantine", "file_id": meta.file_id})
            self.generation += 1
            self._mark_structure_change()
            return "quarantined"

    def _reflush_sst_from_wal(self, meta) -> bool:
        """Rebuild a corrupt SST from WAL records covering exactly its
        sequence range (valid for flush-produced files: one freeze, one
        contiguous range).  Commits a replace edit on success."""
        recs = []
        for s, p in self.wal.replay(meta.seq_min, repair=False):
            if meta.seq_min <= s <= meta.seq_max:
                recs.append((s, p))
        got = {s for s, _ in recs}
        if got != set(range(meta.seq_min, meta.seq_max + 1)):
            return False  # not fully covered: never rebuild a partial file
        mt = Memtable(self.schema)
        for s, p in sorted(recs):
            mt.append(self._decode_wal_chunk(s, p))
        frozen = mt.freeze(dedup=not self.options.append_mode)
        new_meta = write_sst(
            self.store, f"{self._dir}/sst", self.schema, frozen,
            level=meta.level,
            tag_dicts={k: enc.values() for k, enc in self.encoders.items()},
        )
        self._write_sst_index(new_meta, frozen)
        self.manifest.commit({
            "kind": "edit",
            "add": [new_meta.to_dict()],
            "remove": [meta.file_id],
        })
        return True

    # ---- read path -----------------------------------------------------
    def scan_host(
        self,
        ts_range: tuple[int | None, int | None] = (None, None),
        columns: list[str] | None = None,
        tag_filters: dict[str, set] | None = None,
        tag_preds: dict[str, object] | None = None,
        ft_tokens: dict[str, list] | None = None,
        with_tag_codes: bool = False,
    ) -> dict[str, np.ndarray]:
        """Verified scan: on SST corruption the file is quarantined (and
        repaired from a replica / WAL re-flush when covered) and the scan
        retries — the region keeps serving from its remaining files; the
        corrupt bytes are never merged into results.  See
        ``_scan_host_impl`` for the scan machinery itself."""
        for _attempt in range(8):
            try:
                return self._scan_host_impl(ts_range, columns, tag_filters,
                                            tag_preds, ft_tokens,
                                            with_tag_codes)
            except SstCorruption as e:
                self._handle_sst_corruption(e)
        return self._scan_host_impl(ts_range, columns, tag_filters,
                                    tag_preds, ft_tokens, with_tag_codes)

    def _scan_host_impl(
        self,
        ts_range: tuple[int | None, int | None] = (None, None),
        columns: list[str] | None = None,
        tag_filters: dict[str, set] | None = None,
        tag_preds: dict[str, object] | None = None,
        ft_tokens: dict[str, list] | None = None,
        with_tag_codes: bool = False,
    ) -> dict[str, np.ndarray]:
        """Merged, deduped host columns for the requested time range.

        Sources: SSTs overlapping the range (file-level time pruning, bloom
        skipping-index pruning on ``tag_filters`` equality/IN sets, then
        Parquet row-group pruning) and the live memtable.  Selected SSTs
        decode CONCURRENTLY on the scan pipeline's bounded pool
        (storage/scan.py; ``GREPTIME_SCAN_THREADS``), with scan-driven
        readahead on prefetching object stores, and sources merge by
        sorted-run merge instead of a global lexsort.  Dedup keep-max-seq
        across sources; tombstones applied then dropped.

        ``tag_preds`` maps tag columns to term predicates (e.g. compiled
        regex matchers) used for FILE-LEVEL pruning only, via the sidecar's
        exact term dictionary (inverted-index analog) — the caller still
        applies the predicate row-wise to the returned columns.
        ``ft_tokens`` maps string-FIELD columns to full-text query tokens
        (AND semantics) pruned against the sidecar token sets.

        ``with_tag_codes=True`` is the code-path scan for device-cache
        builds: string tag columns come back as ``__tagcode_<name>__``
        int32 companions in region code space INSTEAD of raw object
        arrays — no per-row python object is ever materialized for a
        dictionary-encoded column on this path.
        """
        from greptimedb_tpu.storage.index import (
            sst_may_match, sst_pred_may_match, sst_tokens_may_match,
        )
        from greptimedb_tpu.storage.scan import (
            M_SCAN_FILES, estimate_staging_bytes, merge_parts,
            prefetch_store, read_parts,
        )
        from greptimedb_tpu.utils.tracing import TRACER

        want = None
        if columns is not None:
            internal = [TSID, SEQ, OP, self.ts_name]
            want = list(dict.fromkeys(columns + internal))
        selected: list[SstMeta] = []
        total = 0
        for m in self.sst_files:
            total += 1
            if not m.overlaps(*ts_range):
                continue
            if tag_filters or tag_preds or ft_tokens:
                idx = self._sst_index(m)
                if idx is not None:
                    if tag_filters and not sst_may_match(idx, tag_filters):
                        continue
                    if tag_preds and not all(
                        sst_pred_may_match(idx, col, pred)
                        for col, pred in tag_preds.items()
                    ):
                        continue
                    if ft_tokens and not all(
                        sst_tokens_may_match(idx, col, toks)
                        for col, toks in ft_tokens.items()
                    ):
                        continue
            selected.append(m)
        if total:
            M_SCAN_FILES.labels("pruned").inc(total - len(selected))
        internal = (TSID, SEQ, OP)
        schema_cols = {c.name for c in self.schema}
        eff_want = (want if want is not None
                    else list(schema_cols) + list(internal))
        # code-path tags: string tags only (integer tags are not
        # dictionary-encoded in SSTs and stay raw on either path)
        code_tags = {
            c.name for c in self.schema.tag_columns
            if c.dtype.is_string_like and c.name in eff_want
        } if with_tag_codes else set()
        code_cols = {tagcode_col(t) for t in code_tags}
        tag_enc = self.encoders if with_tag_codes else None
        with TRACER.stage("scan", region=self.region_id,
                          files=len(selected)):
            prefetch_store(self.store, selected)
            est = estimate_staging_bytes(selected, len(eff_want), ts_range)
            with TRACER.stage("scan_decode", files=len(selected)):
                parts = read_parts(
                    [
                        (lambda m=m: read_sst(
                            self.store, m, self.schema, ts_range, want,
                            tag_filters, tag_encoders=tag_enc,
                            decode_tags=not with_tag_codes))
                        for m in selected
                    ],
                    memory=self.memory, est_bytes=est,
                )
            if not self.memtable.is_empty:
                lo, hi = ts_range
                for chunk in self.memtable.snapshot_chunks():
                    ts = chunk[self.ts_name]
                    sel = np.ones(len(ts), dtype=bool)
                    if lo is not None:
                        sel &= ts >= lo
                    if hi is not None:
                        sel &= ts < hi
                    if not sel.any():
                        continue
                    part = {
                        k: v[sel]
                        for k, v in chunk.items()
                        if (k in code_cols) or (
                            k in eff_want and k not in code_tags
                            and (k in schema_cols or k in internal))
                    }
                    n = int(sel.sum())
                    for c in self.schema:  # chunks predating ALTER ADD
                        if c.name not in eff_want or c.name in part:
                            continue
                        if c.name in code_tags:
                            if tagcode_col(c.name) not in part:
                                fill = default_fill_array(c, 1)[0]
                                code = self.encoders[c.name].get_or_insert(
                                    fill)
                                part[tagcode_col(c.name)] = np.full(
                                    n, code, dtype=np.int32)
                        else:
                            part[c.name] = default_fill_array(c, n)
                    parts.append(part)
            if not parts:
                empty: dict[str, np.ndarray] = {}
                for c in self.schema:
                    if want is None or c.name in want:
                        if c.name in code_tags:
                            empty[tagcode_col(c.name)] = np.empty(
                                0, dtype=np.int32)
                        else:
                            empty[c.name] = np.empty(
                                0, dtype=object if c.dtype.is_string_like
                                else np.int64 if c.dtype.is_timestamp
                                else c.dtype.to_numpy()
                            )
                empty[TSID] = np.empty(0, dtype=np.int64)
                empty[SEQ] = np.empty(0, dtype=np.int64)
                empty[OP] = np.empty(0, dtype=np.int8)
                return empty
            with TRACER.stage("scan_merge", parts=len(parts)):
                merged, _path = merge_parts(parts, self.ts_name, TSID, SEQ)
            keep = np.ones(len(merged[TSID]), dtype=bool)
            if not self.options.append_mode:
                tsid, ts = merged[TSID], merged[self.ts_name]
                if len(tsid) > 1:
                    same = (tsid[1:] == tsid[:-1]) & (ts[1:] == ts[:-1])
                    keep[:-1] = ~same
            alive = keep & (merged[OP] != OP_DELETE)
            return {k: v[alive] for k, v in merged.items()}


class RegionEngine:
    """Owns all regions under one data home (the datanode's storage engine,
    reference RegionServer + MitoEngine)."""

    def __init__(self, data_home: str,
                 default_options: RegionOptions | None = None,
                 log_store_factory=None,
                 store: "ObjectStore | None" = None,
                 memory=None):
        self.data_home = data_home
        # default: local disk; pass an S3ObjectStore (storage/s3.py) for
        # cloud storage — WAL stays local/remote-broker either way
        self.store = store if store is not None else FsObjectStore(data_home)
        self.default_options = default_options or RegionOptions()
        self.regions: dict[int, Region] = {}
        # region_id -> LogStore; None = node-local file WAL.  A remote
        # factory (e.g. RemoteLogStore over a SharedLogBroker) makes the
        # node (nearly) stateless: failover replays from shared infra
        self.log_store_factory = log_store_factory
        # optional WorkloadMemoryManager shared by all regions (ingest
        # write-buffer quota); settable post-init by the embedding app
        self.memory = memory
        # region_id -> {"repair_source": ..., "wal_resync": ...}: repair
        # hooks installed on a region BEFORE its open-time WAL replay, so
        # interior corruption found at open can resync instead of raising
        # (meta/cluster.py wire_repair_sources sets the live equivalents)
        self.repair_hooks: dict[int, dict] = {}

    def _log_store(self, region_id: int):
        if self.log_store_factory is None:
            return None
        return self.log_store_factory(region_id)

    def _wal_dir(self, region_id: int) -> str:
        return os.path.join(self.data_home, f"region_{region_id}", "wal")

    # ---- manifest corruption recovery (ISSUE 9) ------------------------
    def _wal_floor(self, region_id: int) -> int | None:
        """Smallest sequence still replayable from the region's WAL, or
        None when the log is empty/absent — the cover probe for manifest
        recovery."""
        log = self._log_store(region_id)
        close = False
        if log is None:
            wal_dir = self._wal_dir(region_id)
            if not os.path.isdir(wal_dir):
                return None
            log = FileLogStore(wal_dir)
            close = True
        try:
            for seq, _payload in log.replay(0, repair=False):
                return seq
            return None
        finally:
            if close:
                log.close()

    def _open_manifest_verified(self, region_id: int) -> Manifest:
        """Manifest.open with corrupt-delta recovery: when verification
        fails past a good prefix, recover through WAL replay if the log
        covers everything since the prefix's flushed_seq (suspect files
        move to ``quarantine/``, open proceeds, replay restores the data
        actions); otherwise quarantine the REGION — files moved aside,
        marker written, open fails loudly until an operator intervenes.
        Never silently applies metadata over a hole."""
        try:
            return Manifest.open(self.store, f"region_{region_id}/manifest")
        except ManifestCorruption as mc:
            m = mc.manifest
            floor = self._wal_floor(region_id)
            # recoverable ONLY when (a) the damage is tail-shaped (the
            # lost action was the unacked commit a crash tore — an acked
            # mid-chain action could be a schema/dicts change WAL replay
            # cannot re-derive) and (b) the WAL actually replays from
            # the prefix's flushed_seq
            covered = (mc.tail_only and m.exists and floor is not None
                       and floor <= m.state.flushed_seq + 1)
            m.quarantine_files(mc.bad_files)
            if not covered:
                m.quarantine_region(mc.detail)
                raise RegionQuarantined(
                    f"region {region_id}: {mc.detail}; not recoverable "
                    f"(tail_only={mc.tail_only}, WAL floor={floor}, "
                    f"prefix flushed_seq={m.state.flushed_seq}) — region "
                    "quarantined, files preserved under manifest/"
                    "quarantine/") from mc
            M_REPAIRED.labels("manifest", "wal_replay").inc()
            return m

    def create_region(
        self, region_id: int, schema: Schema,
        options: RegionOptions | None = None,
    ) -> Region:
        if region_id in self.regions:
            raise StorageError(f"region {region_id} already open")
        opts = options or self.default_options
        manifest = Manifest.open(self.store, f"region_{region_id}/manifest")
        if manifest.exists:
            raise StorageError(f"region {region_id} already exists on disk")
        manifest.commit({"kind": "schema", "schema": schema.to_dict()})
        manifest.commit({"kind": "options", "options": opts.to_dict()})
        region = Region(region_id, self.store, schema, manifest,
                        self._wal_dir(region_id), opts,
                        log_store=self._log_store(region_id),
                        memory=self.memory)
        self.regions[region_id] = region
        return region

    def ensure_region(
        self, region_id: int, schema: Schema,
        options: RegionOptions | None = None,
    ) -> Region:
        """Idempotent create-or-open for resumable procedures: an open
        region or an on-disk manifest from a prior attempt is adopted;
        only a genuinely absent region is created. Real storage failures
        propagate untouched (never masked as already-exists). The manifest
        opened for the existence probe is handed to the create/open path —
        manifest open is checkpoint+delta reads, costly on object stores."""
        if region_id in self.regions:
            return self.regions[region_id]
        manifest = self._open_manifest_verified(region_id)
        if manifest.exists:
            return self.open_region(region_id, _manifest=manifest)
        # create path re-opens fresh: the immediately-pre-commit existence
        # re-check is what makes two nodes racing create on a shared object
        # store fail loudly instead of committing duplicate schema actions
        return self.create_region(region_id, schema, options)

    def open_region(self, region_id: int, take_ownership: bool = True,
                    _manifest: "Manifest | None" = None) -> Region:
        """Open an existing region.  ``take_ownership=False`` = follower open:
        replay the (possibly leader-shared) WAL read-only, never repairing
        torn tails the live leader may still be appending."""
        if region_id in self.regions:
            return self.regions[region_id]
        manifest = (_manifest if _manifest is not None
                    else self._open_manifest_verified(region_id))
        if not manifest.exists:
            raise RegionNotFound(f"region {region_id} not found in {self.data_home}")
        opts = RegionOptions(**manifest.state.options) if manifest.state.options else self.default_options
        region = Region(region_id, self.store, manifest.state.schema, manifest,
                        self._wal_dir(region_id), opts,
                        log_store=self._log_store(region_id),
                        memory=self.memory)
        hooks = self.repair_hooks.get(region_id) or {}
        region.repair_source = hooks.get("repair_source")
        region.wal_resync = hooks.get("wal_resync")
        region.replay_wal(repair=take_ownership)
        self.regions[region_id] = region
        return region

    def gc(self, grace_seconds: float = 3600.0) -> list[str]:
        """Global GC sweep (reference src/mito2/src/gc.rs + the global GC
        worker RFC 2025-07-23): delete SST/index objects under open
        regions' directories that no manifest references and that are
        older than the grace period (in-flight flushes commit their
        manifest edit AFTER the object write — grace covers the window).
        Returns deleted paths."""
        import re as _re
        import time as _time

        deleted: list[str] = []
        now = _time.time()
        # discover regions from STORAGE, not just open handles — the GC
        # worker typically runs against a data home with nothing open
        ids = set(self.regions)
        for path in self.store.list(""):
            m = _re.match(r"region_(\d+)/", path)
            if m:
                ids.add(int(m.group(1)))
        for rid in sorted(ids):
            region = self.regions.get(rid)
            if region is not None:
                files = region.sst_files
                quarantined = region.manifest.state.quarantined
            else:
                try:
                    manifest = Manifest.open(
                        self.store, f"region_{rid}/manifest")
                except (ManifestCorruption, RegionQuarantined):
                    continue  # unverifiable live set: GC must not guess
                if not manifest.exists:
                    continue  # not a region we can reason about: skip
                files = list(manifest.state.files.values())
                quarantined = manifest.state.quarantined
            live = {m.path for m in files}
            live |= {f"region_{rid}/sst/{m.file_id}.idx" for m in files}
            # quarantined SSTs stay repairable: never GC their objects
            live |= {d["path"] for d in quarantined.values()}
            live |= {f"region_{rid}/sst/{fid}.idx" for fid in quarantined}
            prefix = f"region_{rid}/sst"
            for path in self.store.list(prefix):
                if path in live:
                    continue
                if not _re.search(r"\.(parquet|idx)$", path):
                    continue
                mtime = self.store.last_modified(path)
                if mtime is None:
                    continue  # cannot prove age: never risk an in-flight flush
                if now - mtime < grace_seconds:
                    continue
                self.store.delete(path)
                deleted.append(path)
        return deleted

    def close_region(self, region_id: int) -> None:
        """Detach a region WITHOUT deleting its objects (recycle-bin drop:
        the data must survive until undrop or purge)."""
        region = self.regions.pop(region_id, None)
        if region is not None:
            region.wal.close()

    def drop_region(self, region_id: int) -> None:
        region = self.regions.pop(region_id, None)
        prefix = f"region_{region_id}"
        for p in self.store.list(prefix):
            self.store.delete(p)
        if region is not None:
            region.wal.close()

    def close(self, flush: bool = False) -> None:
        """Close WAL/segment handles; with ``flush=True`` (the graceful
        SIGTERM shutdown path — standalone CLI, datanode serve) dirty
        regions flush first, their WALs truncate to the hot tail, and a
        clean restart replays O(recent) instead of the full log.  The
        default stays cheap for embedders/tests — a dirty region simply
        replays on the next open (the crash path, which is exercised
        constantly).  Flush failures are surfaced on stderr but never
        block the close."""
        for r in self.regions.values():
            if flush:
                try:
                    r.flush()
                except Exception as e:  # noqa: BLE001 — shutdown must
                    import sys as _sys   # finish; replay covers the rest

                    print(f"flush-on-close failed for region "
                          f"{r.region_id}: {e}", file=_sys.stderr)
            r.wal.close()
        self.regions.clear()
