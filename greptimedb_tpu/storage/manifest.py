"""Region manifest: versioned action log + checkpoints.

Equivalent of the reference's manifest (src/mito2/src/manifest/{action.rs,
checkpointer.rs,manager.rs}, SURVEY.md §5.4 mechanism 2): every metadata
mutation (SST add/remove, schema change, flushed-sequence advance, dict
growth) is an appended JSON action file; a checkpoint collapses the prefix
so region open replays O(recent) actions, not history.

Layout under <region>/manifest/:
    checkpoint-<version>.json   full state at version
    delta-<version>.json        one action, applied in version order
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

from greptimedb_tpu.datatypes.schema import Schema
from greptimedb_tpu.storage.object_store import ObjectStore
from greptimedb_tpu.storage.sst import SstMeta

CHECKPOINT_EVERY = 16


@dataclass
class ManifestState:
    schema: Schema | None = None
    files: dict[str, SstMeta] = field(default_factory=dict)
    flushed_seq: int = 0
    truncated_seq: int = 0
    # tag dictionaries: column -> list of values (code = index); series
    # registry: list of tuples of tag codes (tsid = index)
    dicts: dict[str, list] = field(default_factory=dict)
    series: list[list[int]] = field(default_factory=list)
    options: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {
            "schema": self.schema.to_dict() if self.schema else None,
            "files": {k: v.to_dict() for k, v in self.files.items()},
            "flushed_seq": self.flushed_seq,
            "truncated_seq": self.truncated_seq,
            "dicts": self.dicts,
            "series": self.series,
            "options": self.options,
        }

    @staticmethod
    def from_dict(d: dict) -> "ManifestState":
        return ManifestState(
            schema=Schema.from_dict(d["schema"]) if d.get("schema") else None,
            files={k: SstMeta.from_dict(v) for k, v in d.get("files", {}).items()},
            flushed_seq=d.get("flushed_seq", 0),
            truncated_seq=d.get("truncated_seq", 0),
            dicts=d.get("dicts", {}),
            series=d.get("series", []),
            options=d.get("options", {}),
        )

    def apply(self, action: dict) -> None:
        kind = action["kind"]
        if kind == "edit":
            for f in action.get("add", []):
                m = SstMeta.from_dict(f)
                self.files[m.file_id] = m
            for fid in action.get("remove", []):
                self.files.pop(fid, None)
            if "flushed_seq" in action:
                self.flushed_seq = max(self.flushed_seq, action["flushed_seq"])
        elif kind == "schema":
            self.schema = Schema.from_dict(action["schema"])
        elif kind == "dicts":
            # append-only growth of tag dictionaries / series registry
            for col, vals in action.get("dicts", {}).items():
                cur = self.dicts.setdefault(col, [])
                cur.extend(vals[len(cur):])
            self.series.extend(action.get("series", [])[len(self.series):])
        elif kind == "reset_dicts":
            # wholesale replacement: series keys change ARITY when a tag
            # column is added online, which append-only growth cannot express
            self.dicts = dict(action.get("dicts", {}))
            self.series = list(action.get("series", []))
        elif kind == "truncate":
            self.files.clear()
            self.truncated_seq = action["truncated_seq"]
            self.flushed_seq = max(self.flushed_seq, action["truncated_seq"])
        elif kind == "options":
            self.options.update(action["options"])
        else:
            raise ValueError(f"unknown manifest action kind: {kind}")


class Manifest:
    def __init__(self, store: ObjectStore, manifest_dir: str):
        self.store = store
        self.dir = manifest_dir
        self.version = 0
        self.state = ManifestState()
        self._actions_since_checkpoint = 0

    # ---- open/replay ----------------------------------------------------
    @staticmethod
    def open(store: ObjectStore, manifest_dir: str) -> "Manifest":
        m = Manifest(store, manifest_dir)
        entries = store.list(manifest_dir)
        ckpt_versions = []
        delta_versions = []
        for p in entries:
            fn = p.rsplit("/", 1)[-1]
            if fn.startswith("checkpoint-"):
                ckpt_versions.append(int(fn[len("checkpoint-"):-len(".json")]))
            elif fn.startswith("delta-"):
                delta_versions.append(int(fn[len("delta-"):-len(".json")]))
        base = 0
        if ckpt_versions:
            base = max(ckpt_versions)
            raw = json.loads(store.read(f"{manifest_dir}/checkpoint-{base:020d}.json"))
            m.state = ManifestState.from_dict(raw)
            m.version = base
        for v in sorted(x for x in delta_versions if x > base):
            action = json.loads(store.read(f"{manifest_dir}/delta-{v:020d}.json"))
            m.state.apply(action)
            m.version = v
        return m

    @property
    def exists(self) -> bool:
        return self.state.schema is not None

    # ---- mutation -------------------------------------------------------
    def commit(self, action: dict) -> int:
        self.state.apply(action)
        self.version += 1
        self.store.write(
            f"{self.dir}/delta-{self.version:020d}.json",
            json.dumps(action).encode(),
        )
        self._actions_since_checkpoint += 1
        if self._actions_since_checkpoint >= CHECKPOINT_EVERY:
            self.checkpoint()
        return self.version

    def checkpoint(self) -> None:
        self.store.write(
            f"{self.dir}/checkpoint-{self.version:020d}.json",
            json.dumps(self.state.to_dict()).encode(),
        )
        self._actions_since_checkpoint = 0
        # GC superseded deltas/checkpoints
        for p in self.store.list(self.dir):
            fn = p.rsplit("/", 1)[-1]
            if fn.startswith("delta-") and int(fn[6:-5]) <= self.version:
                self.store.delete(p)
            elif fn.startswith("checkpoint-") and int(fn[11:-5]) < self.version:
                self.store.delete(p)
