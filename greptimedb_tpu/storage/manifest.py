"""Region manifest: versioned action log + checkpoints, CRC-verified.

Equivalent of the reference's manifest (src/mito2/src/manifest/{action.rs,
checkpointer.rs,manager.rs}, SURVEY.md §5.4 mechanism 2): every metadata
mutation (SST add/remove, schema change, flushed-sequence advance, dict
growth) is an appended JSON action file; a checkpoint collapses the prefix
so region open replays O(recent) actions, not history.

Layout under <region>/manifest/:
    checkpoint-<version>.json   full state at version
    delta-<version>.json        one action, applied in version order
    quarantine/<name>           corrupt files moved aside (never deleted)
    QUARANTINED                 marker: open refuses until cleared

Durability hardening (ISSUE 9, mirroring the reference's checksummed
manifest storage):

- every file is wrapped in a ``GTM1 <crc32>`` envelope and verified on
  open — a bit flip is detected, not parsed into wrong metadata;
- ``commit`` persists the delta BEFORE mutating in-memory state, so a
  failed write can never leave memory a version ahead of disk (the next
  commit would write version+1 over a hole);
- open REFUSES version gaps: deltas must be consecutive from the
  checkpoint base.  A corrupt/missing delta raises ManifestCorruption
  carrying the last good prefix — the region open path recovers through
  WAL replay when the log covers the lost actions, and quarantines the
  region (files moved aside + marker, open fails loudly) when it does
  not;
- ``checkpoint`` read-back-verifies the new checkpoint file before GC
  deletes the deltas it supersedes — GC can never destroy the only
  readable history behind an unreadable checkpoint.
"""

from __future__ import annotations

import json
import os
import zlib
from dataclasses import dataclass, field

from greptimedb_tpu.datatypes.schema import Schema
from greptimedb_tpu.errors import FencedError, StorageError
from greptimedb_tpu.storage.durability import (
    M_CORRUPTION,
    M_FENCE_CLAIMS,
    M_FENCE_REJECTED,
    M_QUARANTINED,
    ManifestCorruption,
    RegionQuarantined,
)
from greptimedb_tpu.storage.object_store import ObjectStore, content_etag
from greptimedb_tpu.storage.sst import SstMeta
from greptimedb_tpu.utils.chaos import CHAOS

CHECKPOINT_EVERY = 16

_MAGIC = b"GTM1 "
_QUARANTINE_MARKER = "QUARANTINED"
_EPOCH_MARKER = "EPOCH"


def fencing_enabled() -> bool:
    """GREPTIME_S3_FENCING (default on): epoch-fenced conditional puts
    for manifest/watermark writes on shared object storage.  Off = the
    pre-fencing plain-write behavior everywhere (A/B twin); standalone
    regions never arm a fence either way, so the single-node hot path is
    untouched by the knob."""
    return os.environ.get("GREPTIME_S3_FENCING", "on").lower() not in (
        "off", "0", "false")

_KNOWN_KINDS = frozenset(
    {"edit", "schema", "dicts", "reset_dicts", "truncate", "options",
     "quarantine"})


def _encode_file(obj: dict) -> bytes:
    body = json.dumps(obj).encode()
    return _MAGIC + b"%08x\n" % (zlib.crc32(body) & 0xFFFFFFFF) + body


def _decode_file(data: bytes) -> dict | None:
    """Parse a manifest file; None = corrupt (CRC mismatch / unparsable).
    Files written before the envelope (legacy plain JSON) still load —
    their integrity is best-effort, exactly as before."""
    try:
        if data.startswith(_MAGIC):
            nl = data.index(b"\n", len(_MAGIC))
            want = int(data[len(_MAGIC):nl], 16)
            body = data[nl + 1:]
            if (zlib.crc32(body) & 0xFFFFFFFF) != want:
                return None
            return json.loads(body)
        return json.loads(data)
    except (ValueError, IndexError):
        return None


@dataclass
class ManifestState:
    schema: Schema | None = None
    files: dict[str, SstMeta] = field(default_factory=dict)
    flushed_seq: int = 0
    truncated_seq: int = 0
    # tag dictionaries: column -> list of values (code = index); series
    # registry: list of tuples of tag codes (tsid = index)
    dicts: dict[str, list] = field(default_factory=dict)
    series: list[list[int]] = field(default_factory=list)
    options: dict = field(default_factory=dict)
    # SSTs pulled from the live set after failing read verification:
    # file_id -> meta dict.  Kept in state (not just moved aside on disk)
    # so every node agrees the file is out of service until repaired.
    quarantined: dict[str, dict] = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {
            "schema": self.schema.to_dict() if self.schema else None,
            "files": {k: v.to_dict() for k, v in self.files.items()},
            "flushed_seq": self.flushed_seq,
            "truncated_seq": self.truncated_seq,
            "dicts": self.dicts,
            "series": self.series,
            "options": self.options,
            "quarantined": self.quarantined,
        }

    @staticmethod
    def from_dict(d: dict) -> "ManifestState":
        return ManifestState(
            schema=Schema.from_dict(d["schema"]) if d.get("schema") else None,
            files={k: SstMeta.from_dict(v) for k, v in d.get("files", {}).items()},
            flushed_seq=d.get("flushed_seq", 0),
            truncated_seq=d.get("truncated_seq", 0),
            dicts=d.get("dicts", {}),
            series=d.get("series", []),
            options=d.get("options", {}),
            quarantined=d.get("quarantined", {}),
        )

    def apply(self, action: dict) -> None:
        kind = action["kind"]
        if kind == "edit":
            for f in action.get("add", []):
                m = SstMeta.from_dict(f)
                self.files[m.file_id] = m
            for fid in action.get("remove", []):
                self.files.pop(fid, None)
            if "flushed_seq" in action:
                self.flushed_seq = max(self.flushed_seq, action["flushed_seq"])
        elif kind == "schema":
            self.schema = Schema.from_dict(action["schema"])
        elif kind == "dicts":
            # append-only growth of tag dictionaries / series registry
            for col, vals in action.get("dicts", {}).items():
                cur = self.dicts.setdefault(col, [])
                cur.extend(vals[len(cur):])
            self.series.extend(action.get("series", [])[len(self.series):])
        elif kind == "reset_dicts":
            # wholesale replacement: series keys change ARITY when a tag
            # column is added online, which append-only growth cannot express
            self.dicts = dict(action.get("dicts", {}))
            self.series = list(action.get("series", []))
        elif kind == "truncate":
            self.files.clear()
            self.truncated_seq = action["truncated_seq"]
            self.flushed_seq = max(self.flushed_seq, action["truncated_seq"])
        elif kind == "options":
            self.options.update(action["options"])
        elif kind == "quarantine":
            # pull a corrupt SST from the live set (detection) or restore
            # a repaired one (repair) — the scan layer keeps serving the
            # remaining files either way
            fid = action["file_id"]
            if action.get("restore"):
                meta = self.quarantined.pop(fid, None)
                if meta is not None:
                    self.files[fid] = SstMeta.from_dict(meta)
            else:
                meta = self.files.pop(fid, None)
                if meta is not None:
                    self.quarantined[fid] = meta.to_dict()
        else:
            raise ValueError(f"unknown manifest action kind: {kind}")


class Manifest:
    def __init__(self, store: ObjectStore, manifest_dir: str):
        self.store = store
        self.dir = manifest_dir
        self.version = 0
        self.state = ManifestState()
        self._actions_since_checkpoint = 0
        # leader epoch this manifest writes under (None = unfenced, the
        # standalone/local default).  Armed by set_fence at cluster
        # open/failover/migration-upgrade; every subsequent write routes
        # through _write's conditional-put discipline.
        self.fence_epoch: int | None = None

    # ---- epoch fencing (ISSUE 15) --------------------------------------
    @property
    def _epoch_path(self) -> str:
        return f"{self.dir}/{_EPOCH_MARKER}"

    def _read_epoch(self) -> tuple[int | None, bytes | None]:
        """(epoch, raw bytes) of the shared EPOCH marker; (None, None)
        when absent, (-1, raw) when unreadably corrupt (scrub repairs;
        fencing decisions treat it as 'unknown', never as newer)."""
        if not self.store.exists(self._epoch_path):
            return None, None
        try:
            raw = self.store.read(self._epoch_path)
        except StorageError:
            return None, None  # deleted between exists and read
        rec = _decode_file(raw)
        if rec is None or "epoch" not in rec:
            M_CORRUPTION.labels("manifest", "epoch").inc()
            return -1, raw
        return int(rec["epoch"]), raw

    def set_fence(self, epoch: int) -> None:
        """Claim the shared EPOCH marker for ``epoch`` and arm fencing:
        every later commit/checkpoint verifies the marker and writes
        deltas create-only, so a fenced-out leader's delayed write fails
        loudly (FencedError) instead of forking history.  Claiming is
        itself a CAS — two racing claimants resolve to the higher epoch,
        and the loser raises here, before it ever writes a delta."""
        epoch = int(epoch)
        data = _encode_file({"epoch": epoch})
        for _ in range(8):
            cur, raw = self._read_epoch()
            if cur is not None and cur > epoch:
                M_FENCE_CLAIMS.labels("lost").inc()
                raise FencedError(
                    f"manifest {self.dir}: epoch {epoch} superseded by "
                    f"{cur}; this leader is fenced out")
            if cur == epoch:  # our own claim (crash-resume re-open)
                self.fence_epoch = epoch
                return
            try:
                if raw is None:
                    self.store.write_if(self._epoch_path, data,
                                        if_none_match=True)
                else:
                    self.store.write_if(self._epoch_path, data,
                                        if_match=content_etag(raw))
            except FencedError:
                continue  # marker moved under us: re-read and re-decide
            M_FENCE_CLAIMS.labels("won").inc()
            self.fence_epoch = epoch
            return
        M_FENCE_CLAIMS.labels("lost").inc()
        raise FencedError(
            f"manifest {self.dir}: could not claim epoch {epoch} "
            "(marker kept moving)")

    def _verify_fence(self, surface: str) -> None:
        """Raise FencedError when the shared EPOCH marker shows a newer
        leader (called before every fenced write).  Covers the window
        conditional-put alone cannot: after checkpoint GC deleted the
        deltas, a zombie's create-only write would otherwise succeed
        against the emptied version space (the ABA shape)."""
        cur, _raw = self._read_epoch()
        if cur is not None and self.fence_epoch is not None \
                and cur > self.fence_epoch:
            M_FENCE_REJECTED.labels(surface).inc()
            raise FencedError(
                f"manifest {self.dir}: write fenced out — epoch "
                f"{self.fence_epoch} superseded by {cur} ({surface})")

    def _write(self, path: str, data: bytes, *, create: bool = False,
               surface: str = "manifest") -> None:
        """THE manifest write path (lint GL-D003 owner: no manifest or
        marker bytes reach the store except through here).  Unfenced
        manifests write plainly — byte-for-byte the pre-fencing
        behavior.  Fenced manifests verify the epoch marker first, and
        version-keyed files (``create=True``: deltas) are create-only
        CAS puts, so two leaders racing on one version resolve to one
        winner."""
        if self.fence_epoch is None:
            # epoch-less writer backstop: if ANYONE has claimed an epoch
            # on this manifest, an unfenced write is a pre-fencing
            # zombie (its region opened before epochs were minted) and
            # must refuse — epoch-less writes bypassing the fence would
            # re-open the interleave.  Standalone manifests never have
            # the marker: one existence probe per commit.
            if fencing_enabled() and self.store.exists(self._epoch_path):
                M_FENCE_REJECTED.labels(surface).inc()
                raise FencedError(
                    f"manifest {self.dir}: epoch-less write refused — "
                    f"a leader epoch is claimed on this manifest "
                    f"({surface}); this writer predates fencing")
            self.store.write(path, data)
            return
        self._verify_fence(surface)
        if not create:
            self.store.write(path, data)
            return
        try:
            self.store.write_if(path, data, if_none_match=True)
            return
        except FencedError:
            pass
        # conflict under OUR verified epoch: nobody else may write here,
        # so the existing object is this leader's own orphaned earlier
        # attempt (the s3.cas crash window — the CAS landed remotely but
        # the ack never came back).  Identical bytes: the commit already
        # landed.  Different bytes: clobber the orphan exactly like the
        # plain-write path always has (it was never applied or acked) —
        # via a conditional REPLACE keyed on the orphan's etag, so a new
        # leader claiming the epoch and touching this version between
        # our verify and the write still loses us the CAS (FencedError)
        # instead of us silently overwriting its history.
        self._verify_fence(surface)  # a REAL fence still raises here
        try:
            existing = self.store.read(path)
        except StorageError:
            existing = None
        if existing is None:
            # the orphan vanished between the conflict and the read —
            # only another writer deletes manifest files; stay loud
            M_FENCE_REJECTED.labels(surface).inc()
            raise FencedError(
                f"manifest {self.dir}: {path} changed under epoch "
                f"{self.fence_epoch} ({surface})")
        if _decode_file(existing) is not None \
                and _decode_file(existing) == _decode_file(data):
            return
        self.store.write_if(path, data, if_match=content_etag(existing))

    # ---- open/replay ----------------------------------------------------
    @staticmethod
    def open(store: ObjectStore, manifest_dir: str) -> "Manifest":
        """Open and verify.  Raises RegionQuarantined when a prior
        uncovered corruption marked the region, and ManifestCorruption
        (carrying the recoverable prefix) when verification fails past a
        good prefix — callers with a WAL decide recovery vs quarantine."""
        m = Manifest(store, manifest_dir)
        entries = store.list(manifest_dir)
        ckpt_versions = []
        delta_versions = []
        for p in entries:
            if f"/{_QUARANTINE_MARKER}" in p or p.endswith(
                    _QUARANTINE_MARKER):
                raise RegionQuarantined(
                    f"manifest {manifest_dir} is quarantined "
                    f"({p}): clear the marker after repair to reopen")
            if "/quarantine/" in p:
                continue  # moved-aside corpses: never re-read as live
            fn = p.rsplit("/", 1)[-1]
            if fn.startswith("checkpoint-"):
                ckpt_versions.append(int(fn[len("checkpoint-"):-len(".json")]))
            elif fn.startswith("delta-"):
                delta_versions.append(int(fn[len("delta-"):-len(".json")]))
        bad_files: list[str] = []
        bad_ckpt_max = None
        base = 0
        # newest checkpoint that verifies wins; corrupt ones are suspects
        for v in sorted(ckpt_versions, reverse=True):
            path = f"{manifest_dir}/checkpoint-{v:020d}.json"
            raw = _decode_file(store.read(path))
            if raw is None:
                M_CORRUPTION.labels("manifest", "checkpoint").inc()
                bad_files.append(path)
                bad_ckpt_max = max(bad_ckpt_max or 0, v)
                continue
            m.state = ManifestState.from_dict(raw)
            m.version = base = v
            break
        detail = None
        tail_only = False
        expected = base + 1
        for v in sorted(x for x in delta_versions if x > base):
            if v != expected:
                # version gap: a delta is MISSING — refuse to silently
                # apply later deltas over the hole.  Deltas exist beyond
                # the hole, so this is mid-chain loss, never tail debris.
                M_CORRUPTION.labels("manifest", "gap").inc()
                detail = f"delta version gap: expected {expected}, found {v}"
                bad_files.extend(
                    f"{manifest_dir}/delta-{w:020d}.json"
                    for w in sorted(x for x in delta_versions if x >= v))
                break
            path = f"{manifest_dir}/delta-{v:020d}.json"
            action = _decode_file(store.read(path))
            if action is None:
                M_CORRUPTION.labels("manifest", "delta").inc()
                detail = f"corrupt delta at version {v}"
                bad_files.extend(
                    f"{manifest_dir}/delta-{w:020d}.json"
                    for w in sorted(x for x in delta_versions if x >= v))
                # crash-debris shape only if NOTHING follows the corpse:
                # the lost action was the last (unacked) commit
                tail_only = max(delta_versions) == v
                break
            m.state.apply(action)
            m.version = v
            expected = v + 1
        if detail is None and bad_files:
            if bad_ckpt_max is not None and m.version >= bad_ckpt_max:
                # a corrupt checkpoint fully superseded by an intact
                # delta chain: nothing is lost — move the corpse aside
                # and open normally (detected + quarantined, not fatal)
                m.quarantine_files(bad_files)
                return m
            detail = "corrupt checkpoint(s) newer than the loaded state"
        if detail is not None:
            raise ManifestCorruption(m, bad_files, detail,
                                     tail_only=tail_only)
        return m

    # ---- corruption handling (driven by the region open path) ----------
    def quarantine_files(self, paths: list[str]) -> None:
        """Move suspect files aside (``quarantine/`` subdir, preserved,
        never deleted) so the recovered prefix can move forward without
        colliding with their version numbers."""
        for p in paths:
            if not self.store.exists(p):
                continue
            fn = p.rsplit("/", 1)[-1]
            self.store.rename(p, f"{self.dir}/quarantine/{fn}")
            M_QUARANTINED.labels("manifest").inc()

    def quarantine_region(self, reason: str) -> None:
        """Uncovered loss: move suspects aside AND mark the region so
        every future open fails loudly until an operator intervenes.
        Fence-checked like any manifest write — a fenced-out zombie must
        not poison the new leader's region with a stale marker."""
        self._write(
            f"{self.dir}/{_QUARANTINE_MARKER}",
            _encode_file({"reason": reason, "version": self.version}),
            surface="quarantine")

    @property
    def exists(self) -> bool:
        return self.state.schema is not None

    # ---- mutation -------------------------------------------------------
    def commit(self, action: dict) -> int:
        if action.get("kind") not in _KNOWN_KINDS:
            raise ValueError(
                f"unknown manifest action kind: {action.get('kind')}")
        data = _encode_file(action)
        after = None
        if CHAOS.enabled:  # durability-boundary crash point + data faults
            data, after = CHAOS.filter_io("manifest.delta", data)
        # persist FIRST, apply on success: a failed write must leave the
        # in-memory state at the on-disk version, or the next commit
        # would write version+2 over a hole (the open-time gap check
        # above would then refuse the whole manifest).  Fenced manifests
        # write create-only: two split-brain leaders racing on this
        # version resolve to ONE winner, the loser raises FencedError
        self._write(f"{self.dir}/delta-{self.version + 1:020d}.json",
                    data, create=True, surface="delta")
        if after is not None:
            raise after
        self.state.apply(action)
        self.version += 1
        self._actions_since_checkpoint += 1
        if self._actions_since_checkpoint >= CHECKPOINT_EVERY:
            self.checkpoint()
        return self.version

    def checkpoint(self) -> None:
        path = f"{self.dir}/checkpoint-{self.version:020d}.json"
        data = _encode_file(self.state.to_dict())
        after = None
        if CHAOS.enabled:  # durability-boundary crash point + data faults
            data, after = CHAOS.filter_io("manifest.checkpoint", data)
        # fence-verified overwrite (not create-only: a crash between a
        # landed checkpoint write and its read-back verification retries
        # the SAME version — and a loser's same-version checkpoint is
        # byte-deterministic from the delta chain both leaders loaded)
        self._write(path, data, surface="checkpoint")
        if after is not None:
            raise after
        # read-back verify BEFORE GC: the deltas this checkpoint
        # supersedes are the only other copy of region metadata — they
        # may only die once the replacement provably reads back clean
        if _decode_file(self.store.read(path)) is None:
            M_CORRUPTION.labels("manifest", "checkpoint").inc()
            raise StorageError(
                f"checkpoint {path} failed read-back verification; "
                "superseded deltas retained")
        self._actions_since_checkpoint = 0
        self._gc_superseded()

    def _gc_superseded(self) -> None:
        """GC deltas/checkpoints the current checkpoint supersedes (never
        the quarantine corner).  Unfenced manifests delete plainly —
        byte-for-byte the pre-fencing behavior.  Fenced manifests verify
        the epoch marker first and then delete conditionally
        (``delete_if`` keyed on each file's observed etag), so a
        fenced-out zombie replaying a stale GC plan loses the CAS
        instead of destroying files a newer leader re-minted under the
        same version numbers (the ABA shape conditional PUT alone does
        not cover on the delete side).  A lost CAS SKIPS the file —
        never falls back to a plain delete."""
        CHAOS.inject("manifest.gc")
        fenced = self.fence_epoch is not None
        if fenced:
            self._verify_fence("gc")
        for p in self.store.list(self.dir):
            if "/quarantine/" in p or p.endswith(_QUARANTINE_MARKER):
                continue
            fn = p.rsplit("/", 1)[-1]
            if fn.startswith("delta-") and int(fn[6:-5]) <= self.version:
                pass
            elif fn.startswith("checkpoint-") and \
                    int(fn[11:-5]) < self.version:
                pass
            else:
                continue
            if not fenced:
                self.store.delete(p)
                continue
            meta = self.store.head(p)
            if meta is None:
                continue  # raced with another GC: already gone
            try:
                self.store.delete_if(p, if_match=meta["etag"])
            except FencedError:
                # file changed between head and delete — a newer leader
                # owns this version space now; leave its bytes alone
                M_FENCE_REJECTED.labels("gc").inc()
