"""Device-resident region cache: host columns → HBM tensors, reused across
queries.

The TPU answer to the reference's tiered read cache
(src/mito2/src/cache/: page/vector caches keep decoded batches hot in RAM;
here the hot tier is HBM). A region's merged scan result is canonicalized
once — tags to int32 codes, ts to int64, fields to f32, rows padded to a
shape-class bucket — and uploaded; queries then jit straight over the
cached tensors. Invalidation is by region generation (bumped on every
write/flush/compact).

Capacity: simple LRU by bytes; eviction drops device references and lets
JAX free HBM.
"""

from __future__ import annotations

import collections
import threading
import weakref
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from greptimedb_tpu.datatypes.batch import pad_rows
from greptimedb_tpu.datatypes.schema import Schema
from greptimedb_tpu.storage.memtable import (
    SEQ, TAGCODE_PREFIX, TSID, tagcode_col,
)
from greptimedb_tpu.storage.region import Region
from greptimedb_tpu.utils.telemetry import REGISTRY

# Registry mirrors of the per-instance cache counters (reference: the
# per-crate lazy_static CACHE_HIT/CACHE_MISS vectors in src/mito2/src/
# metrics.rs).  The instance attributes (hits/misses/...) stay the
# per-cache source of truth for tests and /status; these registry
# counters make the same events SQL-queryable via runtime_metrics and
# scrapeable at /metrics, which is what bench.py/bench_promql.py read.
M_CACHE_EVENTS = REGISTRY.counter(
    "greptime_cache_events_total",
    "Resident-cache events (hit/miss/build/eviction/invalidation/"
    "quota_reject/extend)",
    labels=("cache", "kind", "event"),
)
M_CACHE_BYTES = REGISTRY.gauge(
    "greptime_cache_resident_bytes",
    "Bytes resident in each device cache (HBM for device tensors)",
    labels=("cache",),
)
M_CACHE_ENTRIES = REGISTRY.gauge(
    "greptime_cache_entries",
    "Entries resident in each device cache",
    labels=("cache",),
)


def _export_cache_gauges(name: str, cache) -> None:
    """Point the per-cache bytes/entries gauges at this instance via a
    weakref: scrape-time pulls read live state without keeping a dead
    cache (tests build hundreds of short-lived dbs) alive forever.  The
    newest instance wins the label — one standalone instance per process
    is the served configuration."""
    ref = weakref.ref(cache)
    M_CACHE_BYTES.labels(name).set_function(
        lambda: c._bytes if (c := ref()) is not None else 0.0)
    M_CACHE_ENTRIES.labels(name).set_function(
        lambda: len(c._lru) if (c := ref()) is not None else 0.0)


_DICTS_VERSION = 0  # process-wide monotonic dict-content version


def next_dicts_version() -> int:
    """Shared monotonic version for dictionary-derived compiled constants
    (used by both DeviceTable and GridTable builds)."""
    global _DICTS_VERSION
    _DICTS_VERSION += 1
    return _DICTS_VERSION

# One multi-hundred-MB device_put RPC can break the TPU relay tunnel
# (observed: UNAVAILABLE mid-upload of a 34M-row table). Large columns
# stream in bounded pieces (storage/scan.py stream_to_device).


def _to_device(arr: np.ndarray) -> jnp.ndarray:
    """Delegates to the scan pipeline's double-buffered streamer: bounded
    chunks with two dispatches in flight, so host staging overlaps the
    previous chunk's transfer instead of serializing on it."""
    from greptimedb_tpu.storage.scan import stream_to_device

    return stream_to_device(arr)


@jax.tree_util.register_pytree_node_class
@dataclass
class DeviceTable:
    """A region's (or shard's) query-ready resident tensors.

    columns: ts (int64), fields (f32/ints), per-tag code columns (int32),
    plus __tsid__ (int32). Sorted by (tsid, ts) — segment ops get
    indices_are_sorted on the series axis for free.
    """

    columns: dict[str, jnp.ndarray]
    row_mask: jnp.ndarray
    num_series: int
    dicts: dict[str, list] = field(default_factory=dict)
    # tag columns whose codes are nondecreasing in row order — unlocks the
    # scatter-free sorted segment reduction in the query executor
    sorted_tags: tuple = ()
    # monotonic per-build version of ``dicts``: kernels that bake dict-
    # derived constants (vector/fulltext) key their cache on it
    dicts_version: int = 0
    # lineage root: the dicts_version of the FULL build this table
    # descends from.  Device-side extends bump dicts_version but keep the
    # root (dictionaries only ever APPEND within a lineage), so
    # incrementally extendable derived state — the fulltext fingerprint
    # matrix — keys on the root and extends by vocabulary tail instead of
    # rebuilding per append
    dicts_root: int = 0

    @property
    def padded_rows(self) -> int:
        return int(self.row_mask.shape[0])

    def nbytes(self) -> int:
        total = self.row_mask.nbytes
        for v in self.columns.values():
            total += v.nbytes
        return total

    def tree_flatten(self):
        names = sorted(self.columns)
        children = tuple(self.columns[n] for n in names) + (self.row_mask,)
        aux = (
            tuple(names),
            self.num_series,
            tuple((k, tuple(v)) for k, v in sorted(self.dicts.items())),
            tuple(self.sorted_tags),
            self.dicts_version,
            self.dicts_root,
        )
        return children, aux

    @classmethod
    def tree_unflatten(cls, aux, children):
        names, num_series, dict_items, sorted_tags, dver, droot = aux
        cols = dict(zip(names, children[:-1]))
        return cls(cols, children[-1], num_series,
                   {k: list(v) for k, v in dict_items}, sorted_tags, dver,
                   droot)


def _canonical_column(
    schema: Schema, encoders: dict, name: str, arr: np.ndarray,
    dicts: dict[str, list],
) -> np.ndarray:
    """One column of host scan output → device encoding (unpadded).

    The single definition of canonicalization, shared by the full build
    and the incremental extend path so the two can never diverge: tags →
    region dictionary codes (int32); string FIELDs → ad-hoc dictionary
    codes seeded from ``dicts`` (NULL becomes ""); numerics → device
    dtype; internal columns pass through.  ``dicts`` is updated in place.
    """
    if name == TSID:
        return arr.astype(np.int32)
    if schema.has_column(name):
        c = schema.column(name)
        if c.is_tag:
            enc = encoders[name]
            uniq, inv = np.unique(arr.astype(object), return_inverse=True)
            codes = np.fromiter(
                (enc.get(v) for v in uniq), dtype=np.int32, count=len(uniq)
            )
            dicts[name] = enc.values()
            return codes[inv]
        if c.dtype.is_string_like:
            # string FIELD (log lines, json): ad-hoc dictionary — codes
            # live on device, values in dicts for decode
            from greptimedb_tpu.datatypes.batch import DictionaryEncoder

            enc = DictionaryEncoder(dicts.get(name, []))
            # NULL string fields become "" (np.unique cannot order None)
            arr = np.array(["" if v is None else v for v in arr],
                           dtype=object)
            uniq, inv = np.unique(arr, return_inverse=True)
            codes = np.fromiter(
                (enc.get_or_insert(v) for v in uniq), dtype=np.int32,
                count=len(uniq),
            )
            dicts[name] = enc.values()
            return codes[inv]
        return arr.astype(c.dtype.to_device_dtype())
    return arr  # internal numeric column (e.g. __op__)


def _pad_value(schema: Schema, name: str, dtype: np.dtype):
    """Padding-row fill for a canonicalized column: poison code -1 for
    tag/string-dict columns, NaN for floats, 0 otherwise."""
    if name != TSID and schema.has_column(name):
        c = schema.column(name)
        if c.is_tag or c.dtype.is_string_like:
            return -1
    return np.nan if np.issubdtype(dtype, np.floating) else 0


def build_device_table(
    region: Region,
    ts_range: tuple[int | None, int | None] = (None, None),
    columns: list[str] | None = None,
) -> DeviceTable:
    """Scan, canonicalize and upload one region's data.

    Real regions scan on the CODE path: string tags arrive as
    ``__tagcode_<name>__`` int32 companions already in region code space
    (storage/sst.py maps each file's dictionary once), so canonicalization
    is a rename — no per-row object array, no re-hash.  Duck-typed views
    (combined/metric/file engines) keep the raw scan + re-encode;
    ``GREPTIME_SCAN_TAG_CODES=off`` forces the raw path for A/B."""
    import os

    if (getattr(region, "scan_supports_codes", False)
            and os.environ.get("GREPTIME_SCAN_TAG_CODES", "on") != "off"):
        host = region.scan_host(ts_range, columns, with_tag_codes=True)
    else:
        host = region.scan_host(ts_range, columns)
    schema = region.schema
    n = len(host[TSID])
    padded = pad_rows(n)

    dev_cols: dict[str, jnp.ndarray] = {}
    host_canon: dict[str, np.ndarray] = {}
    dicts: dict[str, list] = {}
    for name, arr in host.items():
        if name == SEQ:
            continue  # sequences are a storage concern; queries never see them
        if name.startswith(TAGCODE_PREFIX):
            # code-path tag column: already region codes
            name = name[len(TAGCODE_PREFIX):-2]
            vals = arr.astype(np.int32, copy=False)
            dicts[name] = region.encoders[name].values()
        else:
            vals = _canonical_column(schema, region.encoders, name, arr,
                                     dicts)
        out = np.full(padded, _pad_value(schema, name, vals.dtype),
                      dtype=vals.dtype)
        out[:n] = vals
        host_canon[name] = vals
        dev_cols[name] = _to_device(out)
    mask = np.zeros(padded, dtype=bool)
    mask[:n] = True
    # monotone tag detection: rows are (tsid, ts)-sorted; a tag qualifies
    # for sorted segment reductions when its codes are nondecreasing AND
    # bijective with series runs (each code run is exactly one tsid run, so
    # ts — and hence any time bucket — is ascending within every code run).
    # Detection runs on the host copies — reading dev_cols back would pull
    # the whole column through the device tunnel again.
    sorted_tags = []
    if n > 0:
        tsid_runs = 1 + int((np.diff(host_canon[TSID]) != 0).sum())
        for c in schema.tag_columns:
            if c.name in host_canon:
                codes = host_canon[c.name]
                d = np.diff(codes)
                if bool((d >= 0).all()) and 1 + int((d != 0).sum()) == tsid_runs:
                    sorted_tags.append(c.name)
    global _DICTS_VERSION
    _DICTS_VERSION += 1
    return DeviceTable(dev_cols, jnp.asarray(mask), region.num_series, dicts,
                       tuple(sorted_tags), _DICTS_VERSION, _DICTS_VERSION)


def _canonical_delta(
    region, chunks: list[dict], dicts: dict[str, list]
) -> tuple[dict[str, np.ndarray], int]:
    """Canonicalize append-log chunks (same rules as build_device_table —
    shared _canonical_column — unpadded).  ``dicts`` holds the resident
    table's dictionaries and is extended in place so codes stay
    consistent across deltas."""
    schema = region.schema
    host = {
        k: np.concatenate([np.asarray(c[k]) for c in chunks])
        for k in chunks[0]
    }
    dn = len(host[TSID])
    out: dict[str, np.ndarray] = {}
    for name, arr in host.items():
        if name == SEQ or name.startswith(TAGCODE_PREFIX):
            continue  # codes fold into their tag column below
        tc = tagcode_col(name)
        if (tc in host and schema.has_column(name)
                and schema.column(name).is_tag):
            # memtable chunks carry write-time region codes: reuse them
            # instead of re-hashing the raw strings per delta
            out[name] = host[tc].astype(np.int32, copy=False)
            dicts[name] = region.encoders[name].values()
            continue
        out[name] = _canonical_column(schema, region.encoders, name, arr,
                                      dicts)
    return out, dn


def extend_device_table(
    table: DeviceTable, region, chunks: list[dict], live_rows: int
) -> tuple[DeviceTable, int]:
    """Append new rows to a resident DeviceTable WITHOUT re-uploading the
    base: only the delta crosses host→device; growth beyond the padding
    bucket concatenates on device; the (tsid, ts) sort order every
    consumer relies on is restored by a device-side lexsort + gather
    (HBM-local, no PCIe traffic).

    Correctness precondition (enforced by Region's append log): delta rows
    are PUT-only with timestamps strictly after all resident rows, so no
    dedup/tombstone interaction with the base is possible.
    """
    dicts = dict(table.dicts)
    delta, dn = _canonical_delta(region, chunks, dicts)
    n_old = live_rows
    n_new = n_old + dn
    old_padded = table.padded_rows
    new_padded = pad_rows(n_new)
    ts_name = region.schema.time_index.name

    cols: dict[str, jnp.ndarray] = {}
    for name, col in table.columns.items():
        dv = delta.get(name)
        if dv is None:  # column absent from delta (shouldn't happen)
            dv = np.zeros(dn, dtype=np.asarray(col[:1]).dtype)
        if new_padded > old_padded:
            pad_np = np.full(
                new_padded - old_padded,
                _pad_value(region.schema, name, dv.dtype),
                dtype=dv.dtype,
            )
            col = jnp.concatenate([col, jnp.asarray(pad_np)])
        cols[name] = col.at[n_old:n_new].set(jnp.asarray(dv))
    mask = table.row_mask
    if new_padded > old_padded:
        mask = jnp.concatenate(
            [mask, jnp.zeros(new_padded - old_padded, dtype=bool)]
        )
    mask = mask.at[n_old:n_new].set(True)

    # restore global (tsid, ts) order; padding rows pin to the end via the
    # inverted mask as the primary key
    order = jnp.lexsort(
        (cols[ts_name], cols[TSID], (~mask).astype(jnp.int32))
    )
    cols = {k: v[order] for k, v in cols.items()}
    mask = mask[order]

    # sorted-tag monotonicity survives the re-sort only if no new series
    # appeared (tag-per-tsid mapping unchanged); otherwise drop until the
    # next full rebuild re-derives it
    sorted_tags = (
        table.sorted_tags if region.num_series == table.num_series else ()
    )
    global _DICTS_VERSION
    _DICTS_VERSION += 1
    return (
        DeviceTable(cols, mask, region.num_series, dicts, sorted_tags,
                    _DICTS_VERSION, table.dicts_root),
        n_new,
    )


def _append_pos(region) -> "int | None":
    """The region's absolute append-log position (Region.append_pos);
    falls back to the raw list length for duck-typed region-likes that
    predate position trimming."""
    pos = getattr(region, "append_pos", None)
    if pos is not None:
        return pos
    log = getattr(region, "_append_log", None)
    return len(log) if log is not None else None


def _chunks_since(region, pos: int) -> "list | None":
    """Append-log chunks after absolute position ``pos``; None when the
    position predates the region's trimmed window (consumer must rebuild)."""
    f = getattr(region, "append_chunks_since", None)
    if f is not None:
        return f(pos)
    log = getattr(region, "_append_log", None)
    return log[pos:] if log is not None else None


@dataclass
class _Entry:
    # DeviceTable, GridTable, or None (negative grid-eligibility cache)
    table: object
    delta_pos: int | None = None  # consumed append-log position (absolute)
    live_rows: int = 0
    # grid catch-up validity keys (see get_grid): the SST set the table
    # was built from and the region's content-mutation epoch at build time
    sst_ids: frozenset | None = None
    mutation_epoch: int = -1


class RegionCacheManager:
    """LRU of DeviceTables.

    Regions with the incremental protocol (base_version + append log) key
    by base_version; pure time-forward appends EXTEND the resident tensors
    device-side instead of rebuilding (reference analog: the write-through
    cache keeps mito's page cache warm across flushes,
    src/mito2/src/cache/write_cache.rs).  Duck-typed views and restricted
    scans keep generation-keyed full rebuilds.
    """

    def __init__(self, capacity_bytes: int = 8 << 30, mesh=None):
        # delta volume beyond max(min_extend_rows, fraction * resident
        # rows) → full rebuild (restores sorted-tag eligibility and
        # compacts fragmentation); small deltas always extend
        self.rebuild_fraction = 0.25
        self.min_extend_rows = 4096
        self.capacity = capacity_bytes
        # device mesh for series-axis sharding of resident grids (set by
        # GreptimeDB when >1 device is visible); None = single device
        self.mesh = mesh
        # optional DerivedLayoutCache chained into invalidate_region (set
        # by GreptimeDB): every drop/truncate/repartition path that
        # invalidates a region's resident tensors must also drop its
        # derived bucket-major layouts, or they leak device bytes and
        # inflate the layout_cache workload usage
        self.derived_layouts = None
        # optional PromLayoutCache chained the same way: a dropped /
        # truncated / repartitioned region's resident PromQL selections,
        # sort layouts and group-id vectors must free with the region —
        # version checks catch staleness, but only explicit invalidation
        # catches deletion
        self.promql_derived = None
        self._lru: "collections.OrderedDict[tuple, _Entry]" = (
            collections.OrderedDict()
        )
        self._bytes = 0
        # guards _lru/_bytes: scheduler workers (get/get_grid) and
        # ingest-pool workers (extend_hot_tail, auto-create paths) mutate
        # them concurrently — an unguarded OrderedDict iteration would
        # raise "mutated during iteration" mid-query and unguarded
        # read-modify-writes of _bytes drift the accounting _shrink
        # evicts by.  Reentrant: _evict/_shrink run nested under it.
        # Device builds/extends run OUTSIDE it — only dict/counter ops
        # are held.
        self._struct_lock = threading.RLock()
        # serializes ingest-side hot-tail extenders (the ingest pool runs
        # several writers); acquired non-blocking — a contended extend is
        # skipped, the query-time path stays responsible
        self._hot_tail_lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.extends = 0
        _export_cache_gauges("region_device", self)

    def get(
        self,
        region: Region,
        ts_range: tuple[int | None, int | None] = (None, None),
        columns: list[str] | None = None,
    ) -> DeviceTable:
        base_ver = getattr(region, "base_version", None)
        append_log = getattr(region, "_append_log", None)
        incremental = (
            base_ver is not None
            and append_log is not None
            and ts_range == (None, None)
            and columns is None
        )
        version = base_ver if incremental else region.generation
        key = (
            region.region_id,
            version,
            ts_range,
            tuple(columns) if columns else None,
        )
        pos = _append_pos(region) if incremental else None
        entry = self._lru.get(key)
        if entry is not None:
            if not incremental or entry.delta_pos == pos:
                M_CACHE_EVENTS.labels("region_device", "table", "hit").inc()
                with self._struct_lock:
                    self.hits += 1
                    if key in self._lru:
                        self._lru.move_to_end(key)
                return entry.table
            # resident base is current; new append-log chunks extend it
            chunks = _chunks_since(region, entry.delta_pos)
            delta_rows = (sum(len(c[TSID]) for c in chunks)
                          if chunks is not None else None)
            if delta_rows is not None and delta_rows <= max(
                self.min_extend_rows,
                entry.live_rows * self.rebuild_fraction,
            ):
                with self._struct_lock:
                    self.extends += 1
                M_CACHE_EVENTS.labels(
                    "region_device", "table", "extend").inc()
                # whole-entry swap (not field mutation): a concurrent
                # reader holds a self-consistent entry either way
                new_table, new_rows = extend_device_table(
                    entry.table, region, chunks, entry.live_rows
                )
                with self._struct_lock:
                    if self._lru.get(key) is entry:
                        # bytes delta only when the swap applies — an
                        # entry replaced/evicted meanwhile keeps its own
                        # accounting (an unconditional += would drift
                        # _bytes upward and make _shrink evict live
                        # entries forever after).  delta_pos derives from
                        # the chunks actually applied, NOT a pos read
                        # earlier: a chunk landing between the pos read
                        # and the fetch would be in the table yet
                        # recorded unconsumed, and the next extend would
                        # append its rows a second time.
                        self._bytes += (new_table.nbytes()
                                        - entry.table.nbytes())
                        self._lru[key] = _Entry(
                            new_table,
                            delta_pos=entry.delta_pos + len(chunks),
                            live_rows=new_rows,
                            sst_ids=entry.sst_ids,
                            mutation_epoch=entry.mutation_epoch,
                        )
                        self._lru.move_to_end(key)
                self._shrink()
                return new_table
            self._evict(key)  # too much drift (or trimmed past): rebuild

        with self._struct_lock:
            self.misses += 1
        M_CACHE_EVENTS.labels("region_device", "table", "miss").inc()
        table = build_device_table(region, ts_range, columns)
        if incremental and _append_pos(region) != pos:
            # a chunk landed while building: the table may contain rows
            # past ``pos`` (the build reads the live memtable), so caching
            # it at pos would double-apply them on the next extend, and
            # recording the newer pos could silently drop rows the build
            # raced past.  Serve the (self-consistent) table uncached —
            # the next quiet query populates the entry; under sustained
            # ingest extend_hot_tail keeps the grid entries fresh instead.
            return table
        entry = _Entry(
            table,
            delta_pos=pos,
            live_rows=int(np.asarray(table.row_mask).sum()),
        )
        with self._struct_lock:
            # drop stale versions of the same region+range; versions live
            # in two namespaces (base_version for incremental full-table
            # entries, generation for restricted scans), so only compare
            # within the same (range, columns) class
            stale = [
                k for k in self._lru
                if k[0] == key[0] and k[2:] == key[2:] and k[1] != key[1]
            ]
            for k in stale:
                self._evict(k)
            old = self._lru.get(key)
            if old is not None and old.table is not None:
                self._bytes -= old.table.nbytes()  # concurrent double-build
            self._lru[key] = entry
            self._bytes += table.nbytes()
            self._shrink()
        return table

    def peek_table(self, region):
        """The region's resident full-table DeviceTable if one is ALREADY
        resident at the current base version, else None — never builds.
        Consumers that only accelerate when warm (the log-query DSL's
        fingerprint route) use this so a cold table stays on its host
        path instead of paying a device build it didn't ask for.  The
        entry may lag the append log; callers must treat the resident
        dictionaries as a (valid) prefix, not the complete vocabulary."""
        base_ver = getattr(region, "base_version", None)
        if base_ver is None:
            return None
        entry = self._lru.get((region.region_id, base_ver, (None, None),
                               None))
        return entry.table if entry is not None else None

    def get_grid(self, region):
        """Dense-grid resident table for a region (storage/grid.py), or
        None when the region is ineligible (cached negatively per
        base_version so queries don't re-probe every time).  Pure appends
        extend the resident grid device-side; structure changes rebuild."""
        from greptimedb_tpu.storage.grid import (
            build_grid_table, extend_grid_table,
        )

        from greptimedb_tpu.storage.grid import catch_up_grid_table

        base_ver = getattr(region, "base_version", None)
        append_log = getattr(region, "_append_log", None)
        if base_ver is None or append_log is None:
            return None  # duck-typed views (joins, staged scans): row path
        key = (region.region_id, "grid", base_ver)
        pos = _append_pos(region)
        entry = self._lru.get(key)
        if entry is not None:
            if entry.delta_pos == pos:
                M_CACHE_EVENTS.labels("region_device", "grid", "hit").inc()
                with self._struct_lock:
                    self.hits += 1
                    if key in self._lru:
                        self._lru.move_to_end(key)
                return entry.table
            chunks = _chunks_since(region, entry.delta_pos)
            if entry.table is None:
                # negative entry: re-probe only after substantial growth —
                # an ineligible (irregular/sparse) region must not pay a
                # full eligibility scan per query
                if chunks is not None:
                    appended = sum(len(c[TSID]) for c in chunks)
                    if appended <= max(
                            self.min_extend_rows,
                            entry.live_rows * self.rebuild_fraction):
                        return None
            elif chunks is not None:
                with self._struct_lock:
                    self.extends += 1
                M_CACHE_EVENTS.labels("region_device", "grid", "extend").inc()
                extended = extend_grid_table(entry.table, region, chunks,
                                             mesh=self.mesh)
                if extended is not None:
                    # whole-entry swap (not field mutation): a concurrent
                    # reader holds a self-consistent entry either way;
                    # bytes delta only when the swap applies, and
                    # delta_pos derives from the chunks actually applied
                    # (see get)
                    with self._struct_lock:
                        if self._lru.get(key) is entry:
                            self._bytes += (extended.nbytes()
                                            - entry.table.nbytes())
                            self._lru[key] = _Entry(
                                extended,
                                delta_pos=entry.delta_pos + len(chunks),
                                live_rows=entry.live_rows,
                                sst_ids=entry.sst_ids,
                                mutation_epoch=entry.mutation_epoch,
                            )
                            self._lru.move_to_end(key)
                    self._shrink()
                    return extended
            self._evict(key)  # delta does not fit (or trimmed past)

        with self._struct_lock:
            self.misses += 1
        M_CACHE_EVENTS.labels("region_device", "grid", "miss").inc()
        rows_now = region.memtable.num_rows + sum(
            m.num_rows for m in region.sst_files
        )
        cur_ids = frozenset(m.file_id for m in region.sst_files)
        epoch = getattr(region, "mutation_epoch", None)

        # incremental catch-up: a previous base_version's resident grid is
        # still valid row-for-row when only content-PRESERVING structure
        # changes happened (flush: mutation_epoch unchanged, old SST set
        # intact, memtable/append-log empty) — extend it from the new
        # files (reads prune to the not-yet-resident ts range) instead of
        # re-reading the whole region
        with self._struct_lock:
            prev_key = next(
                (k for k in self._lru
                 if k[0] == region.region_id and k[1:2] == ("grid",)), None)
            prev = self._lru.get(prev_key) if prev_key is not None else None
        if prev is not None and epoch is not None:
            if (prev.table is not None and prev.sst_ids is not None
                    and prev.mutation_epoch == epoch
                    and region.memtable.is_empty and not append_log
                    and prev.sst_ids <= cur_ids):
                new_metas = [m for m in region.sst_files
                             if m.file_id not in prev.sst_ids]
                caught = catch_up_grid_table(
                    prev.table, region, new_metas, mesh=self.mesh)
                if caught is not None:
                    M_CACHE_EVENTS.labels(
                        "region_device", "grid", "catch_up").inc()
                    with self._struct_lock:
                        self.extends += 1
                        got = self._lru.pop(prev_key, None)
                        if got is not None and got.table is not None:
                            self._bytes -= got.table.nbytes()
                        if (caught is not prev.table
                                and self.derived_layouts is not None):
                            # dicts_version moved on: the old grid's
                            # derived layouts can never hit again
                            self.derived_layouts.invalidate_region(key[0])
                        old = self._lru.get(key)
                        if old is not None and old.table is not None:
                            self._bytes -= old.table.nbytes()
                        self._lru[key] = _Entry(
                            caught, delta_pos=pos,
                            live_rows=rows_now, sst_ids=cur_ids,
                            mutation_epoch=epoch,
                        )
                        self._bytes += caught.nbytes()
                        self._shrink()
                    return caught

        table = build_grid_table(region, mesh=self.mesh)
        if table is not None and _append_pos(region) != pos:
            # raced an ingest append mid-build (see get's miss path):
            # serve uncached rather than cache a table whose delta_pos
            # cannot be trusted.  Negative (None) entries cache anyway —
            # delta_pos staleness only delays the next eligibility probe.
            return table
        entry = _Entry(table, delta_pos=pos, live_rows=rows_now,
                       sst_ids=cur_ids,
                       mutation_epoch=epoch if epoch is not None else -1)
        with self._struct_lock:
            stale = [
                k for k in self._lru
                if (k[0] == key[0] and k[1:2] == ("grid",)
                    and k[2] != base_ver)
            ]
            for k in stale:
                self._evict(k)
            old = self._lru.get(key)
            if old is not None and old.table is not None:
                self._bytes -= old.table.nbytes()  # concurrent double-build
            self._lru[key] = entry
            if table is not None:
                self._bytes += table.nbytes()
            self._shrink()
        return table

    def get_sharded(self, region):
        """Series-sharded row table (parallel/dist.py ShardedTable) for
        mesh aggregation of irregular/sparse regions that the dense grid
        refuses.  Keyed by generation: any write rebuilds (row order under
        the shard permutation is not extendable in place the way grid
        columns are)."""
        if self.mesh is None:
            return None
        from greptimedb_tpu.parallel.dist import shard_region

        key = (region.region_id, "sharded", region.generation)
        entry = self._lru.get(key)
        if entry is not None:
            M_CACHE_EVENTS.labels("region_device", "sharded", "hit").inc()
            with self._struct_lock:
                self.hits += 1
                if key in self._lru:
                    self._lru.move_to_end(key)
            return entry.table
        with self._struct_lock:
            self.misses += 1
        M_CACHE_EVENTS.labels("region_device", "sharded", "miss").inc()
        table = shard_region(region, self.mesh)
        with self._struct_lock:
            for k in [
                k for k in self._lru
                if k[0] == key[0] and k[1:2] == ("sharded",) and k != key
            ]:
                self._evict(k)
            old = self._lru.get(key)
            if old is not None and old.table is not None:
                self._bytes -= old.table.nbytes()
            self._lru[key] = _Entry(table)
            self._bytes += table.nbytes()
            self._shrink()
        return table

    def install_grid(self, region, table) -> None:
        """Adopt an externally built resident GridTable (snapshot restore:
        storage/grid.py load_grid_snapshot) as the region's current grid
        entry, exactly as if get_grid had built it."""
        key = (region.region_id, "grid", region.base_version)
        rows_now = region.memtable.num_rows + sum(
            m.num_rows for m in region.sst_files
        )
        # same stale-version sweep as get_grid's miss path: entries for
        # other base_versions are dead weight that would count against
        # capacity and could shrink-evict the fresh grid
        with self._struct_lock:
            for k in [
                k for k in self._lru
                if k[0] == key[0] and k[1:2] == ("grid",)
            ]:
                self._evict(k)
            self._lru[key] = _Entry(
                table, delta_pos=_append_pos(region), live_rows=rows_now,
                sst_ids=frozenset(m.file_id for m in region.sst_files),
                mutation_epoch=getattr(region, "mutation_epoch", -1),
            )
            self._bytes += table.nbytes()
            self._shrink()

    def extend_hot_tail(self, region) -> bool:
        """Eager hot-tail append for freshly ACKED ingest rows: when this
        region already has a resident grid at the current base_version,
        scatter the pending append-log delta into its not-yet-covered
        tail right now (ingest-side), so the next query finds the grid
        current instead of paying the extend itself.  Opportunistic —
        the extender lock is taken non-blocking, so contending ingest
        workers skip instead of queueing; a False return means the
        query-time extend/rebuild path (get_grid) remains responsible.
        Small deltas are left to accumulate (one scatter dispatch per
        tiny batch would throttle ingest).

        Publication is a whole-entry swap, never field-wise mutation:
        concurrent readers (scheduler workers in get_grid) hold either
        the old entry or the new one, and both are internally consistent
        (table matches delta_pos) — a torn pair would silently serve a
        grid missing acked rows."""
        from greptimedb_tpu.storage.grid import extend_grid_table
        from greptimedb_tpu.utils.tracing import TRACER

        base_ver = getattr(region, "base_version", None)
        if base_ver is None:
            return False
        key = (region.region_id, "grid", base_ver)
        if not self._hot_tail_lock.acquire(blocking=False):
            return False
        try:
            entry = self._lru.get(key)
            if entry is None or entry.table is None:
                return False
            pos = _append_pos(region)
            if entry.delta_pos == pos:
                return False
            chunks = _chunks_since(region, entry.delta_pos)
            if chunks is None:
                return False  # trimmed past: query path rebuilds
            delta_rows = sum(len(c[TSID]) for c in chunks)
            if delta_rows < self.min_extend_rows:
                return False  # let small batches accumulate
            with TRACER.stage("ingest_grid_tail", region=region.region_id,
                              rows=delta_rows):
                extended = extend_grid_table(entry.table, region, chunks,
                                             mesh=self.mesh)
            if extended is None:
                return False  # off-grid delta: get_grid will evict/rebuild
            M_CACHE_EVENTS.labels("region_device", "grid", "hot_tail").inc()
            with self._struct_lock:
                self.extends += 1
                # not evicted/replaced meanwhile; delta_pos derives from
                # the chunks actually scattered, not the earlier pos read
                # (see get)
                if self._lru.get(key) is entry:
                    self._bytes += extended.nbytes() - entry.table.nbytes()
                    self._lru[key] = _Entry(
                        extended,
                        delta_pos=entry.delta_pos + len(chunks),
                        live_rows=entry.live_rows,
                        sst_ids=entry.sst_ids,
                        mutation_epoch=entry.mutation_epoch,
                    )
            self._shrink()
            return True
        finally:
            self._hot_tail_lock.release()

    def _shrink(self) -> None:
        with self._struct_lock:
            while self._bytes > self.capacity and len(self._lru) > 1:
                self._evict(next(iter(self._lru)))

    def _evict(self, key) -> None:
        with self._struct_lock:
            e = self._lru.pop(key, None)
            if e is not None and e.table is not None:
                self._bytes -= e.table.nbytes()
        if (self.derived_layouts is not None and key[1:2] == ("grid",)):
            # a grid leaving residency (capacity pressure, stale-version
            # sweep, failed extend) strands its derived layouts: the next
            # grid build bumps dicts_version, so they could never hit
            # again — drop them now instead of leaking device bytes
            self.derived_layouts.invalidate_region(key[0])
        if (self.promql_derived is not None
                and key[2:] == ((None, None), None)):
            # same stranding rule for the PromQL derived state: sort and
            # bounds layouts key on the full-table DeviceTable's
            # dicts_version, which the next build bumps — a full-table
            # entry leaving residency makes them permanently unhittable
            self.promql_derived.invalidate_region(key[0])

    def invalidate_region(self, region_id: int) -> None:
        with self._struct_lock:
            for k in [k for k in self._lru if k[0] == region_id]:
                self._evict(k)
        if self.derived_layouts is not None:
            self.derived_layouts.invalidate_region(region_id)
        if self.promql_derived is not None:
            self.promql_derived.invalidate_region(region_id)


@dataclass
class _LayoutEntry:
    version: int  # GridTable.dicts_version the layout was derived from
    arrays: tuple
    nbytes: int


class _ByteLRUCache:
    """Shared machinery for the derived resident caches (SQL bucket-major
    layouts, PromQL evaluation state): an LRU of version-tagged entries
    bounded by bytes, with reject-to-fallback admission through an
    optional WorkloadMemoryManager probe and region-scoped invalidation.
    Subclasses define the key shape and hit/miss bookkeeping; the
    eviction/admission/reclaim semantics exist exactly once here so the
    two caches cannot drift."""

    # registry label ("layout" / "promql"); subclasses override
    metric_cache = "derived"

    def __init__(self, capacity_bytes: int | None, env_var: str):
        import os

        if capacity_bytes is None:
            capacity_bytes = int(os.environ.get(env_var, str(1 << 30)))
        self.capacity = capacity_bytes
        # optional callable(nbytes) -> bool wired by the server to
        # WorkloadMemoryManager.try_admit(<workload>, ...)
        self.memory_probe = None
        self._lru: "collections.OrderedDict[tuple, _LayoutEntry]" = (
            collections.OrderedDict()
        )
        self._bytes = 0
        self.rejects = 0
        self.builds = 0
        self.evictions = 0
        _export_cache_gauges(self.metric_cache, self)

    def _kind_of(self, key: tuple) -> str:
        """Entry kind for registry labels (PromLayoutCache keys carry it)."""
        return "layout"

    @property
    def bytes(self) -> int:
        return self._bytes

    def __len__(self) -> int:
        return len(self._lru)

    def _lookup_entry(self, key: tuple, version):
        """Arrays for ``key`` at ``version``, or None.  A stale entry
        (older derivation version) is evicted immediately — the version
        bump IS the invalidation."""
        entry = self._lru.get(key)
        if entry is not None and entry.version == version:
            self._lru.move_to_end(key)
            return entry.arrays
        if entry is not None:
            self._evict(key)
        return None

    def admit(self, nbytes: int) -> bool:
        """Reject-to-fallback admission: evict LRU entries to make room,
        then consult the workload memory probe.  False means the caller
        serves from its uncached fallback path."""
        if nbytes > self.capacity:
            self.rejects += 1
            M_CACHE_EVENTS.labels(
                self.metric_cache, "any", "quota_reject").inc()
            return False
        while self._bytes + nbytes > self.capacity and self._lru:
            self._evict(next(iter(self._lru)))
        if self.memory_probe is not None and not self.memory_probe(nbytes):
            self.rejects += 1
            M_CACHE_EVENTS.labels(
                self.metric_cache, "any", "quota_reject").inc()
            return False
        return True

    def _store_entry(self, key: tuple, version, arrays, nbytes: int) -> None:
        if key in self._lru:
            self._evict(key)
        self._lru[key] = _LayoutEntry(version, arrays, nbytes)
        self._bytes += nbytes
        self.builds += 1
        M_CACHE_EVENTS.labels(
            self.metric_cache, self._kind_of(key), "build").inc()

    def reclaim(self, nbytes: int) -> None:
        """WorkloadMemoryManager reclaim hook: free at least ``nbytes``
        by LRU eviction (admission pressure from other workloads)."""
        freed = 0
        while freed < nbytes and self._lru:
            k = next(iter(self._lru))
            freed += self._lru[k].nbytes
            self._evict(k)

    def invalidate_region(self, region_id: int) -> None:
        for k in [k for k in self._lru if k[0] == region_id]:
            M_CACHE_EVENTS.labels(
                self.metric_cache, self._kind_of(k), "invalidation").inc()
            self._evict(k)

    def _evict(self, key) -> None:
        e = self._lru.pop(key, None)
        if e is not None:
            self._bytes -= e.nbytes
            self.evictions += 1
            M_CACHE_EVENTS.labels(
                self.metric_cache, self._kind_of(key), "eviction").inc()


class PromLayoutCache(_ByteLRUCache):
    """Resident derived state for the PromQL evaluation hot path — the
    PromQL twin of DerivedLayoutCache, holding four kinds of entries:

    - ``selection``: per (region, matcher set) the matched tsid vector and
      its padded device copy, so repeated evaluations skip the inverted-
      index walk AND the O(series) label-dict materialization (labels are
      decoded lazily, only for output groups);
    - ``sort``: per (region, field column) the composite (tsid, ts)-key
      sort of the resident table — key/ts/val/tsid/valid arrays presorted
      once on device, reused by every window kernel instead of re-sorting
      the full table inside each eval;
    - ``bounds``: per (selection, field column) the series row ranges and
      [S, L] timestamp matrix that turn few-step window boundaries into
      sequential compares instead of full-array binary searches;
    - ``group``: per (selection, by/without grouping) the device group-id
      vector + segment layout computed from the region's dictionary-
      encoded tag codes, replacing the per-eval Python loop over label
      dicts.

    Invalidation follows PR 1's generation discipline: every entry stores
    the version it was derived from (region ``generation`` for
    selection/group, resident-table ``dicts_version`` for sort — both bump
    on every ingest/flush/compaction) and a mismatch at lookup evicts and
    rebuilds.  Capacity is LRU by bytes; ``admit`` consults the optional
    WorkloadMemoryManager probe with reject-to-fallback — a rejected build
    is served uncached from the identical code path, so results are
    bit-exact either way.
    """

    KINDS = ("selection", "sort", "group", "bounds")
    metric_cache = "promql"

    def _kind_of(self, key: tuple) -> str:
        return key[1]

    def __init__(self, capacity_bytes: int | None = None, mesh=None):
        super().__init__(capacity_bytes, "GREPTIME_PROMQL_CACHE_BYTES")
        # series-axis mesh (parallel/dist.py promql_row_shardings): resident
        # sort layouts are placed sharded when a multi-device mesh exists
        self.mesh = mesh
        self.hits = dict.fromkeys(self.KINDS, 0)
        self.misses = dict.fromkeys(self.KINDS, 0)

    def lookup(self, kind: str, region_id: int, key: tuple, version):
        """Payload for (kind, region, key) at ``version``, or None (same
        contract as DerivedLayoutCache.lookup)."""
        payload = self._lookup_entry((region_id, kind, key), version)
        self.hits[kind] += payload is not None
        self.misses[kind] += payload is None
        M_CACHE_EVENTS.labels(
            "promql", kind, "hit" if payload is not None else "miss").inc()
        return payload

    def store(self, kind: str, region_id: int, key: tuple, version,
              payload, nbytes: int) -> None:
        self._store_entry((region_id, kind, key), version, payload, nbytes)

    def stats(self) -> dict:
        """Flat counters for the bench JSON line / status endpoints."""
        out = {"bytes": self._bytes, "entries": len(self._lru),
               "rejects": self.rejects, "builds": self.builds,
               "evictions": self.evictions}
        for kind in self.KINDS:
            out[f"{kind}_hits"] = self.hits[kind]
            out[f"{kind}_misses"] = self.misses[kind]
        return out

class DerivedLayoutCache(_ByteLRUCache):
    """Resident derived layouts for the aligned-window range-aggregation
    path: per (region, step class) the bucket-major reduction of the
    resident grid — the ``[S, nb, r]`` reshape contracted once on device
    into per-(series, bucket) partial sums ``[C, S, NB]`` and validity
    counts ``[S, NB]`` — reused across warm queries so the per-query
    aligned-window work drops to a bucket-axis slice plus the tiny
    series-axis merge (the "pay the transpose once" pattern of tensor-
    runtime query engines, arXiv:2203.01877).

    Invalidation is by GridTable.dicts_version (bumped on every grid
    build AND device-side append extension, which in turn follow the
    region's ingest/flush/compaction generation bumps): a version
    mismatch evicts the stale entry and rebuilds.  Capacity is LRU by
    bytes; ``admit`` additionally consults an optional
    WorkloadMemoryManager probe so the extra resident copy can never OOM
    the device — rejected builds fall back to the dynamic-slice kernel.
    """

    metric_cache = "layout"

    def __init__(self, capacity_bytes: int | None = None):
        super().__init__(capacity_bytes, "GREPTIME_LAYOUT_CACHE_BYTES")
        self.hits = 0
        self.misses = 0

    def lookup(self, region_id: int, step_class: tuple, version: int):
        """Arrays for (region, step class) at ``version``, or None."""
        arrays = self._lookup_entry((region_id, step_class), version)
        self.hits += arrays is not None
        self.misses += arrays is None
        M_CACHE_EVENTS.labels(
            "layout", "layout",
            "hit" if arrays is not None else "miss").inc()
        return arrays

    def store(self, region_id: int, step_class: tuple, version: int,
              arrays: tuple, nbytes: int) -> None:
        self._store_entry((region_id, step_class), version, arrays, nbytes)
