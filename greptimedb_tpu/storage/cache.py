"""Device-resident region cache: host columns → HBM tensors, reused across
queries.

The TPU answer to the reference's tiered read cache
(src/mito2/src/cache/: page/vector caches keep decoded batches hot in RAM;
here the hot tier is HBM). A region's merged scan result is canonicalized
once — tags to int32 codes, ts to int64, fields to f32, rows padded to a
shape-class bucket — and uploaded; queries then jit straight over the
cached tensors. Invalidation is by region generation (bumped on every
write/flush/compact).

Capacity: simple LRU by bytes; eviction drops device references and lets
JAX free HBM.
"""

from __future__ import annotations

import collections
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from greptimedb_tpu.datatypes.batch import pad_rows
from greptimedb_tpu.datatypes.schema import Schema
from greptimedb_tpu.storage.memtable import SEQ, TSID
from greptimedb_tpu.storage.region import Region


@jax.tree_util.register_pytree_node_class
@dataclass
class DeviceTable:
    """A region's (or shard's) query-ready resident tensors.

    columns: ts (int64), fields (f32/ints), per-tag code columns (int32),
    plus __tsid__ (int32). Sorted by (tsid, ts) — segment ops get
    indices_are_sorted on the series axis for free.
    """

    columns: dict[str, jnp.ndarray]
    row_mask: jnp.ndarray
    num_series: int
    dicts: dict[str, list] = field(default_factory=dict)
    # tag columns whose codes are nondecreasing in row order — unlocks the
    # scatter-free sorted segment reduction in the query executor
    sorted_tags: tuple = ()

    @property
    def padded_rows(self) -> int:
        return int(self.row_mask.shape[0])

    def nbytes(self) -> int:
        total = self.row_mask.nbytes
        for v in self.columns.values():
            total += v.nbytes
        return total

    def tree_flatten(self):
        names = sorted(self.columns)
        children = tuple(self.columns[n] for n in names) + (self.row_mask,)
        aux = (
            tuple(names),
            self.num_series,
            tuple((k, tuple(v)) for k, v in sorted(self.dicts.items())),
            tuple(self.sorted_tags),
        )
        return children, aux

    @classmethod
    def tree_unflatten(cls, aux, children):
        names, num_series, dict_items, sorted_tags = aux
        cols = dict(zip(names, children[:-1]))
        return cls(cols, children[-1], num_series,
                   {k: list(v) for k, v in dict_items}, sorted_tags)


def build_device_table(
    region: Region,
    ts_range: tuple[int | None, int | None] = (None, None),
    columns: list[str] | None = None,
) -> DeviceTable:
    """Scan, canonicalize and upload one region's data."""
    host = region.scan_host(ts_range, columns)
    schema = region.schema
    n = len(host[TSID])
    padded = pad_rows(n)

    dev_cols: dict[str, jnp.ndarray] = {}
    dicts: dict[str, list] = {}
    for name, arr in host.items():
        if name == SEQ:
            continue  # sequences are a storage concern; queries never see them
        if name == TSID:
            out = np.zeros(padded, dtype=np.int32)
            out[:n] = arr.astype(np.int32)
            dev_cols[TSID] = jnp.asarray(out)
            continue
        if schema.has_column(name):
            c = schema.column(name)
            if c.is_tag:
                enc = region.encoders[name]
                uniq, inv = np.unique(arr.astype(object), return_inverse=True)
                codes = np.fromiter(
                    (enc.get(v) for v in uniq), dtype=np.int32, count=len(uniq)
                )
                out = np.full(padded, -1, dtype=np.int32)
                out[:n] = codes[inv]
                dev_cols[name] = jnp.asarray(out)
                dicts[name] = enc.values()
                continue
            if c.dtype.is_string_like:
                # string FIELD (log lines, json): ad-hoc dictionary per
                # build — codes live on device, values in dicts for decode
                from greptimedb_tpu.datatypes.batch import DictionaryEncoder

                enc = DictionaryEncoder()
                # NULL string fields become "" (np.unique cannot order None)
                arr = np.array(
                    ["" if v is None else v for v in arr], dtype=object
                )
                uniq, inv = np.unique(arr, return_inverse=True)
                codes = np.fromiter(
                    (enc.get_or_insert(v) for v in uniq), dtype=np.int32,
                    count=len(uniq),
                )
                out = np.full(padded, -1, dtype=np.int32)
                out[:n] = codes[inv]
                dev_cols[name] = jnp.asarray(out)
                dicts[name] = enc.values()
                continue
            dev_dtype = c.dtype.to_device_dtype()
            pad_val = np.nan if np.issubdtype(dev_dtype, np.floating) else 0
            out = np.full(padded, pad_val, dtype=dev_dtype)
            out[:n] = arr.astype(dev_dtype)
            dev_cols[name] = jnp.asarray(out)
        else:
            # internal numeric column (e.g. __op__)
            out = np.zeros(padded, dtype=arr.dtype)
            out[:n] = arr
            dev_cols[name] = jnp.asarray(out)
    mask = np.zeros(padded, dtype=bool)
    mask[:n] = True
    # monotone tag detection: rows are (tsid, ts)-sorted; a tag qualifies
    # for sorted segment reductions when its codes are nondecreasing AND
    # bijective with series runs (each code run is exactly one tsid run, so
    # ts — and hence any time bucket — is ascending within every code run)
    sorted_tags = []
    if n > 0:
        tsid_runs = 1 + int((np.diff(np.asarray(dev_cols[TSID])[:n]) != 0).sum())
        for c in schema.tag_columns:
            if c.name in dev_cols:
                codes = np.asarray(dev_cols[c.name])[:n]
                d = np.diff(codes)
                if bool((d >= 0).all()) and 1 + int((d != 0).sum()) == tsid_runs:
                    sorted_tags.append(c.name)
    return DeviceTable(dev_cols, jnp.asarray(mask), region.num_series, dicts,
                       tuple(sorted_tags))


class RegionCacheManager:
    """LRU of DeviceTables keyed by (region_id, generation, range, cols)."""

    def __init__(self, capacity_bytes: int = 8 << 30):
        self.capacity = capacity_bytes
        self._lru: "collections.OrderedDict[tuple, DeviceTable]" = (
            collections.OrderedDict()
        )
        self._bytes = 0
        self.hits = 0
        self.misses = 0

    def get(
        self,
        region: Region,
        ts_range: tuple[int | None, int | None] = (None, None),
        columns: list[str] | None = None,
    ) -> DeviceTable:
        key = (
            region.region_id,
            region.generation,
            ts_range,
            tuple(columns) if columns else None,
        )
        hit = self._lru.get(key)
        if hit is not None:
            self.hits += 1
            self._lru.move_to_end(key)
            return hit
        self.misses += 1
        table = build_device_table(region, ts_range, columns)
        # drop stale generations of the same region+range
        stale = [k for k in self._lru if k[0] == key[0] and k[1] != key[1]]
        for k in stale:
            self._evict(k)
        self._lru[key] = table
        self._bytes += table.nbytes()
        while self._bytes > self.capacity and len(self._lru) > 1:
            self._evict(next(iter(self._lru)))
        return table

    def _evict(self, key) -> None:
        t = self._lru.pop(key, None)
        if t is not None:
            self._bytes -= t.nbytes()

    def invalidate_region(self, region_id: int) -> None:
        for k in [k for k in self._lru if k[0] == region_id]:
            self._evict(k)
