"""Crash-consistent storage: corruption detection, quarantine, repair.

Reference analogs: raft-engine's recovery modes (TolerateTailCorruption
vs AbsoluteConsistency — a torn tail is expected crash debris, interior
corruption is data loss that must be surfaced), mito2's checksummed
manifest (src/mito2/src/manifest/) and Taurus-style repair-from-replica
(arXiv 2506.20010: log/page durability with explicit corruption
detection + repair is what makes a disaggregated store production
grade).  The shared contract for every store (WAL, manifest, SST):

- **detected**: every byte rehydrated from disk is verified (CRC'd
  manifest files, header+payload-checksummed WAL records, Parquet page
  checksums);
- **quarantined**: damaged bytes are moved aside (``.quarantine``
  sidecars / ``manifest/quarantine/``), never deleted — an operator or
  a later tool can still inspect them;
- **surfaced**: ``greptime_durability_corruption_total{store,kind}``
  counts every detection, quarantines and repairs have their own
  counters;
- **repaired or refused**: a covered loss is resynced (remote WAL,
  follower replica over the Flight object plane, WAL re-flush); an
  uncovered loss fails OPEN loudly — corruption is never silently
  served and acked writes are never silently dropped.
"""

from __future__ import annotations

from greptimedb_tpu.errors import StorageError
from greptimedb_tpu.utils.telemetry import REGISTRY

M_CORRUPTION = REGISTRY.counter(
    "greptime_durability_corruption_total",
    "Corruptions detected while reading local durability stores",
    labels=("store", "kind"),
)
M_QUARANTINED = REGISTRY.counter(
    "greptime_durability_quarantined_total",
    "Damaged files/spans moved aside (never deleted) after detection",
    labels=("store",),
)
M_REPAIRED = REGISTRY.counter(
    "greptime_durability_repaired_total",
    "Corruptions repaired, by store and repair source",
    labels=("store", "source"),
)
# Epoch fencing on shared object storage (ISSUE 15): claims are the
# leadership handoffs minted by Metasrv; rejections are fenced-out
# leaders stopped BEFORE they could fork history.
M_FENCE_CLAIMS = REGISTRY.counter(
    "greptime_fence_claims_total",
    "Leader-epoch fence claims on shared storage",
    labels=("outcome",),
)
M_FENCE_REJECTED = REGISTRY.counter(
    "greptime_fence_rejected_total",
    "Writes refused by epoch fencing, by write surface",
    labels=("surface",),
)


class CorruptionError(StorageError):
    """Verified-read failure: on-disk bytes do not match their checksums."""


class SstCorruption(CorruptionError):
    """A Parquet SST failed page-checksum/decode verification on read."""

    def __init__(self, meta, cause: Exception):
        super().__init__(
            f"corrupt SST {meta.path} ({meta.num_rows} rows, "
            f"seq [{meta.seq_min},{meta.seq_max}]): {cause}")
        self.meta = meta
        self.cause = cause


class ManifestCorruption(CorruptionError):
    """Manifest open found corrupt/missing files past a good prefix.

    Carries the best recoverable prefix (``manifest``) plus the suspect
    file list; the region open path decides between recovery (WAL covers
    the lost actions) and region quarantine (it does not).
    """

    def __init__(self, manifest, bad_files: list[str], detail: str,
                 tail_only: bool = False):
        super().__init__(
            f"manifest corruption in {manifest.dir}: {detail} "
            f"(good prefix at version {manifest.version}, "
            f"suspect files: {bad_files})")
        self.manifest = manifest
        self.bad_files = bad_files
        self.detail = detail
        # True = the damage sits at the TAIL of the delta chain (the
        # crash-debris shape: the lost action was the unacked one being
        # written).  Only this shape is eligible for WAL-covered
        # recovery — mid-chain rot may have destroyed schema/dicts
        # actions that replay cannot re-derive, so it must quarantine.
        self.tail_only = tail_only


class RegionQuarantined(StorageError):
    """The region's manifest is quarantined: open refuses until an
    operator clears the marker (corruption must never be served)."""


class WalHole(StorageError):
    """Interior WAL corruption lost an acked sequence range and no
    resync source covered it — surfaced instead of silently dropping."""

    def __init__(self, region_id: int, ranges: list[tuple[int, int]]):
        super().__init__(
            f"region {region_id}: WAL interior corruption lost acked "
            f"sequence range(s) {ranges} and no resync source covers "
            "them; damaged bytes preserved in .quarantine sidecars")
        self.ranges = ranges


def quarantine_object(store, path: str) -> str:
    """Move ``path`` aside to ``path + '.quarantine'`` (bytes preserved,
    original name freed for a repaired copy).  Returns the new path."""
    qpath = path + ".quarantine"
    store.rename(path, qpath)
    return qpath


# ---- resync / repair source plumbing ---------------------------------------


def resync_from_log_store(log):
    """WAL resync callable from any LogStore (a follower's local WAL, a
    SharedLogBroker topic via RemoteLogStore): returns
    ``fetch(from_seq, to_seq) -> list[(seq, payload)]`` over the
    inclusive range, replaying read-only (never repairs a store it does
    not own)."""

    def fetch(from_seq: int, to_seq: int):
        out = []
        for seq, payload in log.replay(from_seq, repair=False):
            if from_seq <= seq <= to_seq:
                out.append((seq, payload))
        return out

    return fetch


def resync_from_peer_wal(client, region_id: int):
    """WAL resync over the PR 6 Flight object plane: fetch the peer
    replica's WAL segment objects (visible under its data home as
    ``region_<id>/wal/*.wal``) and scan them locally for the missing
    range.  ``client`` needs ``list_region_objects``/``fetch_object``
    (DatanodeClient or an in-process Datanode)."""
    import os
    import tempfile

    from greptimedb_tpu.storage.wal import FileLogStore

    def fetch(from_seq: int, to_seq: int):
        with tempfile.TemporaryDirectory() as tmp:
            names = [p for p in client.list_region_objects(region_id)
                     if "/wal/" in p and p.endswith(".wal")]
            if not names:
                return []
            for p in names:
                data = client.fetch_object(p)
                # gl: allow[GL-D001] -- scratch copy of a PEER's WAL in a TemporaryDirectory, read-only-scanned then deleted; no durability surface
                with open(os.path.join(tmp, p.rsplit("/", 1)[-1]),
                          "wb") as f:
                    f.write(data)
            # read-only scan of OUR copies: repair here never touches
            # the peer, and a torn tail in the copy just ends the scan
            log = FileLogStore(tmp)
            try:
                return resync_from_log_store(log)(from_seq, to_seq)
            finally:
                log.close()

    return fetch


def repair_sst_from_peer(client):
    """SST repair source over the Flight object plane: returns
    ``fetch(path) -> bytes | None`` pulling the replica's copy of the
    object; None when the peer does not have it."""

    def fetch(path: str):
        try:
            data = client.fetch_object(path)
        except Exception:  # noqa: BLE001 — a missing/unreachable peer
            return None    # is "not covered", not a new failure mode
        return data or None

    return fetch
