"""CLI for greptime-lint: ``python -m greptimedb_tpu.analysis``."""

from __future__ import annotations

import argparse
import json
import sys


def main(argv: list[str] | None = None) -> int:
    from greptimedb_tpu.analysis import core

    ap = argparse.ArgumentParser(
        prog="python -m greptimedb_tpu.analysis",
        description="greptime-lint: concurrency/hot-path/durability/"
                    "telemetry static analysis over greptimedb_tpu/")
    ap.add_argument("--pass", dest="passes", action="append",
                    help="run only this pass (repeatable); default all")
    ap.add_argument("--list-passes", action="store_true",
                    help="list registered passes and finding codes")
    ap.add_argument("--baseline", action="store_true",
                    help="write the current findings to baseline.json "
                         "(preserving existing justifications; new "
                         "entries get a TODO reason the gate rejects)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignoring baseline.json")
    ap.add_argument("--show-suppressed", action="store_true",
                    help="also list inline-allowed and baselined findings")
    ap.add_argument("--json", action="store_true", help="JSON output")
    ap.add_argument("--write-config", action="store_true",
                    help="regenerate CONFIG.md from the knob inventory")
    args = ap.parse_args(argv)

    if args.list_passes:
        for p in core.all_passes():
            print(f"{p.name}: {p.title}")
            for code, desc in sorted(p.codes.items()):
                print(f"  {code}  {desc}")
        return 0

    if args.write_config:
        from greptimedb_tpu.analysis.passes.hygiene import render_config_md
        import os

        path = os.path.join(os.path.dirname(core.package_root()),
                            "CONFIG.md")
        with open(path, "w", encoding="utf-8") as f:
            f.write(render_config_md())
        print(f"wrote {path}")
        return 0

    active, inline = core.run_passes(names=args.passes)
    if args.baseline:
        path = core.write_baseline(active)
        print(f"wrote {len(active)} entries to {path}")
        return 0

    if args.no_baseline:
        new, matched, stale = active, [], []
    else:
        new, matched, stale = core.apply_baseline(
            active, core.load_baseline())

    if args.json:
        print(json.dumps({
            "new": [vars(f) for f in new],
            "baselined": [vars(f) for f in matched],
            "inline_suppressed": [vars(f) for f in inline],
            "stale_baseline": stale,
        }, indent=1))
    else:
        for f in new:
            print(f.render())
        if args.show_suppressed:
            for f in matched:
                print(f"[baselined] {f.render()}  -- {f.reason}")
            for f in inline:
                print(f"[allowed]   {f.render()}  -- {f.reason}")
        for e in stale:
            print(f"[stale baseline entry] {e['code']} {e['file']} "
                  f"[{e['scope']}] {e['key']}")
        print(f"{len(new)} finding(s), {len(matched)} baselined, "
              f"{len(inline)} inline-allowed, {len(stale)} stale "
              "baseline entr(ies)")
    return 1 if new or stale else 0


if __name__ == "__main__":
    sys.exit(main())
