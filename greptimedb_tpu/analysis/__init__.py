"""greptime-lint: AST-based static analysis over greptimedb_tpu.

Five pass families (see passes/), a checked-in justified-suppression
baseline (baseline.json), a runtime lock-order witness (witness.py),
and a CLI::

    python -m greptimedb_tpu.analysis            # run, report, exit 1
    python -m greptimedb_tpu.analysis --baseline # re-snapshot baseline
    python -m greptimedb_tpu.analysis --write-config  # regenerate CONFIG.md

The tier-1 gate (tests/test_analysis.py) runs every pass over the whole
package and fails on any non-baselined finding.
"""

from greptimedb_tpu.analysis.core import (  # noqa: F401
    AnalysisContext, Finding, Pass, all_passes, analyze_source,
    apply_baseline, check_package, load_baseline, load_package, run_passes,
    write_baseline,
)
