"""greptime-lint core: pass registry, findings, baseline, suppressions.

The reference enforces its concurrency/hot-path/durability invariants
mechanically — ``[workspace.lints]`` + clippy deny-lists run over every
crate on every build (Cargo.toml workspace.lints, plus custom disallowed-
methods entries for blocking calls in async context).  This package is
that discipline for the Python reproduction: AST-based passes over
``greptimedb_tpu/`` with per-finding codes, a checked-in baseline of
*justified* suppressions, and a tier-1 gate (tests/test_analysis.py)
that fails on any non-baselined finding.

Mechanics shared by every pass live here:

- **SourceModule / AnalysisContext** — each ``.py`` file parsed once
  (source, AST, per-line suppression / marker comments), shared across
  passes.
- **Inline suppressions** — ``# gl: allow[CODE] -- reason`` on the
  offending line (or the line above) suppresses that code there; a
  reason is REQUIRED or the allow is ignored.  These are the in-code
  twin of clippy's ``#[allow(...)]`` with the justification attached.
- **Markers** — ``# gl: holds[lockattr]`` declares that a function runs
  with a lock already held (callers acquire it — e.g. ``_write_locked``
  helpers); ``# gl: warm-path`` / ``# gl: warm-path(host)`` mark a
  function as a warm path for the device-sync pass.
- **Baseline** — ``analysis/baseline.json``: a list of findings matched
  by (code, file, scope, key) — never by line number, so unrelated
  edits don't churn it — each carrying a mandatory justification.
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field

# ---------------------------------------------------------------------------
# Findings
# ---------------------------------------------------------------------------


@dataclass
class Finding:
    code: str  # e.g. "GL-L002"
    file: str  # path relative to the package root, posix separators
    line: int
    scope: str  # enclosing qualname ("RegionCacheManager.get") or "<module>"
    key: str  # stable identity detail for baseline matching (not the line)
    message: str
    reason: str = ""  # justification, populated when suppressed

    @property
    def identity(self) -> tuple:
        return (self.code, self.file, self.scope, self.key)

    def render(self) -> str:
        return (f"{self.file}:{self.line}: {self.code} [{self.scope}] "
                f"{self.message}")


# ---------------------------------------------------------------------------
# Source loading
# ---------------------------------------------------------------------------

_ALLOW_RE = re.compile(
    r"#\s*gl:\s*allow\[([A-Za-z0-9_,\- ]+)\]\s*(?:--\s*)?(.*)")
_HOLDS_RE = re.compile(r"#\s*gl:\s*holds\[([A-Za-z0-9_,\. ]+)\]")
_WARM_RE = re.compile(r"#\s*gl:\s*warm-path(\((host)\))?")


@dataclass
class SourceModule:
    relpath: str  # posix, relative to package root (e.g. "storage/cache.py")
    source: str
    tree: ast.Module
    lines: list[str] = field(default_factory=list)
    # line -> (set of codes, reason); allows without a reason are dropped
    allows: dict[int, tuple[set[str], str]] = field(default_factory=dict)
    holds: dict[int, set[str]] = field(default_factory=dict)
    warm: dict[int, str] = field(default_factory=dict)  # line -> "full"|"host"

    @classmethod
    def from_source(cls, source: str, relpath: str) -> "SourceModule":
        tree = ast.parse(source)
        mod = cls(relpath=relpath, source=source, tree=tree,
                  lines=source.splitlines())
        for i, line in enumerate(mod.lines, 1):
            if "# gl:" not in line and "#gl:" not in line:
                continue
            m = _ALLOW_RE.search(line)
            if m and m.group(2).strip():
                codes = {c.strip() for c in m.group(1).split(",") if c.strip()}
                mod.allows[i] = (codes, m.group(2).strip())
            m = _HOLDS_RE.search(line)
            if m:
                mod.holds.setdefault(i, set()).update(
                    a.strip() for a in m.group(1).split(",") if a.strip())
            m = _WARM_RE.search(line)
            if m:
                mod.warm[i] = "host" if m.group(2) else "full"
        return mod

    def allow_reason(self, finding: Finding) -> str | None:
        """Reason string when an inline allow covers ``finding`` (on its
        line or the line directly above), else None."""
        for ln in (finding.line, finding.line - 1):
            entry = self.allows.get(ln)
            if entry is not None and finding.code in entry[0]:
                return entry[1]
        return None

    def marker_lines(self, node: ast.AST) -> range:
        """Lines on which a def-scoped marker (holds/warm-path) counts for
        ``node``: the def line through the first body statement's start —
        covers decorators-free defs with the marker on the signature or a
        leading comment line inside the body."""
        first = getattr(node, "body", [None])[0]
        end = first.lineno if first is not None else node.lineno + 1
        return range(node.lineno, end + 1)

    def holds_for(self, func: ast.AST) -> set[str]:
        out: set[str] = set()
        for ln in self.marker_lines(func):
            out |= self.holds.get(ln, set())
        return out

    def warm_for(self, func: ast.AST) -> str | None:
        for ln in self.marker_lines(func):
            if ln in self.warm:
                return self.warm[ln]
        return None


class AnalysisContext:
    def __init__(self, modules: list[SourceModule]):
        self.modules = modules
        self._by_path = {m.relpath: m for m in modules}

    def module(self, relpath: str) -> SourceModule | None:
        return self._by_path.get(relpath)


def package_root() -> str:
    """Directory of the greptimedb_tpu package itself."""
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def load_package(root: str | None = None) -> AnalysisContext:
    root = root or package_root()
    modules = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(
            d for d in dirnames if d not in ("__pycache__",))
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            with open(path, encoding="utf-8") as f:
                src = f.read()
            modules.append(SourceModule.from_source(src, rel))
    return AnalysisContext(modules)


# ---------------------------------------------------------------------------
# AST helpers shared by passes
# ---------------------------------------------------------------------------


def qualname_map(tree: ast.Module) -> dict[ast.AST, str]:
    """Map every function/class node to its dotted qualname."""
    out: dict[ast.AST, str] = {}

    def walk(node: ast.AST, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                q = f"{prefix}.{child.name}" if prefix else child.name
                out[child] = q
                walk(child, q)
            else:
                walk(child, prefix)

    walk(tree, "")
    return out


def attr_chain(node: ast.AST) -> str | None:
    """Dotted name for Name/Attribute chains: ``self._lru`` ->
    "self._lru", ``os.path.join`` -> "os.path.join"; None for anything
    with a non-name base (calls, subscripts)."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


# ---------------------------------------------------------------------------
# Pass registry
# ---------------------------------------------------------------------------


class Pass:
    name: str = ""
    title: str = ""
    codes: dict[str, str] = {}

    def run(self, ctx: AnalysisContext) -> list[Finding]:
        raise NotImplementedError


PASS_REGISTRY: dict[str, Pass] = {}


def register(cls):
    PASS_REGISTRY[cls.name] = cls()
    return cls


def all_passes() -> list[Pass]:
    # importing the passes package populates the registry
    from greptimedb_tpu.analysis import passes  # noqa: F401

    return [PASS_REGISTRY[k] for k in sorted(PASS_REGISTRY)]


def run_passes(
    ctx: AnalysisContext | None = None,
    names: list[str] | None = None,
) -> tuple[list[Finding], list[Finding]]:
    """Run passes over ``ctx`` (default: the whole package).  Returns
    (active, inline_suppressed); baseline filtering is separate
    (apply_baseline) so the CLI can show either view."""
    ctx = ctx or load_package()
    passes = all_passes()
    if names is not None:
        passes = [p for p in passes if p.name in names]
    active: list[Finding] = []
    suppressed: list[Finding] = []
    for p in passes:
        for f in p.run(ctx):
            mod = ctx.module(f.file)
            reason = mod.allow_reason(f) if mod is not None else None
            if reason is not None:
                f.reason = reason
                suppressed.append(f)
            else:
                active.append(f)
    active.sort(key=lambda f: (f.file, f.line, f.code))
    suppressed.sort(key=lambda f: (f.file, f.line, f.code))
    return active, suppressed


# ---------------------------------------------------------------------------
# Baseline
# ---------------------------------------------------------------------------

BASELINE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "baseline.json")


def load_baseline(path: str | None = None) -> list[dict]:
    path = path or BASELINE_PATH
    if not os.path.exists(path):
        return []
    with open(path, encoding="utf-8") as f:
        return json.load(f)


def apply_baseline(
    findings: list[Finding], baseline: list[dict],
) -> tuple[list[Finding], list[Finding], list[dict]]:
    """Split ``findings`` against the baseline.  Returns (new, matched,
    stale_entries) where matched findings carry the entry's justification
    and stale entries matched nothing (they must be pruned — a baseline
    can only shrink honestly)."""
    from collections import Counter

    pool = Counter(
        (e["code"], e["file"], e["scope"], e["key"]) for e in baseline)
    reasons = {(e["code"], e["file"], e["scope"], e["key"]): e.get(
        "reason", "") for e in baseline}
    new: list[Finding] = []
    matched: list[Finding] = []
    for f in findings:
        if pool.get(f.identity, 0) > 0:
            pool[f.identity] -= 1
            f.reason = reasons.get(f.identity, "")
            matched.append(f)
        else:
            new.append(f)
    stale = []
    for e in baseline:
        ident = (e["code"], e["file"], e["scope"], e["key"])
        if pool.get(ident, 0) > 0:
            pool[ident] -= 1
            stale.append(e)
    return new, matched, stale


def baseline_entries(findings: list[Finding],
                     old: list[dict] | None = None) -> list[dict]:
    """Serialize findings as baseline entries, preserving justifications
    from ``old`` for identities that persist; new entries get a TODO
    reason the tier-1 gate rejects until a human justifies them."""
    old_reasons: dict[tuple, list[str]] = {}
    for e in old or []:
        ident = (e["code"], e["file"], e["scope"], e["key"])
        old_reasons.setdefault(ident, []).append(e.get("reason", ""))
    out = []
    for f in findings:
        reasons = old_reasons.get(f.identity)
        reason = reasons.pop(0) if reasons else "TODO: justify or fix"
        out.append({
            "code": f.code, "file": f.file, "scope": f.scope, "key": f.key,
            "line": f.line,  # informational only — matching ignores it
            "message": f.message, "reason": reason,
        })
    return out


def write_baseline(findings: list[Finding], path: str | None = None) -> str:
    path = path or BASELINE_PATH
    entries = baseline_entries(findings, load_baseline(path))
    with open(path, "w", encoding="utf-8") as f:
        json.dump(entries, f, indent=1)
        f.write("\n")
    return path


# ---------------------------------------------------------------------------
# Test / CLI convenience
# ---------------------------------------------------------------------------


def analyze_source(source: str, relpath: str,
                   names: list[str] | None = None) -> list[Finding]:
    """Run passes over one in-memory module (fixture snippets in
    tests/test_analysis.py).  Inline allows apply; no baseline."""
    ctx = AnalysisContext([SourceModule.from_source(source, relpath)])
    active, _ = run_passes(ctx, names)
    return active


def check_package(names: list[str] | None = None):
    """The tier-1 entry: (new, matched, stale, inline_suppressed) over
    the live package against the checked-in baseline.  A subset run
    (``names``) only consults baseline entries owned by those passes —
    other passes' entries are not "stale" just because they didn't run."""
    active, inline = run_passes(load_package(), names)
    baseline = load_baseline()
    if names is not None:
        codes = {c for p in all_passes() if p.name in names
                 for c in p.codes}
        baseline = [e for e in baseline if e["code"] in codes]
    new, matched, stale = apply_baseline(active, baseline)
    return new, matched, stale, inline
