"""greptime-lint passes.  Importing this package registers every pass
with the core registry (core.all_passes)."""

from greptimedb_tpu.analysis.passes import (  # noqa: F401
    durability,
    hotpath,
    hygiene,
    lock_discipline,
    lock_order,
)
