"""Hot-path device-sync pass: no host syncs or per-row loops in warm code.

The TCR paper (arXiv 2203.01877) finding this pass mechanizes: host-side
work sneaking into tensor-runtime hot paths is the dominant silent
regression.  Our warm-path zero-sync guarantees were previously
protected only by point tests (interleaved A/B medians, tracemalloc
pins); this pass protects the CODE.

Functions opt in with a marker on the ``def`` line (or the line below):

- ``# gl: warm-path`` — device-warm code (kernels, resident-layout
  extension): both checks apply.
- ``# gl: warm-path(host)`` — host-side vectorized code (wire parsers):
  only the per-row loop check applies (``np.asarray`` on host arrays is
  free there).

Codes:

- **GL-H001** — implicit host sync in a device-warm function:
  ``np.asarray``/``np.array``/``jax.device_get`` on a value,
  ``.item()``/``.tolist()``/``.block_until_ready()``, or
  ``float()/int()/bool()`` of a non-literal.  Each one is a device
  round-trip serialized into the warm path.
- **GL-H002** — a per-row Python loop in any warm function: ``for``
  over ``range(len(...))``/``range(n)``, ``zip(...)`` of arrays, or
  ``enumerate(...)``.  O(rows) python-object work is the exact failure
  mode the vectorized ingest/scan pipelines exist to avoid (their
  ``*_object_decode_rows_total`` metrics pin it at 0 dynamically; this
  pins it statically).  Loops over columns/specs (``for k, v in
  d.items()``, ``for spec in specs``) do not match.

Markers also flow into nested functions: a closure defined inside a
warm function is warm (jitted kernel bodies are closures).
"""

from __future__ import annotations

import ast

from greptimedb_tpu.analysis.core import (
    AnalysisContext, Finding, Pass, attr_chain, qualname_map, register,
)

SYNC_CALL_CHAINS = {"np.asarray", "np.array", "numpy.asarray",
                    "numpy.array", "jax.device_get"}
SYNC_METHOD_TAILS = {"item", "tolist", "block_until_ready"}
CAST_BUILTINS = {"float", "int", "bool"}

ROWY_NAMES = {"n", "nrows", "num_rows", "rows", "n_rows"}


def _is_rowy_loop(node: ast.For) -> bool:
    """Per-ROW loop shapes only: ``range(len(x))`` / ``range(n)`` and
    ``zip(cols[a], cols[b], ...)`` over subscripted columns.  O(columns)
    iteration (``for k, v in d.items()``, ``enumerate(fields)``, ``for
    spec in specs``) is the vectorized code's legitimate shape and does
    not match."""
    it = node.iter
    if isinstance(it, ast.Call):
        chain = attr_chain(it.func)
        if chain == "range":
            if it.args and isinstance(it.args[-1], ast.Call) and attr_chain(
                    it.args[-1].func) == "len":
                return True
            if it.args and isinstance(it.args[-1], ast.Name) and (
                    it.args[-1].id in ROWY_NAMES):
                return True
            return False
        if chain == "zip" and len(it.args) >= 2 and any(
                isinstance(a, ast.Subscript) for a in it.args):
            return True
    return False


class _WarmWalker:
    def __init__(self, pass_, mod, scope: str, mode: str,
                 in_closure: bool = False):
        self.p = pass_
        self.mod = mod
        self.scope = scope
        self.mode = mode  # "full" | "host"
        # inside a nested def (a traced kernel closure): host CASTS of
        # runtime values (float/int/bool) are also flagged there — in the
        # outer function's epilogue they are ordinary host math
        self.in_closure = in_closure
        self.ordinals: dict[tuple, int] = {}

    def _emit(self, code: str, node: ast.AST, key_base: tuple, msg: str):
        n = self.ordinals.get(key_base, 0)
        self.ordinals[key_base] = n + 1
        key = ":".join(str(x) for x in key_base) + (f":{n}" if n else "")
        self.p.findings.append(Finding(
            code=code, file=self.mod.relpath, line=node.lineno,
            scope=self.scope, key=key, message=msg))

    def walk(self, node: ast.AST):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                sub_mode = self.mod.warm_for(child) or self.mode
                sub = _WarmWalker(self.p, self.mod,
                                  f"{self.scope}.{child.name}", sub_mode,
                                  in_closure=True)
                sub.walk(child)
                continue
            if isinstance(child, ast.For) and _is_rowy_loop(child):
                self._emit("GL-H002", child, ("rowloop",),
                           "per-row Python loop in warm path "
                           f"(iterating {ast.unparse(child.iter)[:60]!r})")
            if isinstance(child, ast.Call) and self.mode == "full":
                self._check_call(child)
            self.walk(child)

    def _check_call(self, node: ast.Call):
        chain = attr_chain(node.func)
        if chain in SYNC_CALL_CHAINS:
            self._emit("GL-H001", node, ("sync", chain),
                       f"host sync {chain!r} in warm path")
            return
        tail = chain.rsplit(".", 1)[-1] if chain else None
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr in SYNC_METHOD_TAILS):
            self._emit("GL-H001", node, ("sync", node.func.attr),
                       f"host sync .{node.func.attr}() in warm path")
            return
        if (self.in_closure and tail in CAST_BUILTINS and chain == tail
                and len(node.args) == 1
                and not isinstance(node.args[0], ast.Constant)):
            self._emit("GL-H001", node, ("cast", tail),
                       f"{tail}() of a runtime value inside a kernel "
                       "closure (device scalar pull)")


@register
class HotPathPass(Pass):
    name = "hotpath"
    title = "no host syncs / per-row loops in warm paths"
    codes = {
        "GL-H001": "implicit host sync in a device-warm function",
        "GL-H002": "per-row Python loop in a warm function",
    }

    def run(self, ctx: AnalysisContext) -> list[Finding]:
        self.findings: list[Finding] = []
        for mod in ctx.modules:
            if not mod.warm:
                continue
            qnames = qualname_map(mod.tree)
            marked = []
            for node, qual in qnames.items():
                if not isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                mode = mod.warm_for(node)
                if mode is not None:
                    marked.append((node, qual, mode))
            # drop marked functions nested inside other marked functions
            # (the outer walk covers them)
            outer = []
            spans = [(n.lineno, max(getattr(n, "end_lineno", n.lineno),
                                    n.lineno)) for n, _, _ in marked]
            for i, (node, qual, mode) in enumerate(marked):
                if any(j != i and spans[j][0] < node.lineno
                       and spans[j][1] >= spans[i][1]
                       for j in range(len(marked))):
                    continue
                outer.append((node, qual, mode))
            for node, qual, mode in outer:
                _WarmWalker(self, mod, qual, mode).walk(node)
        return self.findings
