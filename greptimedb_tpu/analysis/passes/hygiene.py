"""Telemetry/knob hygiene pass: metric names + env-knob inventory.

Two invariant families, both previously enforced ad hoc:

**Metrics** (the tests/test_telemetry.py registry static check, now
delegating here).  Every ``greptime_*`` metric name registered in code
must be literal-analyzable, convention-clean and collision-free:

- **GL-T001** — one metric name registered at two sites with a
  different kind or label set (the runtime Registry records these in
  ``collisions``; this catches them before any import runs).
- **GL-T002** — a literal metric or label name violating the
  Prometheus ``[a-z_][a-z0-9_]*`` convention or missing the
  ``greptime_`` prefix.
- **GL-T003** — a histogram whose exploded self-export tables
  (``_bucket``/``_sum``/``_count``) collide with another registered
  metric (the self-monitor imports the registry into tables named this
  way — a collision silently merges two metrics' history).

``check_registry(registry)`` is the RUNTIME twin shared with the tier-1
telemetry test: same name convention, applied to whatever actually got
registered (dynamic names included).

**Knobs.**  Every ``GREPTIME_*`` environment variable read anywhere in
the package must be documented in KNOB_DOCS below, from which CONFIG.md
is generated (render_config_md) — defaults and reader modules extracted
from the code, so the table can never drift silently:

- **GL-K001** — a knob read in code but missing from KNOB_DOCS (and
  hence from CONFIG.md).
- **GL-K002** — a KNOB_DOCS entry no code reads (stale documentation).

Reference analog: the workspace-wide lints + config-docs discipline
(config/config.md generated from the config structs).
"""

from __future__ import annotations

import ast
import re

from greptimedb_tpu.analysis.core import (
    AnalysisContext, Finding, Pass, attr_chain, qualname_map, register,
)

NAME_RE = re.compile(r"^[a-z_][a-z0-9_]*$")
METRIC_PREFIX = "greptime_"
KNOB_RE = re.compile(r"^GREPTIME_[A-Z0-9_]+$")

REGISTER_METHODS = {"counter": "counter", "gauge": "gauge",
                    "histogram": "histogram"}

# ---------------------------------------------------------------------------
# Knob documentation: name -> one-line effect.  Subsystem/readers/defaults
# are extracted from the code; this table holds only what cannot be
# derived.  CONFIG.md is generated from the union — the tier-1 gate fails
# when either side drifts (GL-K001 / GL-K002 / stale CONFIG.md).
# ---------------------------------------------------------------------------

KNOB_DOCS: dict[str, str] = {
    "GREPTIME_AOT_WARMUP": (
        "`off` disables AOT warmup; `auto` (default) replays the usage "
        "journal's top-K shape classes at open + drains the rest on "
        "scheduler-idle ticks whenever the compile cache is armed."),
    "GREPTIME_AOT_WARMUP_TOP_K": (
        "How many journaled shape classes replay synchronously at "
        "region-open (the rest warm on idle ticks)."),
    "GREPTIME_CHAOS": (
        "Seeded fault-injection spec (`seed=N;point=prob:action[:...]`) "
        "consulted at every remote/disk boundary; unset = disabled "
        "(zero overhead)."),
    "GREPTIME_COMPILE_CACHE": (
        "Persistent compile cache: `auto` arms the AOT artifact store + "
        "usage journal for persistent data homes; `on` forces it (also "
        "wiring jax's own compilation-cache hook); `off` disables."),
    "GREPTIME_COMPILE_CACHE_DIR": (
        "Override location of the AOT artifact store + usage journal "
        "(default `<data_home>/compile_cache`)."),
    "GREPTIME_COMPILE_CACHE_QUOTA_BYTES": (
        "Disk quota for serialized AOT artifacts (`compile_cache` "
        "workload, kind=disk; oldest artifacts evict first)."),
    "GREPTIME_LOCK_WITNESS": (
        "`on` installs the runtime lock-order witness (records real "
        "acquisition chains, fails on ABBA inversions) for the "
        "concurrency/chaos test tiers; unset = witness never imported."),
    "GREPTIME_FLOW_CKPT_INTERVAL_S": (
        "Flow checkpoint cadence: GTF1 state+watermark snapshots persist "
        "at most this often (post-fold and on scheduler-idle ticks; "
        "0 disables periodic checkpointing, shutdown still saves)."),
    "GREPTIME_FLOW_DEVICE": (
        "`off` disables the device flow runtime everywhere: streaming "
        "flows keep the host dict-of-partials engine byte-for-byte "
        "(flow/device.py + checkpoint.py never imported)."),
    "GREPTIME_FLOW_QUOTA_BYTES": (
        "Memory-manager quota for the `flow` workload (resident "
        "[G, W] partial-state matrices; reject-to-host-fallback "
        "admission)."),
    "GREPTIME_FULLTEXT": (
        "`off` disables the fingerprint text index everywhere: "
        "LIKE/MATCHES/regex/LogQL predicates walk their dictionaries "
        "host-side byte-for-byte as before (A/B twin)."),
    "GREPTIME_FULLTEXT_CACHE_BYTES": (
        "Capacity of the resident fulltext cache (fingerprint matrices, "
        "verified-vocabulary memos, combined line-filter vectors)."),
    "GREPTIME_FULLTEXT_MIN_GRAM": (
        "Shortest indexed n-gram (2 or 3): 2 doubles index build work "
        "but lets two-character literals prune."),
    "GREPTIME_FULLTEXT_QUOTA_BYTES": (
        "Memory-manager quota for the `fulltext` workload "
        "(reject-to-host-fallback admission)."),
    "GREPTIME_FULLTEXT_WORDS": (
        "uint32 words per fingerprint row (32 bloom bits each): more "
        "words = fewer prefilter false positives, more HBM."),
    "GREPTIME_GRID": (
        "`off` disables the dense resident time-grid path; queries fall "
        "back to row-major device tables."),
    "GREPTIME_GRID_BUDGET_BYTES": (
        "HBM budget for resident dense grids; regions past it stay on "
        "the row path."),
    "GREPTIME_GRID_MIN_DENSITY": (
        "Minimum (rows / series x buckets) fill ratio for a region to "
        "qualify for the dense grid."),
    "GREPTIME_INGEST_VECTOR": (
        "`off` restores the legacy row-at-a-time wire decoders "
        "(byte-for-byte) instead of the vectorized CSV/arrow parse "
        "pipeline."),
    "GREPTIME_INGEST_WORKERS": (
        "Width of the parallel per-region ingest append pool."),
    "GREPTIME_JOIN_MAX_ROWS": (
        "Hard cap on join output rows; larger products raise instead of "
        "exhausting memory."),
    "GREPTIME_JOIN_WARN_ROWS": (
        "Join output size above which a slow-join warning is logged."),
    "GREPTIME_LAYOUT_CACHE": (
        "`off` disables the bucket-major derived layout cache (aligned "
        "range-window aggregation falls back to dynamic-slice)."),
    "GREPTIME_LAYOUT_CACHE_BYTES": (
        "Capacity of the bucket-major derived layout cache."),
    "GREPTIME_LAYOUT_CACHE_QUOTA_BYTES": (
        "Memory-manager quota for the `layout_cache` workload "
        "(reject-to-fallback admission)."),
    "GREPTIME_MESH": (
        "`off` disables device-mesh sharding even when multiple devices "
        "are visible."),
    "GREPTIME_MESH_AXIS": (
        "Axis name for the 1-D device mesh the resident tables shard "
        "over."),
    "GREPTIME_MESH_MIN_ROWS": (
        "Minimum region rows before mesh-sharded dispatch is worth the "
        "collective overhead."),
    "GREPTIME_PLAN_FUSION": (
        "`off` restores the multi-kernel PromQL chain (window kernel + "
        "eager epilogue + eager group reduce) byte-for-byte instead of "
        "the whole-plan fused single-dispatch programs."),
    "GREPTIME_PREFETCH_THREADS": (
        "S3 scan-readahead fetcher thread count (the read path joins "
        "in-flight prefetches)."),
    "GREPTIME_PROMQL_CACHE": (
        "`off` disables the resident PromQL evaluation cache (matcher "
        "selections, sort layouts, group-id vectors)."),
    "GREPTIME_PROMQL_CACHE_BYTES": (
        "Capacity of the resident PromQL evaluation cache."),
    "GREPTIME_PROMQL_CACHE_QUOTA_BYTES": (
        "Memory-manager quota for the `promql_cache` workload."),
    "GREPTIME_RPC_DEADLINE_S": (
        "Per-call deadline for Flight RPCs (rides each attempt as the "
        "gRPC timeout)."),
    "GREPTIME_RPC_RETRIES": (
        "Retry budget for transient Flight RPC failures (backoff + "
        "jitter envelope)."),
    "GREPTIME_S3_FENCING": (
        "`off` disables leader-epoch fencing of manifest/watermark "
        "writes on shared object storage (conditional puts under the "
        "Metasrv-minted epoch; standalone regions never arm a fence "
        "either way)."),
    "GREPTIME_SCAN_FORCE_LEXSORT": (
        "`1` forces the legacy global lexsort instead of the sorted-run "
        "merge (A/B bit-exactness harness)."),
    "GREPTIME_SCAN_QUOTA_BYTES": (
        "Memory-manager quota for the `scan` staging workload "
        "(reject-to-sequential fallback)."),
    "GREPTIME_SCAN_TAG_CODES": (
        "`off` disables dictionary-code tag transfer on cold scans "
        "(per-row object arrays come back, for A/B)."),
    "GREPTIME_SCAN_THREADS": (
        "Cold-scan parallel SST decode pool width (default "
        "min(8, files, cores))."),
    "GREPTIME_SCHEDULER": (
        "`off` restores the inline per-protocol execution path "
        "byte-for-byte (serving/ package never imported)."),
    "GREPTIME_SCHEDULER_BATCH": (
        "`off` disables cross-query stacked dispatch while keeping "
        "admission/priorities."),
    "GREPTIME_SCHEDULER_LINGER_MS": (
        "Group-commit linger ceiling for coalescible query arrivals "
        "(adaptive: scaled by same-class pressure, 0 when idle)."),
    "GREPTIME_SCHEDULER_MAX_BATCH": (
        "Maximum queries coalesced into one stacked device dispatch."),
    "GREPTIME_SCHEDULER_QUEUE": (
        "Bound on total queued queries before submissions are rejected "
        "with ResourcesExhausted."),
    "GREPTIME_SCHEDULER_TIMEOUT_S": (
        "Default per-query deadline; queries shed if still queued past "
        "it."),
    "GREPTIME_SCHEDULER_WORKERS": (
        "Scheduler worker pool size (default 1: the db lock serializes "
        "execution anyway)."),
    "GREPTIME_SCRUB": (
        "Online integrity scrubber: `auto` (default) arms the verified "
        "background sweep for persistent data homes on scheduler idle "
        "capacity; `on` starts sweeping immediately (standby nodes "
        "scrub too); `off` disables (module never constructed)."),
    "GREPTIME_SCRUB_BATCH": (
        "Artifacts verified per scrubber idle tick (the preemption "
        "granularity: interactive queries wait at most one batch)."),
    "GREPTIME_SCRUB_INTERVAL_S": (
        "Pause between completed scrub sweeps (a sweep itself is paced "
        "by idle ticks and can take much longer)."),
    "GREPTIME_SELF_MONITOR": (
        "`on` starts the self-monitoring loop (own spans/metrics "
        "exported into own tables); module never imported when unset."),
    "GREPTIME_SELF_MONITOR_INTERVAL_S": (
        "Flush interval of the self-monitoring export loop."),
    "GREPTIME_SLO": (
        "`off` disables the SLO observatory AND the budgeted idle "
        "economy (serving/slo.py + serving/idle.py never imported; the "
        "legacy chained idle hook and static deadlines serve "
        "byte-for-byte); default on."),
    "GREPTIME_SLO_ALPHA": (
        "Relative-error bound of the DDSketch-style latency sketches "
        "(smaller = more buckets = tighter quantiles)."),
    "GREPTIME_SLO_SLOT_S": (
        "Burn-rate ring-buffer slot width in seconds; the 5m/30m/1h/6h "
        "windows are fixed slot COUNTS, so shrinking this compresses "
        "every window proportionally (bench_soak uses that)."),
    "GREPTIME_SLO_THRESHOLD_MS": (
        "Default per-request latency objective for the interactive "
        "class; normal/background scale it by 4x/20x."),
    "GREPTIME_SLO_OBJECTIVE": (
        "Default availability objective (fraction of requests that "
        "must meet the threshold; 1-objective is the error budget)."),
    "GREPTIME_SLO_OVERRIDES": (
        "Per-tenant objective overrides, "
        "`tenant=threshold_ms:objective,...`."),
    "GREPTIME_SLO_FAST_BURN": (
        "Burn-rate multiplier that fires the fast (1h/5m) alert pair — "
        "and throttles every idle consumer while firing."),
    "GREPTIME_SLO_SLOW_BURN": (
        "Burn-rate multiplier that fires the slow (6h/30m) alert "
        "pair."),
    "GREPTIME_SLO_MIN_SAMPLES": (
        "Minimum short-window sample count before an alert pair may "
        "fire (thin traffic cannot page)."),
    "GREPTIME_SLO_ADMIT_MS": (
        "Background-admission allowance at FULL error budget; the "
        "journal-estimated cost of background work must fit the "
        "budget-scaled fraction of this."),
    "GREPTIME_SLO_DEADLINE_FACTOR": (
        "Adaptive per-class deadline = observed p99 x this factor "
        "(replaces the static GREPTIME_SCHEDULER_TIMEOUT_S once "
        "enough samples exist)."),
    "GREPTIME_SLO_DEADLINE_FLOOR_S": (
        "Lower bound of the adaptive deadline (a fast p99 must not "
        "strangle occasional legitimate slow queries)."),
    "GREPTIME_SLO_ROTATE_S": (
        "Sketch two-generation rotation period: adaptive deadlines and "
        "linger read the live+previous generations, so old latency "
        "regimes age out."),
    "GREPTIME_IDLE_QUANTUM_MS": (
        "Idle-economy accounting quantum: a consumer tick costs "
        "max(1, elapsed/quantum) credits, so long ticks auto-yield "
        "future grants."),
    "GREPTIME_IDLE_STARVE_TICKS": (
        "Starvation bound: a consumer passed over this many eligible "
        "ticks wins the next grant outright (counted in "
        "greptime_idle_starved_total — nonzero means misconfigured "
        "weights)."),
    "GREPTIME_IDLE_WEIGHTS": (
        "Idle-economy weight overrides, `name=weight,...` (substring "
        "match on the consumer name)."),
    "GREPTIME_SORTED_SEGMENTS": (
        "Segment-reduction strategy: `auto` picks scatter on CPU / "
        "sorted on TPU; `force`/`off` override for A/B."),
    "GREPTIME_TENANT_INFLIGHT": (
        "Default per-tenant concurrent-query cap (0 = unlimited)."),
    "GREPTIME_TENANT_MEM_BYTES": (
        "Default per-tenant memory budget, registered as a "
        "`tenant:<name>` workload."),
    "GREPTIME_TENANT_QPS": (
        "Default per-tenant token-bucket query rate (0 = unlimited)."),
    "GREPTIME_TENANT_QUERY_EST_BYTES": (
        "Per-query memory estimate charged against the tenant budget at "
        "admission."),
    "GREPTIME_VECTOR_MAX_DISTINCT": (
        "Distinct-value ceiling for vectorized set-ops; above it the "
        "evaluator falls back to hashing."),
    "GREPTIME_WAL_GROUP_COMMIT": (
        "`off` disables leader/follower WAL group commit (every append "
        "pays its own write+fsync)."),
    "GREPTIME_WAL_LINGER_MS": (
        "WAL group-commit linger: how long a contended leader holds the "
        "batch open for joiners (0 = flush immediately)."),
    "GREPTIME_WAL_REPLICAS": (
        "Shared-log broker replication factor (default 1 = legacy "
        "single copy; 3 = majority-quorum appends with read-repair — "
        "replay survives the loss or corruption of any minority of "
        "copies)."),
}


# ---------------------------------------------------------------------------
# Static collection
# ---------------------------------------------------------------------------


def _docstring_lines(tree: ast.Module) -> set[int]:
    out: set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.Module, ast.ClassDef, ast.FunctionDef,
                             ast.AsyncFunctionDef)):
            body = getattr(node, "body", [])
            if body and isinstance(body[0], ast.Expr) and isinstance(
                    body[0].value, ast.Constant) and isinstance(
                    body[0].value.value, str):
                c = body[0].value
                out.update(range(c.lineno,
                                 getattr(c, "end_lineno", c.lineno) + 1))
    return out


def collect_metric_registrations(ctx: AnalysisContext):
    """[(name, kind, labels|None, file, line, scope)] for every literal
    REGISTRY.counter/gauge/histogram call in the package."""
    regs = []
    for mod in ctx.modules:
        qnames = qualname_map(mod.tree)
        funcs = sorted(
            ((n.lineno, getattr(n, "end_lineno", n.lineno), q)
             for n, q in qnames.items()
             if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))),
        )

        def scope_of(line: int) -> str:
            best = "<module>"
            best_span = None
            for lo, hi, q in funcs:
                if lo <= line <= hi and (best_span is None
                                         or hi - lo < best_span):
                    best, best_span = q, hi - lo
            return best

        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = attr_chain(node.func)
            if chain is None:
                continue
            parts = chain.split(".")
            if parts[-1] not in REGISTER_METHODS or len(parts) < 2:
                continue
            recv = parts[-2]
            if "registry" not in recv.lower() and recv != "r":
                # REGISTRY.counter / self.registry.gauge style receivers
                # only — plain .counter() methods elsewhere don't count
                continue
            if not node.args or not isinstance(node.args[0], ast.Constant) \
                    or not isinstance(node.args[0].value, str):
                continue
            name = node.args[0].value
            labels = None
            for kw in node.keywords:
                if kw.arg == "labels" and isinstance(kw.value, ast.Tuple):
                    if all(isinstance(e, ast.Constant)
                           for e in kw.value.elts):
                        labels = tuple(e.value for e in kw.value.elts)
            if labels is None and len(node.args) >= 3 and isinstance(
                    node.args[2], ast.Tuple):
                if all(isinstance(e, ast.Constant)
                       for e in node.args[2].elts):
                    labels = tuple(e.value for e in node.args[2].elts)
            regs.append((name, REGISTER_METHODS[parts[-1]], labels,
                         mod.relpath, node.lineno, scope_of(node.lineno)))
    return regs


def collect_knob_reads(ctx: AnalysisContext):
    """[(knob, default|None, file, line)] for every GREPTIME_* string
    literal outside docstrings.  When the literal is the first argument
    of a call whose second argument is a constant, that constant is
    recorded as the default (the `environ.get(name, default)` shape)."""
    reads = []
    for mod in ctx.modules:
        if mod.relpath == "analysis/passes/hygiene.py":
            continue  # KNOB_DOCS itself is documentation, not a reader
        doclines = _docstring_lines(mod.tree)
        seen: set[int] = set()  # id() of constants consumed via calls
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) and node.args and isinstance(
                    node.args[0], ast.Constant) and isinstance(
                    node.args[0].value, str) and KNOB_RE.match(
                    node.args[0].value):
                default = None
                if len(node.args) >= 2 and isinstance(
                        node.args[1], ast.Constant):
                    default = node.args[1].value
                seen.add(id(node.args[0]))
                reads.append((node.args[0].value, default, mod.relpath,
                              node.lineno))
        for node in ast.walk(mod.tree):
            if (isinstance(node, ast.Constant)
                    and isinstance(node.value, str)
                    and KNOB_RE.match(node.value)
                    and id(node) not in seen
                    and node.lineno not in doclines):
                reads.append((node.value, None, mod.relpath, node.lineno))
    return reads


# ---------------------------------------------------------------------------
# Runtime twin (shared with tests/test_telemetry.py)
# ---------------------------------------------------------------------------


def check_registry(registry, norm=None) -> list[str]:
    """Problems in a LIVE registry: recorded collisions, name/label
    convention violations, self-export table collisions (optionally
    normalizer round-trip when ``norm`` is given).  The tier-1 telemetry
    test imports every metric-registering module and then asserts this
    returns []."""
    problems = list(registry.collisions)
    tables: set[str] = set()
    for name, m in registry._metrics.items():
        if not NAME_RE.match(name):
            problems.append(f"bad metric name {name!r}")
        for ln in m.label_names:
            if not NAME_RE.match(ln):
                problems.append(f"bad label {ln!r} on {name}")
        if norm is not None and norm(name) != name:
            problems.append(f"{name!r} mutates through the OTLP normalizer")
        exploded = ([name + s for s in ("_bucket", "_sum", "_count")]
                    if m.kind == "histogram" else [name])
        for t in exploded:
            if t in tables:
                problems.append(f"self-export table collision: {t}")
            tables.add(t)
    return problems


# ---------------------------------------------------------------------------
# CONFIG.md generation
# ---------------------------------------------------------------------------


def render_config_md(ctx: AnalysisContext | None = None) -> str:
    from greptimedb_tpu.analysis.core import load_package

    ctx = ctx or load_package()
    reads = collect_knob_reads(ctx)
    by_knob: dict[str, dict] = {}
    for knob, default, relpath, _line in reads:
        e = by_knob.setdefault(knob, {"default": None, "readers": set()})
        e["readers"].add(relpath)
        if default is not None and e["default"] is None:
            e["default"] = default
    lines = [
        "# CONFIG — `GREPTIME_*` environment knobs",
        "",
        "Generated by the greptime-lint knob pass "
        "(`python -m greptimedb_tpu.analysis --write-config`).",
        "Do not edit by hand: the tier-1 gate regenerates this table and "
        "fails on drift —",
        "a knob read in code but absent here is a GL-K001 finding.",
        "",
        "| Knob | Default | Read by | Effect |",
        "|---|---|---|---|",
    ]
    for knob in sorted(set(by_knob) | set(KNOB_DOCS)):
        info = by_knob.get(knob, {"default": None, "readers": set()})
        default = info["default"]
        if default is None:
            default_s = "unset"
        elif default == "":
            default_s = '`""`'
        else:
            default_s = f"`{default}`"
        readers = ", ".join(f"`{r}`" for r in sorted(info["readers"])) \
            or "—"
        doc = KNOB_DOCS.get(knob, "**UNDOCUMENTED (GL-K001)**")
        lines.append(f"| `{knob}` | {default_s} | {readers} | {doc} |")
    return "\n".join(lines) + "\n"


@register
class HygienePass(Pass):
    name = "hygiene"
    title = "metric-name + env-knob hygiene"
    codes = {
        "GL-T001": "metric registered with conflicting kind/labels",
        "GL-T002": "metric/label name violates the naming convention",
        "GL-T003": "histogram self-export tables collide with a metric",
        "GL-K001": "GREPTIME_* knob read in code but undocumented",
        "GL-K002": "documented knob never read by any code",
    }

    def run(self, ctx: AnalysisContext) -> list[Finding]:
        findings: list[Finding] = []
        regs = collect_metric_registrations(ctx)
        first_site: dict[str, tuple] = {}
        for name, kind, labels, relpath, line, scope in regs:
            if not NAME_RE.match(name) or not name.startswith(METRIC_PREFIX):
                findings.append(Finding(
                    code="GL-T002", file=relpath, line=line, scope=scope,
                    key=name,
                    message=f"metric name {name!r} violates the "
                            f"'{METRIC_PREFIX}[a-z0-9_]*' convention"))
            for ln in labels or ():
                if not NAME_RE.match(str(ln)):
                    findings.append(Finding(
                        code="GL-T002", file=relpath, line=line, scope=scope,
                        key=f"{name}:{ln}",
                        message=f"label {ln!r} on {name!r} violates the "
                                "naming convention"))
            prev = first_site.get(name)
            if prev is None:
                first_site[name] = (kind, labels, relpath, line)
            else:
                pkind, plabels, pfile, pline = prev
                if pkind != kind or (labels is not None
                                     and plabels is not None
                                     and labels != plabels):
                    findings.append(Finding(
                        code="GL-T001", file=relpath, line=line, scope=scope,
                        key=name,
                        message=(f"{name!r} registered as {pkind}"
                                 f"{plabels} at {pfile}:{pline}, "
                                 f"re-registered as {kind}{labels}")))
        # histogram explosion vs literal names
        names = set(first_site)
        for name, (kind, _labels, relpath, line) in first_site.items():
            if kind != "histogram":
                continue
            for suffix in ("_bucket", "_sum", "_count"):
                if name + suffix in names:
                    findings.append(Finding(
                        code="GL-T003", file=relpath, line=line,
                        scope="<module>", key=name + suffix,
                        message=(f"histogram {name!r} self-export table "
                                 f"{name + suffix!r} collides with a "
                                 "registered metric")))
        # knobs
        reads = collect_knob_reads(ctx)
        flagged: set[str] = set()
        for knob, _default, relpath, line in reads:
            if knob not in KNOB_DOCS and knob not in flagged:
                flagged.add(knob)
                findings.append(Finding(
                    code="GL-K001", file=relpath, line=line,
                    scope="<module>", key=knob,
                    message=(f"knob {knob} read here but missing from "
                             "analysis KNOB_DOCS / CONFIG.md")))
        read_names = {k for k, _d, _f, _l in reads}
        # stale-doc detection only makes sense over the WHOLE package
        # (fixture snippets would mark every documented knob stale)
        whole_package = ctx.module("analysis/passes/hygiene.py") is not None
        for knob in sorted(set(KNOB_DOCS) - read_names
                           if whole_package else ()):
            findings.append(Finding(
                code="GL-K002", file="analysis/passes/hygiene.py", line=1,
                scope="KNOB_DOCS", key=knob,
                message=f"documented knob {knob} is never read by any "
                        "code"))
        return findings
