"""Lock-discipline pass: guarded attributes + blocking calls under locks.

Codes:

- **GL-L001** — a read/write of a declared guarded attribute outside its
  lock.  The guard map below is the single declarative source of truth
  for which shared attributes are protected by which lock (the docstring
  promises next to each ``threading.Lock()`` today, made checkable).
  Mode ``"mutate"`` guards writes/mutating calls only (lock-free read
  fast paths stay legal — the cache's ``_lru.get`` discipline); ``"rw"``
  guards reads too (torn-pair state like the region append log).
- **GL-L002** — a blocking call (fsync, flush, sleep, socket/Flight IO,
  ``block_until_ready``) made while ANY lock is held.  Every such site
  either loses the lock's latency budget (writers pile up behind one
  fsync) or is a deliberate serialization point — in which case it
  carries an inline ``# gl: allow[GL-L002] -- why`` justification.

Clippy analog: ``disallowed_methods`` under ``[workspace.lints]`` plus
the await-holding-lock lint family.

Construction (``__init__``/``__new__``) is exempt: objects are published
after construction, happens-before included.  Helper methods that run
with a caller-held lock declare it with ``# gl: holds[lockattr]``.
"""

from __future__ import annotations

import ast

from greptimedb_tpu.analysis.core import (
    AnalysisContext, Finding, Pass, SourceModule, attr_chain, qualname_map,
    register,
)

# ---------------------------------------------------------------------------
# Declarative guard map: relpath -> class -> attr -> (lock attr, mode).
# Class "" = module scope.  Attribute sites match by NAME within methods
# of the class (any receiver — helpers like ``w.rejected`` in
# WorkloadMemoryManager mutate Workload fields under the manager lock).
# ---------------------------------------------------------------------------

GUARDED: dict[str, dict[str, dict[str, tuple[str, str]]]] = {
    "storage/cache.py": {
        "RegionCacheManager": {
            "_lru": ("_struct_lock", "mutate"),
            "_bytes": ("_struct_lock", "mutate"),
            "hits": ("_struct_lock", "mutate"),
            "misses": ("_struct_lock", "mutate"),
            "extends": ("_struct_lock", "mutate"),
        },
    },
    "storage/region.py": {
        "Region": {
            "_append_log": ("_append_log_lock", "rw"),
            "_append_base": ("_append_log_lock", "rw"),
        },
    },
    "serving/scheduler.py": {
        "QueryScheduler": {
            "_queues": ("_cond", "mutate"),
            "_sqlish_inflight": ("_cond", "rw"),
        },
        "": {
            "_interactive_waiting": ("_wait_lock", "mutate"),
        },
    },
    "utils/memory.py": {
        "WorkloadMemoryManager": {
            "_workloads": ("_lock", "mutate"),
            "peak_bytes": ("_lock", "mutate"),
            "rejected": ("_lock", "mutate"),
            "reclaims": ("_lock", "mutate"),
        },
    },
    "utils/telemetry.py": {
        "_Child": {
            "value": ("_mu", "mutate"),
            "_value": ("_mu", "mutate"),
            "counts": ("_mu", "mutate"),
            "total": ("_mu", "mutate"),
            "sum": ("_mu", "mutate"),
        },
        "Registry": {
            "_metrics": ("_lock", "mutate"),
            "collisions": ("_lock", "mutate"),
        },
    },
    "storage/scan.py": {
        "_Staging": {
            "_bytes": ("_lock", "mutate"),
        },
    },
    "compile/service.py": {
        "PlanCompiler": {
            "mem_builds": ("_lock", "mutate"),
            "aot_hits": ("_lock", "mutate"),
            "persists": ("_lock", "mutate"),
        },
    },
    "compile/journal.py": {
        "UsageJournal": {
            "_entries": ("_lock", "mutate"),
            "_costs": ("_lock", "mutate"),
            "_dirty": ("_lock", "rw"),
        },
    },
    "flow/device.py": {
        "FlowDeviceRuntime": {
            "_kernels": ("_kern_lock", "mutate"),
        },
    },
    "serving/slo.py": {
        "SloEngine": {
            "_keys": ("_lock", "mutate"),
            "_exec_cls": ("_lock", "mutate"),
            "_wait_cls": ("_lock", "mutate"),
            "_alerts": ("_lock", "rw"),
            "_overrides": ("_lock", "mutate"),
        },
    },
    "serving/idle.py": {
        "IdleEconomy": {
            "_consumers": ("_lock", "mutate"),
        },
    },
    "fulltext/resident.py": {
        "FulltextIndexCache": {
            "_lru": ("_struct_lock", "mutate"),
            "_bytes": ("_struct_lock", "mutate"),
            "hits": ("_struct_lock", "mutate"),
            "misses": ("_struct_lock", "mutate"),
            "builds": ("_struct_lock", "mutate"),
            "rejects": ("_struct_lock", "mutate"),
            "evictions": ("_struct_lock", "mutate"),
        },
    },
}

# dict/list/set/OrderedDict methods that mutate their receiver
MUTATORS = {
    "append", "extend", "insert", "remove", "pop", "popitem", "clear",
    "update", "setdefault", "move_to_end", "sort", "reverse", "add",
    "discard", "appendleft", "popleft",
}

# call targets considered blocking for GL-L002: matched against the last
# component of the dotted callee chain
BLOCKING_TAIL = {
    "fsync", "_fsync_dir", "sleep", "urlopen", "block_until_ready",
    "do_get", "do_put", "do_action", "sendall", "recv", "connect", "flush",
}
# full dotted chains additionally treated as blocking
BLOCKING_CHAIN = {"os.fsync", "time.sleep"}

_LOCKISH = ("lock", "_cond", "_mu", "mutex")


def is_lockish(name: str | None) -> bool:
    if not name:
        return False
    tail = name.rsplit(".", 1)[-1].lower()
    return any(t in tail for t in _LOCKISH)


def lock_tail(node: ast.AST) -> str | None:
    """Last component of a lock-ish with/acquire target, else None."""
    chain = attr_chain(node)
    if chain is None or not is_lockish(chain):
        return None
    return chain.rsplit(".", 1)[-1]


class _FunctionWalker:
    """Walks one function's statements tracking the set of held locks
    (with-blocks plus explicit acquire/release), reporting guarded-attr
    and blocking-call violations to ``pass_``."""

    def __init__(self, pass_, mod: SourceModule, scope: str,
                 class_chain: tuple[str, ...], held: set[str]):
        self.p = pass_
        self.mod = mod
        self.scope = scope
        self.class_chain = class_chain
        self.held = set(held)
        self.ordinals: dict[tuple, int] = {}

    # ---- guard map lookup ----------------------------------------------
    def _guard_for(self, attr: str) -> tuple[str, str] | None:
        per_mod = GUARDED.get(self.mod.relpath)
        if not per_mod:
            return None
        for cls, attrs in per_mod.items():
            if attr not in attrs:
                continue
            if cls == "" and not self.class_chain:
                return attrs[attr]
            if cls in self.class_chain:
                return attrs[attr]
        return None

    def _emit(self, code: str, node: ast.AST, key_base: tuple, message: str):
        n = self.ordinals.get(key_base, 0)
        self.ordinals[key_base] = n + 1
        key = ":".join(str(x) for x in key_base) + (f":{n}" if n else "")
        self.p.findings.append(Finding(
            code=code, file=self.mod.relpath, line=node.lineno,
            scope=self.scope, key=key, message=message))

    # ---- attribute site checks -----------------------------------------
    def _check_attr_site(self, attr: str, node: ast.AST, kind: str):
        guard = self._guard_for(attr)
        if guard is None:
            return
        lock, mode = guard
        if kind == "read" and mode != "rw":
            return
        if lock in self.held:
            return
        self._emit(
            "GL-L001", node, (attr, kind),
            f"{kind} of {attr!r} without holding {lock!r} "
            f"(declared guard, mode={mode})")

    def _mutation_targets(self, target: ast.AST, node: ast.AST):
        """Attr names mutated by an assignment target (``x.attr = .``,
        ``x.attr[k] = .``)."""
        if isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._mutation_targets(elt, node)
            return
        t = target
        if isinstance(t, ast.Subscript):
            t = t.value
        if isinstance(t, ast.Attribute):
            self._check_attr_site(t.attr, node, "write")
        elif isinstance(t, ast.Name) and not self.class_chain:
            # module-global state (``_interactive_waiting += delta``)
            self._check_attr_site(t.id, node, "write")

    # ---- statement walk -------------------------------------------------
    def walk(self, stmts: list[ast.stmt]):
        for stmt in stmts:
            self._stmt(stmt)

    def _stmt(self, stmt: ast.stmt):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested defs run later — lock state does not transfer; a
            # holds marker re-establishes it explicitly
            sub = _FunctionWalker(
                self.p, self.mod, f"{self.scope}.{stmt.name}",
                self.class_chain, self.mod.holds_for(stmt))
            sub.walk(stmt.body)
            return
        if isinstance(stmt, ast.ClassDef):
            return  # handled at the top level of the pass
        if isinstance(stmt, ast.With):
            acquired = []
            for item in stmt.items:
                tail = lock_tail(item.context_expr)
                if tail is not None and tail not in self.held:
                    acquired.append(tail)
                self._expr(item.context_expr)
            self.held.update(acquired)
            self.walk(stmt.body)
            self.held.difference_update(acquired)
            return
        if isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                self._mutation_targets(t, stmt)
            self._expr(stmt.value)
            for t in stmt.targets:
                self._expr_reads_only(t)
            return
        if isinstance(stmt, ast.AugAssign):
            self._mutation_targets(stmt.target, stmt)
            self._expr(stmt.value)
            return
        if isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self._mutation_targets(stmt.target, stmt)
                self._expr(stmt.value)
            return
        if isinstance(stmt, ast.Delete):
            for t in stmt.targets:
                self._mutation_targets(t, stmt)
                # del x.attr[k] also reads x.attr
            return
        if isinstance(stmt, ast.Expr):
            self._expr(stmt.value, stmt_level=True)
            return
        if isinstance(stmt, (ast.If, ast.While)):
            self._expr(stmt.test)
            self.walk(stmt.body)
            self.walk(stmt.orelse)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._expr(stmt.iter)
            self.walk(stmt.body)
            self.walk(stmt.orelse)
            return
        if isinstance(stmt, ast.Try):
            self.walk(stmt.body)
            for h in stmt.handlers:
                self.walk(h.body)
            self.walk(stmt.orelse)
            self.walk(stmt.finalbody)
            return
        if isinstance(stmt, (ast.Return, ast.Raise)):
            for v in ast.iter_child_nodes(stmt):
                if isinstance(v, ast.expr):
                    self._expr(v)
            return
        # anything else: scan contained expressions generically
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                self._expr(child)
            elif isinstance(child, ast.stmt):
                self._stmt(child)

    # ---- expressions -----------------------------------------------------
    def _expr_reads_only(self, node: ast.AST):
        """Visit the VALUE part of an assignment target chain (e.g. the
        ``self`` in ``self._lru[k] = v``) without re-flagging the write."""
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._expr(child)

    def _expr(self, node: ast.AST, stmt_level: bool = False):
        if isinstance(node, ast.Call):
            chain = attr_chain(node.func)
            tail = chain.rsplit(".", 1)[-1] if chain else None
            # explicit acquire()/release() tracking (hot-tail pattern,
            # group-commit leader's release-around-IO)
            if tail == "acquire" and chain and is_lockish(
                    chain.rsplit(".", 1)[0]):
                self.held.add(chain.split(".")[-2])
            elif tail == "release" and chain and is_lockish(
                    chain.rsplit(".", 1)[0]):
                self.held.discard(chain.split(".")[-2])
            elif tail is not None:
                # mutating method on a guarded attribute?
                if tail in MUTATORS and isinstance(node.func, ast.Attribute):
                    recv = node.func.value
                    if isinstance(recv, ast.Subscript):
                        recv = recv.value  # self._queues[p].append(...)
                    if isinstance(recv, ast.Attribute):
                        self._check_attr_site(recv.attr, node, "write")
                # blocking call under a held lock?
                if self.held and (
                    tail in BLOCKING_TAIL
                    or (chain in BLOCKING_CHAIN)
                ):
                    held = ",".join(sorted(self.held))
                    self._emit(
                        "GL-L002", node, ("blocking", tail),
                        f"blocking call {(chain or tail)!r} while holding "
                        f"lock(s) {held}")
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.expr) and child is not node.func:
                    self._expr(child)
            if isinstance(node.func, (ast.Attribute,)):
                # receiver expression may itself read guarded attrs
                self._expr(node.func.value)
            elif not isinstance(node.func, ast.Name):
                self._expr(node.func)
            return
        if isinstance(node, ast.Attribute) and isinstance(node.ctx, ast.Load):
            self._check_attr_site(node.attr, node, "read")
            self._expr(node.value)
            return
        if isinstance(node, (ast.Lambda,)):
            return  # deferred execution: lock state does not transfer
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._expr(child)


@register
class LockDisciplinePass(Pass):
    name = "lock_discipline"
    title = "guarded attributes + blocking calls under locks"
    codes = {
        "GL-L001": "guarded attribute accessed without its lock",
        "GL-L002": "blocking call while holding a lock",
    }

    def run(self, ctx: AnalysisContext) -> list[Finding]:
        self.findings: list[Finding] = []
        for mod in ctx.modules:
            qnames = qualname_map(mod.tree)
            # hoisted per module (not per node): the class-name set and
            # the function-qualname set each walk all qnames once
            class_names = {n.name for n in qnames
                           if isinstance(n, ast.ClassDef)}
            func_quals = {
                q for n, q in qnames.items()
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
            for node, qual in qnames.items():
                if not isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                # only walk OUTERMOST functions; nested defs are walked
                # inline (lock state resets at the boundary)
                parts = qual.split(".")
                if any(".".join(parts[:i]) in func_quals
                       for i in range(1, len(parts))):
                    continue  # nested def: parent walks it inline
                if parts[-1] in ("__init__", "__new__"):
                    continue  # construction: unpublished object
                chain = tuple(p for p in parts[:-1] if p in class_names)
                w = _FunctionWalker(self, mod, qual, chain,
                                    mod.holds_for(node))
                w.walk(node.body)
        return self.findings
