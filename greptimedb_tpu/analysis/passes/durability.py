"""Durability pass: every persisted write routes through the fsync
discipline.

Generalizes the ad-hoc bare-open lint that lived in
tests/test_durability.py (which now delegates here — single source of
truth): storage code must not write bytes to disk except through the
modules that OWN the temp+fsync+rename discipline, and an atomic
``os.replace`` is only durable when the parent directory is fsynced
afterwards (the half of atomic-replace durability the rename alone does
not give — a power loss can forget the directory entry even though the
file's blocks hit disk).

Scope: ``storage/``.  Codes:

- **GL-D001** — a bare binary-mode ``open(..., "wb"/"ab"/"xb")`` in
  storage code outside the owner modules (wal.py, object_store.py,
  s3.py).  Everything else must write through ObjectStore /
  FileLogStore so chaos injection, checksums and fsync policy apply.
- **GL-D002** — ``os.replace``/``os.rename`` in storage code in a
  function that never fsyncs the parent directory (no ``_fsync_dir``
  call).  Owner modules are exempt only where they ARE the helper.
- **GL-D003** — a manifest or watermark-marker write that bypasses the
  fenced conditional-put owners (ISSUE 15).  Manifest bytes reach the
  store only through ``Manifest._write``/``set_fence`` (which verify
  the leader epoch and CAS version-keyed files); the broker watermark
  marker only through ``SharedLogBroker._persist_watermarks``.  A
  plain write anywhere else re-opens the split-brain interleave the
  fencing closed — baseline-free from day one.

Reference analog: the object-store stack's write-path invariants that
greptimedb gets from opendal plus its own atomic-write helpers.
"""

from __future__ import annotations

import ast

from greptimedb_tpu.analysis.core import (
    AnalysisContext, Finding, Pass, attr_chain, qualname_map, register,
)

SCOPE_PREFIX = "storage/"
# modules that OWN the fsync discipline; bare opens are their job
OPEN_OWNERS = {"storage/wal.py", "storage/object_store.py", "storage/s3.py"}
WRITE_MODES = set("wax")

# GL-D003 declarative map (ISSUE 15): per fenced-surface module, the
# store-write call shapes that count as a manifest/watermark write and
# the owner scopes allowed to perform them.  ``"open"`` additionally
# matches ANY write-mode open() in the module (the broker's watermark
# marker is plain file IO).
FENCED_WRITE_OWNERS: dict[str, tuple[frozenset, frozenset]] = {
    "storage/manifest.py": (
        frozenset({"store.write", "store.write_if"}),
        frozenset({"Manifest._write", "Manifest.set_fence"}),
    ),
    "storage/remote_wal.py": (
        frozenset({"open"}),
        frozenset({"SharedLogBroker._persist_watermarks"}),
    ),
}


def _write_mode_open(call: ast.Call) -> bool:
    """Any-mode writable open() (text or binary — the watermark marker
    is text json)."""
    mode = None
    if len(call.args) >= 2 and isinstance(call.args[1], ast.Constant):
        mode = call.args[1].value
    for kw in call.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
            mode = kw.value.value
    if not isinstance(mode, str):
        return False
    return bool(WRITE_MODES & set(mode)) or "+" in mode


def _binary_write_mode(call: ast.Call) -> bool:
    """True for open(..., "wb"/"ab"/"xb"/"r+b"-style writable binary)."""
    mode = None
    if len(call.args) >= 2 and isinstance(call.args[1], ast.Constant):
        mode = call.args[1].value
    for kw in call.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
            mode = kw.value.value
    if not isinstance(mode, str) or "b" not in mode:
        return False
    return bool(WRITE_MODES & set(mode)) or "+" in mode


@register
class DurabilityPass(Pass):
    name = "durability"
    title = "persisted writes route through the fsync discipline"
    codes = {
        "GL-D001": "bare binary write open() outside the owner modules",
        "GL-D002": "os.replace/rename without a parent-directory fsync",
        "GL-D003": "manifest/watermark write bypassing the fenced "
                   "conditional-put owner",
    }

    def run(self, ctx: AnalysisContext) -> list[Finding]:
        findings: list[Finding] = []
        for mod in ctx.modules:
            if not mod.relpath.startswith(SCOPE_PREFIX):
                continue
            qnames = qualname_map(mod.tree)
            funcs = [n for n in qnames
                     if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]

            def scope_of(node: ast.AST) -> str:
                best = "<module>"
                best_span = None
                for f in funcs:
                    end = getattr(f, "end_lineno", f.lineno)
                    if f.lineno <= node.lineno <= end:
                        span = end - f.lineno
                        if best_span is None or span < best_span:
                            best, best_span = qnames[f], span
                return best

            # which functions call _fsync_dir (directly, any receiver)
            fsyncs_dir: set[str] = set()
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.Call):
                    chain = attr_chain(node.func) or ""
                    if chain.rsplit(".", 1)[-1] == "_fsync_dir":
                        fsyncs_dir.add(scope_of(node))

            ordinals: dict[tuple, int] = {}

            def emit(code: str, node: ast.AST, key_base: tuple, msg: str):
                scope = scope_of(node)
                n = ordinals.get((code, scope) + key_base, 0)
                ordinals[(code, scope) + key_base] = n + 1
                key = ":".join(key_base) + (f":{n}" if n else "")
                findings.append(Finding(
                    code=code, file=mod.relpath, line=node.lineno,
                    scope=scope, key=key, message=msg))

            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Call):
                    continue
                chain = attr_chain(node.func) or ""
                if (chain == "open" and mod.relpath not in OPEN_OWNERS
                        and _binary_write_mode(node)):
                    emit("GL-D001", node, ("bare-open",),
                         "bare binary write open() — storage code must "
                         "write through ObjectStore/FileLogStore "
                         "(temp+fsync+rename discipline)")
                if chain in ("os.replace", "os.rename"):
                    scope = scope_of(node)
                    if scope == "_fsync_dir" or scope in fsyncs_dir:
                        continue
                    emit("GL-D002", node, (chain,),
                         f"{chain} without a parent-directory fsync in "
                         f"{scope!r} — the rename is not durable until "
                         "the directory entry is (use object_store."
                         "_fsync_dir)")
                fenced = FENCED_WRITE_OWNERS.get(mod.relpath)
                if fenced is not None:
                    patterns, owners = fenced
                    hit = any(
                        chain == p or chain.endswith("." + p)
                        for p in patterns if p != "open"
                    ) or ("open" in patterns and chain == "open"
                          and _write_mode_open(node))
                    if hit and scope_of(node) not in owners:
                        emit("GL-D003", node, ("fenced-write",),
                             f"manifest/watermark write ({chain}) outside "
                             f"the fenced conditional-put owner(s) "
                             f"{sorted(owners)} — plain writes bypass "
                             "epoch fencing and can interleave two "
                             "leaders' histories on shared storage")
        return findings
