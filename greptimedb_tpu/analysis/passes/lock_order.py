"""Lock-order pass: static acquisition graph + cycle detection.

Codes:

- **GL-O001** — a cycle in the static lock-acquisition graph: lock B is
  acquired while A is held on one path and A while B is held on another
  (the ABBA deadlock shape).  Edges come from syntactic nesting
  (``with A: ... with B:``), explicit ``acquire()`` while another lock
  is held, intra-module calls to functions whose bodies acquire locks,
  and a small declarative table of cross-module acquirers (methods whose
  lock lives in another module — the region append-log API the cache
  layer calls under its own lock).
- **GL-O002** — re-acquisition of a NON-reentrant ``threading.Lock``
  while it is already held on the same path (self-deadlock; an RLock
  self-edge is legal and ignored).

Lock nodes are named ``relpath:Class.attr`` (or ``relpath:name`` for
module globals); lock KIND (Lock/RLock/Condition) is read from the
``threading.X()`` constructor at the assignment site.

The static graph is necessarily partial (dynamic dispatch, cross-module
calls).  Its runtime twin — greptimedb_tpu.analysis.witness — records
REAL acquisition chains in the concurrency/chaos test tiers and fails
on inversions the static pass cannot see; the two share this pass's
"edge + first-seen site" vocabulary.
"""

from __future__ import annotations

import ast

from greptimedb_tpu.analysis.core import (
    AnalysisContext, Finding, Pass, attr_chain, qualname_map, register,
)
from greptimedb_tpu.analysis.passes.lock_discipline import lock_tail

# Cross-module acquirers the intra-module call resolution cannot see:
# method/function name -> lock node it acquires.  Kept small and
# verified; the runtime witness is the net under this declarative table.
CROSS_MODULE_ACQUIRES: dict[str, list[str]] = {
    # region append-log API (storage/region.py) — called by the cache
    # layer, sometimes under RegionCacheManager._struct_lock
    "append_pos": ["storage/region.py:Region._append_log_lock"],
    "append_chunks_since": ["storage/region.py:Region._append_log_lock"],
    "_append_pos": ["storage/region.py:Region._append_log_lock"],
    "_chunks_since": ["storage/region.py:Region._append_log_lock"],
    # memory admission (utils/memory.py) — called from ingest and cache
    "admit": ["utils/memory.py:WorkloadMemoryManager._lock"],
    "try_admit": ["utils/memory.py:WorkloadMemoryManager._lock"],
}


def _lock_defs(mod) -> dict[str, str]:
    """attr/global name -> kind ("Lock"|"RLock"|"Condition") for locks
    created in this module via ``threading.X()``."""
    out: dict[str, str] = {}
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Assign) or not isinstance(
                node.value, ast.Call):
            continue
        chain = attr_chain(node.value.func)
        if chain not in ("threading.Lock", "threading.RLock",
                         "threading.Condition"):
            continue
        kind = chain.rsplit(".", 1)[-1]
        for t in node.targets:
            name = attr_chain(t)
            if name is None:
                continue
            out[name.rsplit(".", 1)[-1]] = kind
    return out


class _Graph:
    def __init__(self):
        # (a, b) -> (file, line, scope) first observed
        self.edges: dict[tuple[str, str], tuple[str, int, str]] = {}
        self.kinds: dict[str, str] = {}  # node -> Lock/RLock/Condition
        self.self_acquire: list[tuple[str, str, int, str]] = []

    def add_edge(self, a: str, b: str, site: tuple[str, int, str]):
        if a == b:
            return
        self.edges.setdefault((a, b), site)

    def cycles(self) -> list[list[str]]:
        """Elementary cycles via DFS over the edge set (the graph is tiny
        — a handful of locks), deduped by rotation."""
        adj: dict[str, list[str]] = {}
        for (a, b) in self.edges:
            adj.setdefault(a, []).append(b)
        seen_cycles: set[tuple[str, ...]] = set()
        out: list[list[str]] = []

        def dfs(start: str, node: str, path: list[str], visited: set[str]):
            for nxt in adj.get(node, ()):
                if nxt == start and len(path) > 1:
                    # canonical rotation for dedup
                    i = path.index(min(path))
                    canon = tuple(path[i:] + path[:i])
                    if canon not in seen_cycles:
                        seen_cycles.add(canon)
                        out.append(list(canon))
                elif nxt not in visited and nxt >= start:
                    visited.add(nxt)
                    dfs(start, nxt, path + [nxt], visited)
                    visited.discard(nxt)

        for n in sorted(adj):
            dfs(n, n, [n], {n})
        return out


class _ModuleScan:
    """Collect, per function, the locks it acquires directly, and the
    nesting edges within it."""

    def __init__(self, mod, graph: _Graph):
        self.mod = mod
        self.graph = graph
        self.lock_kinds = _lock_defs(mod)
        self.qnames = qualname_map(mod.tree)
        self.class_of: dict[str, str] = {}
        # function qualname -> set of lock nodes acquired directly
        self.direct: dict[str, set[str]] = {}
        # deferred: (held_node, callee_name, site) — resolved after every
        # function's direct set is known
        self.calls_under_lock: list[tuple[str, str, tuple]] = []

    def node_for(self, tail: str, class_chain: tuple[str, ...]) -> str:
        cls = class_chain[-1] if class_chain else ""
        base = f"{cls}.{tail}" if cls else tail
        kind = self.lock_kinds.get(tail, "Lock")
        node = f"{self.mod.relpath}:{base}"
        self.graph.kinds.setdefault(node, kind)
        return node

    def scan(self):
        class_names = {n.name for n in self.qnames
                       if isinstance(n, ast.ClassDef)}
        for node, qual in self.qnames.items():
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            parts = qual.split(".")
            chain = tuple(p for p in parts[:-1] if p in class_names)
            held0 = {
                self.node_for(t, chain) for t in self.mod.holds_for(node)
            }
            self._walk(node.body, qual, chain, set(held0))

    def _walk(self, stmts, scope: str, chain, held: set[str]):
        for stmt in stmts:
            self._stmt(stmt, scope, chain, held)

    def _acquire(self, tail: str, scope: str, chain, held: set[str],
                 lineno: int) -> str:
        node = self.node_for(tail, chain)
        site = (self.mod.relpath, lineno, scope)
        if node in held and self.graph.kinds.get(node) == "Lock":
            self.graph.self_acquire.append(
                (node, self.mod.relpath, lineno, scope))
        for h in held:
            self.graph.add_edge(h, node, site)
        self.direct.setdefault(scope, set()).add(node)
        return node

    def _stmt(self, stmt, scope: str, chain, held: set[str]):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            sub_held = {self.node_for(t, chain)
                        for t in self.mod.holds_for(stmt)}
            self._walk(stmt.body, f"{scope}.{stmt.name}", chain, sub_held)
            return
        if isinstance(stmt, ast.ClassDef):
            return
        if isinstance(stmt, ast.With):
            acquired = []
            for item in stmt.items:
                tail = lock_tail(item.context_expr)
                if tail is not None:
                    n = self._acquire(tail, scope, chain, held,
                                      item.context_expr.lineno)
                    if n not in held:
                        acquired.append(n)
            held.update(acquired)
            self._walk(stmt.body, scope, chain, held)
            held.difference_update(acquired)
            return
        for call in self._calls_in(stmt):
            cchain = attr_chain(call.func)
            if cchain is None:
                continue
            parts = cchain.split(".")
            tail = parts[-1]
            if tail == "acquire" and len(parts) >= 2 and lock_tail(
                    call.func.value) is not None:
                held.add(self._acquire(parts[-2], scope, chain, held,
                                       call.lineno))
            elif tail == "release" and len(parts) >= 2 and lock_tail(
                    call.func.value) is not None:
                held.discard(self.node_for(parts[-2], chain))
            elif held:
                site = (self.mod.relpath, call.lineno, scope)
                for h in sorted(held):
                    self.calls_under_lock.append((h, tail, site))
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.stmt):
                self._stmt(child, scope, chain, held)

    @staticmethod
    def _calls_in(stmt):
        """Calls in this statement's own expressions (not nested stmts)."""
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                for sub in ast.walk(child):
                    if isinstance(sub, ast.Call):
                        yield sub
            elif isinstance(child, ast.withitem):
                for sub in ast.walk(child.context_expr):
                    if isinstance(sub, ast.Call):
                        yield sub


@register
class LockOrderPass(Pass):
    name = "lock_order"
    title = "lock acquisition graph cycles"
    codes = {
        "GL-O001": "cycle in the static lock-acquisition graph",
        "GL-O002": "re-acquiring a non-reentrant Lock already held",
    }

    def build_graph(self, ctx: AnalysisContext) -> _Graph:
        graph = _Graph()
        scans = [_ModuleScan(m, graph) for m in ctx.modules]
        for s in scans:
            s.scan()
        # resolve calls-under-lock: intra-module by function/method name,
        # plus the declarative cross-module table
        by_name: dict[tuple[str, str], set[str]] = {}
        for s in scans:
            for qual, locks in s.direct.items():
                by_name.setdefault(
                    (s.mod.relpath, qual.rsplit(".", 1)[-1]), set()
                ).update(locks)
        for s in scans:
            for held, callee, site in s.calls_under_lock:
                targets = set(by_name.get((s.mod.relpath, callee), ()))
                targets.update(CROSS_MODULE_ACQUIRES.get(callee, ()))
                for t in targets:
                    graph.kinds.setdefault(t, "Lock")
                    graph.add_edge(held, t, site)
        return graph

    def run(self, ctx: AnalysisContext) -> list[Finding]:
        graph = self.build_graph(ctx)
        findings: list[Finding] = []
        for cyc in graph.cycles():
            edges = [(cyc[i], cyc[(i + 1) % len(cyc)])
                     for i in range(len(cyc))]
            sites = [graph.edges.get(e) for e in edges]
            first = min((s for s in sites if s), default=("<unknown>", 0, ""))
            findings.append(Finding(
                code="GL-O001", file=first[0], line=first[1],
                scope=first[2], key="|".join(cyc),
                message=("lock-order cycle: " + " -> ".join(
                    cyc + [cyc[0]]))))
        for node, relpath, lineno, scope in graph.self_acquire:
            findings.append(Finding(
                code="GL-O002", file=relpath, line=lineno, scope=scope,
                key=node,
                message=(f"non-reentrant Lock {node} acquired while "
                         "already held on this path")))
        return findings
