"""Runtime lock-order witness: records real acquisition chains, fails
on inversions.

The static lock-order pass (passes/lock_order.py) sees syntactic
nesting; this witness sees what actually happens — including chains
through dynamic dispatch and cross-module calls the static graph cannot
resolve.  Chaos-style discipline (utils/chaos.py):

- **Zero overhead disabled.**  Production code NEVER imports this
  module (the tier-1 pin asserts it is absent from ``sys.modules`` after
  driving the write path); nothing is patched, ``threading.Lock`` is the
  stock factory.  There is no "cheap disabled check" on any hot path —
  the disabled cost is exactly zero.
- **Scoped.**  ``capture()`` patches the ``threading`` lock factories
  for its dynamic extent; only locks CREATED inside the scope are
  witnessed (tests build their scheduler/cache/region fixtures inside
  it).  ``uninstall`` restores the stock factories; witnessed locks
  created meanwhile keep working (they hold a real lock underneath).
- **Deterministic verdicts.**  An inversion is an EDGE conflict — lock B
  acquired under A somewhere, A under B elsewhere — so a seeded ABBA
  interleaving is caught even when the timing never actually deadlocks.

Env: ``GREPTIME_LOCK_WITNESS=on`` lets the concurrency/chaos test tiers
install the witness for the whole session (tests/conftest.py); unset,
this module is never imported.
"""

from __future__ import annotations

import sys
import threading
from contextlib import contextmanager

_ORIG_LOCK = threading.Lock
_ORIG_RLOCK = threading.RLock


def _creation_site() -> str:
    f = sys._getframe(2)
    code = f.f_code
    return f"{code.co_filename.rsplit('/', 1)[-1]}:{f.f_lineno}"


class Inversion(Exception):
    pass


class _WitnessedLock:
    """Wraps a real lock; reports acquisition ordering to the witness.
    Quacks like threading.Lock/RLock (with-statement, acquire/release,
    Condition(lock=...) compatible)."""

    def __init__(self, witness: "LockWitness", inner, name: str,
                 reentrant: bool):
        self._w = witness
        self._inner = inner
        self._name = name
        self._reentrant = reentrant

    def acquire(self, blocking: bool = True, timeout: float = -1):
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._w._note_acquire(self)
        return got

    def release(self):
        self._w._note_release(self)
        self._inner.release()

    __enter__ = acquire

    def __exit__(self, *exc):
        self.release()

    # Condition() interop: Condition probes these on its lock argument.
    # RLock has real implementations; a PLAIN Lock does not (CPython's
    # Condition falls back to acquire/release there) — we must emulate
    # those fallbacks, not blindly delegate, or Event()/Queue()/
    # Condition(Lock()) built on a witnessed Lock crash at wait() time.
    def _is_owned(self):
        if self._reentrant:
            return self._inner._is_owned()
        # CPython's plain-lock fallback semantics
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True

    def _release_save(self):
        self._w._note_release(self)
        if self._reentrant:
            return self._inner._release_save()
        self._inner.release()
        return None

    def _acquire_restore(self, state):
        if self._reentrant:
            self._inner._acquire_restore(state)
        else:
            self._inner.acquire()
        self._w._note_acquire(self)

    def locked(self):
        return self._inner.locked()

    def __getattr__(self, name):
        # stdlib interop (_at_fork_reinit via os.register_at_fork, ...):
        # anything not intercepted delegates to the real lock
        return getattr(self._inner, name)

    def __repr__(self):
        return f"<witnessed {self._name} {self._inner!r}>"


class LockWitness:
    """Acquisition-order recorder.  ``edges`` maps (held_name, acquired_
    name) -> first-seen (thread, chain); an inversion is recorded when
    both (a, b) and (b, a) exist."""

    MAX_CHAINS = 10_000  # soak-run bound; edges stay (they're the verdict)

    def __init__(self):
        self._mu = _ORIG_LOCK()  # stock lock: the witness never
        # witnesses itself
        self._tls = threading.local()
        self._site_seq: dict[str, int] = {}
        self.edges: dict[tuple[str, str], str] = {}
        self.inversions: list[str] = []
        self.chains: list[tuple[str, ...]] = []  # real acquisition chains
        self.installed = False

    # ---- recording -----------------------------------------------------
    def _held(self) -> list:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def _note_acquire(self, lock: _WitnessedLock):
        stack = self._held()
        if lock._reentrant and any(l is lock for l in stack):
            stack.append(lock)  # reentrant re-entry: no new edges
            return
        new_edges = []
        for held in stack:
            if held is lock:
                continue
            a, b = held._name, lock._name
            if a == b:
                continue
            new_edges.append((a, b))
        stack.append(lock)
        if not new_edges:
            return
        chain = tuple(l._name for l in stack)
        with self._mu:
            if len(self.chains) < self.MAX_CHAINS:
                self.chains.append(chain)
            for a, b in new_edges:
                if (a, b) not in self.edges:
                    self.edges[(a, b)] = (
                        f"{threading.current_thread().name}: "
                        + " -> ".join(chain))
                if (b, a) in self.edges:
                    msg = (f"lock-order inversion: {a} -> {b} "
                           f"({self.edges[(a, b)]}) but {b} -> {a} "
                           f"({self.edges[(b, a)]})")
                    if not any(m.startswith(
                            f"lock-order inversion: {a} -> {b} ")
                            or m.startswith(
                            f"lock-order inversion: {b} -> {a} ")
                            for m in self.inversions):
                        self.inversions.append(msg)

    def _note_release(self, lock: _WitnessedLock):
        stack = self._held()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] is lock:
                del stack[i]
                return

    # ---- factories -----------------------------------------------------
    def _name(self, site: str) -> str:
        """Per-INSTANCE name: creation site + sequence.  Instance-level
        (not lockdep class-level) identity on purpose — two locks minted
        by the same constructor line (every Region's `_append_log_lock`,
        two locks on one source line) must not alias, or their mutual
        ordering (the classic "always lock regions in id order" deadlock
        family) self-cancels as a skipped self-edge."""
        with self._mu:
            n = self._site_seq.get(site, 0)
            self._site_seq[site] = n + 1
        return f"{site}#{n}" if n else site

    def _make_lock(self):
        return _WitnessedLock(self, _ORIG_LOCK(),
                              self._name(_creation_site()), False)

    def _make_rlock(self):
        return _WitnessedLock(self, _ORIG_RLOCK(),
                              self._name(_creation_site()), True)

    # ---- install -------------------------------------------------------
    def install(self):
        if self.installed:
            return
        threading.Lock = self._make_lock
        threading.RLock = self._make_rlock
        self.installed = True

    def uninstall(self):
        if not self.installed:
            return
        threading.Lock = _ORIG_LOCK
        threading.RLock = _ORIG_RLOCK
        self.installed = False

    @contextmanager
    def capture(self):
        """Install for a dynamic extent; locks created inside are
        witnessed for their whole lifetime."""
        self.install()
        try:
            yield self
        finally:
            self.uninstall()

    def check(self):
        """Raise Inversion when any inversion was recorded."""
        if self.inversions:
            raise Inversion("; ".join(self.inversions))


WITNESS = LockWitness()


def install_from_env() -> bool:
    """Session-wide install when GREPTIME_LOCK_WITNESS=on (called by the
    concurrency/chaos test tiers' conftest — never by production code)."""
    import os

    if os.environ.get("GREPTIME_LOCK_WITNESS", "").lower() in (
            "on", "1", "true"):
        WITNESS.install()
        return True
    return False
