"""Distribution layer: partition rules, device mesh, collective exchange.

The reference distributes via table partitioning across regions + plan
push-down + Arrow Flight merge (SURVEY.md §2.6). On TPU the same three
ideas become (SURVEY.md §5.8 "TPU-native equivalent"):

- partition rules  → sharding the series axis across a jax Mesh;
- plan push-down   → the commutativity split (reference
  dist_plan/commutativity.rs): each shard computes partial aggregates
  locally inside shard_map;
- MergeScan/Flight → XLA collectives (psum/pmin/pmax) over ICI.
"""

from greptimedb_tpu.parallel.partition import PartitionRule, split_rows
from greptimedb_tpu.parallel.dist import (
    ShardedTable,
    create_mesh,
    shard_table,
    DistAggExecutor,
)

__all__ = [
    "PartitionRule",
    "split_rows",
    "ShardedTable",
    "create_mesh",
    "shard_table",
    "DistAggExecutor",
]
