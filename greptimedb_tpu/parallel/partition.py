"""Partition rules: route rows/series to shards.

Equivalent of the reference's MultiDimPartitionRule
(src/partition/src/multi_dim.rs:50, RFC multi-dimension-partition-rule):
a table's PARTITION ON COLUMNS (...) (expr, ...) clause defines disjoint
regions by tag-expression ranges; PartitionRuleManager::split_rows routes
writes (manager.rs:232). Here a rule routes to mesh shards; the default
(no explicit rule) is hash-of-series, which balances high-cardinality
workloads across devices.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from greptimedb_tpu.errors import InvalidArguments, PlanError
from greptimedb_tpu.query.ast import BinaryOp, Column, Expr, Literal, UnaryOp
from greptimedb_tpu.query.parser import Parser


def _parse_expr(text: str) -> Expr:
    p = Parser(text)
    e = p.expr()
    return e


@dataclass
class PartitionRule:
    """Expression-based multi-dimensional partition rule.

    ``exprs[i]`` holds for rows in partition i; expressions must be
    disjoint and cover the key space (checked loosely at write time: rows
    matching nothing raise). An empty rule list = single partition / hash.
    """

    columns: list[str]
    exprs: list[Expr]
    num_partitions: int

    @staticmethod
    def from_sql(columns: list[str], texts: list[str]) -> "PartitionRule":
        exprs = [_parse_expr(t) for t in texts]
        return PartitionRule(columns, exprs, max(len(exprs), 1))

    @staticmethod
    def hash_rule(num_partitions: int, columns: list[str] | None = None) -> "PartitionRule":
        return PartitionRule(columns or [], [], num_partitions)

    def evaluate(self, row_values: dict[str, np.ndarray], n: int) -> np.ndarray:
        """Vectorized partition index per row; -1 when nothing matches."""
        if not self.exprs:
            # stable hash of the rule's key columns (crc32: process- and
            # restart-independent, unlike the salted builtin hash)
            import zlib

            key = None
            names = self.columns or sorted(row_values)
            for name in names:
                if name not in row_values:
                    continue
                arr = row_values[name]
                h = np.array(
                    [zlib.crc32(str(v).encode("utf-8")) for v in arr],
                    dtype=np.int64,
                )
                key = h if key is None else key * 1000003 + h
            if key is None:
                return np.zeros(n, dtype=np.int64)
            return np.abs(key) % self.num_partitions
        out = np.full(n, -1, dtype=np.int64)
        for i, e in enumerate(self.exprs):
            m = _eval_bool(e, row_values, n)
            out = np.where((out < 0) & m, i, out)
        return out


def _eval_bool(e: Expr, env: dict[str, np.ndarray], n: int) -> np.ndarray:
    if isinstance(e, BinaryOp):
        op = e.op.upper()
        if op == "AND":
            return _eval_bool(e.left, env, n) & _eval_bool(e.right, env, n)
        if op == "OR":
            return _eval_bool(e.left, env, n) | _eval_bool(e.right, env, n)
        l = _eval_val(e.left, env, n)
        r = _eval_val(e.right, env, n)
        import operator

        table = {
            "=": operator.eq, "!=": operator.ne, "<": operator.lt,
            "<=": operator.le, ">": operator.gt, ">=": operator.ge,
        }
        if op not in table:
            raise PlanError(f"partition expr operator {op}")
        return table[op](l, r)
    if isinstance(e, UnaryOp) and e.op == "NOT":
        return ~_eval_bool(e.operand, env, n)
    raise PlanError(f"partition expr {e}")


def _eval_val(e: Expr, env: dict[str, np.ndarray], n: int):
    if isinstance(e, Column):
        if e.name not in env:
            raise InvalidArguments(f"partition column {e.name} missing")
        return env[e.name]
    if isinstance(e, Literal):
        return e.value
    raise PlanError(f"partition expr value {e}")


def split_rows(
    rule: PartitionRule, columns: dict[str, np.ndarray], n: int
) -> dict[int, np.ndarray]:
    """Row indices per partition (reference PartitionRuleManager::split_rows)."""
    env = {
        c: np.asarray(columns[c], dtype=object)
        for c in (rule.columns or sorted(columns))
        if c in columns
    }
    if rule.exprs:
        idx = rule.evaluate(env, n)
        bad = idx < 0
        if bad.any():
            raise InvalidArguments(
                f"{int(bad.sum())} rows match no partition (first at {int(np.nonzero(bad)[0][0])})"
            )
    else:
        idx = rule.evaluate(env, n)
    return {
        int(p): np.nonzero(idx == p)[0] for p in np.unique(idx)
    }
